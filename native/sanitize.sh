#!/usr/bin/env bash
# ASan/UBSan job for the native storage engine (SURVEY §5.3). Builds the
# engine together with its self-test under sanitizers and runs the full
# exercise (CRUD, compaction, reopen recovery, torn-tail sweep).
set -euo pipefail
cd "$(dirname "$0")"
out=$(mktemp -d)
trap 'rm -rf "$out"' EXIT
g++ -O1 -g -std=c++17 -fsanitize=address,undefined -fno-omit-frame-pointer \
    -o "$out/engine_selftest" engine_selftest.cpp storage_engine.cpp -lz
"$out/engine_selftest" "$out"
echo "sanitizers clean"
