// Sanitizer self-test for the storage engine (SURVEY §5.3: the C++ parts of
// this build carry ASan/UBSan jobs to compensate for leaving Rust's type
// system). Exercises the whole C API — puts/deletes across column families,
// reopen-recovery, torn-tail truncation at odd offsets, compaction, dump —
// under -fsanitize=address,undefined. Build+run via native/sanitize.sh or
// tests/test_storage.py::test_native_engine_sanitizers.

#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

extern "C" {
void* nse_open(const char* path);
int nse_write_batch(void* h, const uint8_t* body, uint32_t len);
int nse_get(void* h, const char* cf, const uint8_t* key, uint32_t klen,
            const uint8_t** val, uint32_t* vlen);
int nse_contains(void* h, const char* cf, const uint8_t* key, uint32_t klen);
uint64_t nse_len(void* h, const char* cf);
void nse_dump(void* h, const char* cf, const uint8_t** buf, uint64_t* len);
void nse_compact(void* h);
void nse_close(void* h);
}

static void put_u32(std::string& s, uint32_t v) { s.append((char*)&v, 4); }
static void put_u16(std::string& s, uint16_t v) { s.append((char*)&v, 2); }

// One write-batch body in the engine's wire format:
//   u32 n_ops | per op: u8 op | u16 cf_len | cf | u32 klen | key [| u32 vlen | value]
static std::string batch_put(const char* cf, const std::string& k, const std::string& v) {
    std::string s;
    put_u32(s, 1);
    s.push_back((char)0);
    put_u16(s, (uint16_t)strlen(cf));
    s += cf;
    put_u32(s, (uint32_t)k.size());
    s += k;
    put_u32(s, (uint32_t)v.size());
    s += v;
    return s;
}

static std::string batch_del(const char* cf, const std::string& k) {
    std::string s;
    put_u32(s, 1);
    s.push_back((char)1);
    put_u16(s, (uint16_t)strlen(cf));
    s += cf;
    put_u32(s, (uint32_t)k.size());
    s += k;
    return s;
}

static void write(void* h, const std::string& body) {
    assert(nse_write_batch(h, (const uint8_t*)body.data(), (uint32_t)body.size()) == 0);
}

int main(int argc, char** argv) {
    std::string dir = argc > 1 ? argv[1] : "/tmp/nse-sanitize";
    std::string wal = dir + "/wal.log";
    remove(wal.c_str());

    // 1. Populate two column families, overwrite and delete.
    void* h = nse_open(dir.c_str());
    assert(h);
    for (int i = 0; i < 200; i++) {
        std::string k = "key-" + std::to_string(i);
        std::string v(100 + (i % 37), (char)('a' + i % 26));
        write(h, batch_put("alpha", k, v));
        if (i % 2) write(h, batch_put("beta", k, v + v));
        if (i % 5 == 4) write(h, batch_del("alpha", "key-" + std::to_string(i - 2)));
    }
    uint64_t alpha_len = nse_len(h, "alpha");
    uint64_t beta_len = nse_len(h, "beta");
    assert(alpha_len > 0 && beta_len > 0);
    const uint8_t* val; uint32_t vlen;
    assert(nse_get(h, "alpha", (const uint8_t*)"key-1", 5, &val, &vlen) == 1);
    assert(vlen == 101);
    nse_compact(h);
    assert(nse_len(h, "alpha") == alpha_len);
    nse_close(h);

    // 2. Reopen: recovery reproduces the same state; dump walks every entry.
    h = nse_open(dir.c_str());
    assert(nse_len(h, "alpha") == alpha_len);
    assert(nse_len(h, "beta") == beta_len);
    const uint8_t* buf; uint64_t blen;
    nse_dump(h, "beta", &buf, &blen);
    assert(blen > 0);
    nse_close(h);

    // 3. Torn tail: truncate the log at many odd byte offsets; recovery must
    // neither crash nor read out of bounds (ASan enforces the latter).
    FILE* f = fopen(wal.c_str(), "rb");
    assert(f);
    fseek(f, 0, SEEK_END);
    long full = ftell(f);
    std::vector<uint8_t> data(full);
    fseek(f, 0, SEEK_SET);
    assert(fread(data.data(), 1, full, f) == (size_t)full);
    fclose(f);
    for (long cut = full - 1; cut >= 0; cut -= (full / 97 + 1)) {
        FILE* w = fopen(wal.c_str(), "wb");
        fwrite(data.data(), 1, cut, w);
        fclose(w);
        void* h2 = nse_open(dir.c_str());
        assert(h2);
        assert(nse_len(h2, "alpha") <= alpha_len);
        nse_close(h2);
    }
    printf("sanitize selftest ok\n");
    return 0;
}
