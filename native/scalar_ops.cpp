// Batched host-side ed25519 scalar pipeline for the TPU verifier.
//
// The pipelined verify path (narwhal_tpu/tpu/verifier.py) is bounded by
// per-item Python work: the SHA-512 challenge k = H(R || A || M) mod L, the
// canonicality prechecks, and — in msm mode — the random-linear-combination
// scalars z*k mod L and sum(z*s) mod L on Python bigints (~250 ms per 32k
// batch, vs ~260 ms of device compute: the host was the bottleneck). This
// file does the same work in C at ~1 us/item with the GIL released (ctypes
// calls drop it), so host packing of batch N+1 genuinely overlaps the device
// compute of batch N.
//
// Parity targets (behavior, not code): the precheck + challenge rules of
// /root/reference/types/src/primary.rs:487-537's certificate verification
// via ed25519-dalek (canonical s < L, canonical field encodings y < p), and
// the batch-verification scalar math of RFC 8032 / dalek's batch_verify.
// Arithmetic is original: 64-bit-limb schoolbook multiplies with unsigned
// __int128 carries, and a fold-based reduction mod L using
// 2^252 === -DELTA (mod L) with explicit sign tracking.
//
// Assumes little-endian host (x86/arm64): 32-byte scalars are memcpy'd
// straight into 4x64-bit limb vectors.

#include <cstdint>
#include <cstdlib>
#include <cstring>

typedef uint64_t u64;
typedef unsigned __int128 u128;

// ---- SHA-512 (FIPS 180-4), self-contained ---------------------------------
// No OpenSSL dev headers ship in this environment, so the digest is
// implemented here. The round/initial constants are the standard published
// tables (fractional parts of cube/square roots of the first primes),
// generated programmatically; the whole function is fuzz-checked against
// hashlib.sha512 in tests/test_tpu_ed25519.py.

static const u64 SHA512_K[80] = {
    0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL, 0xb5c0fbcfec4d3b2fULL, 0xe9b5dba58189dbbcULL,
    0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL, 0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL,
    0xd807aa98a3030242ULL, 0x12835b0145706fbeULL, 0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
    0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL, 0x9bdc06a725c71235ULL, 0xc19bf174cf692694ULL,
    0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL, 0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL,
    0x2de92c6f592b0275ULL, 0x4a7484aa6ea6e483ULL, 0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
    0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL, 0xb00327c898fb213fULL, 0xbf597fc7beef0ee4ULL,
    0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL, 0x06ca6351e003826fULL, 0x142929670a0e6e70ULL,
    0x27b70a8546d22ffcULL, 0x2e1b21385c26c926ULL, 0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
    0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL, 0x81c2c92e47edaee6ULL, 0x92722c851482353bULL,
    0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL, 0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL,
    0xd192e819d6ef5218ULL, 0xd69906245565a910ULL, 0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
    0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL, 0x2748774cdf8eeb99ULL, 0x34b0bcb5e19b48a8ULL,
    0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL, 0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL,
    0x748f82ee5defb2fcULL, 0x78a5636f43172f60ULL, 0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
    0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL, 0xbef9a3f7b2c67915ULL, 0xc67178f2e372532bULL,
    0xca273eceea26619cULL, 0xd186b8c721c0c207ULL, 0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL,
    0x06f067aa72176fbaULL, 0x0a637dc5a2c898a6ULL, 0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
    0x28db77f523047d84ULL, 0x32caab7b40c72493ULL, 0x3c9ebe0a15c9bebcULL, 0x431d67c49c100d4cULL,
    0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL, 0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL,
};
static const u64 SHA512_H0[8] = {
    0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL, 0xa54ff53a5f1d36f1ULL,
    0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL, 0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL,
};

static inline u64 rotr64(u64 x, int n) { return (x >> n) | (x << (64 - n)); }
static inline u64 load_be64(const uint8_t *p) {
  u64 v = 0;
  for (int i = 0; i < 8; ++i) v = (v << 8) | p[i];
  return v;
}
static inline void store_be64(uint8_t *p, u64 v) {
  for (int i = 7; i >= 0; --i) { p[i] = (uint8_t)v; v >>= 8; }
}

static void sha512_block(u64 h[8], const uint8_t *blk) {
  u64 w[80];
  for (int t = 0; t < 16; ++t) w[t] = load_be64(blk + 8 * t);
  for (int t = 16; t < 80; ++t) {
    u64 s0 = rotr64(w[t - 15], 1) ^ rotr64(w[t - 15], 8) ^ (w[t - 15] >> 7);
    u64 s1 = rotr64(w[t - 2], 19) ^ rotr64(w[t - 2], 61) ^ (w[t - 2] >> 6);
    w[t] = w[t - 16] + s0 + w[t - 7] + s1;
  }
  u64 a = h[0], b = h[1], c = h[2], d = h[3];
  u64 e = h[4], f = h[5], g = h[6], hh = h[7];
  for (int t = 0; t < 80; ++t) {
    u64 S1 = rotr64(e, 14) ^ rotr64(e, 18) ^ rotr64(e, 41);
    u64 ch = (e & f) ^ (~e & g);
    u64 t1 = hh + S1 + ch + SHA512_K[t] + w[t];
    u64 S0 = rotr64(a, 28) ^ rotr64(a, 34) ^ rotr64(a, 39);
    u64 maj = (a & b) ^ (a & c) ^ (b & c);
    u64 t2 = S0 + maj;
    hh = g; g = f; f = e; e = d + t1;
    d = c; c = b; b = a; a = t1 + t2;
  }
  h[0] += a; h[1] += b; h[2] += c; h[3] += d;
  h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
}

// digest = SHA512(seg1 || seg2 || seg3); the three-segment shape matches the
// challenge input R || A || M without concatenating on the Python side.
static void sha512_3seg(const uint8_t *s1, size_t n1, const uint8_t *s2,
                        size_t n2, const uint8_t *s3, size_t n3,
                        uint8_t out[64]) {
  u64 h[8];
  memcpy(h, SHA512_H0, sizeof(h));
  uint8_t buf[128];
  size_t fill = 0, total = n1 + n2 + n3;
  const uint8_t *segs[3] = {s1, s2, s3};
  size_t lens[3] = {n1, n2, n3};
  for (int s = 0; s < 3; ++s) {
    const uint8_t *p = segs[s];
    size_t rem = lens[s];
    while (rem) {
      size_t take = 128 - fill < rem ? 128 - fill : rem;
      memcpy(buf + fill, p, take);
      fill += take; p += take; rem -= take;
      if (fill == 128) { sha512_block(h, buf); fill = 0; }
    }
  }
  buf[fill++] = 0x80;
  if (fill > 112) {
    memset(buf + fill, 0, 128 - fill);
    sha512_block(h, buf);
    fill = 0;
  }
  memset(buf + fill, 0, 128 - fill);
  // 128-bit big-endian bit length; message sizes here fit 64 bits.
  store_be64(buf + 120, (u64)total << 3);
  store_be64(buf + 112, (u64)total >> 61);
  sha512_block(h, buf);
  for (int i = 0; i < 8; ++i) store_be64(out + 8 * i, h[i]);
}

// L = 2^252 + DELTA (the ed25519 group order)
static const u64 L_LIMBS[4] = {0x5812631a5cf5d3edULL, 0x14def9dea2f79cd6ULL,
                               0ULL, 0x1000000000000000ULL};
// DELTA = L - 2^252 (125 bits)
static const u64 DELTA_LIMBS[2] = {0x5812631a5cf5d3edULL,
                                   0x14def9dea2f79cd6ULL};
// P = 2^255 - 19 (field prime), for the y < p canonical-encoding check
static const u64 P_LIMBS[4] = {0xffffffffffffffedULL, 0xffffffffffffffffULL,
                               0xffffffffffffffffULL, 0x7fffffffffffffffULL};

// ---- n-limb helpers (little-endian limb order) ----------------------------

static inline int limbs_cmp(const u64 *a, const u64 *b, int n) {
  for (int i = n - 1; i >= 0; --i) {
    if (a[i] != b[i]) return a[i] < b[i] ? -1 : 1;
  }
  return 0;
}

static inline bool limbs_is_zero(const u64 *a, int n) {
  for (int i = 0; i < n; ++i)
    if (a[i]) return false;
  return true;
}

// out[na+nb] = a[na] * b[nb] (schoolbook; out must not alias inputs)
static void limbs_mul(const u64 *a, int na, const u64 *b, int nb, u64 *out) {
  memset(out, 0, sizeof(u64) * (na + nb));
  for (int i = 0; i < na; ++i) {
    u128 carry = 0;
    for (int j = 0; j < nb; ++j) {
      u128 cur = (u128)a[i] * b[j] + out[i + j] + carry;
      out[i + j] = (u64)cur;
      carry = cur >> 64;
    }
    out[i + nb] = (u64)carry;
  }
}

// a[n] -= b[n]; requires a >= b
static void limbs_sub(u64 *a, const u64 *b, int n) {
  u64 borrow = 0;
  for (int i = 0; i < n; ++i) {
    u64 bi = b[i] + borrow;
    borrow = (b[i] + borrow < b[i]) || (a[i] < bi);
    a[i] -= bi;
  }
}

// Reduce x[nx] (nx <= 9) mod L into out[4]. Fold rule: for v = r + q*2^252,
// v === r - q*DELTA (mod L); track the sign of the running magnitude
// explicitly and fix it up at the end. Each fold shrinks the magnitude by
// ~127 bits, so at most 4 folds for 576-bit inputs.
static void reduce_mod_l(const u64 *x, int nx, u64 out[4]) {
  u64 v[10];
  memset(v, 0, sizeof(v));
  memcpy(v, x, sizeof(u64) * nx);
  int neg = 0;
  for (;;) {
    // done when v < 2^252 (limbs 4.. zero and limb3 < 2^60)
    bool high = v[3] >> 60;
    for (int i = 4; i < 10 && !high; ++i) high = v[i] != 0;
    if (!high) break;
    // q = v >> 252 (up to 6 limbs), r = v mod 2^252
    u64 q[7];
    for (int i = 0; i < 6; ++i) q[i] = (v[i + 3] >> 60) | (v[i + 4] << 4);
    q[6] = v[9] >> 60;
    u64 r[4] = {v[0], v[1], v[2], v[3] & 0x0fffffffffffffffULL};
    // y = q * DELTA (<= 9 limbs)
    u64 y[9];
    limbs_mul(q, 7, DELTA_LIMBS, 2, y);
    // v = |r - y|, flipping the sign when y > r
    u64 rwide[9];
    memset(rwide, 0, sizeof(rwide));
    memcpy(rwide, r, sizeof(r));
    memset(v, 0, sizeof(v));
    if (limbs_cmp(rwide, y, 9) >= 0) {
      memcpy(v, rwide, sizeof(rwide));
      limbs_sub(v, y, 9);
    } else {
      memcpy(v, y, sizeof(y));
      limbs_sub(v, rwide, 9);
      neg ^= 1;
    }
  }
  // v < 2^252 < L
  if (neg && !limbs_is_zero(v, 4)) {
    u64 l[4];
    memcpy(l, L_LIMBS, sizeof(l));
    limbs_sub(l, v, 4);
    memcpy(out, l, sizeof(l));
  } else {
    memcpy(out, v, sizeof(u64) * 4);
  }
}

// out[4] = a[na] * b[nb] mod L (na+nb <= 9)
static void mulmod_l(const u64 *a, int na, const u64 *b, int nb, u64 out[4]) {
  u64 prod[9];
  memset(prod, 0, sizeof(prod));
  limbs_mul(a, na, b, nb, prod);
  reduce_mod_l(prod, na + nb, out);
}

// acc[4] = (acc + t) mod L; both < L
static void addmod_l(u64 acc[4], const u64 t[4]) {
  u64 carry = 0;
  for (int i = 0; i < 4; ++i) {
    u64 s = acc[i] + t[i];
    u64 c1 = s < acc[i];
    acc[i] = s + carry;
    carry = c1 | (acc[i] < s);
  }
  if (carry || limbs_cmp(acc, L_LIMBS, 4) >= 0) limbs_sub(acc, L_LIMBS, 4);
}

// ---- exported batch entry points ------------------------------------------

extern "C" {

// Precheck + challenge scalars for n signatures.
//   pk:      n x 32 bytes      sig: n x 64 bytes (R || S)
//   msg:     concatenated messages, item i = msg[msg_off[i] : msg_off[i+1]]
//   out_k:   n x 32 bytes, k_i = SHA512(R_i || A_i || M_i) mod L (LE)
//   out_ok:  n bytes, 1 iff the item passes the canonicality prechecks
//            (s < L, masked y_A < p, masked y_R < p)
// Returns 0 on success, nonzero on internal failure (EVP init).
int ed25519_precheck_k(int64_t n, const uint8_t *pk, const uint8_t *sig,
                       const uint8_t *msg, const int64_t *msg_off,
                       uint8_t *out_k, uint8_t *out_ok) {
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t *a = pk + 32 * i;
    const uint8_t *r = sig + 64 * i;
    const uint8_t *s = sig + 64 * i + 32;
    out_ok[i] = 0;
    memset(out_k + 32 * i, 0, 32);

    u64 sl[4], yl[4];
    memcpy(sl, s, 32);
    if (limbs_cmp(sl, L_LIMBS, 4) >= 0) continue;  // non-canonical s
    memcpy(yl, a, 32);
    yl[3] &= 0x7fffffffffffffffULL;  // drop the x-sign bit
    if (limbs_cmp(yl, P_LIMBS, 4) >= 0) continue;  // non-canonical A
    memcpy(yl, r, 32);
    yl[3] &= 0x7fffffffffffffffULL;
    if (limbs_cmp(yl, P_LIMBS, 4) >= 0) continue;  // non-canonical R

    uint8_t digest[64];
    sha512_3seg(r, 32, a, 32, msg + msg_off[i],
                (size_t)(msg_off[i + 1] - msg_off[i]), digest);
    u64 h[8], k[4];
    memcpy(h, digest, 64);
    reduce_mod_l(h, 8, k);
    memcpy(out_k + 32 * i, k, 32);
    out_ok[i] = 1;
  }
  return 0;
}

// Self-test hook: SHA512 over one contiguous buffer.
void sha512_test(const uint8_t *data, int64_t n, uint8_t *out) {
  sha512_3seg(data, (size_t)n, nullptr, 0, nullptr, 0, out);
}

// Random-linear-combination scalars for one msm bucket of m items.
//   k_rows: m x 32 (challenge scalars < L)   s_rows: m x 32 (sig S < L)
//   z_rows: m x 16 (fresh 128-bit coefficients)
//   out_ak: m x 32, ak_i = z_i * k_i mod L
//   out_sum: 32 bytes, sum(z_i * s_i) mod L
void scalar_fold(int64_t m, const uint8_t *k_rows, const uint8_t *s_rows,
                 const uint8_t *z_rows, uint8_t *out_ak, uint8_t *out_sum) {
  u64 acc[4] = {0, 0, 0, 0};
  for (int64_t i = 0; i < m; ++i) {
    u64 z[2], k[4], s[4], ak[4], zs[4];
    memcpy(z, z_rows + 16 * i, 16);
    memcpy(k, k_rows + 32 * i, 32);
    memcpy(s, s_rows + 32 * i, 32);
    mulmod_l(z, 2, k, 4, ak);
    memcpy(out_ak + 32 * i, ak, 32);
    mulmod_l(z, 2, s, 4, zs);
    addmod_l(acc, zs);
  }
  memcpy(out_sum, acc, 32);
}

// Pairwise 256-bit modular multiply: out_i = a_i * b_i mod L. Used by the
// aggregate-certificate lane (y_i = w_g * z_i, then y_i * k_i) where the
// scalars exceed the 128-bit z lane scalar_fold handles.
void scalar_mulmod(int64_t m, const uint8_t *a_rows, const uint8_t *b_rows,
                   uint8_t *out_rows) {
  for (int64_t i = 0; i < m; ++i) {
    u64 a[4], b[4], o[4];
    memcpy(a, a_rows + 32 * i, 32);
    memcpy(b, b_rows + 32 * i, 32);
    mulmod_l(a, 4, b, 4, o);
    memcpy(out_rows + 32 * i, o, 32);
  }
}

// Self-test hook: reduce one nx-limb value mod L (nx <= 9).
void reduce_mod_l_test(const uint8_t *x, int64_t nx, uint8_t *out) {
  u64 xl[9], o[4];
  memset(xl, 0, sizeof(xl));
  memcpy(xl, x, (size_t)nx * 8);
  reduce_mod_l(xl, (int)nx, o);
  memcpy(out, o, 32);
}

}  // extern "C"
