// Native storage engine: WAL-backed column-family byte store.
//
// The TPU-era equivalent of the reference's RocksDB C++ core behind
// typed-store (/root/reference/storage/, node/src/lib.rs:53-123). On-disk
// format is IDENTICAL to the Python engine in narwhal_tpu/storage.py —
// records of <u32 payload_len><u32 crc32><body>, body =
//   <u32 op_count> { <u8 op><u16 cf_name_len><name><u32 klen><key>
//                    [<u32 vlen><value>  if op==0 (put)] }
// — so a store written by either engine reopens under the other.
//
// Exposed as a C ABI consumed through ctypes (narwhal_tpu/native.py); the
// Python layer keeps column-family objects and the notify_read waiters, this
// layer owns the hash tables and the log.

#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>
#include <zlib.h>

namespace {

struct Engine {
    std::string path;            // empty = memory-only
    FILE* log = nullptr;
    std::unordered_map<std::string, std::unordered_map<std::string, std::string>> cfs;
    uint64_t dirty_bytes = 0;
    uint64_t append_count = 0;
    std::string dump_buf;        // last nse_dump result

    std::string log_path() const { return path + "/wal.log"; }
};

uint32_t rd_u32(const uint8_t* p) {
    uint32_t v;
    std::memcpy(&v, p, 4);
    return v;  // little-endian hosts only (x86/ARM/TPU VMs)
}

uint16_t rd_u16(const uint8_t* p) {
    uint16_t v;
    std::memcpy(&v, p, 2);
    return v;
}

void wr_u32(std::string& out, uint32_t v) {
    out.append(reinterpret_cast<const char*>(&v), 4);
}

// Apply one record body to the tables. Returns false on malformed input.
bool apply_body(Engine* e, const uint8_t* body, size_t len) {
    if (len < 4) return false;
    size_t pos = 0;
    uint32_t count = rd_u32(body + pos);
    pos += 4;
    for (uint32_t i = 0; i < count; i++) {
        if (pos + 3 > len) return false;
        uint8_t op = body[pos];
        uint16_t nlen = rd_u16(body + pos + 1);
        pos += 3;
        if (pos + nlen + 4 > len) return false;
        std::string name(reinterpret_cast<const char*>(body + pos), nlen);
        pos += nlen;
        uint32_t klen = rd_u32(body + pos);
        pos += 4;
        if (pos + klen > len) return false;
        std::string key(reinterpret_cast<const char*>(body + pos), klen);
        pos += klen;
        auto& cf = e->cfs[name];
        if (op == 0) {
            if (pos + 4 > len) return false;
            uint32_t vlen = rd_u32(body + pos);
            pos += 4;
            if (pos + vlen > len) return false;
            cf[key].assign(reinterpret_cast<const char*>(body + pos), vlen);
            pos += vlen;
        } else {
            cf.erase(key);
        }
    }
    return pos == len;
}

void replay(Engine* e) {
    FILE* f = std::fopen(e->log_path().c_str(), "rb");
    if (!f) return;
    std::fseek(f, 0, SEEK_END);
    long size = std::ftell(f);
    std::fseek(f, 0, SEEK_SET);
    std::vector<uint8_t> data(size > 0 ? size : 0);
    if (size > 0 && std::fread(data.data(), 1, size, f) != (size_t)size) {
        std::fclose(f);
        return;
    }
    std::fclose(f);
    size_t pos = 0, valid_end = 0;
    while (pos + 8 <= data.size()) {
        uint32_t plen = rd_u32(data.data() + pos);
        uint32_t crc = rd_u32(data.data() + pos + 4);
        size_t body_end = pos + 8 + plen;
        if (body_end > data.size()) break;
        const uint8_t* body = data.data() + pos + 8;
        if ((uint32_t)crc32(0, body, plen) != crc) break;
        if (!apply_body(e, body, plen)) break;
        pos = body_end;
        valid_end = pos;
    }
    if (valid_end < data.size()) {
        // torn tail: truncate to the last clean record boundary
        if (truncate(e->log_path().c_str(), (off_t)valid_end) != 0) {
            // best effort; appends still start from a clean in-memory state
        }
    }
}

uint64_t live_size(const Engine* e) {
    uint64_t total = 0;
    for (const auto& [name, cf] : e->cfs)
        for (const auto& [k, v] : cf) total += k.size() + v.size();
    return total;
}

void append_record(Engine* e, const uint8_t* body, uint32_t len) {
    if (!e->log) return;
    uint32_t crc = (uint32_t)crc32(0, body, len);
    std::fwrite(&len, 4, 1, e->log);
    std::fwrite(&crc, 4, 1, e->log);
    std::fwrite(body, 1, len, e->log);
    std::fflush(e->log);
    e->dirty_bytes += len;
    e->append_count += 1;
}

void compact(Engine* e) {
    if (!e->log) return;
    std::string tmp = e->log_path() + ".tmp";
    FILE* f = std::fopen(tmp.c_str(), "wb");
    if (!f) return;
    for (const auto& [name, cf] : e->cfs) {
        for (const auto& [key, value] : cf) {
            std::string body;
            wr_u32(body, 1);
            body.push_back((char)0);
            uint16_t nlen = (uint16_t)name.size();
            body.append(reinterpret_cast<const char*>(&nlen), 2);
            body += name;
            wr_u32(body, (uint32_t)key.size());
            body += key;
            wr_u32(body, (uint32_t)value.size());
            body += value;
            uint32_t plen = (uint32_t)body.size();
            uint32_t crc = (uint32_t)crc32(
                0, reinterpret_cast<const uint8_t*>(body.data()), plen);
            std::fwrite(&plen, 4, 1, f);
            std::fwrite(&crc, 4, 1, f);
            std::fwrite(body.data(), 1, plen, f);
        }
    }
    std::fclose(f);
    std::fclose(e->log);
    std::rename(tmp.c_str(), e->log_path().c_str());
    e->log = std::fopen(e->log_path().c_str(), "ab");
    e->dirty_bytes = live_size(e);
}

}  // namespace

extern "C" {

void* nse_open(const char* path) {
    Engine* e = new Engine();
    if (path && path[0]) {
        e->path = path;
        replay(e);
        e->log = std::fopen(e->log_path().c_str(), "ab");
        if (!e->log) {
            delete e;
            return nullptr;
        }
    }
    return e;
}

// body uses the record-body format; applied to tables and appended to the WAL.
int nse_write_batch(void* h, const uint8_t* body, uint32_t len) {
    Engine* e = static_cast<Engine*>(h);
    if (!apply_body(e, body, len)) return -1;
    append_record(e, body, len);
    if (e->dirty_bytes > (64u << 20) && e->append_count % 4096 == 0 &&
        e->dirty_bytes > 2 * live_size(e)) {
        compact(e);
    }
    return 0;
}

// Returns 1 and sets (*val, *vlen) on hit; pointer valid until next mutation.
int nse_get(void* h, const char* cf, const uint8_t* key, uint32_t klen,
            const uint8_t** val, uint32_t* vlen) {
    Engine* e = static_cast<Engine*>(h);
    auto it = e->cfs.find(cf);
    if (it == e->cfs.end()) return 0;
    auto kit = it->second.find(std::string(reinterpret_cast<const char*>(key), klen));
    if (kit == it->second.end()) return 0;
    *val = reinterpret_cast<const uint8_t*>(kit->second.data());
    *vlen = (uint32_t)kit->second.size();
    return 1;
}

int nse_contains(void* h, const char* cf, const uint8_t* key, uint32_t klen) {
    Engine* e = static_cast<Engine*>(h);
    auto it = e->cfs.find(cf);
    if (it == e->cfs.end()) return 0;
    return it->second.count(std::string(reinterpret_cast<const char*>(key), klen))
               ? 1
               : 0;
}

uint64_t nse_len(void* h, const char* cf) {
    Engine* e = static_cast<Engine*>(h);
    auto it = e->cfs.find(cf);
    return it == e->cfs.end() ? 0 : it->second.size();
}

// Serialize a whole column family as { <u32 klen><key><u32 vlen><val> }*;
// returns the buffer (valid until the next nse_dump/nse_close) via out args.
void nse_dump(void* h, const char* cf, const uint8_t** buf, uint64_t* len) {
    Engine* e = static_cast<Engine*>(h);
    e->dump_buf.clear();
    auto it = e->cfs.find(cf);
    if (it != e->cfs.end()) {
        for (const auto& [key, value] : it->second) {
            wr_u32(e->dump_buf, (uint32_t)key.size());
            e->dump_buf += key;
            wr_u32(e->dump_buf, (uint32_t)value.size());
            e->dump_buf += value;
        }
    }
    *buf = reinterpret_cast<const uint8_t*>(e->dump_buf.data());
    *len = e->dump_buf.size();
}

void nse_compact(void* h) { compact(static_cast<Engine*>(h)); }

// Close only the WAL: tables stay readable (parity with the Python engine,
// whose close() stops appends but keeps serving reads).
void nse_close_log(void* h) {
    Engine* e = static_cast<Engine*>(h);
    if (e->log) {
        std::fclose(e->log);
        e->log = nullptr;
    }
}

void nse_close(void* h) {
    Engine* e = static_cast<Engine*>(h);
    if (e->log) std::fclose(e->log);
    delete e;
}

}  // extern "C"
