"""Headline benchmark: consensus DAG ordering throughput, device vs host.

Runs the Bullshark commit path over identical synthetic certificate streams
through the host engine (pointer-chasing, like
/root/reference/consensus/src/utils.rs) and the TPU engine (adjacency-tensor
walks, narwhal_tpu/tpu/dag_kernels.py), mirroring the reference's criterion
bench `consensus/benches/process_certificates.rs:18-80` (committee of 2f+1
optimal rounds; no stored reference numbers exist for it, so `vs_baseline`
is the device/host ratio measured in this same process).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import random
import time

COMMITTEE = 20
ROUNDS = 120
GC = 50


def _stream(size: int, rounds: int):
    from narwhal_tpu.fixtures import CommitteeFixture, make_certificates
    from narwhal_tpu.types import Certificate

    f = CommitteeFixture(size=size)
    genesis = {c.digest for c in Certificate.genesis(f.committee)}
    certs, _ = make_certificates(
        f.committee, 1, rounds, genesis, failure_probability=0.1,
        rng=random.Random(7),
    )
    return f, certs


def _drive(engine_factory, fixture, certs) -> tuple[float, int]:
    from narwhal_tpu.consensus import ConsensusState
    from narwhal_tpu.types import Certificate

    engine = engine_factory()
    state = ConsensusState(Certificate.genesis(fixture.committee))
    committed = 0
    index = 0
    t0 = time.perf_counter()
    for c in certs:
        out = engine.process_certificate(state, index, c)
        index += len(out)
        committed += len(out)
    dt = time.perf_counter() - t0
    assert committed > 0, "bench stream produced no commits"
    return len(certs) / dt, committed


def main() -> None:
    from narwhal_tpu.consensus import Bullshark
    from narwhal_tpu.stores import NodeStorage
    from narwhal_tpu.tpu.dag_kernels import TpuBullshark

    fixture, certs = _stream(COMMITTEE, ROUNDS)

    def host():
        return Bullshark(fixture.committee, NodeStorage(None).consensus_store, GC)

    def device():
        return TpuBullshark(fixture.committee, NodeStorage(None).consensus_store, GC)

    # Warmup (jit compile) on a short prefix, then timed runs.
    warm_f, warm_certs = _stream(COMMITTEE, 10)
    _drive(device, warm_f, warm_certs)

    host_rate, host_committed = _drive(host, fixture, certs)
    dev_rate, dev_committed = _drive(device, fixture, certs)
    assert host_committed == dev_committed, (host_committed, dev_committed)

    print(
        json.dumps(
            {
                "metric": "bullshark_ordering_certs_per_s",
                "value": round(dev_rate, 1),
                "unit": "certs/s",
                "vs_baseline": round(dev_rate / host_rate, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
