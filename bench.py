"""Headline benchmark: ed25519 signature-verification throughput per chip.

The north-star metric (BASELINE.json: ">=4x Certificate verify throughput;
sig-verify/s/chip"): the reference's per-node throughput ceiling is set by
certificate signature verification (/root/reference/types/src/primary.rs:
487-537 via ed25519-dalek/BLS). We measure verified signatures per second:

  baseline: the host library loop (OpenSSL via `cryptography`, the exact
            code the CPU fallback runs) on this machine's CPU,
  value:    the TPU batch kernel (narwhal_tpu/tpu/ed25519.py) on the one
            real chip, end-to-end including host packing + transfers.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import time

BATCH = 32768
ROUNDS = 3


def main() -> None:
    # Persist compiled kernels across runs (first compile is minutes; the
    # cache makes every later bench/boot start in seconds). Routed through
    # enable_compilation_cache for the per-platform subdirectory.
    import jax  # noqa: F401

    from narwhal_tpu.tpu import enable_compilation_cache

    enable_compilation_cache()

    from narwhal_tpu.crypto import KeyPair, _host_batch_verify
    from narwhal_tpu.tpu.verifier import TpuVerifier

    keys = [KeyPair.generate() for _ in range(32)]
    items = []
    for i in range(BATCH):
        kp = keys[i % len(keys)]
        msg = b"bench" + i.to_bytes(8, "big") * 4  # digest-sized message
        items.append((kp.public, msg, kp.sign(msg)))

    # Host baseline (single-threaded OpenSSL loop, like the fallback path).
    t0 = time.perf_counter()
    host_ok = _host_batch_verify(items)
    host_dt = time.perf_counter() - t0
    assert all(host_ok)
    host_rate = BATCH / host_dt

    verifier = TpuVerifier(max_bucket=BATCH)
    out = verifier(items)  # warmup: compile + first dispatch
    assert out == host_ok, "kernel disagrees with host library"

    # Pipelined steady state: submits (host packing + async dispatch) run on
    # a worker thread while the main thread collects — the collect's device
    # readback wait releases the GIL, so packing of batch N+1 overlaps both
    # the readback of batch N and the device compute of the queued batches.
    # This is how the node's AsyncVerifierPool drives the chip under load.
    from concurrent.futures import ThreadPoolExecutor

    # The tunneled device's round-trip latency drifts minute to minute, so a
    # single window can under- or over-state the chip by 30%+. Measure
    # several sustained windows and report the MEDIAN window throughput,
    # with the observed spread alongside so the number's stability is part
    # of the artifact (VERDICT r3: a one-window headline is not
    # reproducible).
    depth = 3
    window = 4  # batches per measurement window
    windows = 7  # odd: rates[len//2] is the true median window
    with ThreadPoolExecutor(max_workers=1) as pool:
        futures = [pool.submit(verifier.submit, items) for _ in range(depth)]
        rates = []
        for _ in range(windows):
            t0 = time.perf_counter()
            for _ in range(window):
                out = verifier.collect(futures.pop(0).result())
                assert all(out)
                futures.append(pool.submit(verifier.submit, items))
            rates.append(window * BATCH / (time.perf_counter() - t0))
        for f in futures:
            verifier.collect(f.result())
    rates.sort()
    tpu_rate = rates[len(rates) // 2]
    rate_spread = (rates[0], rates[-1])

    # Device-only rate via an on-device iteration chain (two-point
    # differencing cancels the flat link latency): the chip's stable
    # capability, independent of the host link's minute-to-minute bandwidth
    # drift that the pipelined end-to-end number is exposed to.
    import jax.numpy as jnp
    from jax import lax

    from narwhal_tpu.tpu import ed25519 as kern

    import numpy as np

    rng = np.random.default_rng(0)
    # Match the production e2e bucket: the msm doubling chain is shared
    # across the whole bucket, so per-item device throughput IMPROVES with
    # bucket size (8192 understated the 32k-bucket rate by ~2x).
    dev_b = BATCH
    a_y = jnp.asarray(rng.integers(0, 1 << 13, (dev_b, 20), dtype=np.int32))
    sign = jnp.zeros((dev_b,), jnp.int32)
    dig = jnp.asarray(rng.integers(0, 16, (dev_b, 64), dtype=np.int32))

    def repeat_kernel(reps):
        @jax.jit
        def f(a_y, sign, dig):
            def body(i, acc):
                # Perturb per-iteration but stay in the 4-bit digit domain
                # the kernel's select tree assumes.
                oks, okc = kern.verify_batch_kernel(
                    a_y, sign, a_y, sign, (dig + (i & 1)) & 15, dig
                )
                return acc + jnp.sum(oks.astype(jnp.int32)) + jnp.sum(
                    okc.astype(jnp.int32)
                )
            return lax.fori_loop(0, reps, body, jnp.int32(0))
        return f

    def timed(fn, *args, iters=3):
        """(median, max-min noise) over `iters` runs after a warmup."""
        ts = []
        int(fn(*args))  # warm/compile
        for _ in range(iters):
            t0 = time.perf_counter()
            int(fn(*args))
            ts.append(time.perf_counter() - t0)
        ts.sort()
        return ts[len(ts) // 2], ts[-1] - ts[0]

    def chain_rate(make_fn, per_iter, args=None, spreads=(10, 30)):
        """Two-point differenced on-device iteration chain -> items/s."""
        args = (a_y, sign, dig) if args is None else args
        small_fn = make_fn(2)
        t_small, noise_small = timed(small_fn, *args)
        for spread in spreads:  # widen if link noise swamps the delta
            t_big, noise_big = timed(make_fn(2 + spread), *args)
            delta = t_big - t_small
            # Sanity: the delta must stand clear of the observed timing
            # noise (no assumption about absolute kernel speed).
            if delta > 4 * max(noise_small, noise_big, 1e-3):
                return spread * per_iter / delta
        return None

    item_rate = chain_rate(repeat_kernel, dev_b)

    # The production batch path: one random-linear-combination accumulate
    # per batch (msm_accumulate_kernel) — the shared doubling chain's
    # amortization is the round-3 throughput multiple. The per-batch host
    # Horner epilogue (~300 bigint point ops on the [4, 20, 64] readback)
    # is timed separately: in the pipelined flow it overlaps the next
    # batch's device compute, so steady state is bounded by max(device,
    # epilogue), reported below as the effective rate.
    z_dig = jnp.asarray(rng.integers(0, 16, (dev_b, 32), dtype=np.int32))

    def repeat_msm(reps):
        @jax.jit
        def f(a_y, sign, dig):
            def body(i, acc):
                va, vr, valid = kern.msm_accumulate_kernel(
                    a_y, sign, a_y, sign, (dig + (i & 1)) & 15, z_dig
                )
                return acc + va[0, 0, 0] + vr[0, 0, 0] + jnp.sum(
                    valid.astype(jnp.int32)
                )
            return lax.fori_loop(0, reps, body, jnp.int32(0))
        return f

    msm_accum_rate = chain_rate(repeat_msm, dev_b)

    from narwhal_tpu.tpu.verifier import msm_epilogue_check

    va_host, vr_host = (
        np.asarray(v)
        for v in kern.msm_accumulate_kernel(
            np.asarray(a_y), np.asarray(sign), np.asarray(a_y), np.asarray(sign),
            np.asarray(dig), np.asarray(z_dig),
        )[:2]
    )
    t0 = time.perf_counter()
    for _ in range(5):
        msm_epilogue_check(va_host, vr_host, 12345, kern)
    epi_dt = (time.perf_counter() - t0) / 5

    # Roofline accounting (VERDICT r4 item 2): measure the raw VPU fe_mul
    # rate at the kernel's own lane width, derive the analytic fe_mul-
    # equivalent cost per signature, and report achieved-vs-roofline so
    # "fast" is falsifiable.
    fe_b = 8192
    fe_a = jnp.asarray(rng.integers(0, 1 << 13, (kern.NLIMB, fe_b), dtype=np.int32))
    fe_bv = jnp.asarray(rng.integers(0, 1 << 13, (kern.NLIMB, fe_b), dtype=np.int32))

    def repeat_fe(reps):
        @jax.jit
        def f(a, b):
            def body(i, acc):
                c = kern.fe_mul(a + (i & 1), b)
                return acc + c[0]
            # Scalar result: timed() forces with int(...), which rejects
            # non-scalar arrays.
            return jnp.sum(lax.fori_loop(0, reps, body, jnp.zeros((fe_b,), jnp.int32)))
        return f

    fe_rate = chain_rate(repeat_fe, fe_b, args=(fe_a, fe_bv), spreads=(4096, 16384))
    muls_per_sig = kern.msm_field_muls_per_signature(dev_b)
    utilization = (
        round(msm_accum_rate * muls_per_sig / fe_rate, 3)
        if (msm_accum_rate and fe_rate)
        else None
    )
    # Noisy-link fallback: if the msm chain timing was inconclusive, the
    # per-item kernel's stable rate is still a valid device-only headline —
    # but label its source so nobody records an item-kernel number as the
    # msm batch rate.
    if msm_accum_rate:
        device_rate = min(msm_accum_rate, dev_b / epi_dt)
        device_source = "msm-batch"
    else:
        device_rate = item_rate
        device_source = "per-item-kernel-fallback"

    print(
        json.dumps(
            {
                "metric": "ed25519_verify_per_s_per_chip",
                "value": round(tpu_rate, 1),
                "unit": "verifies/s",
                "vs_baseline": round(tpu_rate / host_rate, 3),
                "window_min_per_s": round(rate_spread[0], 1),
                "window_max_per_s": round(rate_spread[1], 1),
                "device_only_per_s": round(device_rate, 1) if device_rate else None,
                "device_only_vs_baseline": (
                    round(device_rate / host_rate, 3) if device_rate else None
                ),
                "device_only_per_item_kernel_per_s": (
                    round(item_rate, 1) if item_rate else None
                ),
                "device_only_source": device_source,
                "msm_accumulate_per_s": (
                    round(msm_accum_rate, 1) if msm_accum_rate else None
                ),
                "msm_host_epilogue_ms_per_batch": round(epi_dt * 1000, 2),
                "fe_mul_per_s": round(fe_rate, 1) if fe_rate else None,
                "fe_muls_per_verify": round(muls_per_sig, 1),
                "vpu_utilization_vs_fe_mul_roofline": utilization,
                "host_per_s": round(host_rate, 1),
                "note": "value = median pipelined e2e window (of "
                f"{windows} windows x {window} batches) incl. host packing "
                "(native/scalar_ops.cpp) and tunneled transfers; "
                "window_min/max give the observed spread; device_only = the "
                "production batch path's steady-state rate min(device msm "
                f"accumulate, host Horner epilogue) at batch {BATCH} "
                "(random-linear-combination check); "
                "device_only_per_item_kernel = the per-item Straus kernel "
                "(the fallback path, round 2's headline); "
                "vpu_utilization_vs_fe_mul_roofline = msm accumulate rate x "
                "analytic fe-mul-equivalents per verify "
                "(ed25519.msm_field_muls_per_signature documents the "
                "derivation) / the measured raw fe_mul chain rate",
            }
        )
    )


if __name__ == "__main__":
    import sys

    if "--multichip" in sys.argv:
        # The multi-chip device-plane leg: per-device-count sweep (1/2/4/8
        # virtual devices, each in its own subprocess) ->
        # benchmark/results/multichip_scaling.json with per-(kernel, mesh
        # shape) compile walls. See benchmark/multichip.py.
        from benchmark.multichip import main as multichip_main

        multichip_main([a for a in sys.argv[1:] if a != "--multichip"])
    elif "--fuzz" in sys.argv:
        # The FaultPlan fuzzer: seeded random fault schedules under the
        # simnet safety/liveness oracles, failures shrunk to minimal
        # reproducers, one perf-ledger record per campaign. See
        # narwhal_tpu/simnet/fuzz.py.
        from narwhal_tpu.simnet.fuzz import main as fuzz_main

        raise SystemExit(
            fuzz_main([a for a in sys.argv[1:] if a != "--fuzz"])
        )
    else:
        main()
