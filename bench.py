"""Headline benchmark: ed25519 signature-verification throughput per chip.

The north-star metric (BASELINE.json: ">=4x Certificate verify throughput;
sig-verify/s/chip"): the reference's per-node throughput ceiling is set by
certificate signature verification (/root/reference/types/src/primary.rs:
487-537 via ed25519-dalek/BLS). We measure verified signatures per second:

  baseline: the host library loop (OpenSSL via `cryptography`, the exact
            code the CPU fallback runs) on this machine's CPU,
  value:    the TPU batch kernel (narwhal_tpu/tpu/ed25519.py) on the one
            real chip, end-to-end including host packing + transfers.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import time

BATCH = 8192
ROUNDS = 4


def main() -> None:
    # Persist compiled kernels across runs (first compile is minutes; the
    # cache makes every later bench/boot start in seconds).
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR", os.path.join(os.path.dirname(__file__), ".jax_cache")
    )
    import jax

    jax.config.update("jax_compilation_cache_dir", os.environ["JAX_COMPILATION_CACHE_DIR"])

    from narwhal_tpu.crypto import KeyPair, _host_batch_verify
    from narwhal_tpu.tpu.verifier import TpuVerifier

    keys = [KeyPair.generate() for _ in range(32)]
    items = []
    for i in range(BATCH):
        kp = keys[i % len(keys)]
        msg = b"bench" + i.to_bytes(8, "big") * 4  # digest-sized message
        items.append((kp.public, msg, kp.sign(msg)))

    # Host baseline (single-threaded OpenSSL loop, like the fallback path).
    t0 = time.perf_counter()
    host_ok = _host_batch_verify(items)
    host_dt = time.perf_counter() - t0
    assert all(host_ok)
    host_rate = BATCH / host_dt

    verifier = TpuVerifier(max_bucket=BATCH)
    out = verifier(items)  # warmup: compile + first dispatch
    assert out == host_ok, "kernel disagrees with host library"

    t0 = time.perf_counter()
    for _ in range(ROUNDS):
        out = verifier(items)
    tpu_dt = (time.perf_counter() - t0) / ROUNDS
    assert all(out)
    tpu_rate = BATCH / tpu_dt

    print(
        json.dumps(
            {
                "metric": "ed25519_verify_per_s_per_chip",
                "value": round(tpu_rate, 1),
                "unit": "verifies/s",
                "vs_baseline": round(tpu_rate / host_rate, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
