"""Headline benchmark: ed25519 signature-verification throughput per chip.

The north-star metric (BASELINE.json: ">=4x Certificate verify throughput;
sig-verify/s/chip"): the reference's per-node throughput ceiling is set by
certificate signature verification (/root/reference/types/src/primary.rs:
487-537 via ed25519-dalek/BLS). We measure verified signatures per second:

  baseline: the host library loop (OpenSSL via `cryptography`, the exact
            code the CPU fallback runs) on this machine's CPU,
  value:    the TPU batch kernel (narwhal_tpu/tpu/ed25519.py) on the one
            real chip, end-to-end including host packing + transfers.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import time

BATCH = 32768
ROUNDS = 3


def main() -> None:
    # Persist compiled kernels across runs (first compile is minutes; the
    # cache makes every later bench/boot start in seconds).
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR", os.path.join(os.path.dirname(__file__), ".jax_cache")
    )
    import jax

    jax.config.update("jax_compilation_cache_dir", os.environ["JAX_COMPILATION_CACHE_DIR"])

    from narwhal_tpu.crypto import KeyPair, _host_batch_verify
    from narwhal_tpu.tpu.verifier import TpuVerifier

    keys = [KeyPair.generate() for _ in range(32)]
    items = []
    for i in range(BATCH):
        kp = keys[i % len(keys)]
        msg = b"bench" + i.to_bytes(8, "big") * 4  # digest-sized message
        items.append((kp.public, msg, kp.sign(msg)))

    # Host baseline (single-threaded OpenSSL loop, like the fallback path).
    t0 = time.perf_counter()
    host_ok = _host_batch_verify(items)
    host_dt = time.perf_counter() - t0
    assert all(host_ok)
    host_rate = BATCH / host_dt

    verifier = TpuVerifier(max_bucket=BATCH)
    out = verifier(items)  # warmup: compile + first dispatch
    assert out == host_ok, "kernel disagrees with host library"

    # Pipelined steady state: submits (host packing + async dispatch) run on
    # a worker thread while the main thread collects — the collect's device
    # readback wait releases the GIL, so packing of batch N+1 overlaps both
    # the readback of batch N and the device compute of the queued batches.
    # This is how the node's AsyncVerifierPool drives the chip under load.
    from concurrent.futures import ThreadPoolExecutor

    depth = 3
    rounds = ROUNDS * 2
    with ThreadPoolExecutor(max_workers=1) as pool:
        futures = [pool.submit(verifier.submit, items) for _ in range(depth)]
        t0 = time.perf_counter()
        done = 0
        for _ in range(rounds):
            out = verifier.collect(futures.pop(0).result())
            assert all(out)
            done += BATCH
            futures.append(pool.submit(verifier.submit, items))
        tpu_dt = (time.perf_counter() - t0) / done * BATCH
        for f in futures:
            verifier.collect(f.result())
    tpu_rate = BATCH / tpu_dt

    print(
        json.dumps(
            {
                "metric": "ed25519_verify_per_s_per_chip",
                "value": round(tpu_rate, 1),
                "unit": "verifies/s",
                "vs_baseline": round(tpu_rate / host_rate, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
