"""gRPC public plane: Validator / Proposer / Configuration on the primary and
Transactions on the worker.

Reference: the reference's client-facing edges are tonic gRPC against
types/proto/narwhal.proto:127-160 (built in types/build.rs:42-121, mounted at
primary/src/grpc_server/mod.rs:25-106 and worker/src/worker.rs:369-423) — any
language can submit transactions or drive external consensus. This module
serves the same services from `narwhal_tpu/proto/narwhal.proto` using
grpc.aio with hand-rolled method handlers (no grpc_tools codegen needed; the
message classes come from protoc --python_out).

The internal typed-RPC surface (api_server.ConsensusApi, the worker's
tx_server) remains the high-throughput path; gRPC is the interoperable edge,
exactly as anemo (internal) vs tonic (public) split in the reference.
"""

from __future__ import annotations

import logging

import grpc

from .consensus.dag import ValidatorDagError
from .proto import narwhal_pb2 as pb

logger = logging.getLogger("narwhal.grpc")

_PKG = "narwhal"


def _unary(handler, request_cls):
    async def call(request_bytes, context):
        request = request_cls.FromString(request_bytes)
        reply = await handler(request, context)
        return reply.SerializeToString()

    return grpc.unary_unary_rpc_method_handler(
        call, request_deserializer=None, response_serializer=None
    )


def _raw_unary(handler):
    """Unary method whose request/response are raw bytes end to end — the
    telemetry plane's scrape text and flight-recorder JSON need no protoc
    message types, matching the raw-bytes generic-handler idiom above."""

    async def call(request_bytes, context):
        return await handler(request_bytes, context)

    return grpc.unary_unary_rpc_method_handler(
        call, request_deserializer=None, response_serializer=None
    )


def _stream_in(handler, request_cls):
    async def call(request_iter, context):
        async def typed():
            async for raw in request_iter:
                yield request_cls.FromString(raw)

        reply = await handler(typed(), context)
        return reply.SerializeToString()

    return grpc.stream_unary_rpc_method_handler(
        call, request_deserializer=None, response_serializer=None
    )


class _Service:
    """One gRPC service assembled from (method name -> handler) pairs."""

    def __init__(self, name: str, methods: dict):
        self.name = f"{_PKG}.{name}"
        self.methods = methods

    def generic_handler(self) -> grpc.GenericRpcHandler:
        return grpc.method_handlers_generic_handler(self.name, self.methods)


class GrpcPublicApi:
    """The primary's public consensus API over gRPC, backed by the same
    seams as the typed-RPC ConsensusApi: BlockWaiter (collection fetch),
    BlockRemover (deletion fan-out), the external Dag (causal reads), and
    the committee (configuration)."""

    def __init__(
        self,
        name,
        committee,
        block_waiter,
        block_remover,
        dag=None,
        primary_address: str = "",
        registry=None,  # metrics.Registry: Telemetry.Scrape source
        tracer=None,  # tracing.Tracer: Telemetry.DumpFlightRecorder source
    ):
        self.name = name
        self.committee = committee
        self.block_waiter = block_waiter
        self.block_remover = block_remover
        self.dag = dag
        self.primary_address = primary_address
        self.registry = registry
        self.tracer = tracer
        self._server: grpc.aio.Server | None = None
        self.address: str = ""

    def set_primary_address(self, address: str) -> None:
        """Single write seam for the advertised primary address: the
        bound (possibly ephemeral) port only exists after Primary.spawn,
        so Node installs it here rather than poking the attribute."""
        self.primary_address = address

    # -- Validator ---------------------------------------------------------
    async def _get_collections(self, request, context):
        from .primary.block_waiter import BlockError, BlockResponse

        results = await self.block_waiter.get_blocks(list(request.collection_ids))
        out = pb.GetCollectionsResponse()
        for digest, res in zip(request.collection_ids, results):
            item = out.results.add(collection_id=digest)
            if isinstance(res, BlockResponse):
                for batch_digest, batch in res.batches:
                    item.batches.add(
                        digest=batch_digest, transactions=list(batch.transactions)
                    )
            elif isinstance(res, BlockError):
                item.error = res.kind
            else:
                item.error = "BatchError"
        return out

    async def _remove_collections(self, request, context):
        from .primary.block_remover import BlockRemoverError

        try:
            await self.block_remover.remove_blocks(list(request.collection_ids))
        except BlockRemoverError as e:
            await context.abort(grpc.StatusCode.INTERNAL, f"remove failed: {e.kind}")
        return pb.Empty()

    async def _read_causal(self, request, context):
        if self.dag is None:
            await context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                "ReadCausal requires external consensus (the Dag service)",
            )
        try:
            digests = await self.dag.read_causal(request.collection_id)
        except ValidatorDagError as e:
            await context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        except Exception as e:
            # A dag-internal failure (device dispatch, shutdown race) is not
            # the caller naming an unknown digest: surface it as INTERNAL so
            # clients retry elsewhere instead of treating data as absent.
            logger.exception("ReadCausal failed")
            await context.abort(grpc.StatusCode.INTERNAL, str(e))
        return pb.ReadCausalResponse(collection_ids=list(digests))

    # -- Proposer ----------------------------------------------------------
    async def _rounds(self, request, context):
        if self.dag is None:
            await context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                "Rounds requires external consensus (the Dag service)",
            )
        try:
            oldest, newest = await self.dag.rounds(bytes(request.public_key))
        except ValidatorDagError as e:
            await context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        except Exception as e:
            logger.exception("Rounds failed")
            await context.abort(grpc.StatusCode.INTERNAL, str(e))
        return pb.RoundsResponse(oldest_round=oldest, newest_round=newest)

    async def _node_read_causal(self, request, context):
        if self.dag is None:
            await context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                "NodeReadCausal requires external consensus (the Dag service)",
            )
        try:
            digests = await self.dag.node_read_causal(
                bytes(request.public_key), request.round
            )
        except ValidatorDagError as e:
            await context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        except Exception as e:
            logger.exception("NodeReadCausal failed")
            await context.abort(grpc.StatusCode.INTERNAL, str(e))
        return pb.NodeReadCausalResponse(collection_ids=list(digests))

    # -- Configuration -----------------------------------------------------
    async def _new_epoch(self, request, context):
        # Reference parity: Configuration::new_epoch is unimplemented
        # (primary/src/grpc_server/configuration.rs:78-81).
        await context.abort(grpc.StatusCode.UNIMPLEMENTED, "Not Implemented!")

    async def _new_network_info(self, request, context):
        if request.epoch_number != self.committee.epoch:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"epoch {request.epoch_number} != current {self.committee.epoch}",
            )
        updates = {
            bytes(v.public_key): (v.stake_weight, v.primary_address)
            for v in request.validators
        }
        try:
            self.committee.update_primary_network_info(updates)
        except Exception as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        return pb.Empty()

    async def _get_primary_address(self, request, context):
        return pb.GetPrimaryAddressResponse(primary_address=self.primary_address)

    # -- Telemetry ---------------------------------------------------------
    async def _scrape(self, request_bytes, context):
        if self.registry is None:
            await context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                "Telemetry.Scrape: node mounted no registry",
            )
        return self.registry.render().encode()

    async def _dump_flight(self, request_bytes, context):
        import json

        if self.tracer is None:
            await context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                "Telemetry.DumpFlightRecorder: node mounted no tracer",
            )
        # Request body: optional 4-byte little-endian max_events cap.
        max_events = None
        if len(request_bytes) >= 4:
            cap = int.from_bytes(request_bytes[:4], "little")
            max_events = cap or None
        dump = self.tracer.dump(max_events)
        return json.dumps(dump, sort_keys=True, separators=(",", ":")).encode()

    # -- lifecycle ---------------------------------------------------------
    def _services(self) -> list[_Service]:
        return [
            _Service(
                "Validator",
                {
                    "GetCollections": _unary(
                        self._get_collections, pb.CollectionRequest
                    ),
                    "RemoveCollections": _unary(
                        self._remove_collections, pb.CollectionRequest
                    ),
                    "ReadCausal": _unary(self._read_causal, pb.ReadCausalRequest),
                },
            ),
            _Service(
                "Proposer",
                {
                    "Rounds": _unary(self._rounds, pb.RoundsRequest),
                    "NodeReadCausal": _unary(
                        self._node_read_causal, pb.NodeReadCausalRequest
                    ),
                },
            ),
            _Service(
                "Configuration",
                {
                    "NewEpoch": _unary(self._new_epoch, pb.NewEpochRequest),
                    "NewNetworkInfo": _unary(
                        self._new_network_info, pb.NewNetworkInfoRequest
                    ),
                    "GetPrimaryAddress": _unary(self._get_primary_address, pb.Empty),
                },
            ),
            _Service(
                "Telemetry",
                {
                    "Scrape": _raw_unary(self._scrape),
                    "DumpFlightRecorder": _raw_unary(self._dump_flight),
                },
            ),
        ]

    async def spawn(self, address: str) -> str:
        server = grpc.aio.server()
        for svc in self._services():
            server.add_generic_rpc_handlers((svc.generic_handler(),))
        port = server.add_insecure_port(address)
        await server.start()
        host = address.rsplit(":", 1)[0]
        self.address = f"{host}:{port}"
        self._server = server
        logger.info("gRPC public API listening on %s", self.address)
        return self.address

    async def shutdown(self) -> None:
        if self._server is not None:
            await self._server.stop(grace=0.5)
            self._server = None


class GrpcTransactions:
    """Worker-side client transaction ingest over gRPC
    (Transactions.SubmitTransaction / SubmitTransactionStream), feeding the
    same batch-maker channel as the typed tx_server — and gated by the same
    admission control: overload aborts with StatusCode.RESOURCE_EXHAUSTED
    instead of queueing unboundedly."""

    def __init__(self, tx_batch_maker, metrics=None, gate=None):
        self.tx_batch_maker = tx_batch_maker
        self.metrics = metrics
        self.gate = gate  # pacing.IngestGate, shared with the typed ingest
        self._server: grpc.aio.Server | None = None
        self.address: str = ""

    async def _admit(self, context) -> None:
        if self.gate is None:
            return
        from .pacing import IngestOverloadError

        try:
            await self.gate.admit()
        except IngestOverloadError as e:
            await context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))

    async def _submit(self, request, context):
        await self._admit(context)
        tx = request.transaction
        frame = len(tx).to_bytes(4, "little") + tx
        if self.metrics is not None:
            self.metrics.tx_received.inc()
        await self.tx_batch_maker.send((1, frame))
        return pb.Empty()

    async def _submit_stream(self, request_iter, context):
        async for request in request_iter:
            await self._submit(request, context)
        return pb.Empty()

    async def spawn(self, address: str) -> str:
        server = grpc.aio.server()
        server.add_generic_rpc_handlers(
            (
                _Service(
                    "Transactions",
                    {
                        "SubmitTransaction": _unary(self._submit, pb.Transaction),
                        "SubmitTransactionStream": _stream_in(
                            self._submit_stream, pb.Transaction
                        ),
                    },
                ).generic_handler(),
            )
        )
        port = server.add_insecure_port(address)
        await server.start()
        host = address.rsplit(":", 1)[0]
        self.address = f"{host}:{port}"
        self._server = server
        logger.info("gRPC Transactions listening on %s", self.address)
        return self.address

    async def shutdown(self) -> None:
        if self._server is not None:
            await self._server.stop(grace=0.5)
            self._server = None
