"""simnet — deterministic adversary & fault-simulation harness.

A seeded, virtual-clock, socket-free network for whole-committee
simulation: the production protocol stack (actors, framing, handshakes,
AEAD) runs unmodified over an in-memory fabric behind the
`network/transport.py` seam, driven by an event loop whose time is
simulated (`SimLoop`). Scenarios — partitions, link jitter/loss, crashes,
worker loss, byzantine equivocation, epoch reconfiguration under traffic —
are declared as a `FaultPlan` and replay bit-identically per seed.

    from narwhal_tpu.simnet import (
        FaultPlan, Partition, Crash, Equivocate, run_scenario, oracles,
    )

    result = run_scenario(nodes=4, duration=5.0, plan=FaultPlan(
        seed=7, events=(Partition(at=1.0, heal=3.0, groups=((0, 1), (2, 3))),),
    ))
    oracles.assert_safety(result.commits)
    oracles.assert_liveness(result.rounds,
                            result.round_marks["heal@3.0"], min_rounds=2)

See README § "Fault simulation" for the grammar, oracle semantics, and the
determinism guarantees.
"""

from . import fuzz, oracles
from .byzantine import Equivocator
from .clock import SimDeadlockError, SimLoop
from .cluster import SimCluster, node_id
from .fabric import CURRENT_NODE, EventLog, SimFabric
from .plan import (
    Crash,
    Equivocate,
    FaultPlan,
    LinkFault,
    LinkSpec,
    Partition,
    Reconfigure,
    WorkerLoss,
)
from .scenario import ScenarioResult, run_scenario

__all__ = [
    "CURRENT_NODE",
    "Crash",
    "Equivocate",
    "Equivocator",
    "EventLog",
    "FaultPlan",
    "LinkFault",
    "LinkSpec",
    "Partition",
    "Reconfigure",
    "ScenarioResult",
    "SimCluster",
    "SimDeadlockError",
    "SimFabric",
    "SimLoop",
    "WorkerLoss",
    "fuzz",
    "node_id",
    "oracles",
    "run_scenario",
]
