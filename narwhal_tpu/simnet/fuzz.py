"""FaultPlan fuzzer: seeded random fault schedules under the oracles.

The simnet perf work (co-hosted crypto plane, batched fabric delivery,
inline frame drains) exists to buy adversarial COVERAGE: a 4-node seeded
scenario now costs a few wall seconds, so instead of a handful of
hand-written plans the repo can sweep hundreds of randomly drawn
crash/partition/jitter/equivocation/reconfiguration schedules per run and
hold every one to the safety + liveness oracles.

Three pieces:

* `generate_plan(seed)` — a deterministic draw from the FaultPlan DSL
  (simnet/plan.py). Plans are quorum-survivable by construction: at most
  f = (n-1)//3 nodes are byzantine or permanently crashed, partitions
  always heal, and every disruption resolves with enough virtual runway
  left that the end-of-run liveness check is a real assertion rather than
  a coin flip. The generator seeds `random.Random` with a string (seed
  derivation is PYTHONHASHSEED-independent), so seed k names the same
  plan on every host.

* `check_plan(plan)` — run the scenario, then `assert_safety` over honest
  commits and `assert_liveness` over honest non-crashed nodes. Any
  exception out of the scenario itself (a SimDeadlockError, a protocol
  crash) is a finding too, not a fuzzer error.

* `shrink(plan, still_fails)` — minimize a failing plan to a reproducer:
  a greedy event-deletion pass (drop any event whose removal keeps the
  plan failing) followed by a parameter-halving pass (pull times and link
  conditions toward their defaults while the plan still fails). Bounded
  by `max_checks` re-runs so shrinking a flaky failure terminates.

`run_campaign` drives N seeds, shrinks every failure, and returns one
JSON-able payload; the CLI (`bench.py --fuzz`) appends it to the perf
ledger as one `fuzz` record per campaign.
"""

from __future__ import annotations

import random
import time
from dataclasses import asdict, replace

from .oracles import OracleViolation, assert_liveness, assert_safety
from .plan import (
    Crash,
    Equivocate,
    FaultPlan,
    LinkFault,
    LinkSpec,
    Partition,
    Reconfigure,
)
from .scenario import run_scenario

# Virtual seconds a disruption must leave between its resolution and the
# scenario end so healed/restarted nodes can demonstrably make progress.
_RUNWAY = 1.2


def generate_plan(seed: int, nodes: int = 4, duration: float = 2.5) -> FaultPlan:
    """Draw one quorum-survivable FaultPlan, deterministically from seed."""
    rng = random.Random(f"narwhal-fuzz-{seed}")
    f = max(0, (nodes - 1) // 3)
    fault_budget = f  # nodes allowed byzantine or permanently down
    safe_end = max(0.6, duration - _RUNWAY)

    default_link = _draw_default_link(rng)
    events: list = []
    used_nodes: set[int] = set()
    have_partition = False
    have_reconfigure = False
    have_restart = False
    for _ in range(rng.randint(1, 3)):
        kind = rng.choice(
            ("crash", "partition", "jitter", "equivocate", "reconfigure")
        )
        if kind == "crash" and fault_budget > 0:
            node = rng.randrange(nodes)
            if node in used_nodes:
                continue
            used_nodes.add(node)
            at = round(rng.uniform(0.3, max(0.31, safe_end - 0.4)), 3)
            # Crash-with-restart and Reconfigure never share a plan: a
            # node whose crash window overlaps (or whose restart follows)
            # an epoch change loses the reconfigure broadcast and is
            # stranded in the old epoch — rejoining needs the snapshot
            # state-sync of ROADMAP item 1, which the system does not
            # claim yet. The fuzzer's first campaign found exactly this
            # (seeds 25/46/62/90/91/99, each shrinking to the 2-event
            # {Crash+restart, Reconfigure} reproducer); until state-sync
            # lands, the generator keeps plans inside the claimed
            # envelope. Permanent crashes still compose with Reconfigure
            # (the liveness oracle excludes nodes that stay down).
            if rng.random() < 0.6 and not have_reconfigure:
                restart_at = round(
                    min(at + rng.uniform(0.3, 0.8), safe_end), 3
                )
                have_restart = True
                events.append(Crash(at=at, node=node, restart_at=restart_at))
            else:
                fault_budget -= 1  # stays down: excluded from liveness
                events.append(Crash(at=at, node=node))
        elif kind == "partition" and not have_partition:
            have_partition = True
            at = round(rng.uniform(0.3, max(0.31, safe_end - 0.4)), 3)
            heal = round(min(at + rng.uniform(0.3, 1.0), safe_end), 3)
            minority = rng.sample(range(nodes), rng.randint(1, nodes // 2))
            rest = sorted(set(range(nodes)) - set(minority))
            events.append(
                Partition(
                    at=at, heal=heal,
                    groups=(tuple(sorted(minority)), tuple(rest)),
                )
            )
        elif kind == "jitter":
            a, b = rng.sample(range(nodes), 2)
            at = round(rng.uniform(0.1, max(0.11, safe_end - 0.3)), 3)
            end = round(min(at + rng.uniform(0.3, 1.2), safe_end), 3)
            link = LinkSpec(
                latency=round(rng.uniform(0.002, 0.02), 4),
                jitter=round(rng.uniform(0.0, 0.005), 4),
                drop=rng.choice((0.0, 0.005, 0.02)),
            )
            events.append(
                LinkFault(at=at, a=min(a, b), b=max(a, b), link=link, end=end)
            )
        elif kind == "equivocate" and fault_budget > 0:
            node = rng.randrange(nodes)
            if node in used_nodes:
                continue
            used_nodes.add(node)
            fault_budget -= 1
            start = round(rng.uniform(0.0, duration / 2), 3)
            events.append(Equivocate(node=node, start=start))
        elif kind == "reconfigure" and not have_reconfigure and not have_restart:
            have_reconfigure = True
            at = round(rng.uniform(0.5, max(0.6, duration - 1.5)), 3)
            events.append(Reconfigure(at=at))
    events.sort(key=lambda e: (getattr(e, "at", getattr(e, "start", 0.0))))
    return FaultPlan(seed=seed, default_link=default_link, events=tuple(events))


def _draw_default_link(rng: random.Random) -> LinkSpec:
    return LinkSpec(
        latency=rng.choice((0.001, 0.002, 0.005)),
        jitter=rng.choice((0.0, 0.0005, 0.001)),
        drop=rng.choice((0.0, 0.0, 0.0, 0.01)),
    )


def check_plan(
    plan: FaultPlan,
    nodes: int = 4,
    duration: float = 2.5,
    load_rate: int = 0,
    workers: int = 1,
) -> tuple[bool, str | None, object]:
    """Run one plan under the oracles: (ok, violation, ScenarioResult).

    Safety runs over honest nodes' commits; liveness over honest nodes
    that are up at scenario end. A scenario-level exception (deadlock,
    protocol crash) is reported as a violation with the result None."""
    try:
        result = run_scenario(
            nodes=nodes,
            workers=workers,
            duration=duration,
            load_rate=load_rate,
            plan=plan,
        )
    except Exception as exc:  # noqa: BLE001 — any blowup is a finding
        return False, f"{type(exc).__name__}: {exc}", None
    try:
        assert_safety(result.commits, honest=result.honest())
        live = [i for i in result.honest() if i not in result.crashed]
        assert_liveness(result.rounds, min_rounds=1.0, nodes=live)
    except OracleViolation as violation:
        return False, str(violation), result
    return True, None, result


def describe_plan(plan: FaultPlan) -> dict:
    """JSON-able plan description (the reproducer format in ledger rows)."""
    return {
        "seed": plan.seed,
        "default_link": asdict(plan.default_link),
        "events": [
            {"kind": type(event).__name__, **asdict(event)}
            for event in plan.events
        ],
    }


def _with_event(plan: FaultPlan, index: int, event) -> FaultPlan:
    events = list(plan.events)
    events[index] = event
    return replace(plan, events=tuple(events))


def _halve(value: float, floor: float = 0.0, eps: float = 5e-3) -> float:
    halved = round(value / 2, 4)
    return floor if halved - floor < eps else halved


def _halved_variants(plan: FaultPlan):
    """Yield candidate plans with ONE numeric parameter pulled halfway
    toward its default — the shrinker's second pass."""
    link = plan.default_link
    for name in ("latency", "jitter", "drop"):
        value = getattr(link, name)
        if value > 0:
            yield replace(
                plan, default_link=replace(link, **{name: _halve(value)})
            )
    for i, event in enumerate(plan.events):
        if isinstance(event, Crash):
            if event.at > 0.05:
                yield _with_event(plan, i, replace(event, at=_halve(event.at)))
            if event.restart_at is not None:
                yield _with_event(plan, i, replace(event, restart_at=None))
        elif isinstance(event, Partition):
            window = event.heal - event.at
            if event.at > 0.05:
                at = _halve(event.at)
                yield _with_event(
                    plan, i, replace(event, at=at, heal=round(at + window, 4))
                )
            if window > 0.1:
                yield _with_event(
                    plan, i,
                    replace(event, heal=round(event.at + _halve(window), 4)),
                )
        elif isinstance(event, LinkFault):
            if event.at > 0.05:
                yield _with_event(plan, i, replace(event, at=_halve(event.at)))
            if event.end is not None and event.end - event.at > 0.1:
                yield _with_event(
                    plan, i,
                    replace(
                        event,
                        end=round(event.at + _halve(event.end - event.at), 4),
                    ),
                )
            for name in ("latency", "jitter", "drop"):
                value = getattr(event.link, name)
                if value > 0:
                    yield _with_event(
                        plan, i,
                        replace(
                            event, link=replace(event.link, **{name: _halve(value)})
                        ),
                    )
        elif isinstance(event, Equivocate):
            if event.start > 0.05:
                yield _with_event(
                    plan, i, replace(event, start=_halve(event.start))
                )
        elif isinstance(event, Reconfigure):
            if event.at > 0.05:
                yield _with_event(plan, i, replace(event, at=_halve(event.at)))


def shrink(plan: FaultPlan, still_fails, max_checks: int = 64) -> FaultPlan:
    """Minimize a failing plan to a reproducer.

    `still_fails(candidate) -> bool` re-runs whatever check failed (for a
    real campaign: `not check_plan(candidate)[0]`). Pass 1 greedily
    deletes events whose removal keeps the plan failing; pass 2 halves
    numeric parameters toward their defaults. Bounded by `max_checks`
    candidate evaluations so a flaky predicate cannot loop forever."""
    checks = 0

    def fails(candidate: FaultPlan) -> bool:
        nonlocal checks
        if checks >= max_checks:
            return False
        checks += 1
        return bool(still_fails(candidate))

    # Pass 1: event deletion (restart the scan after every success so the
    # smallest surviving subset is found greedily).
    changed = True
    while changed:
        changed = False
        events = list(plan.events)
        for i in range(len(events)):
            candidate = replace(
                plan, events=tuple(events[:i] + events[i + 1:])
            )
            if fails(candidate):
                plan = candidate
                changed = True
                break
    # Pass 2: parameter halving.
    changed = True
    while changed:
        changed = False
        for candidate in _halved_variants(plan):
            if fails(candidate):
                plan = candidate
                changed = True
                break
    return plan


def run_campaign(
    count: int = 100,
    base_seed: int = 0,
    nodes: int = 4,
    duration: float = 2.5,
    load_rate: int = 0,
    workers: int = 1,
    shrink_failing: bool = True,
    progress=None,
) -> dict:
    """Explore `count` seeded plans; shrink every failure. Returns the
    campaign payload (one perf-ledger `fuzz` record)."""
    t0 = time.monotonic()
    scenarios: list[dict] = []
    failures: list[dict] = []
    for i in range(count):
        seed = base_seed + i
        plan = generate_plan(seed, nodes=nodes, duration=duration)
        ok, violation, result = check_plan(
            plan, nodes=nodes, duration=duration,
            load_rate=load_rate, workers=workers,
        )
        row = {
            "seed": seed,
            "events": [type(event).__name__ for event in plan.events],
            "ok": ok,
            "rounds": max(result.rounds) if result and result.rounds else 0,
        }
        if not ok:
            row["violation"] = violation
            finding: dict = {
                "seed": seed,
                "violation": violation,
                "plan": describe_plan(plan),
            }
            if shrink_failing:
                minimal = shrink(
                    plan,
                    lambda p: not check_plan(
                        p, nodes=nodes, duration=duration,
                        load_rate=load_rate, workers=workers,
                    )[0],
                )
                finding["minimal_plan"] = describe_plan(minimal)
            failures.append(finding)
        scenarios.append(row)
        if progress is not None:
            progress(row)
    return {
        "count": count,
        "base_seed": base_seed,
        "nodes": nodes,
        "workers": workers,
        "duration_virtual_s": duration,
        "load_rate": load_rate,
        "ok": not failures,
        "failures": failures,
        "scenarios": scenarios,
        "wall_s": round(time.monotonic() - t0, 3),
    }


def main(argv=None) -> int:
    import argparse
    import json
    import sys

    parser = argparse.ArgumentParser(
        description="Seeded FaultPlan fuzzer under the simnet oracles"
    )
    parser.add_argument("--count", type=int, default=100)
    parser.add_argument("--seed", type=int, default=0, help="base seed")
    parser.add_argument("--nodes", type=int, default=4)
    parser.add_argument("--workers", type=int, default=1)
    parser.add_argument("--duration", type=float, default=2.5)
    parser.add_argument("--load-rate", type=int, default=0)
    parser.add_argument(
        "--no-shrink", action="store_true",
        help="report failures without minimizing them",
    )
    parser.add_argument("--out", default=None, help="write the campaign JSON here")
    args = parser.parse_args(argv)

    def progress(row: dict) -> None:
        mark = "ok" if row["ok"] else "FAIL"
        print(
            f"seed {row['seed']:>6} {mark:>4} rounds={row['rounds']:>3} "
            f"events={','.join(row['events']) or '-'}"
        )
        if not row["ok"]:
            print(f"  violation: {row['violation']}")

    campaign = run_campaign(
        count=args.count,
        base_seed=args.seed,
        nodes=args.nodes,
        duration=args.duration,
        load_rate=args.load_rate,
        workers=args.workers,
        shrink_failing=not args.no_shrink,
        progress=progress,
    )
    print(
        f"fuzz: {campaign['count']} scenarios, "
        f"{len(campaign['failures'])} failure(s), "
        f"{campaign['wall_s']}s wall"
    )
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(campaign, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"wrote {args.out}")
    try:
        from tools.perf import ledger as perf_ledger

        perf_ledger.append("fuzz", campaign, argv=sys.argv[1:])
    except ImportError:
        pass  # running outside the repo tree: the --out artifact stands
    return 0 if campaign["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
