"""Scenario runner: one seeded, virtual-clock simulation from boot to verdict.

`run_scenario(...)` is a synchronous entry point that owns the whole
lifecycle: build a `SimLoop` (virtual clock), install a seeded `SimFabric`
behind the transport seam, boot a `SimCluster`, drive load and the
`FaultPlan`'s events at their virtual times, then tear everything down with
bounded (virtual-time, therefore instant) cleanup and return a
`ScenarioResult` the oracles consume.

Determinism contract: with the same arguments and `plan.seed`, two runs in
the same process produce bit-identical commit sequences AND a bit-identical
fabric event log (`ScenarioResult.event_log_digest`). Everything
time-driven runs on the virtual clock; the only RNG consumers are the
fabric's seeded jitter/drop stream and the globally seeded `random` module
(retry jitter, lucky broadcasts), both reset at scenario start. Across
*processes* the guarantee additionally requires a pinned PYTHONHASHSEED
(set-iteration order over byte keys follows the process hash seed).

Wall-clock cost is the scenario's CPU work only: every `asyncio.sleep`,
pacing deadline, retry backoff and cleanup grace elapses in simulated time.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import random as _random
import time as _wall
from dataclasses import dataclass, field

from .. import types as _types
from ..network import NetworkClient, auth as _auth, transport
from ..network.rpc import WireStats
from ..messages import ReconfigureMsg, SubmitTransactionStreamMsg
from .byzantine import Equivocator
from .clock import SimLoop
from .cluster import SimCluster, node_id
from .fabric import SimFabric
from .plan import (
    Crash,
    Equivocate,
    FaultPlan,
    LinkFault,
    Partition,
    Reconfigure,
    WorkerLoss,
)


@dataclass
class ScenarioResult:
    nodes: int
    duration: float
    seed: int
    commits: list  # per node: [(epoch, round, digest-hex), ...]
    rounds: list  # per node: last committed round at scenario end
    round_marks: dict  # event label -> per-node committed rounds snapshot
    executed: list  # per node: executed tx count
    identical_execution_prefix: bool
    sent_txs: int
    shed_txs: int
    inject_errors: int
    latency_p50_ms: float
    latency_p95_ms: float
    latency_samples: int
    epochs: tuple
    equivocation: dict  # node index -> {"twins_sent": n, "rounds": [...]}
    wire_bytes_sent: int
    wire_frames_sent: int
    event_log_digest: str
    event_log_len: int
    wall_s: float
    byzantine: tuple = ()
    crashed: tuple = ()
    # Certificate wire forms accumulated in each alive node's store
    # ({"compact": n, "full": n} per node): scenario tests pin that a
    # compact-committee run really exercised the half-aggregated form.
    cert_forms: list = field(default_factory=list)
    log_entries: list = field(default_factory=list, repr=False)
    # Per-node flight-recorder dumps (tracing.Tracer.dump) captured before
    # teardown: span edges + occupancy instants on the VIRTUAL clock, so the
    # same seed reproduces a bit-identical traced event log (the trace
    # determinism test keys on this field).
    flight_dumps: list = field(default_factory=list, repr=False)

    def honest(self) -> list[int]:
        return [i for i in range(self.nodes) if i not in self.byzantine]


def run_scenario(
    nodes: int = 4,
    workers: int = 1,
    duration: float = 5.0,
    plan: FaultPlan | None = None,
    load_rate: int = 0,
    tx_size: int = 64,
    auth: bool = True,
    max_header_delay: float = 0.05,
    max_batch_delay: float = 0.05,
    parameters=None,
    drain_tail: float = 1.0,
    keep_log: bool = False,
) -> ScenarioResult:
    plan = plan or FaultPlan()
    loop = SimLoop()
    asyncio.set_event_loop(loop)
    fabric = SimFabric(seed=plan.seed, default_link=plan.default_link)
    transport.install(fabric)
    # Retry jitter / lucky broadcasts draw from the global random module:
    # pin it to the plan's seed so their draws replay too.
    _random.seed(plan.seed)
    # Handshake nonces/ephemerals come from the auth entropy seam: a seeded
    # hash stream makes every wire transcript — and thus the whole event
    # log — replay bit-identically.
    entropy_state = [b"simnet" + plan.seed.to_bytes(8, "big")]

    def seeded_entropy(n: int) -> bytes:
        out = b""
        while len(out) < n:
            entropy_state[0] = hashlib.sha256(entropy_state[0]).digest()
            out += entropy_state[0]
        return out[:n]

    prev_entropy = _auth.set_entropy(seeded_entropy)
    # Same contract for the batch verifier's outer combination weights
    # (types.host_batch_verify_aggregates): seeded weights keep the group
    # arithmetic of a replayed run bit-identical too.
    prev_weights = _types.set_weight_entropy(seeded_entropy)
    t_wall = _wall.monotonic()
    try:
        result = loop.run_until_complete(
            _drive(
                fabric, plan, nodes, workers, duration, load_rate, tx_size,
                auth, max_header_delay, max_batch_delay, parameters,
                drain_tail, keep_log,
            )
        )
        result.wall_s = round(_wall.monotonic() - t_wall, 3)
        return result
    finally:
        _auth.set_entropy(prev_entropy)
        _types.set_weight_entropy(prev_weights)
        transport.uninstall()
        _cleanup(loop)


def _cleanup(loop: SimLoop) -> None:
    """Bounded straggler cleanup (mirrors tests/conftest.py, but the grace
    window elapses in virtual time, so it costs no wall clock)."""
    pending = [t for t in asyncio.all_tasks(loop) if not t.done()]
    for t in pending:
        t.cancel()
    if pending:
        loop.run_until_complete(asyncio.wait(pending, timeout=15.0))
    loop.run_until_complete(loop.shutdown_asyncgens())
    asyncio.set_event_loop(None)
    loop.close()


async def _drive(
    fabric, plan, nodes, workers, duration, load_rate, tx_size, auth,
    max_header_delay, max_batch_delay, parameters, drain_tail, keep_log,
) -> ScenarioResult:
    cluster = SimCluster(
        size=nodes,
        fabric=fabric,
        workers=workers,
        parameters=parameters,
        auth=auth,
        max_header_delay=max_header_delay,
        max_batch_delay=max_batch_delay,
    )
    wire0 = WireStats.snapshot()
    await cluster.start()

    byzantine = plan.byzantine_nodes()
    equivocators: dict[int, Equivocator] = {}

    def install_equivocator(i: int) -> None:
        if i not in equivocators and cluster.authorities[i].primary is not None:
            equivocators[i] = Equivocator(
                cluster.authorities[i],
                cluster.fixture.authorities[i],
                cluster.committee,
            )

    # Executed-output drains: per-node executed counts + order prefixes
    # (also keeps tx_execution_output from wedging full — the PR-6 lesson).
    executed = [0] * nodes
    exec_orders: list[list[bytes]] = [[] for _ in range(nodes)]
    latencies: list[float] = []
    sent_at: dict[int, float] = {}
    drains: dict[int, asyncio.Task] = {}

    def spawn_drain(i: int) -> None:
        async def drain() -> None:
            ch = cluster.authorities[i].primary.tx_execution_output
            while True:
                _, tx = await ch.recv()
                executed[i] += 1
                exec_orders[i].append(bytes(tx[:9]))
                if i == 0 and tx[:1] == b"\x00":
                    sid = int.from_bytes(tx[1:9], "big")
                    t0 = sent_at.pop(sid, None)
                    if t0 is not None:
                        latencies.append(asyncio.get_event_loop().time() - t0)

        old = drains.pop(i, None)
        if old is not None:
            old.cancel()
        drains[i] = asyncio.ensure_future(drain())

    for i in range(nodes):
        spawn_drain(i)
    for event in plan.events:
        if isinstance(event, Equivocate) and event.start <= 0:
            install_equivocator(event.node)

    # -- load ---------------------------------------------------------------
    sent = {"txs": 0, "shed": 0, "errors": 0}
    stop_load = asyncio.Event()
    client = NetworkClient()
    injectors: list[asyncio.Task] = []
    if load_rate > 0:
        tx_size = max(tx_size, 10)
        lanes = [
            (i, cluster.worker_cache.worker(a.name, wid).transactions)
            for i, a in enumerate(cluster.authorities)
            for wid in range(workers)
        ]
        share = max(1, load_rate // len(lanes))
        sid_counter = [0]

        async def inject(owner: int, lane: str) -> None:
            loop = asyncio.get_event_loop()
            while not stop_load.is_set():
                tick = loop.time()
                txs = []
                for _ in range(share):
                    sid_counter[0] += 1
                    sid = sid_counter[0]
                    sent_at[sid] = loop.time()
                    txs.append(
                        b"\x00" + sid.to_bytes(8, "big")
                        + b"\x01" * (tx_size - 9)
                    )
                try:
                    await client.request(
                        lane, SubmitTransactionStreamMsg(tuple(txs)),
                        timeout=2.0,
                    )
                    sent["txs"] += len(txs)
                except Exception as e:
                    if "RESOURCE_EXHAUSTED" in str(e):
                        sent["shed"] += len(txs)
                    else:  # crashed/partitioned lane: drop this tick
                        sent["errors"] += 1
                    for tx in txs:
                        sent_at.pop(int.from_bytes(tx[1:9], "big"), None)
                await asyncio.sleep(max(0.0, 1.0 - (loop.time() - tick)))

        injectors = [
            asyncio.ensure_future(inject(i, lane)) for i, lane in lanes
        ]

    # -- the fault-plan driver ----------------------------------------------
    round_marks: dict[str, list[float]] = {}
    crashed: set[int] = set()
    epoch_counter = [cluster.committee.epoch]

    def mark(label: str) -> None:
        round_marks[label] = cluster.committed_rounds()

    async def apply(event) -> None:
        if isinstance(event, Partition):
            mark(f"partition@{event.at}")
            fabric.set_partition(
                tuple(tuple(node_id(i) for i in g) for g in event.groups)
            )
        elif isinstance(event, LinkFault):
            fabric.set_link(node_id(event.a), node_id(event.b), event.link)
        elif isinstance(event, Crash):
            mark(f"crash@{event.at}")
            crashed.add(event.node)
            drains.pop(event.node).cancel()
            eq = equivocators.pop(event.node, None)
            if eq is not None:
                eq.uninstall()
            await cluster.crash_node(event.node)
        elif isinstance(event, WorkerLoss):
            mark(f"workerloss@{event.at}")
            await cluster.authorities[event.node].stop_worker(event.worker_id)
        elif isinstance(event, Reconfigure):
            mark(f"reconfigure@{event.at}")
            epoch_counter[0] += 1
            await _reconfigure(cluster, epoch_counter[0], auth)

    async def driver() -> None:
        loop = asyncio.get_event_loop()
        t0 = loop.time()
        # Expand Partition into (at, apply) + (heal, heal), Crash into
        # (at, crash) + (restart_at, restart), keeping virtual order.
        schedule: list[tuple[float, int, object, str]] = []
        for seq, event in enumerate(plan.timed_events()):
            schedule.append((event.at, seq, event, "apply"))
            if isinstance(event, Partition):
                schedule.append((event.heal, seq, event, "heal"))
            if isinstance(event, Crash) and event.restart_at is not None:
                schedule.append((event.restart_at, seq, event, "restart"))
            if isinstance(event, LinkFault) and event.end is not None:
                schedule.append((event.end, seq, event, "clear"))
        for at, _, event, phase in sorted(schedule, key=lambda e: (e[0], e[1])):
            delay = t0 + at - loop.time()
            if delay > 0:
                await asyncio.sleep(delay)
            if phase == "apply":
                await apply(event)
            elif phase == "heal":
                mark(f"heal@{event.heal}")
                fabric.set_partition(None)
            elif phase == "clear":
                fabric.set_link(node_id(event.a), node_id(event.b), None)
            elif phase == "restart":
                mark(f"restart@{event.restart_at}")
                crashed.discard(event.node)
                await cluster.restart_node(event.node)
                spawn_drain(event.node)
                if event.node in plan.byzantine_nodes():
                    install_equivocator(event.node)

    driver_task = asyncio.ensure_future(driver())
    late_tasks: list[asyncio.Task] = []
    for event in plan.events:
        if isinstance(event, Equivocate) and event.start > 0:
            async def late_install(e=event):
                await asyncio.sleep(e.start)
                install_equivocator(e.node)

            late_tasks.append(asyncio.ensure_future(late_install()))

    # -- run the window ------------------------------------------------------
    await asyncio.sleep(duration)
    stop_load.set()
    for t in injectors + late_tasks:
        t.cancel()
    await driver_task
    if drain_tail > 0:
        await asyncio.sleep(drain_tail)

    # -- capture BEFORE teardown (shutdown ordering is not part of the
    #    deterministic contract) -------------------------------------------
    mark("end")
    rounds = cluster.committed_rounds()
    # Flight recorders, captured while the nodes are alive: every timestamp
    # inside rides the virtual clock, so the dumps are part of the same-seed
    # determinism contract the event log carries.
    flight_dumps = []
    for i, a in enumerate(cluster.authorities):
        if a.primary is not None:
            flight_dumps.append(a.primary.tracer.dump())
        for wid in sorted(a.workers):
            flight_dumps.append(a.workers[wid].tracer.dump())
    cert_forms = []
    for a in cluster.authorities:
        forms = {"compact": 0, "full": 0}
        if a.primary is not None:
            for cert in a.primary.storage.certificate_store.after_round(1):
                forms["compact" if cert.is_compact else "full"] += 1
        cert_forms.append(forms)
    wire1 = WireStats.snapshot()
    log_digest = fabric.log.digest()
    log_len = len(fabric.log)
    lat = sorted(latencies)

    def pct(p: float) -> float:
        if not lat:
            return 0.0
        return lat[min(len(lat) - 1, int(p * len(lat)))]

    prefix_nodes = [
        exec_orders[i] for i in range(nodes) if i not in crashed
    ] or [[]]
    min_len = min(len(o) for o in prefix_nodes)
    identical = all(
        o[:min_len] == prefix_nodes[0][:min_len] for o in prefix_nodes
    )
    epochs = tuple(
        sorted({e for seq in cluster.commits for (e, _, _) in seq})
    )
    equivocation = {
        i: {"twins_sent": eq.twins_sent, "rounds": [r for r, _, _ in eq.twin_digests]}
        for i, eq in equivocators.items()
    }

    for eq in equivocators.values():
        eq.uninstall()
    for t in drains.values():
        t.cancel()
    client.close()
    await cluster.shutdown()

    return ScenarioResult(
        nodes=nodes,
        duration=duration,
        seed=plan.seed,
        commits=cluster.commits,
        rounds=rounds,
        round_marks=round_marks,
        executed=executed,
        identical_execution_prefix=identical,
        sent_txs=sent["txs"],
        shed_txs=sent["shed"],
        inject_errors=sent["errors"],
        latency_p50_ms=round(pct(0.50) * 1000, 2),
        latency_p95_ms=round(pct(0.95) * 1000, 2),
        latency_samples=len(lat),
        epochs=epochs,
        equivocation=equivocation,
        wire_bytes_sent=wire1["bytes_sent"] - wire0["bytes_sent"],
        wire_frames_sent=wire1["frames_sent"] - wire0["frames_sent"],
        event_log_digest=log_digest,
        event_log_len=log_len,
        wall_s=0.0,
        byzantine=tuple(sorted(byzantine)),
        crashed=tuple(sorted(crashed)),
        cert_forms=cert_forms,
        log_entries=list(fabric.log.entries) if keep_log else [],
        flight_dumps=flight_dumps,
    )


async def _reconfigure(cluster, epoch: int, auth: bool) -> None:
    """In-band epoch change under traffic: push a NewEpoch ReconfigureMsg
    (same committee, epoch bumped) through every primary's own-worker
    control plane, like the reference app drives state_handler.rs."""
    doc = json.loads(cluster.committee.to_json())
    doc["epoch"] = epoch
    msg = ReconfigureMsg("new_epoch", json.dumps(doc))
    clients = []
    try:
        for i, a in enumerate(cluster.authorities):
            if a.primary is None:
                continue
            if auth:
                from ..network import Credentials, committee_resolver

                client = NetworkClient(
                    credentials=Credentials(
                        cluster.fixture.authorities[i].worker_keypairs[0],
                        committee_resolver(
                            lambda: cluster.committee,
                            lambda: cluster.worker_cache,
                        ),
                    )
                )
            else:
                client = NetworkClient()
            clients.append(client)
            await client.unreliable_send(a.primary.address, msg, timeout=5.0)
    finally:
        for client in clients:
            client.close()
