"""Safety and liveness oracles over a completed scenario.

Safety (the Narwhal/Bullshark guarantee under <= f byzantine stake): honest
nodes commit ONE total order. Nodes run at different speeds — and a
reconfiguration resets the sequence per epoch — so the checkable form is:
grouped by epoch, any two honest nodes' committed certificate sequences are
prefix-compatible (one is a prefix of the other). A single divergent entry
anywhere is a consensus split.

Liveness: committed rounds advance. The scenario runner snapshots per-node
committed rounds at every fault-plan event (`round_marks`), so "rounds
advance after heal" is `min over honest live nodes of (end - mark_at_heal)
>= min_rounds`.

Both raise AssertionError with enough context to debug the divergence — and
both snapshot every live/archived flight recorder first (tracing.on_anomaly),
so the pytest failure hook can attach the rings that led up to the violation.
"""

from __future__ import annotations


class OracleViolation(AssertionError):
    pass


def _violation(message: str) -> OracleViolation:
    """Build the violation AFTER parking flight-recorder dumps in the
    tracing archive: by the time an oracle runs, the scenario's nodes are
    torn down, so the archived rings are the only record of the run."""
    from .. import tracing

    tracing.on_anomaly(f"oracle: {message[:120]}")
    return OracleViolation(message)


def _by_epoch(seq):
    grouped: dict[int, list] = {}
    for epoch, round_, digest in seq:
        grouped.setdefault(epoch, []).append((round_, digest))
    return grouped


def assert_safety(commits, honest=None) -> None:
    """commits: per-node list of (epoch, round, digest) in commit order
    (SimCluster.commits). honest: node indices to check (default: all)."""
    nodes = sorted(honest) if honest is not None else range(len(commits))
    nodes = [i for i in nodes if i < len(commits)]
    for ai in nodes:
        for bi in nodes:
            if bi <= ai:
                continue
            a, b = _by_epoch(commits[ai]), _by_epoch(commits[bi])
            for epoch in set(a) & set(b):
                sa, sb = a[epoch], b[epoch]
                n = min(len(sa), len(sb))
                for k in range(n):
                    if sa[k] != sb[k]:
                        raise _violation(
                            f"SAFETY: nodes {ai} and {bi} disagree at epoch "
                            f"{epoch} commit #{k}: {sa[k]} vs {sb[k]} "
                            f"(sequences of {len(sa)} vs {len(sb)})"
                        )


def assert_liveness(
    end_rounds,
    baseline_rounds=None,
    min_rounds: float = 1.0,
    nodes=None,
) -> None:
    """Every selected node's committed round advanced by >= min_rounds over
    its baseline (a `round_marks` snapshot; default baseline 0)."""
    selected = sorted(nodes) if nodes is not None else range(len(end_rounds))
    for i in selected:
        base = baseline_rounds[i] if baseline_rounds is not None else 0.0
        progress = end_rounds[i] - base
        if progress < min_rounds:
            raise _violation(
                f"LIVENESS: node {i} advanced {progress} rounds "
                f"(from {base} to {end_rounds[i]}), needed >= {min_rounds}"
            )
