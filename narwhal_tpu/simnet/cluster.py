"""SimCluster — a whole committee on the simnet fabric.

Same assembly as `narwhal_tpu.cluster.Cluster` (real PrimaryNode/WorkerNode
actors, real stores, real frames), with three substitutions:

* **addresses are synthetic** (`nodeI:port` strings owned by the fabric) —
  no `get_available_port` probing, no placeholder sockets, no fds. The
  fabric learns which node owns which address at assignment time, which is
  what partitions and crash isolation key on;
* **every node's tasks carry its identity**: `start_node` sets the
  `CURRENT_NODE` context variable around construction + spawn, so every
  task an actor ever spawns — including lazy reconnects rounds later —
  attributes its connections to the right node;
* **commits are recorded**: the per-node Consensus commit tap appends
  `(epoch, round, certificate digest)` to `commits[i]` and mirrors a
  compact entry into the fabric's event log, giving the safety/liveness
  oracles the exact committed sequence without an extra channel.

`crash_node` / `restart_node` model fail-stop: the fabric isolates the node
first (connections reset, connects refused — no goodbye messages escape),
then the node object is torn down; restart builds a fresh node with a fresh
in-memory store, exercising the catch-up path.
"""

from __future__ import annotations

from ..cluster import AuthorityDetails, Cluster
from ..config import WorkerInfo
from dataclasses import replace
from .fabric import CURRENT_NODE, SimFabric


def node_id(index: int) -> str:
    return f"node{index}"


class SimCluster(Cluster):
    def __init__(self, size: int = 4, fabric: SimFabric | None = None, **kwargs):
        self.fabric = fabric or SimFabric()
        # (epoch, round, digest-hex) per node, in exact commit order.
        self.commits: list[list[tuple[int, int, str]]] = [
            [] for _ in range(size)
        ]
        super().__init__(size=size, **kwargs)

    def _assign_addresses(self) -> None:
        committee = self.fixture.committee
        port = 0
        for i, fixture_auth in enumerate(self.fixture.authorities):
            pk = fixture_auth.public
            port += 1
            addr = f"{node_id(i)}:{port}"
            committee.authorities[pk] = replace(
                committee.authorities[pk], primary_address=addr
            )
            addrs = [addr]
            ws = self.fixture.worker_cache.workers[pk]
            for wid, info in ws.items():
                port += 2
                tx_addr = f"{node_id(i)}:{port - 1}"
                w_addr = f"{node_id(i)}:{port}"
                ws[wid] = WorkerInfo(
                    name=info.name, transactions=tx_addr, worker_address=w_addr
                )
                addrs += [tx_addr, w_addr]
            self.fabric.register_node(node_id(i), addrs)

    def _commit_tap(self, index: int):
        record = self.commits[index].append
        log = self.fabric.log

        def tap(output) -> None:
            cert = output.certificate
            entry = (cert.epoch, cert.round, cert.digest.hex())
            record(entry)
            log.append("commit", node_id(index), *entry)

        return tap

    async def start_node(self, index: int) -> AuthorityDetails:
        token = CURRENT_NODE.set(node_id(index))
        try:
            return await super().start_node(index)
        finally:
            CURRENT_NODE.reset(token)

    async def crash_node(self, index: int) -> None:
        """Fail-stop: isolate on the fabric first (peers see resets and
        refused reconnects, never a clean goodbye), then tear down."""
        self.fabric.set_node_down(node_id(index), True)
        await self.stop_node(index)

    async def restart_node(self, index: int) -> AuthorityDetails:
        self.fabric.set_node_down(node_id(index), False)
        if self.authorities[index].primary is not None:
            await self.stop_node(index)
        # A node restarted with a fresh in-memory store recommits its DAG
        # from genesis (deterministic ordering makes the replay identical),
        # so its observation record starts a fresh segment — the safety
        # oracle then checks the replayed sequence against the others'
        # full sequences, which is exactly the prefix property.
        self.commits[index].clear()
        return await self.start_node(index)

    def committed_rounds(self) -> list[float]:
        return [
            a.metric("consensus_last_committed_round") if a.primary else 0.0
            for a in self.authorities
        ]
