"""The virtual clock: an event loop whose time is simulated.

`SimLoop` is a selector event loop with three changes that together make a
whole-committee simulation deterministic and wall-clock free:

* **`time()` is virtual.** It starts at 0.0 and only moves when the loop
  would otherwise sleep: when a `_run_once` iteration has no ready
  callbacks, the selector wrapper advances the virtual clock by exactly the
  timeout the loop computed (the gap to the earliest scheduled timer)
  instead of blocking in `select`. `asyncio.sleep`, `wait_for` deadlines,
  pacing timers and retry backoffs all run against this clock, so a
  10-virtual-second scenario takes however long its *CPU work* takes —
  typically milliseconds — and two runs take identical virtual trajectories.

* **`run_in_executor` runs inline.** Thread handoffs are the one asyncio
  feature whose completion order depends on the host scheduler; executing
  the function synchronously (storage flushes are cheap no-fsync appends in
  the in-memory configurations simnet uses) removes the only source of
  nondeterminism the loop itself could introduce.

* **Quiescence is an error.** A real loop with nothing scheduled blocks in
  `select` forever waiting for I/O; a simulated committee has no external
  I/O, so "no ready callbacks and no timers" means every task is parked on
  an event that can never fire — a deadlock. The loop raises immediately
  with the pending-task count instead of hanging the test.

Timer ordering is inherited from asyncio's scheduled heap (strictly by
`when`, ties by insertion order), so equal-deadline callbacks fire in the
order they were scheduled — deterministically.
"""

from __future__ import annotations

import asyncio
import selectors


class SimDeadlockError(RuntimeError):
    """No ready callbacks, no scheduled timers, no external I/O possible:
    the simulation can never make progress again."""


class _VirtualTimeSelector:
    """Selector wrapper: polls real fds without blocking (only the loop's
    self-pipe is ever registered — simnet opens no sockets) and converts the
    would-be blocking time into a virtual-clock jump."""

    def __init__(self, inner: selectors.BaseSelector):
        self._inner = inner
        self._loop: "SimLoop | None" = None

    def select(self, timeout=None):
        events = self._inner.select(0)
        if events:
            return events
        if timeout is None:
            loop = self._loop
            pending = (
                sum(1 for t in asyncio.all_tasks(loop) if not t.done())
                if loop is not None
                else "?"
            )
            raise SimDeadlockError(
                "simnet deadlock: no runnable callbacks and no timers, but "
                f"{pending} task(s) still pending — every task is waiting "
                "on an event that can never fire"
            )
        if timeout > 0 and self._loop is not None:
            self._loop._sim_now += timeout
        return events

    def __getattr__(self, name):
        return getattr(self._inner, name)


class SimLoop(asyncio.SelectorEventLoop):
    """Event loop on virtual time. Construct, `asyncio.set_event_loop`, and
    drive with `run_until_complete` — `simnet.scenario` wraps the lifecycle."""

    def __init__(self):
        selector = _VirtualTimeSelector(selectors.DefaultSelector())
        super().__init__(selector)
        selector._loop = self
        self._sim_now = 0.0

    def time(self) -> float:
        return self._sim_now

    def run_in_executor(self, executor, func, *args):
        # Inline: see module docstring. Returns an already-resolved future,
        # matching the awaitable contract of the real method.
        fut = self.create_future()
        try:
            fut.set_result(func(*args))
        except Exception as e:  # delivered through the future, like a pool
            fut.set_exception(e)
        return fut
