"""SimFabric — the socket-free, seeded, virtual-latency network.

The fabric implements the two calls the transport seam
(network/transport.py) routes here: `start_server` registers an in-process
listener under a "host:port" string, `open_connection` pairs a client with
it through two directed byte pipes (`asyncio.StreamReader`s fed by duck-typed
writers). Everything above — framing, handshakes, AEAD sealing, write
coalescing — is the production `rpc.py` code, byte for byte; only the medium
changes.

Delivery model:

* every `writer.write(chunk)` enqueues the chunk for delivery into the
  peer's reader at `now + latency + jitter` (seeded RNG), clamped
  non-decreasing per direction so the byte stream stays ordered, like TCP.
  Deliveries are BATCHED: the fabric keeps one pending min-heap ordered by
  (deliver_t, enqueue seq) and arms a single loop timer at the head
  deadline — when it fires, every chunk due at that virtual instant drains
  in one flush, with consecutive same-stream chunks coalesced into one
  `feed_data`. One timer per flush instead of one per chunk is where the
  10x on the asyncio_loop/timer-churn profile line comes from;
* a `drop` hit kills the connection (both readers see ConnectionResetError)
  — on a framed, nonce-sequenced stream a lost segment is unrecoverable, so
  reset-and-reconnect is the honest model of a lossy link;
* partitions/crashes refuse new connects (ConnectionRefusedError) and reset
  live cross-cut connections, so the retry/backoff machinery is exercised
  exactly as by a real outage.

Attribution: the *server* side of an address is known from registration
(`register_node`); the *client* side is read from the `CURRENT_NODE`
context variable, which SimCluster sets around each node's spawn — tasks
inherit it, so every lazy reconnect rounds later still carries its node
identity. Connections with no node attribution (benchmark clients) are
conditioned by the default link and are unaffected by partitions.

Every chunk movement is appended to the event log: `(seq, t_send, t_deliver,
src, dst, kind, nbytes)` with virtual times. Two runs of the same seeded
scenario produce identical logs — `EventLog.digest()` is the equality the
replay test pins.
"""

from __future__ import annotations

import asyncio
import contextvars
import hashlib
import heapq
import itertools
import random

from .plan import LinkSpec

# Pending-queue entry kinds, ordered within a flush by (deliver_t, seq):
# data chunks, graceful EOFs and drop-resets all ride the same queue so a
# half-close or a mid-flight reset can never overtake bytes sent before it.
_DATA, _EOF, _RESET = 0, 1, 2

# The node id on whose behalf the current task opens connections. Set by
# SimCluster around node construction/spawn; inherited by every task those
# actors create (asyncio tasks copy the current context).
CURRENT_NODE: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "simnet_current_node", default=None
)


class EventLog:
    """Append-only record of everything the fabric did, in virtual time."""

    def __init__(self):
        self.entries: list[tuple] = []
        self._seq = itertools.count()

    def append(self, kind: str, *fields) -> None:
        self.entries.append((next(self._seq), kind) + fields)

    def digest(self) -> str:
        h = hashlib.sha256()
        for entry in self.entries:
            h.update(repr(entry).encode())
            h.update(b"\n")
        return h.hexdigest()

    def __len__(self) -> int:
        return len(self.entries)


class _SimSocket:
    """Just enough of a socket for RpcServer's getsockname()."""

    def __init__(self, host: str, port: int):
        self._name = (host, port)

    def getsockname(self):
        return self._name


class SimServer:
    """The asyncio.AbstractServer shape RpcServer.start/stop expects."""

    def __init__(self, fabric: "SimFabric", host: str, port: int):
        self._fabric = fabric
        self.sockets = [_SimSocket(host, port)]
        self._closed = False

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._fabric._unbind(f"{self.sockets[0]._name[0]}:{self.sockets[0]._name[1]}")

    async def wait_closed(self) -> None:
        return None


class _Listener:
    def __init__(self, cb, limit: int, node: str | None, ctx):
        self.cb = cb
        self.limit = limit
        self.node = node  # owning node id (None for unattributed servers)
        self.ctx = ctx  # context the acceptor runs handler tasks in


class _SimWriter:
    """Duck-typed StreamWriter over the fabric: write() hands the chunk to
    the fabric for conditioned delivery into the peer's reader."""

    # No kernel send buffer behind this writer, so drain() never blocks —
    # FrameSender uses this flag to write synchronously (no drainer task).
    sync_drain = True

    def __init__(self, conn: "_SimConnection", direction: int):
        self._conn = conn
        self._dir = direction  # 0: client->server, 1: server->client

    def write(self, data: bytes) -> None:
        if self._conn.reset_exc is not None:
            raise ConnectionResetError(str(self._conn.reset_exc))
        if self._conn.closed[self._dir]:
            # EOF is already in flight; a later chunk would violate stream
            # order. Matches a real transport's write-after-close failure.
            raise ConnectionResetError("write after close")
        self._conn.fabric._transmit(self._conn, self._dir, bytes(data))

    async def drain(self) -> None:
        # No kernel send buffer to fill; readers buffer without bound (the
        # per-connection volume is capped by the protocol's own
        # request/response concurrency limits).
        if self._conn.reset_exc is not None:
            raise ConnectionResetError(str(self._conn.reset_exc))

    def close(self) -> None:
        self._conn.close(self._dir)

    def is_closing(self) -> bool:
        return self._conn.closed[self._dir] or self._conn.reset_exc is not None

    async def wait_closed(self) -> None:
        return None

    def get_extra_info(self, name, default=None):
        if name == "peername":
            if self._dir == 0:  # client writer: peer is the server address
                host, port = self._conn.dst_addr.rsplit(":", 1)
                return (host, int(port))
            return (self._conn.src or "client", 0)
        return default


class _SimConnection:
    """One client<->server pairing: two readers, two writers, per-direction
    FIFO delivery cursors, and a reset latch. Ids are per-fabric so two
    scenarios in one process log identical ids."""

    def __init__(self, fabric: "SimFabric", src: str | None, dst: str | None, dst_addr: str, limit: int):
        self.id = next(fabric._conn_ids)
        self.fabric = fabric
        self.src = src  # client node id (None = external client)
        self.dst = dst  # server node id
        self.dst_addr = dst_addr
        # readers[0]: what the SERVER reads (client->server direction 0)
        # readers[1]: what the CLIENT reads (server->client direction 1)
        self.readers = [
            asyncio.StreamReader(limit=limit),
            asyncio.StreamReader(limit=limit),
        ]
        self.closed = [False, False]
        self.reset_exc: Exception | None = None
        self._next_deliver = [0.0, 0.0]

    def endpoints(self, direction: int) -> tuple[str, str]:
        a, b = self.src or "client", self.dst or "?"
        return (a, b) if direction == 0 else (b, a)

    def reset(self, reason: str) -> None:
        if self.reset_exc is not None:
            return
        self.reset_exc = ConnectionResetError(reason)
        for r in self.readers:
            if r.exception() is None and not r.at_eof():
                r.set_exception(ConnectionResetError(reason))
        self.fabric._conns.discard(self)
        self.fabric.log.append("reset", self.id, reason)
        self.fabric.counters["resets"] += 1

    def close(self, direction: int) -> None:
        """Graceful half-close from one side: the peer reads EOF.
        Direction d's writes land in readers[d], so that is where the EOF
        goes too."""
        if self.closed[direction] or self.reset_exc is not None:
            self.closed[direction] = True
            return
        self.closed[direction] = True
        # EOF rides the fabric's pending queue behind any chunks still in
        # flight on this direction (queue order is (deliver_t, seq), so an
        # equal-deadline EOF still lands after earlier-enqueued data).
        try:
            loop = asyncio.get_event_loop()
            eof_t = max(loop.time(), self._next_deliver[direction])
            self._next_deliver[direction] = eof_t
            self.fabric._schedule(loop, eof_t, _EOF, self, direction, None)
        except RuntimeError:  # closing outside any loop (test teardown)
            self._feed_eof(direction)
        if all(self.closed):
            self.fabric._conns.discard(self)

    def _feed_eof(self, direction: int) -> None:
        reader = self.readers[direction]
        if (
            self.reset_exc is None
            and reader.exception() is None
            and not reader.at_eof()
        ):
            reader.feed_eof()


class SimFabric:
    """The in-memory network: listeners, connections, link conditions."""

    # Snapshot of the most recent fabric's counters: scenarios tear the
    # instance down with the loop, so post-run tooling (the fabric
    # profiler) reads the class-level alias instead.
    last_counters: dict = {}

    def __init__(self, seed: int = 0, default_link: LinkSpec | None = None):
        self.rng = random.Random(seed)
        self.default_link = default_link or LinkSpec()
        self.log = EventLog()
        # Hot-path tallies (plain dict, no locking: the loop is single
        # threaded). Purely observational — nothing reads them to make
        # decisions, so determinism is untouched.
        self.counters = {
            "dials": 0,
            "connects": 0,
            "transmits": 0,
            "bytes_sent": 0,
            "drops": 0,
            "delivers": 0,
            "bytes_delivered": 0,
            "resets": 0,
            "peak_conns": 0,
        }
        SimFabric.last_counters = self.counters
        # Batched delivery: one min-heap of (deliver_t, seq, kind, conn,
        # direction, payload) and ONE armed loop timer at the head
        # deadline, instead of one loop timer per in-flight chunk.
        self._pending: list[tuple] = []
        self._pending_seq = itertools.count()
        self._timer = None
        self._timer_when = 0.0
        self._listeners: dict[str, _Listener] = {}
        self._conns: set[_SimConnection] = set()
        self._conn_ids = itertools.count(1)
        self._ports = itertools.count(40000)
        self._addr_node: dict[str, str] = {}  # "host:port" -> node id
        self._down: set[str] = set()  # crashed/isolated node ids
        self._groups: dict[str, int] | None = None  # node id -> partition group
        self._links: dict[tuple[str, str], LinkSpec] = {}  # (a,b) sorted pair

    # -- topology registration (SimCluster) ---------------------------------
    def register_node(self, node: str, addresses) -> None:
        for addr in addresses:
            self._addr_node[addr] = node

    # -- fault controls (scenario driver) -----------------------------------
    def set_partition(self, groups) -> None:
        """groups: iterable of iterables of node ids; None clears. Existing
        cross-group connections are reset immediately."""
        if groups is None:
            self._groups = None
            self.log.append("heal")
            return
        mapping: dict[str, int] = {}
        for gi, group in enumerate(groups):
            for node in group:
                mapping[node] = gi
        self._groups = mapping
        self.log.append("partition", tuple(sorted(mapping.items())))
        # Sorted by connection id: set iteration is id-ordered and would
        # reorder the resets (and the log) between otherwise identical runs.
        for conn in sorted(self._conns, key=lambda c: c.id):
            if self._cut(conn.src, conn.dst):
                conn.reset("partitioned")

    def set_node_down(self, node: str, down: bool = True) -> None:
        if down:
            self._down.add(node)
            self.log.append("node_down", node)
            for conn in sorted(self._conns, key=lambda c: c.id):
                if conn.src == node or conn.dst == node:
                    conn.reset(f"{node} crashed")
        else:
            self._down.discard(node)
            self.log.append("node_up", node)

    def set_link(self, a: str, b: str, link: LinkSpec | None) -> None:
        key = (a, b) if a <= b else (b, a)
        if link is None:
            self._links.pop(key, None)
            self.log.append("link_clear", key)
        else:
            self._links[key] = link
            self.log.append(
                "link_set", key, link.latency, link.jitter, link.drop
            )

    # -- condition lookups --------------------------------------------------
    def _cut(self, a: str | None, b: str | None) -> bool:
        if self._groups is None or a is None or b is None:
            return False
        ga, gb = self._groups.get(a), self._groups.get(b)
        # Nodes outside every named group share the implicit last group.
        return ga != gb

    def _link_for(self, a: str | None, b: str | None) -> LinkSpec:
        if a is None or b is None:
            return self.default_link
        key = (a, b) if a <= b else (b, a)
        return self._links.get(key, self.default_link)

    # -- the transport-seam surface ----------------------------------------
    async def start_server(self, cb, host: str, port: int, *, limit: int) -> SimServer:
        if port == 0:
            port = next(self._ports)
        key = f"{host}:{port}"
        if key in self._listeners:
            raise OSError(98, f"simnet address already in use: {key}")
        node = self._addr_node.get(key, CURRENT_NODE.get())
        self._listeners[key] = _Listener(
            cb, limit, node, contextvars.copy_context()
        )
        return SimServer(self, host, port)

    def _unbind(self, key: str) -> None:
        self._listeners.pop(key, None)

    async def open_connection(self, host: str, port: int, *, limit: int):
        key = f"{host}:{port}"
        listener = self._listeners.get(key)
        src = CURRENT_NODE.get()
        dst = self._addr_node.get(key)
        if src is not None and src in self._down:
            # A crashed node's still-cancelling tasks must not reach out.
            raise ConnectionRefusedError(f"{src} is down")
        if listener is None or (dst is not None and dst in self._down):
            raise ConnectionRefusedError(f"no simnet listener on {key}")
        if self._cut(src, dst):
            raise ConnectionRefusedError(f"partition cuts {src}->{key}")
        link = self._link_for(src, dst)
        # One connect RTT under the link's conditions before the streams
        # exist, like a SYN exchange. The dial is logged at DRAW time so the
        # seeded rng stream is fully reconstructible from the event log.
        self.log.append("dial", src or "client", key)
        self.counters["dials"] += 1
        delay = link.latency + (
            self.rng.uniform(0.0, link.jitter) if link.jitter else 0.0
        )
        if delay > 0:
            await asyncio.sleep(delay)
        conn = _SimConnection(self, src, dst or key, key, limit)
        self._conns.add(conn)
        if len(self._conns) > self.counters["peak_conns"]:
            self.counters["peak_conns"] = len(self._conns)
        self.log.append("connect", conn.id, src or "client", key)
        self.counters["connects"] += 1
        server_writer = _SimWriter(conn, 1)
        client_writer = _SimWriter(conn, 0)
        # The handler task runs in the LISTENER's captured context so the
        # server side is attributed to its owning node (dispatch tasks it
        # spawns inherit that context, exactly like a real accept loop).
        listener.ctx.run(
            asyncio.ensure_future, listener.cb(conn.readers[0], server_writer)
        )
        return conn.readers[1], client_writer

    # -- chunk movement -----------------------------------------------------
    def _transmit(self, conn: _SimConnection, direction: int, data: bytes) -> None:
        src, dst = conn.endpoints(direction)
        if self._cut(conn.src, conn.dst):
            conn.reset("partitioned")
            raise ConnectionResetError("partitioned")
        link = self._link_for(conn.src, conn.dst)
        loop = asyncio.get_event_loop()
        now = loop.time()
        if link.drop and self.rng.random() < link.drop:
            # A lost segment on a framed AEAD stream is unrecoverable:
            # model it as the connection dying mid-flight.
            self.log.append("drop", conn.id, src, dst, len(data))
            self.counters["drops"] += 1
            deliver_t = max(
                now + link.latency, conn._next_deliver[direction]
            )
            self._schedule(loop, deliver_t, _RESET, conn, direction, "chunk dropped")
            return
        jitter = self.rng.uniform(0.0, link.jitter) if link.jitter else 0.0
        deliver_t = now + link.latency + jitter
        # Non-decreasing per direction (the TCP-like ordering cursor). The
        # pending queue breaks equal-deadline ties by enqueue sequence, so
        # chunks sharing a virtual instant still deliver in send order —
        # and share one timer flush instead of one timer each (the old
        # design needed a strictly-increasing nanosecond bump because
        # asyncio's timer heap is not FIFO for equal deadlines).
        prev = conn._next_deliver[direction]
        if deliver_t < prev:
            deliver_t = prev
        conn._next_deliver[direction] = deliver_t
        self.log.append(
            "xmit", conn.id, src, dst, len(data),
            round(now, 9), round(deliver_t, 9),
        )
        self.counters["transmits"] += 1
        self.counters["bytes_sent"] += len(data)
        self._schedule(loop, deliver_t, _DATA, conn, direction, data)

    def _schedule(self, loop, when: float, kind: int, conn, direction: int, payload) -> None:
        heapq.heappush(
            self._pending,
            (when, next(self._pending_seq), kind, conn, direction, payload),
        )
        if self._timer is None or when < self._timer_when:
            if self._timer is not None:
                self._timer.cancel()
            self._timer_when = when
            self._timer = loop.call_at(when, self._flush)

    def _flush(self) -> None:
        """Drain every pending entry due at (or before) the current virtual
        instant, in (deliver_t, seq) order, coalescing consecutive chunks
        of one stream into a single feed_data; then re-arm the timer for
        the next head deadline."""
        self._timer = None
        loop = asyncio.get_event_loop()
        # Tiny epsilon so float drift in the virtual clock can never leave
        # the head entry perpetually "one tick in the future" (which would
        # re-arm a zero-delay timer forever).
        now = loop.time() + 1e-9
        pending = self._pending
        cur_conn = None
        cur_dir = 0
        chunks: list[bytes] = []
        while pending and pending[0][0] <= now:
            _t, _seq, kind, conn, direction, payload = heapq.heappop(pending)
            if kind == _DATA and conn is cur_conn and direction == cur_dir:
                chunks.append(payload)
                continue
            if chunks:
                self._feed(cur_conn, cur_dir, chunks)
                chunks = []
            cur_conn = None
            if kind == _DATA:
                cur_conn, cur_dir = conn, direction
                chunks = [payload]
            elif kind == _EOF:
                conn._feed_eof(direction)
            else:  # _RESET (dropped chunk)
                conn.reset(payload)
        if chunks:
            self._feed(cur_conn, cur_dir, chunks)
        if pending:
            self._timer_when = pending[0][0]
            self._timer = loop.call_at(self._timer_when, self._flush)

    def _feed(self, conn: _SimConnection, direction: int, chunks: list) -> None:
        if conn.reset_exc is not None:
            return
        reader = conn.readers[direction]
        # at_eof() is False while buffered bytes remain, so check the flag
        # itself: once EOF is fed, nothing more may enter the stream.
        if reader.exception() is None and not getattr(reader, "_eof", False):
            data = chunks[0] if len(chunks) == 1 else b"".join(chunks)
            self.counters["delivers"] += len(chunks)
            self.counters["bytes_delivered"] += len(data)
            reader.feed_data(data)
