"""FaultPlan — the declarative scenario grammar.

A plan is a seed, default link conditions, and a time-ordered set of fault
events. Times are **virtual seconds** from scenario start; node references
are committee indices (canonical pubkey-sorted order, the same dense ids
certificates and the DAG tensors use). The scenario runner
(simnet/scenario.py) applies each event at its virtual time; the fabric
(simnet/fabric.py) enforces the link-level ones on every byte it carries.

Grammar (constructors are the DSL):

    FaultPlan(
        seed=7,                        # drives jitter/drop AND retry jitter
        default_link=LinkSpec(latency=0.001, jitter=0.0005, drop=0.0),
        events=(
            Partition(at=2.0, heal=5.0, groups=((0, 1), (2, 3))),
            LinkFault(at=1.0, end=4.0, a=0, b=3,
                      link=LinkSpec(latency=0.05, jitter=0.02, drop=0.01)),
            Crash(at=3.0, node=2, restart_at=6.0),
            WorkerLoss(at=2.5, node=1, worker_id=0),
            Equivocate(node=3, start=0.0),
            Reconfigure(at=4.0),       # epoch += 1, in-band, under traffic
        ),
    )

Semantics:

* `LinkSpec` — per-chunk delivery latency (+ uniform jitter from the seeded
  RNG); `drop` is the probability a chunk is lost, which on a framed,
  AEAD-sequenced stream means the CONNECTION dies (both ends see a reset)
  and the retry machinery reconnects — exactly a flaky TCP path.
* `Partition` — nodes in different groups cannot exchange bytes between
  `at` and `heal`: existing cross-group connections are reset, new connects
  are refused. Nodes absent from every group form an implicit last group.
* `Crash` — the node is isolated at `at` (connections reset, connects
  refused) and shut down; with `restart_at` it reboots with a fresh store
  and catches up (the reference's crash/recovery model for in-memory runs).
* `WorkerLoss` — one worker lane dies mid-quorum; the primary and the other
  lanes keep running.
* `Equivocate` — the node signs two conflicting headers per round from
  `start` on and shows different ones to different halves of the committee
  (simnet/byzantine.py).
* `Reconfigure` — an in-band epoch change (new committee json, epoch+1)
  pushed through every primary's own-worker control plane while traffic
  flows.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class LinkSpec:
    """Per-chunk delivery conditions for one (or the default) link."""

    latency: float = 0.001  # seconds, one-way, per chunk
    jitter: float = 0.0  # uniform [0, jitter) added per chunk (seeded RNG)
    drop: float = 0.0  # P(chunk lost) => connection reset


@dataclass(frozen=True)
class Partition:
    at: float
    heal: float
    groups: tuple[tuple[int, ...], ...]


@dataclass(frozen=True)
class LinkFault:
    """Override conditions on the (a, b) node pair, both directions,
    between `at` and `end` (None = until scenario end)."""

    at: float
    a: int
    b: int
    link: LinkSpec
    end: float | None = None


@dataclass(frozen=True)
class Crash:
    at: float
    node: int
    restart_at: float | None = None


@dataclass(frozen=True)
class WorkerLoss:
    at: float
    node: int
    worker_id: int = 0


@dataclass(frozen=True)
class Equivocate:
    node: int
    start: float = 0.0


@dataclass(frozen=True)
class Reconfigure:
    at: float


@dataclass(frozen=True)
class FaultPlan:
    seed: int = 0
    default_link: LinkSpec = field(default_factory=LinkSpec)
    events: tuple = ()

    def byzantine_nodes(self) -> frozenset[int]:
        return frozenset(
            e.node for e in self.events if isinstance(e, Equivocate)
        )

    def timed_events(self) -> list:
        """Every event with an `at` time, sorted by application time (ties
        keep declaration order, so plans are unambiguous)."""
        timed = [e for e in self.events if hasattr(e, "at")]
        order = sorted(enumerate(timed), key=lambda pair: (pair[1].at, pair[0]))
        return [e for _, e in order]
