"""Byzantine equivocator — a validator that signs conflicting headers.

The adversary is a REAL node: it holds its own protocol keypair and uses it
to produce, for every round it proposes in, a second validly-signed header
(the "twin") that conflicts with the one its own core processes. The twin
is pushed as a full `HeaderMsg` by direct reliable send to half the
committee (every node accepts the full form regardless of its own
`header_wire` setting), while the ordinary broadcast path disseminates the
original — so different honest nodes may see the two conflicting headers in
either order.

Twin construction keeps the header *votable* when possible: if the parent
set has slack above the quorum threshold, the twin simply omits one parent
(a perfectly valid header with a different digest). With no slack it
carries a fabricated payload digest instead — still signed, still
conflicting, but honest nodes will never complete its payload sync.

What the protocol must guarantee (and the simnet safety oracle asserts):
the per-(author, round) vote-once rule means the author's implicit stake is
the only stake both twins share, so at most one of the two can ever reach a
quorum certificate — no two honest nodes commit conflicting sequences, with
or without the equivocator's slot filled.
"""

from __future__ import annotations

import logging

from ..crypto import digest256
from ..messages import HeaderMsg
from ..types import Header

logger = logging.getLogger("narwhal.simnet.byzantine")


class Equivocator:
    """Installed over a started node's core: wraps `process_own_header`."""

    def __init__(self, details, fixture_auth, committee):
        self._core = details.primary.primary.core
        self._network = details.primary.primary.network
        self._keypair = fixture_auth.keypair
        self._name = fixture_auth.public
        self._committee = committee
        self._orig = self._core.process_own_header
        self._core.process_own_header = self._process_own_header
        self.twins_sent = 0
        self.twin_digests: list[tuple[int, str, str]] = []  # (round, A, B)
        self._handles = []

    def _build_twin(self, header: Header) -> Header:
        parents = sorted(header.parents)
        # Stake-based count of parents a valid header can stand on: with
        # equal-stake fixtures this is the number of parent certificates a
        # quorum requires.
        if len(parents) > self._committee.quorum_threshold():
            twin_parents = frozenset(parents[1:])
            payload = dict(header.payload)
        else:
            twin_parents = header.parents
            payload = dict(header.payload)
            salt = digest256(
                b"EQUIVOCATE" + header.round.to_bytes(8, "little")
            )
            payload[salt] = 0
        return Header.build(
            self._name,
            header.round,
            header.epoch,
            payload,
            set(twin_parents),
            self._keypair,
        )

    async def _process_own_header(self, header: Header) -> None:
        twin = self._build_twin(header)
        if twin.digest != header.digest:
            msg = HeaderMsg(twin)
            others = self._committee.others_primaries(self._name)
            victims = others[::2]  # deterministic half of the committee
            for _, address, _ in victims:
                self._handles.append(self._network.send(address, msg))
            self.twins_sent += len(victims)
            self.twin_digests.append(
                (header.round, header.digest.hex(), twin.digest.hex())
            )
            logger.debug(
                "equivocated round %d: %s vs %s to %d peers",
                header.round, header.digest.hex()[:12],
                twin.digest.hex()[:12], len(victims),
            )
            # Completed reliable-send handles are dropped; live ones stay
            # referenced so the retry tasks are cancellable at teardown.
            self._handles = [h for h in self._handles if not h.task.done()]
        await self._orig(header)

    def uninstall(self) -> None:
        self._core.process_own_header = self._orig
        for h in self._handles:
            h.cancel()
        self._handles.clear()
