"""Generic compressed DAG: the `dag` crate rebuilt for a GC'd runtime.

Reference: /root/reference/dag/src/{lib,node_dag,bft}.rs — `NodeDag<T>` keeps
every vertex ever seen in one table (weak refs = interior/tombstones, strong
refs = heads), compresses paths through `compressible` vertices on access,
and drops bypassed vertices (their Arc count hits zero), leaving tombstones.

Python redesign: reference counting is replaced by explicit reachability —
a vertex is live iff a head reaches it through *compressed* parent edges.
`parents()` performs the same path compression (memoized by rewriting the
edge list); `sweep()` is the mark phase run from the heads, equivalent to the
drop cascade the Rust version gets for free from Arc. Heavy traversals over
the live window belong on device via the dense adjacency tensors
(narwhal_tpu/tpu/dag_kernels.DagWindow); this structure is the bookkeeping
layer keeping arbitrary-shape history exactly like the reference.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Generic, Hashable, Iterator, Protocol, TypeVar

Digest = Hashable


class Affiliated(Protocol):
    """Minimum interface for DAG values (dag/src/node_dag.rs:19-28)."""

    @property
    def digest(self) -> Digest: ...

    def parents(self) -> list[Digest]: ...

    def compressible(self) -> bool: ...


T = TypeVar("T")


class UnknownDigests(Exception):
    def __init__(self, digests: list[Digest]):
        super().__init__(f"no vertex known by digests {digests!r}")
        self.digests = digests


class DroppedDigest(Exception):
    def __init__(self, digest: Digest):
        super().__init__(f"vertex {digest!r} was dropped (compressed away)")
        self.digest = digest


@dataclass
class _Node(Generic[T]):
    value: T
    parents: list[Digest]
    compressible: bool
    live: bool = True  # False = tombstone (weak ref that can't upgrade)


class NodeDag(Generic[T]):
    """Digest-keyed DAG with head tracking and path compression."""

    def __init__(self):
        self._nodes: dict[Digest, _Node[T]] = {}
        self._heads: set[Digest] = set()

    # -- queries ----------------------------------------------------------

    def contains(self, digest: Digest) -> bool:
        """Was this digest ever inserted (live or tombstone)?"""
        return digest in self._nodes

    def contains_live(self, digest: Digest) -> bool:
        node = self._nodes.get(digest)
        return node is not None and node.live

    def has_head(self, digest: Digest) -> bool:
        if digest not in self._nodes:
            raise UnknownDigests([digest])
        return digest in self._heads

    def head_digests(self) -> list[Digest]:
        return list(self._heads)

    def get(self, digest: Digest) -> T:
        node = self._nodes.get(digest)
        if node is None:
            raise UnknownDigests([digest])
        if not node.live:
            raise DroppedDigest(digest)
        return node.value

    def size(self) -> int:
        """Number of table entries, tombstones included (node_dag.rs:241)."""
        return len(self._nodes)

    def live_size(self) -> int:
        return sum(1 for n in self._nodes.values() if n.live)

    # -- mutation ---------------------------------------------------------

    def try_insert(self, value: Affiliated) -> None:
        """Insert a vertex whose parents are already known; idempotent.

        Parents that were dropped are skipped (the reference logs and
        continues); unknown parents raise UnknownDigests with the full list
        (node_dag.rs:156-227).
        """
        digest = value.digest
        if digest in self._nodes:
            return  # idempotence
        parent_digests = value.parents()
        missing = [d for d in parent_digests if d not in self._nodes]
        if missing:
            raise UnknownDigests(missing)
        kept = [d for d in parent_digests if self._nodes[d].live]
        self._nodes[digest] = _Node(
            value=value,
            parents=kept,
            compressible=bool(value.compressible()),
        )
        self._heads.add(digest)
        for d in kept:
            self._heads.discard(d)

    def make_compressible(self, digest: Digest) -> bool:
        """Mark for GC; returns False if already marked
        (node_dag.rs:139-142)."""
        node = self._nodes.get(digest)
        if node is None:
            raise UnknownDigests([digest])
        if not node.live:
            raise DroppedDigest(digest)
        was = node.compressible
        node.compressible = True
        return not was

    # -- compression ------------------------------------------------------

    def parents(self, digest: Digest) -> list[Digest]:
        """Compressed parents: closest incompressible (live) ancestors.

        Iterative path compression with memoization — every visited vertex's
        edge list is rewritten to the compressed form (dag/src/lib.rs:231-276;
        the rayon parallelism there is unnecessary here because results are
        memoized across the sweep's whole pass).
        """
        # Two-phase DFS: a vertex's edge list is rewritten only after every
        # compressible parent has been rewritten (true post-order; reversed
        # pre-order is NOT topological when ancestors are shared).
        opened: set[Digest] = set()
        stack: list[tuple[Digest, bool]] = [(digest, False)]
        while stack:
            d, ready = stack.pop()
            node = self._nodes[d]
            if ready:
                out: list[Digest] = []
                for p in node.parents:
                    pn = self._nodes.get(p)
                    if pn is None or not pn.live:
                        continue
                    if pn.compressible:
                        out.extend(pn.parents)  # rewritten already (post-order)
                    else:
                        out.append(p)
                node.parents = list(dict.fromkeys(out))  # dedup, stable
                continue
            if d in opened:
                continue
            opened.add(d)
            stack.append((d, True))
            for p in node.parents:
                pn = self._nodes.get(p)
                if pn is not None and pn.live and pn.compressible and p not in opened:
                    stack.append((p, False))
        return list(self._nodes[digest].parents)

    def sweep(self) -> int:
        """Drop vertices bypassed by compression: mark from the heads over
        compressed edges, tombstone the rest. Returns number dropped. (The
        Arc-drop cascade of the Rust version, made explicit.)"""
        reachable: set[Digest] = set()
        queue = deque(self._heads)
        while queue:
            d = queue.popleft()
            if d in reachable:
                continue
            reachable.add(d)
            for p in self.parents(d):
                queue.append(p)
        dropped = 0
        for d, node in self._nodes.items():
            if node.live and d not in reachable:
                node.live = False
                node.value = None  # type: ignore[assignment] # reclaim memory
                node.parents = []
                dropped += 1
        return dropped

    # -- traversal --------------------------------------------------------

    def bft(self, digest: Digest) -> Iterator[T]:
        """Breadth-first traversal over live vertices from `digest`
        (dag/src/bft.rs:57-127), following compressed edges."""
        self.get(digest)  # raises Unknown/Dropped like the reference
        seen: set[Digest] = set()
        queue = deque([digest])
        while queue:
            d = queue.popleft()
            if d in seen:
                continue
            seen.add(d)
            yield self._nodes[d].value
            for p in self.parents(d):
                if p not in seen:
                    queue.append(p)
