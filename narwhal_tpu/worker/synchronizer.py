"""Worker Synchronizer: fetch missing batches on the primary's behalf.

Reference: /root/reference/worker/src/synchronizer.rs:77-384 — executes the
primary's Synchronize command by asking the target authority's same-id worker
for the missing batches, retrying on a timer via lucky_broadcast to
`sync_retry_nodes` random peers; handles Cleanup(round) GC of stale requests
and DeleteBatches.
"""

from __future__ import annotations

import asyncio
import logging

from ..channels import Channel, Subscriber, Watch, drain_cancelled
from ..clock import now as clock_now
from ..config import Committee, Parameters, WorkerCache
from ..messages import SynchronizeMsg, WorkerBatchRequest, WorkerBatchResponse
from ..network import NetworkClient, RpcError
from ..stores import BatchStore
from ..types import Digest, PublicKey, Round, WorkerId, serialized_batch_digest

logger = logging.getLogger("narwhal.worker")


class WorkerSynchronizer:
    def __init__(
        self,
        name: PublicKey,
        worker_id: WorkerId,
        committee: Committee,
        worker_cache: WorkerCache,
        parameters: Parameters,
        store: BatchStore,
        network: NetworkClient,
        rx_command: Channel,
        tx_batch_processor: Channel,
        rx_reconfigure: Watch,
        metrics=None,
    ):
        self.name = name
        self.worker_id = worker_id
        self.committee = committee
        self.worker_cache = worker_cache
        self.parameters = parameters
        self.store = store
        self.network = network
        self.rx_command = rx_command
        self.tx_batch_processor = tx_batch_processor
        self.rx_reconfigure = Subscriber(rx_reconfigure)
        self.metrics = metrics
        # digest -> (deadline round, target authority, request time)
        self.pending: dict[Digest, tuple[Round, PublicKey, float]] = {}
        self.gc_round: Round = 0
        # In-flight fetch attempts. A dropped handle here is the shutdown
        # wedge class: a fetch parked on tx_batch_processor.send after the
        # processor stopped would never be cancelled.
        self._fetch_tasks: set[asyncio.Task] = set()

    def spawn(self) -> asyncio.Task:
        return asyncio.ensure_future(self.run())

    async def run(self) -> None:
        timer = asyncio.ensure_future(asyncio.sleep(self.parameters.sync_retry_delay))
        cmd = asyncio.ensure_future(self.rx_command.recv())
        try:
            while True:
                done, _ = await asyncio.wait(
                    {timer, cmd}, return_when=asyncio.FIRST_COMPLETED
                )
                note = self.rx_reconfigure.peek()
                if note.kind == "shutdown":
                    return
                if note.committee is not None and note.committee is not self.committee:
                    self.committee = note.committee
                if cmd in done:
                    msg = cmd.result()
                    cmd = asyncio.ensure_future(self.rx_command.recv())
                    if isinstance(msg, SynchronizeMsg):
                        await self._synchronize(msg)
                    else:  # Cleanup round
                        self._cleanup(msg)
                if timer in done:
                    timer = asyncio.ensure_future(
                        asyncio.sleep(self.parameters.sync_retry_delay)
                    )
                    await self._retry()
        finally:
            timer.cancel()
            cmd.cancel()
            for t in list(self._fetch_tasks):
                t.cancel()
            await drain_cancelled(self._fetch_tasks, who="worker synchronizer")

    async def _synchronize(self, msg: SynchronizeMsg) -> None:
        missing = [d for d in msg.digests if not self.store.contains(d)]
        t_now = clock_now()
        for d in missing:
            self.pending[d] = (self.gc_round, msg.target, t_now)
        if self.metrics is not None:
            self.metrics.pending_sync_batches.set(len(self.pending))
        if not missing:
            return
        try:
            info = self.worker_cache.worker(msg.target, self.worker_id)
        except KeyError:
            logger.warning("synchronize target has no worker %d", self.worker_id)
            return
        self._spawn_fetch(info.worker_address, tuple(missing))

    def _spawn_fetch(self, address: str, digests: tuple[Digest, ...]) -> None:
        task = asyncio.ensure_future(self._fetch(address, digests))
        self._fetch_tasks.add(task)
        task.add_done_callback(self._fetch_tasks.discard)

    async def _fetch(self, address: str, digests: tuple[Digest, ...]) -> None:
        """One fetch attempt; received batches flow through the others-batch
        processor path, which stores them and notifies the primary."""
        # Trim at send time, not just at spawn time: between the retry tick
        # that built this want-list and this task actually running, digests
        # may have arrived (another fetch's response, a peer's broadcast).
        # Re-requesting them re-ships whole batches for nothing.
        digests = tuple(
            d for d in digests if d in self.pending and not self.store.contains(d)
        )
        if not digests:
            return
        try:
            resp: WorkerBatchResponse = await self.network.request(
                address, WorkerBatchRequest(digests), timeout=5.0
            )
        except (RpcError, OSError):
            return  # the retry timer will lucky-broadcast
        for serialized in resp.batches:
            digest = serialized_batch_digest(serialized)
            # Pop is keyed by the digest THIS response delivered: a
            # concurrent fetch that re-registers at the yield point is
            # satisfied by the same arrival, so losing its entry is correct.
            self.pending.pop(digest, None)  # lint: allow(await-interleaved-rmw)
            await self.tx_batch_processor.send((serialized, False))
        if self.metrics is not None:
            self.metrics.pending_sync_batches.set(len(self.pending))

    async def _retry(self) -> None:
        still_missing = []
        for d in list(self.pending):
            if self.store.contains(d):
                self.pending.pop(d, None)
            else:
                still_missing.append(d)
        if not still_missing:
            if self.metrics is not None:
                self.metrics.pending_sync_batches.set(0)
            return
        # Lucky broadcast the whole want-list to a few random same-id workers
        # (synchronizer.rs:311-345).
        addresses = [
            info.worker_address
            for _, info in self.worker_cache.others_workers(self.name, self.worker_id)
        ]
        if not addresses:
            return
        import random

        # Deliberate draw from the scenario-seeded global stream: retry
        # fan-out choice replays under the same seed.
        chosen = random.sample(  # lint: allow(unseeded-random)
            addresses, min(self.parameters.sync_retry_nodes, len(addresses))
        )
        for addr in chosen:
            self._spawn_fetch(addr, tuple(still_missing))

    def _cleanup(self, round: Round) -> None:
        """Drop pending requests from before the GC round
        (synchronizer.rs:215-282)."""
        self.gc_round = max(self.gc_round, round)
        for d in [d for d, (r, _, _) in self.pending.items() if r < self.gc_round]:
            self.pending.pop(d, None)
        if self.metrics is not None:
            self.metrics.pending_sync_batches.set(len(self.pending))
