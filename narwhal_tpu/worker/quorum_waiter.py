"""QuorumWaiter: reliably disseminate a batch and wait for 2f+1 stake.

Reference: /root/reference/worker/src/quorum_waiter.rs:39-157 — broadcast the
serialized batch to the same-id worker of every other authority via reliable
send, sum acked stake (own stake counts) until quorum_threshold, then forward
the batch onward to the Processor.
"""

from __future__ import annotations

import asyncio
import logging

from ..channels import BoundedFuturesOrdered, Channel, Subscriber, Watch
from ..config import Committee, WorkerCache
from ..messages import WorkerBatchMsg
from ..network import NetworkClient
from ..types import PublicKey, SealedBatch, WorkerId

logger = logging.getLogger("narwhal.worker")

# Batches disseminating concurrently. Sequential dissemination caps
# throughput at batch_size / quorum-RTT; pipelining hides the round-trip
# while BoundedFuturesOrdered keeps the processor seeing batches in seal
# order (the reference gets the same effect from cheap RTTs; here the
# in-flight window is explicit).
MAX_INFLIGHT_BATCHES = 64


class QuorumWaiter:
    def __init__(
        self,
        name: PublicKey,
        worker_id: WorkerId,
        committee: Committee,
        worker_cache: WorkerCache,
        network: NetworkClient,
        rx_message: Channel,
        tx_batch: Channel,
        rx_reconfigure: Watch,
    ):
        self.name = name
        self.worker_id = worker_id
        self.committee = committee
        self.worker_cache = worker_cache
        self.network = network
        self.rx_message = rx_message
        self.tx_batch = tx_batch
        self.rx_reconfigure = Subscriber(rx_reconfigure)

    def spawn(self) -> asyncio.Task:
        return asyncio.ensure_future(self.run())

    async def run(self) -> None:
        pool = BoundedFuturesOrdered(MAX_INFLIGHT_BATCHES)
        forwarder = asyncio.ensure_future(self._forward(pool))
        try:
            while True:
                batch: SealedBatch = await self.rx_message.recv()
                note = self.rx_reconfigure.peek()
                if note.kind == "shutdown":
                    return
                if note.committee is not None and note.committee is not self.committee:
                    # Adopt the reconfigured committee before counting stake.
                    self.committee = note.committee
                # Push blocks once MAX_INFLIGHT_BATCHES are disseminating:
                # backpressure flows to the batch maker's channel.
                await pool.push(self._disseminate(batch))
        finally:
            forwarder.cancel()
            pool.cancel_all()

    async def _forward(self, pool: BoundedFuturesOrdered) -> None:
        """Pop dissemination results in seal order and hand quorum-acked
        batches to the processor."""
        while True:
            try:
                batch, ok = await pool.next()
            except asyncio.CancelledError:
                raise
            except Exception:
                # A dissemination task died unexpectedly (e.g. a peer vanished
                # from a reconfigured committee). Dropping that one batch is
                # the quorum-failure outcome; dying here would silently stall
                # the whole pipeline once the pool fills.
                logger.exception("batch dissemination task failed")
                continue
            if ok:
                # The SealedBatch travels intact: its cached digest spares the
                # processor a re-hash of our own payload bytes.
                await self.tx_batch.send((batch, True))
            else:
                logger.warning("batch dissemination failed to reach quorum")

    async def _disseminate(self, batch: SealedBatch) -> tuple[SealedBatch, bool]:
        serialized = batch.serialized
        others = self.worker_cache.others_workers(self.name, self.worker_id)
        msg = WorkerBatchMsg(serialized)
        handles = [
            (self.committee.stake(pk), self.network.send(info.worker_address, msg))
            for pk, info in others
        ]
        total = self.committee.stake(self.name)  # our own vote
        threshold = self.committee.quorum_threshold()
        pending = {
            asyncio.ensure_future(self._wait(stake, h)) for stake, h in handles
        }
        try:
            while total < threshold and pending:
                done, _ = await asyncio.wait(
                    pending, return_when=asyncio.FIRST_COMPLETED
                )
                for t in done:
                    # asyncio.wait's done set — completed-task reads only.
                    total += t.result()  # lint: allow(no-blocking-in-async)
                    pending.discard(t)
        finally:
            # Remaining reliable sends keep retrying in the background
            # (the reference lets its CancelOnDrop handles continue until
            # the waiter future set is dropped after quorum).
            for t in pending:
                t.cancel()
        return batch, total >= threshold

    @staticmethod
    async def _wait(stake: int, handle) -> int:
        try:
            await handle
            return stake
        except asyncio.CancelledError:
            return 0
