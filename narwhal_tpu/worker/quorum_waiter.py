"""QuorumWaiter: reliably disseminate a batch and wait for 2f+1 stake.

Reference: /root/reference/worker/src/quorum_waiter.rs:39-157 — broadcast the
serialized batch to the same-id worker of every other authority via reliable
send, sum acked stake (own stake counts) until quorum_threshold, then forward
the batch onward to the Processor.
"""

from __future__ import annotations

import asyncio
import logging

from ..channels import Channel, Subscriber, Watch
from ..config import Committee, WorkerCache
from ..messages import WorkerBatchMsg
from ..network import NetworkClient
from ..types import Batch, PublicKey, WorkerId

logger = logging.getLogger("narwhal.worker")


class QuorumWaiter:
    def __init__(
        self,
        name: PublicKey,
        worker_id: WorkerId,
        committee: Committee,
        worker_cache: WorkerCache,
        network: NetworkClient,
        rx_message: Channel,
        tx_batch: Channel,
        rx_reconfigure: Watch,
    ):
        self.name = name
        self.worker_id = worker_id
        self.committee = committee
        self.worker_cache = worker_cache
        self.network = network
        self.rx_message = rx_message
        self.tx_batch = tx_batch
        self.rx_reconfigure = Subscriber(rx_reconfigure)

    def spawn(self) -> asyncio.Task:
        return asyncio.ensure_future(self.run())

    async def run(self) -> None:
        while True:
            batch: Batch = await self.rx_message.recv()
            note = self.rx_reconfigure.peek()
            if note.kind == "shutdown":
                return
            if note.committee is not None and note.committee is not self.committee:
                # Adopt the reconfigured committee before counting stake.
                self.committee = note.committee
            serialized = batch.to_bytes()
            others = self.worker_cache.others_workers(self.name, self.worker_id)
            msg = WorkerBatchMsg(serialized)
            handles = [
                (self.committee.stake(pk), self.network.send(info.worker_address, msg))
                for pk, info in others
            ]

            total = self.committee.stake(self.name)  # our own vote
            threshold = self.committee.quorum_threshold()
            pending = {
                asyncio.ensure_future(self._wait(stake, h)) for stake, h in handles
            }
            try:
                while total < threshold and pending:
                    done, _ = await asyncio.wait(
                        pending, return_when=asyncio.FIRST_COMPLETED
                    )
                    for t in done:
                        total += t.result()
                        pending.discard(t)
            finally:
                # Remaining reliable sends keep retrying in the background
                # (the reference lets its CancelOnDrop handles continue until
                # the waiter future set is dropped after quorum).
                for t in pending:
                    t.cancel()
            if total >= threshold:
                await self.tx_batch.send((serialized, True))
            else:
                logger.warning("batch dissemination failed to reach quorum")

    @staticmethod
    async def _wait(stake: int, handle) -> int:
        try:
            await handle
            return stake
        except asyncio.CancelledError:
            return 0
