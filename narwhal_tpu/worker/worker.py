"""Worker assembly: networks, tx ingest, and the batch pipeline actors.

Reference: /root/reference/worker/src/worker.rs:57-211 (spawn),
TxReceiverHandler :352-423, WorkerReceiverHandler :426-466,
PrimaryReceiverHandler (Synchronize/Cleanup/RequestBatch/DeleteBatches/
Reconfigure) routed through the synchronizer.

One RPC server on `worker_address` carries both the worker<->worker plane and
the primary->worker plane; a second server on `transactions` is the
client-facing tx ingest (the tonic Transactions service analog). A design
delta: RequestBatch and DeleteBatches are served as direct RPC responses
instead of loose WorkerToPrimary messages — same capability, one less round
trip (the reference's BlockWaiter matches responses manually,
primary/src/block_waiter.rs:549-).
"""

from __future__ import annotations

import asyncio
import logging
import os

from ..channels import Channel, Watch, drain_cancelled, metered_channel
from ..config import Committee, Parameters, WorkerCache, env_float, pacing_enabled
from ..messages import (
    BackpressureMsg,
    CleanupMsg,
    DeleteBatchesMsg,
    DeletedBatchesMsg,
    ReconfigureMsg,
    RequestBatchesMsg,
    RequestBatchMsg,
    RequestedBatchesMsg,
    RequestedBatchMsg,
    SubmitTransactionMsg,
    SubmitTransactionStreamMsg,
    SynchronizeMsg,
    WorkerBatchMsg,
    WorkerBatchRequest,
    WorkerBatchResponse,
)
from ..metrics import Registry
from ..network import NetworkClient, RpcServer, WireCounters, cached_allow_sets
from ..pacing import BackpressureState, IngestGate, PacingController
from ..stores import BatchStore
from ..types import (
    Batch,
    PublicKey,
    ReconfigureNotification,
    WorkerId,
    validate_tx_frames,
)
from .batch_maker import BatchMaker
from .metrics import WorkerMetrics
from .primary_connector import PrimaryConnector
from .processor import Processor
from .quorum_waiter import QuorumWaiter
from .synchronizer import WorkerSynchronizer

logger = logging.getLogger("narwhal.worker")


class Worker:
    def __init__(
        self,
        name: PublicKey,
        worker_id: WorkerId,
        committee: Committee,
        worker_cache: WorkerCache,
        parameters: Parameters,
        store: BatchStore,
        registry: Registry | None = None,
        benchmark: bool = False,
        network_keypair=None,
        tracer=None,  # tracing.Tracer: the node's span/flight recorder
    ):
        self.name = name
        self.worker_id = worker_id
        self.committee = committee
        self.worker_cache = worker_cache
        self.parameters = parameters
        self.store = store
        self.registry = registry or Registry()
        if tracer is None:
            from ..tracing import Tracer

            tracer = Tracer(node=f"worker-{name.hex()[:8]}-{worker_id}")
        self.tracer = tracer
        self.metrics = WorkerMetrics(self.registry, tracer=tracer)
        self.benchmark = benchmark

        # Transport identity (worker.rs:137-146 registers worker network keys
        # as known anemo peers). With a keypair the mesh server requires the
        # mutual handshake and the client authenticates to peers; without one
        # (bare component tests) the mesh runs open.
        self.network_keypair = network_keypair
        credentials = None
        if network_keypair is not None:
            from ..network import Credentials, committee_resolver

            credentials = Credentials(
                network_keypair,
                committee_resolver(lambda: self.committee, lambda: self.worker_cache),
            )
        # Per-link wire accounting for the payload plane (batch
        # dissemination is the data-plane bulk of MB/round).
        self.wire_counters = WireCounters(self.registry)
        # Join the co-hosted node's connection pool (network/pool.py): the
        # Primary — holder of the node's network keypair — creates and
        # registers it under the authority name; this worker's mesh lane
        # then rides the node pair's ONE multiplexed connection. Absent
        # pool (standalone worker, split deployment, NARWHAL_POOL=0) the
        # worker keeps legacy dedicated connections.
        from ..network import node_pool

        self.pool = node_pool(self.name) if network_keypair is not None else None
        self.network = NetworkClient(
            credentials=credentials, counters=self.wire_counters, pool=self.pool
        )
        self.server = RpcServer(
            parameters.max_concurrent_requests,
            auth_keypair=network_keypair,
            counters=self.wire_counters,
        )
        self.tx_server = RpcServer(
            parameters.max_concurrent_requests, counters=self.wire_counters
        )
        self.rx_reconfigure: Watch = Watch(ReconfigureNotification("boot"))
        self._tasks: list[asyncio.Task] = []

        # Channels (worker/src/worker.rs:229-346 wiring), depth-gauged
        # (SURVEY §5.6; types/src/metered_channel.rs:15-259).
        def chan(name: str, capacity: int) -> Channel:
            return metered_channel(self.registry, "worker", name, capacity)

        self.tx_batch_maker = chan("batch_maker", 10_000)
        self.tx_quorum_waiter = chan("quorum_waiter", 1_000)
        self.tx_processor = chan("processor", 1_000)
        self.tx_others_processor = chan("others_processor", 1_000)
        self.tx_digest = chan("digest", 10_000)
        self.tx_sync_command = chan("sync_command", 1_000)

        # End-to-end admission control: the primary pushes its downstream
        # (consensus/executor) backlog level here (BackpressureMsg), and the
        # client-facing ingest handlers gate on the max of that level and
        # the local ingest-queue occupancy. Past the high watermark the
        # gate sheds (RESOURCE_EXHAUSTED) or blocks per ingest_policy, so
        # overload degrades to bounded latency instead of unbounded backlog.
        self.backpressure = BackpressureState(
            high=parameters.backpressure_high_watermark,
            low=parameters.backpressure_low_watermark,
            stale_after=parameters.backpressure_stale_after,
            gauge=self.metrics.backpressure_level,
        )
        self.ingest_gate = IngestGate(
            policy=os.environ.get("NARWHAL_INGEST_POLICY", parameters.ingest_policy),
            local_sources=[
                self.tx_batch_maker.occupancy,
                self.tx_quorum_waiter.occupancy,
                self.tx_processor.occupancy,
            ],
            downstream=self.backpressure,
            high=parameters.backpressure_high_watermark,
            low=parameters.backpressure_low_watermark,
            metrics=self.metrics,
        )
        # Adaptive seal pacing: the batch maker's effective delay tracks
        # the EWMA occupancy of the batch pipeline's channels between
        # batch_delay_floor and max_batch_delay. NARWHAL_PACING=0 pins the
        # configured ceiling (the fixed-timer seed behavior).
        self.batch_pacing: PacingController | None = None
        if pacing_enabled():
            self.batch_pacing = PacingController(
                ceiling=parameters.max_batch_delay,
                floor=env_float(
                    "NARWHAL_BATCH_DELAY_FLOOR", parameters.batch_delay_floor
                ),
                low_occupancy=parameters.pacing_low_occupancy,
                high_occupancy=parameters.pacing_high_occupancy,
                ewma_alpha=parameters.pacing_ewma_alpha,
                sources=[
                    self.tx_batch_maker.occupancy,
                    self.tx_quorum_waiter.occupancy,
                    self.tx_processor.occupancy,
                ],
                gauge=self.metrics.pacing_occupancy,
            )

    async def spawn(self) -> None:
        # The node pool may have been registered after our construction
        # (assembly order is harness-specific); re-check before binding so
        # this worker's lane joins it either way.
        if self.pool is None and self.network_keypair is not None:
            from ..network import node_pool

            self.pool = node_pool(self.name)
            if self.pool is not None:
                self.network.attach_pool(self.pool)
        if self.pool is not None:
            from ..network import worker_lane

            self.pool.register_lane(worker_lane(self.worker_id), self.server)
        me = self.worker_cache.worker(self.name, self.worker_id)
        host, port = me.worker_address.rsplit(":", 1)
        bound = await self.server.start(host, int(port))
        self.worker_address = f"{host}:{bound}"
        thost, tport = me.transactions.rsplit(":", 1)
        tbound = await self.tx_server.start(thost, int(tport))
        self.transactions_address = f"{thost}:{tbound}"
        # Interoperable gRPC ingest (the reference's tonic Transactions
        # service, worker.rs:369-423) alongside the high-throughput typed
        # ingest; ephemeral port, surfaced via grpc_transactions_address.
        # grpc.aio binds a REAL socket, so it is skipped under the simnet
        # transport (simulated committees are zero-socket by contract; the
        # typed ingest above already rides the fabric).
        from ..network import transport as _transport

        if _transport.simnet_active():
            self.grpc_transactions_address = ""
        else:
            from ..grpc_api import GrpcTransactions

            self.grpc_transactions = GrpcTransactions(
                self.tx_batch_maker, self.metrics, gate=self.ingest_gate
            )
            self.grpc_transactions_address = await self.grpc_transactions.spawn(
                f"{thost}:0"
            )

        # Route the three planes with the authorization matrix: batch planes
        # accept same-lane workers of any committee member, the control plane
        # (sync/cleanup/delete/reconfigure — worker/src/worker.rs:137-146,
        # synchronizer.rs:215-282) ONLY our own primary. Predicates read
        # self.committee/worker_cache live, so epoch changes apply.
        allow_peer_worker = self._allow_peer_worker if self.network_keypair else None
        allow_own_primary = self._allow_own_primary if self.network_keypair else None
        self.server.route(WorkerBatchMsg, self._on_peer_batch, allow=allow_peer_worker)
        self.server.route(
            WorkerBatchRequest, self._on_batch_request, allow=allow_peer_worker
        )
        self.server.route(SynchronizeMsg, self._on_synchronize, allow=allow_own_primary)
        self.server.route(CleanupMsg, self._on_cleanup, allow=allow_own_primary)
        self.server.route(
            RequestBatchMsg, self._on_request_batch, allow=allow_own_primary
        )
        self.server.route(
            RequestBatchesMsg, self._on_request_batches, allow=allow_own_primary
        )
        self.server.route(
            DeleteBatchesMsg, self._on_delete_batches, allow=allow_own_primary
        )
        self.server.route(ReconfigureMsg, self._on_reconfigure, allow=allow_own_primary)
        self.server.route(
            BackpressureMsg, self._on_backpressure, allow=allow_own_primary
        )
        self.tx_server.route(SubmitTransactionMsg, self._on_tx)
        self.tx_server.route(SubmitTransactionStreamMsg, self._on_tx_stream)

        primary_address = self.committee.primary_address(self.name)

        self._tasks = [
            BatchMaker(
                self.parameters.batch_size,
                self.parameters.max_batch_delay,
                self.tx_batch_maker,
                self.tx_quorum_waiter,
                self.rx_reconfigure,
                self.metrics,
                self.benchmark,
                pacing=self.batch_pacing,
            ).spawn(),
            QuorumWaiter(
                self.name,
                self.worker_id,
                self.committee,
                self.worker_cache,
                self.network,
                self.tx_quorum_waiter,
                self.tx_processor,
                self.rx_reconfigure,
            ).spawn(),
            Processor(
                self.worker_id,
                self.store,
                self.tx_processor,
                self.tx_digest,
                self.rx_reconfigure,
                self.metrics,
            ).spawn(),
            Processor(
                self.worker_id,
                self.store,
                self.tx_others_processor,
                self.tx_digest,
                self.rx_reconfigure,
                self.metrics,
            ).spawn(),
            PrimaryConnector(
                primary_address, self.network, self.tx_digest, self.rx_reconfigure
            ).spawn(),
            WorkerSynchronizer(
                self.name,
                self.worker_id,
                self.committee,
                self.worker_cache,
                self.parameters,
                self.store,
                self.network,
                self.tx_sync_command,
                self.tx_others_processor,
                self.rx_reconfigure,
                self.metrics,
            ).spawn(),
        ]
        # Benchmark-parsed boot line (worker/src/worker.rs:194-204).
        logger.info(
            "Worker %d successfully booted on %s", self.worker_id,
            self.transactions_address,
        )

    # -- handlers ---------------------------------------------------------
    # -- authorization predicates (handshake-verified peer identity) -------
    def _auth_sets(self) -> tuple[frozenset, frozenset]:
        def build():
            lane = frozenset(
                {self.worker_cache.worker(self.name, self.worker_id).name}
                | {
                    info.name
                    for _, info in self.worker_cache.others_workers(
                        self.name, self.worker_id
                    )
                }
                # Pooled links authenticate with the peer NODE's identity
                # (its authority network key), not the per-worker key —
                # the anemo node-granularity trust model: any committee
                # node may reach the batch plane, exactly the set whose
                # same-lane workers could anyway.
                | {a.network_key for a in self.committee.authorities.values()}
            )
            own_primary = frozenset({self.committee.network_key(self.name)})
            return lane, own_primary

        return cached_allow_sets(self, self.committee, self.worker_cache, build)

    def _allow_peer_worker(self, peer) -> bool:
        """Same-lane workers of any committee authority (incl. ourselves)."""
        return peer.key is not None and peer.key in self._auth_sets()[0]

    def _allow_own_primary(self, peer) -> bool:
        """Control-plane frames: only our own authority's primary."""
        return peer.key is not None and peer.key in self._auth_sets()[1]

    async def _on_peer_batch(self, msg: WorkerBatchMsg, peer: str):
        self.metrics.batches_received.inc()
        await self.tx_others_processor.send((msg.serialized_batch, False))
        return None

    async def _on_batch_request(self, msg: WorkerBatchRequest, peer: str):
        found = []
        for d in msg.digests:
            raw = self.store.read(d)
            if raw is not None:
                found.append(raw)
        return WorkerBatchResponse(tuple(found))

    async def _on_synchronize(self, msg: SynchronizeMsg, peer: str):
        await self.tx_sync_command.send(msg)
        return None

    async def _on_cleanup(self, msg: CleanupMsg, peer: str):
        await self.tx_sync_command.send(msg.round)
        return None

    async def _on_request_batch(self, msg: RequestBatchMsg, peer: str):
        raw = self.store.read(msg.digest)
        if raw is None:
            return RequestedBatchMsg(msg.digest, b"", found=False)
        # Serve the stored wire bytes as-is; decoding is the requester's.
        return RequestedBatchMsg(msg.digest, raw)

    async def _on_request_batches(self, msg: RequestBatchesMsg, peer: str):
        # One coalesced store read answers the whole group; entries are
        # byte-identical to the per-digest RequestBatchMsg responses.
        raws = self.store.read_all(msg.digests)
        return RequestedBatchesMsg(
            tuple(
                (d, raw is not None, raw if raw is not None else b"")
                for d, raw in zip(msg.digests, raws)
            )
        )

    async def _on_delete_batches(self, msg: DeleteBatchesMsg, peer: str):
        self.store.delete_all(msg.digests)
        return DeletedBatchesMsg(msg.digests)

    async def _on_reconfigure(self, msg: ReconfigureMsg, peer: str):
        committee = msg.committee()
        if committee is not None:
            self.committee = committee
        self.rx_reconfigure.send(ReconfigureNotification(msg.kind, committee))
        return None

    async def _on_backpressure(self, msg: BackpressureMsg, peer: str):
        self.backpressure.update(msg.level)
        return None

    async def _on_tx(self, msg: SubmitTransactionMsg, peer: str):
        # Admission control first: shedding raises IngestOverloadError,
        # which the RPC server surfaces to the client as an ERR frame whose
        # text carries the RESOURCE_EXHAUSTED prefix verbatim.
        await self.ingest_gate.admit()
        self.metrics.tx_received.inc()
        tx = msg.transaction
        frame = len(tx).to_bytes(4, "little") + tx
        await self.tx_batch_maker.send((1, frame))
        return None

    async def _on_tx_stream(self, msg: SubmitTransactionStreamMsg, peer: str):
        # Bursts stay in wire form: validate the frame structure (the only
        # per-tx work, two unpacks each, no copies) and forward the whole
        # chunk as one channel item straight into batch sealing.
        count = msg.count
        if count == 0:
            return None  # empty submission: no-op, never an empty batch
        await self.ingest_gate.admit()
        frames = msg.frames
        validate_tx_frames(frames, count)
        self.metrics.tx_received.inc(count)
        await self.tx_batch_maker.send((count, frames))
        return None

    # -- lifecycle --------------------------------------------------------
    async def shutdown(self) -> None:
        self.rx_reconfigure.send(ReconfigureNotification("shutdown"))
        for t in self._tasks:
            t.cancel()
        await drain_cancelled(self._tasks, who="worker")
        if self.pool is not None:
            from ..network import worker_lane

            self.pool.unregister_lane(worker_lane(self.worker_id))
        await self.server.stop()
        await self.tx_server.stop()
        if hasattr(self, "grpc_transactions"):
            await self.grpc_transactions.shutdown()
        self.network.close()
