"""Worker metrics (/root/reference/worker/src/metrics.rs)."""

from __future__ import annotations

from ..metrics import Registry


class WorkerMetrics:
    def __init__(self, registry: Registry):
        self.created_batch_size = registry.histogram(
            "worker_created_batch_size", "Size in bytes of sealed batches",
            buckets=(1_000, 10_000, 50_000, 100_000, 250_000, 500_000, 1_000_000),
        )
        self.batches_made = registry.counter(
            "worker_batches_made", "Batches sealed by the batch maker"
        )
        self.batches_received = registry.counter(
            "worker_batches_received", "Batches received from peer workers"
        )
        self.pending_sync_batches = registry.gauge(
            "worker_pending_sync_batches", "Batches the synchronizer is awaiting"
        )
        self.tx_received = registry.counter(
            "worker_tx_received", "Transactions received from clients"
        )
