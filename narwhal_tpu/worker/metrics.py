"""Worker metrics (/root/reference/worker/src/metrics.rs)."""

from __future__ import annotations

from ..metrics import Registry
from ..pacing import StageTimer


class WorkerMetrics:
    def __init__(self, registry: Registry, tracer=None):
        self.tracer = tracer
        # -- pacing / admission control / stage tracing --------------------
        self.stage_latency = registry.histogram(
            "worker_stage_latency_seconds",
            "Per-stage pipeline latency on the worker (stage=seal: first "
            "pending transaction chunk -> batch sealed)",
            labels=("stage",),
        )
        # Span-unified close site for the seal stage: the batch digest (the
        # waterfall's root causal key) exists only once the batch seals, so
        # the batch maker calls seal_timer.close(digest, t0) directly.
        self.seal_timer = StageTimer(self.stage_latency, "seal", tracer=tracer)
        self.effective_batch_delay = registry.gauge(
            "worker_effective_batch_delay_seconds",
            "The adaptive seal delay currently in force (floor when queues "
            "are shallow, max_batch_delay under load)",
        )
        self.pacing_occupancy = registry.gauge(
            "worker_pacing_occupancy",
            "EWMA queue occupancy the batch-maker pacing controller reads",
        )
        self.backpressure_level = registry.gauge(
            "worker_backpressure_level",
            "Downstream backlog level last pushed by our primary (0-1; "
            "stale values fail open to 0)",
        )
        self.ingest_shed = registry.counter(
            "worker_ingest_shed",
            "Client submissions answered RESOURCE_EXHAUSTED by admission "
            "control instead of queueing unboundedly",
        )
        self.ingest_blocked_seconds = registry.histogram(
            "worker_ingest_blocked_seconds",
            "Time client submissions were held at the gate under the "
            "'block' ingest policy before admission",
        )
        self.created_batch_size = registry.histogram(
            "worker_created_batch_size", "Size in bytes of sealed batches",
            buckets=(1_000, 10_000, 50_000, 100_000, 250_000, 500_000, 1_000_000),
        )
        self.batches_made = registry.counter(
            "worker_batches_made", "Batches sealed by the batch maker"
        )
        self.batches_received = registry.counter(
            "worker_batches_received", "Batches received from peer workers"
        )
        self.pending_sync_batches = registry.gauge(
            "worker_pending_sync_batches", "Batches the synchronizer is awaiting"
        )
        self.tx_received = registry.counter(
            "worker_tx_received", "Transactions received from clients"
        )
