"""BatchMaker: accumulate transactions into sealed batches.

Reference: /root/reference/worker/src/batch_maker.rs:48-193 — seal when the
pending bytes reach `batch_size` or `max_batch_delay` elapses; under the
benchmark feature it logs "Batch {digest} contains sample tx {id}" for sample
transactions (first byte 0, u64 id following) and "Batch {digest} contains
{n} B" — the log lines the benchmark harness parses for TPS/latency
(benchmark/benchmark/logs.py:171-244). We emit byte-compatible lines.
"""

from __future__ import annotations

import asyncio
import logging
import struct

from ..channels import Channel, Subscriber, Watch
from ..clock import now
from ..types import SealedBatch, assemble_serialized_batch, iter_serialized_batch_txs

logger = logging.getLogger("narwhal.worker")


class BatchMaker:
    def __init__(
        self,
        batch_size: int,
        max_batch_delay: float,
        rx_transaction: Channel,
        tx_message: Channel,
        rx_reconfigure: Watch,
        metrics=None,
        benchmark: bool = False,
        pacing=None,  # pacing.PacingController: adaptive seal delay
    ):
        self.batch_size = batch_size
        self.max_batch_delay = max_batch_delay
        self.rx_transaction = rx_transaction
        self.tx_message = tx_message
        self.rx_reconfigure = Subscriber(rx_reconfigure)
        self.metrics = metrics
        self.benchmark = benchmark
        self.pacing = pacing
        # Pending transactions stay in wire form: (frame chunks, tx count).
        self._pending: list[bytes] = []
        self._pending_count = 0
        self._pending_bytes = 0
        # Arrival of the first chunk since the last seal: the seal-stage
        # latency sample (worker_stage_latency_seconds{stage="seal"}),
        # closed through the span-unified seal timer — the batch digest is
        # the waterfall's root causal key and exists only at seal time.
        self._pending_t0: float | None = None
        self._seal_timer = metrics.seal_timer if metrics is not None else None

    def spawn(self) -> asyncio.Task:
        return asyncio.ensure_future(self.run())

    def _seal_delay(self) -> float:
        """The effective seal delay for this loop iteration. With a pacing
        controller the delay adapts between its floor and max_batch_delay on
        queue occupancy — but only while transactions are pending: an idle
        batch maker keeps the ceiling (there is nothing whose latency the
        floor could improve, and the timer with an empty pending set is a
        no-op anyway)."""
        if self.pacing is not None and self._pending:
            delay = self.pacing.delay()
        else:
            if self.pacing is not None:
                self.pacing.observe()  # keep the EWMA live across idle gaps
            delay = self.max_batch_delay
        if self.metrics is not None:
            self.metrics.effective_batch_delay.set(delay)
        return delay

    async def run(self) -> None:
        # Fixed deadline, NOT an idle timeout: the timer runs from the last
        # seal, so a steady sub-batch-size trickle still seals every
        # effective delay (batch_maker.rs:77-122 uses an interval timer).
        # The deadline is recomputed from `last_seal` each iteration so a
        # pacing change (queues draining/filling) takes effect mid-wait.
        last_seal = now()
        while True:
            deadline = last_seal + self._seal_delay()
            timeout = max(0.0, deadline - now())
            try:
                # Receives whole client bursts as (count, frames) chunks in
                # wire form: one channel hop and zero per-tx work per burst.
                count, frames = await asyncio.wait_for(
                    self.rx_transaction.recv(), timeout=timeout
                )
                if self.rx_reconfigure.peek().kind == "shutdown":
                    return
                if not self._pending:
                    self._pending_t0 = now()
                self._pending.append(frames)
                self._pending_count += count
                self._pending_bytes += len(frames) - 4 * count
                if self._pending_bytes >= self.batch_size:
                    await self._seal()
                    last_seal = now()
            except asyncio.TimeoutError:
                if self.rx_reconfigure.peek().kind == "shutdown":
                    return
                if self._pending:
                    await self._seal()
                last_seal = now()

    async def _seal(self) -> None:
        serialized = assemble_serialized_batch(self._pending_count, self._pending)
        batch = SealedBatch(serialized, self._pending_count)
        size = self._pending_bytes
        self._pending = []
        self._pending_count = 0
        self._pending_bytes = 0
        if self.benchmark:
            digest_hex = batch.digest.hex()
            for off, n in iter_serialized_batch_txs(serialized):
                # Sample txs: first byte 0, u64 counter follows (the
                # benchmark client's marker, node/src/benchmark_client.rs).
                if n >= 9 and serialized[off] == 0:
                    (sample_id,) = struct.unpack_from(">Q", serialized, off + 1)
                    logger.info(
                        "Batch %s contains sample tx %d", digest_hex, sample_id
                    )
            logger.info("Batch %s contains %d B", digest_hex, size)
        if self.metrics is not None:
            self.metrics.created_batch_size.observe(size)
            self.metrics.batches_made.inc()
        if self._seal_timer is not None and self._pending_t0 is not None:
            self._seal_timer.close(batch.digest, self._pending_t0)
        self._pending_t0 = None
        await self.tx_message.send(batch)
