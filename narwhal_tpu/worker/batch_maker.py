"""BatchMaker: accumulate transactions into sealed batches.

Reference: /root/reference/worker/src/batch_maker.rs:48-193 — seal when the
pending bytes reach `batch_size` or `max_batch_delay` elapses; under the
benchmark feature it logs "Batch {digest} contains sample tx {id}" for sample
transactions (first byte 0, u64 id following) and "Batch {digest} contains
{n} B" — the log lines the benchmark harness parses for TPS/latency
(benchmark/benchmark/logs.py:171-244). We emit byte-compatible lines.
"""

from __future__ import annotations

import asyncio
import logging
import struct
import time

from ..channels import Channel, Subscriber, Watch
from ..types import SealedBatch, assemble_serialized_batch, iter_serialized_batch_txs

logger = logging.getLogger("narwhal.worker")


class BatchMaker:
    def __init__(
        self,
        batch_size: int,
        max_batch_delay: float,
        rx_transaction: Channel,
        tx_message: Channel,
        rx_reconfigure: Watch,
        metrics=None,
        benchmark: bool = False,
    ):
        self.batch_size = batch_size
        self.max_batch_delay = max_batch_delay
        self.rx_transaction = rx_transaction
        self.tx_message = tx_message
        self.rx_reconfigure = Subscriber(rx_reconfigure)
        self.metrics = metrics
        self.benchmark = benchmark
        # Pending transactions stay in wire form: (frame chunks, tx count).
        self._pending: list[bytes] = []
        self._pending_count = 0
        self._pending_bytes = 0

    def spawn(self) -> asyncio.Task:
        return asyncio.ensure_future(self.run())

    async def run(self) -> None:
        # Fixed deadline, NOT an idle timeout: the timer runs from the last
        # seal, so a steady sub-batch-size trickle still seals every
        # max_batch_delay (batch_maker.rs:77-122 uses an interval timer).
        deadline = time.monotonic() + self.max_batch_delay
        while True:
            timeout = max(0.0, deadline - time.monotonic())
            try:
                # Receives whole client bursts as (count, frames) chunks in
                # wire form: one channel hop and zero per-tx work per burst.
                count, frames = await asyncio.wait_for(
                    self.rx_transaction.recv(), timeout=timeout
                )
                if self.rx_reconfigure.peek().kind == "shutdown":
                    return
                self._pending.append(frames)
                self._pending_count += count
                self._pending_bytes += len(frames) - 4 * count
                if self._pending_bytes >= self.batch_size:
                    await self._seal()
                    deadline = time.monotonic() + self.max_batch_delay
            except asyncio.TimeoutError:
                if self.rx_reconfigure.peek().kind == "shutdown":
                    return
                if self._pending:
                    await self._seal()
                deadline = time.monotonic() + self.max_batch_delay

    async def _seal(self) -> None:
        serialized = assemble_serialized_batch(self._pending_count, self._pending)
        batch = SealedBatch(serialized, self._pending_count)
        size = self._pending_bytes
        self._pending = []
        self._pending_count = 0
        self._pending_bytes = 0
        if self.benchmark:
            digest_hex = batch.digest.hex()
            for off, n in iter_serialized_batch_txs(serialized):
                # Sample txs: first byte 0, u64 counter follows (the
                # benchmark client's marker, node/src/benchmark_client.rs).
                if n >= 9 and serialized[off] == 0:
                    (sample_id,) = struct.unpack_from(">Q", serialized, off + 1)
                    logger.info(
                        "Batch %s contains sample tx %d", digest_hex, sample_id
                    )
            logger.info("Batch %s contains %d B", digest_hex, size)
        if self.metrics is not None:
            self.metrics.created_batch_size.observe(size)
            self.metrics.batches_made.inc()
        await self.tx_message.send(batch)
