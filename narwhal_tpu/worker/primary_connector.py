"""PrimaryConnector: forward worker->primary messages to our own primary.

Reference: /root/reference/worker/src/primary_connector.rs:15-75 — reliable
send of each WorkerPrimaryMessage digest notification, bounded in-flight.
"""

from __future__ import annotations

import asyncio

from ..channels import Channel, Subscriber, Watch
from ..network import NetworkClient

MAX_PENDING = 10_000


class PrimaryConnector:
    def __init__(
        self,
        primary_address: str,
        network: NetworkClient,
        rx_digest: Channel,
        rx_reconfigure: Watch,
    ):
        self.primary_address = primary_address
        self.network = network
        self.rx_digest = rx_digest
        self.rx_reconfigure = Subscriber(rx_reconfigure)
        self._inflight = asyncio.Semaphore(MAX_PENDING)

    def spawn(self) -> asyncio.Task:
        return asyncio.ensure_future(self.run())

    async def run(self) -> None:
        while True:
            msg = await self.rx_digest.recv()
            if self.rx_reconfigure.peek().kind == "shutdown":
                return
            await self._inflight.acquire()
            handle = self.network.send(self.primary_address, msg)
            handle.task.add_done_callback(lambda _t: self._inflight.release())
