"""Processor: hash, persist and report each batch to the primary.

Reference: /root/reference/worker/src/processor.rs:22-73 — digest the
*serialized* batch (zero-copy, types/src/worker.rs:44-62), write it to the
batch store, and emit OurBatch (own dissemination path) or OthersBatch (peer
receive path) to the primary connector.
"""

from __future__ import annotations

import asyncio

from ..channels import Channel, Subscriber, Watch
from ..messages import OthersBatchMsg, OurBatchMsg
from ..stores import BatchStore
from ..types import SealedBatch, WorkerId, serialized_batch_digest


class Processor:
    def __init__(
        self,
        worker_id: WorkerId,
        store: BatchStore,
        rx_batch: Channel,
        tx_digest: Channel,
        rx_reconfigure: Watch,
        metrics=None,
    ):
        self.worker_id = worker_id
        self.store = store
        self.rx_batch = rx_batch
        self.tx_digest = tx_digest
        self.rx_reconfigure = Subscriber(rx_reconfigure)
        self.metrics = metrics

    def spawn(self) -> asyncio.Task:
        return asyncio.ensure_future(self.run())

    async def run(self) -> None:
        while True:
            payload, own = await self.rx_batch.recv()
            if self.rx_reconfigure.peek().kind == "shutdown":
                return
            if isinstance(payload, SealedBatch):
                # Own batch: the digest is cached on the sealed object.
                digest, serialized = payload.digest, payload.serialized
            else:
                # Peer bytes are untrusted: hash the wire form ourselves.
                serialized = payload
                digest = serialized_batch_digest(serialized)
            self.store.write(digest, serialized)
            msg = (
                OurBatchMsg(digest, self.worker_id)
                if own
                else OthersBatchMsg(digest, self.worker_id)
            )
            await self.tx_digest.send(msg)
