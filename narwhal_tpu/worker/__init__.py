from .worker import Worker

__all__ = ["Worker"]
