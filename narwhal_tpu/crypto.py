"""Protocol and network cryptography.

The reference selects its crypto by type alias over the fastcrypto traits
(/root/reference/crypto/src/lib.rs:29-46): protocol keys = BLS12-381
(aggregatable), network keys = ed25519, digests = blake2b-256. The comment at
crypto/src/lib.rs:19-27 demands the codebase stay generic over the trait seam —
that seam is exactly where a TPU batch-verifier plugs in.

TPU-first redesign: the protocol scheme here is **ed25519 multi-signature**
rather than BLS aggregation. Certificates carry a vector of ed25519 signatures
aligned with a signer bitmap (the reference carries one aggregate BLS signature
plus the same bitmap, /root/reference/types/src/primary.rs:386-644). Rationale:
ed25519 verification batches perfectly onto wide SIMD/TPU lanes (independent
double-scalar multiplications over a single curve), whereas BLS pairings are a
poor fit for the MXU/VPU; the bandwidth cost (64 bytes/signer vs 48 total) is
noise next to batch payloads. The verifier interface below is the pluggable
seam: `set_batch_verifier` installs the TPU backend (narwhal_tpu.tpu.verifier)
with the host OpenSSL path as the always-present fallback.

Host primitives are OpenSSL-backed via the `cryptography` package (native
speed) when it is installed; containers without the OpenSSL bindings fall
back to the in-tree pure-integer RFC-8032 implementation
(`tpu/ed25519_ref.py` — the same math the device kernel is tested against),
which is slower but bit-identical on the wire. The canonical digest is
SHA-256 (see digest256).
"""

from __future__ import annotations

import asyncio
import hashlib
import os
from dataclasses import dataclass
from typing import Callable, Sequence

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )

    HAVE_OPENSSL = True
except ImportError:  # pragma: no cover - exercised only without OpenSSL
    HAVE_OPENSSL = False

    class InvalidSignature(Exception):
        pass

    Ed25519PrivateKey = Ed25519PublicKey = None

from .bounded_cache import BoundedCache

DIGEST_LEN = 32
PUBLIC_KEY_LEN = 32
SIGNATURE_LEN = 64


def digest256(data: bytes) -> bytes:
    """The canonical 256-bit content digest.

    The reference hashes with blake2b-256 everywhere (fastcrypto blake2b);
    we deliberately use SHA-256: with hardware SHA extensions it measures
    ~2x blake2b's throughput on this host path, and batch hashing is a
    first-order term in the worker's byte budget (every payload byte is
    digested at least twice committee-wide). The choice is an internal
    canonical-format decision — nothing in the protocol depends on the
    hash algorithm beyond collision resistance."""
    return hashlib.sha256(data).digest()


class _RefPrivateKey:
    """RFC-8032 ed25519 signing over the in-tree pure-integer group math
    (`tpu/ed25519_ref.py`) — the fallback identity when the OpenSSL bindings
    are absent. Wire-compatible with ed25519-dalek/OpenSSL: same seed
    expansion, same clamping, same (R, S) layout. A process-wide 8-bit
    fixed-base window table makes the two per-signature base
    multiplications 32-add table walks instead of full double-and-add
    ladders — the table is built once and shared by every hosted node
    (signing was the #1 line of the co-hosted simnet profile)."""

    __slots__ = ("_seed", "_scalar", "_prefix", "public")

    _BASE_WINDOWS: list | None = None

    def __init__(self, seed: bytes):
        from .tpu import ed25519_ref as ref

        h = hashlib.sha512(seed).digest()
        a = int.from_bytes(h[:32], "little")
        a &= (1 << 254) - 8
        a |= 1 << 254
        self._seed = seed
        self._scalar = a
        self._prefix = h[32:]
        self.public = ref.compress(self._g_mul(a))

    @classmethod
    def _g_mul(cls, s: int):
        """[s]B via 8-bit fixed-base windows: table[w][d] = [d * 256^w]B.

        32 windows x 256 entries (~8k one-time point adds, amortised after
        a few hundred signatures) halve the per-call adds vs the earlier
        4-bit table; the walk is 32 adds for a full 255-bit scalar."""
        from .tpu import ed25519_ref as ref

        if cls._BASE_WINDOWS is None:
            windows = []
            base = ref.G
            for _ in range(32):
                row = [ref.IDENTITY]
                for _ in range(255):
                    row.append(ref.point_add(row[-1], base))
                windows.append(row)
                base = row[1]
                for _ in range(8):
                    base = ref.point_double(base)
            cls._BASE_WINDOWS = windows
        acc = ref.IDENTITY
        w = 0
        while s > 0:
            acc = ref.point_add(acc, cls._BASE_WINDOWS[w][s & 255])
            s >>= 8
            w += 1
        return acc

    def sign(self, message: bytes) -> bytes:
        from .tpu import ed25519_ref as ref

        r = (
            int.from_bytes(
                hashlib.sha512(self._prefix + message).digest(), "little"
            )
            % ref.L
        )
        rs = ref.compress(self._g_mul(r))
        k = ref.sha512_mod_l(rs, self.public, message)
        s = (r + k * self._scalar) % ref.L
        return rs + int.to_bytes(s, 32, "little")


@dataclass(frozen=True)
class KeyPair:
    """An ed25519 keypair. `public` is the 32-byte raw public key, which is
    also the authority's protocol name (reference: PublicKey = BLS pubkey used
    as the authority identifier throughout config/committee)."""

    public: bytes
    _private: object

    @staticmethod
    def generate() -> "KeyPair":
        if not HAVE_OPENSSL:
            # Boot-time identity keygen: seeded scenarios derive keypairs
            # from the plan seed via from_seed and never call generate().
            return KeyPair.from_seed(os.urandom(32))  # lint: allow(raw-entropy)
        priv = Ed25519PrivateKey.generate()
        return KeyPair(public=_raw_public(priv.public_key()), _private=priv)

    @staticmethod
    def from_seed(seed: bytes) -> "KeyPair":
        """Deterministic keypair from a 32-byte seed (test fixtures; the
        reference offers a seeded-RNG CommitteeFixture,
        /root/reference/test_utils/src/lib.rs:602-793)."""
        if len(seed) != 32:
            seed = hashlib.blake2b(seed, digest_size=32).digest()
        if not HAVE_OPENSSL:
            priv = _RefPrivateKey(seed)
            return KeyPair(public=priv.public, _private=priv)
        priv = Ed25519PrivateKey.from_private_bytes(seed)
        return KeyPair(public=_raw_public(priv.public_key()), _private=priv)

    def sign(self, message: bytes) -> bytes:
        signature = self._private.sign(message)
        # A freshly produced signature is valid by construction, so seed the
        # process-wide verified-signature cache with it. Under simnet every
        # hosted peer verifies this exact (pk, msg, sig) triple; seeding at
        # sign time turns all of those into cache hits — the co-hosted
        # crypto plane's "verify a broadcast once per process, and never
        # when the signer lives here". Same size guard as verify().
        if len(message) <= _VERIFY_CACHE_MAX_MSG:
            _VERIFY_CACHE.put((self.public, message, signature), True)
        return signature

    def private_bytes(self) -> bytes:
        if isinstance(self._private, _RefPrivateKey):
            return self._private._seed
        from cryptography.hazmat.primitives import serialization as ser

        return self._private.private_bytes(
            ser.Encoding.Raw, ser.PrivateFormat.Raw, ser.NoEncryption()
        )


def _raw_public(pub: Ed25519PublicKey) -> bytes:
    from cryptography.hazmat.primitives import serialization as ser

    return pub.public_bytes(ser.Encoding.Raw, ser.PublicFormat.Raw)


_PUB_CACHE: dict[bytes, Ed25519PublicKey] = {}


def _pub(public_key: bytes) -> Ed25519PublicKey:
    obj = _PUB_CACHE.get(public_key)
    if obj is None:
        obj = Ed25519PublicKey.from_public_bytes(public_key)
        if len(_PUB_CACHE) < 1 << 16:
            # Process-wide decode cache, deliberately shared across every
            # co-hosted node: the value is a pure function of the key bytes,
            # so lost updates and cross-node hits are both benign, and the
            # single-statement insert is atomic under cooperative scheduling.
            _PUB_CACHE[public_key] = obj  # lint: allow(multi-task-mutation)
    return obj


# Verified-signature cache: verification is a deterministic pure function
# of (pk, msg, sig), so results can be shared process-wide. Two real dedup
# sources: a single node verifies the same vote signatures at vote receipt
# and AGAIN inside the assembled certificate it later receives; a
# multi-node-per-host process verifies every broadcast once per hosted
# node (the N=50 profile: 1.03M OpenSSL verifies, 27% of the window's CPU,
# overwhelmingly duplicates). Thread-safe (verify runs on executor
# threads via AsyncVerifierPool); only digest-sized messages are cached so
# data-plane payloads can't blow the budget.
_VERIFY_CACHE = BoundedCache(max_entries=1 << 17)
_VERIFY_CACHE_MAX_MSG = 256


def verify(public_key: bytes, message: bytes, signature: bytes) -> bool:
    """Single ed25519 verification (host path)."""
    key = (public_key, message, signature)
    hit = _VERIFY_CACHE.get(key)
    if hit is not None:
        return hit
    if not HAVE_OPENSSL:
        ok = _ref_verify(public_key, message, signature)
        if len(message) <= _VERIFY_CACHE_MAX_MSG:
            _VERIFY_CACHE.put(key, ok)
        return ok
    try:
        _pub(public_key).verify(signature, message)
        ok = True
    except (InvalidSignature, ValueError):
        ok = False
    if len(message) <= _VERIFY_CACHE_MAX_MSG:
        _VERIFY_CACHE.put(key, ok)
    return ok


# ---------------------------------------------------------------------------
# Batch verification seam (the TPU offload boundary).
#
# A batch item is (public_key, message, signature). The installed backend
# returns a list[bool] of the same length. The host fallback loops over
# OpenSSL; the TPU backend (narwhal_tpu/tpu/verifier.py) coalesces items into
# fixed-shape device batches. This mirrors the north-star seam: worker
# quorum_waiter and primary Certificate::verify push verifies through here.
# ---------------------------------------------------------------------------

BatchItem = tuple[bytes, bytes, bytes]
BatchVerifier = Callable[[Sequence[BatchItem]], list[bool]]


# Per-public-key verifier state for the fallback verifier: a committee is
# a handful of keys each verified thousands of times, so the one-time
# ~1.2k group ops per key turn every subsequent [k](-A) into a 64-add
# table walk (~3x faster verification), and caching the decompressed
# point alongside skips the per-call field exponentiation that
# `decompress` costs. Entry-bounded: tables are ~100 KB each.
_REF_PK_WINDOWS = BoundedCache(max_entries=256)


def _ref_pk_entry(public_key: bytes):
    """(decompressed A, 4-bit windows of -A) for a public key, cached.

    Returns None for a key that does not decompress to a curve point.
    The window table is table[w][d] = [d * 16^w](-A)."""
    from .tpu import ed25519_ref as ref

    entry = _REF_PK_WINDOWS.get(public_key)
    if entry is None:
        a = ref.decompress(public_key)
        if a is None:
            return None
        windows = []
        base = ref.point_neg(a)
        for _ in range(64):
            row = [ref.IDENTITY]
            for _ in range(15):
                row.append(ref.point_add(row[-1], base))
            windows.append(row)
            for _ in range(4):
                base = ref.point_double(base)
        entry = (a, windows)
        _REF_PK_WINDOWS.put(public_key, entry)
    return entry


def _ref_neg_pk_windows(public_key: bytes, a=None):
    """4-bit fixed-base windows of -A: table[w][d] = [d * 16^w](-A)."""
    entry = _ref_pk_entry(public_key)
    return entry[1] if entry is not None else None


def _ref_verify(public_key: bytes, message: bytes, signature: bytes) -> bool:
    """Cofactorless verification on the pure-integer group math — same
    checks as `ed25519_ref.verify`, with BOTH scalar multiplications served
    from fixed-base window tables ([S]B from the generator table, [k](-A)
    from the per-key table) — ~5x the plain double-and-add fallback."""
    from .tpu import ed25519_ref as ref

    if len(public_key) != 32 or len(signature) != 64:
        return False
    entry = _ref_pk_entry(public_key)
    if entry is None:
        return False
    rs, sb = signature[:32], signature[32:]
    s = int.from_bytes(sb, "little")
    if s >= ref.L:
        return False
    if (int.from_bytes(rs, "little") & ((1 << 255) - 1)) >= ref.P:
        return False
    k = ref.sha512_mod_l(rs, public_key, message)
    tab = entry[1]
    rhs = _RefPrivateKey._g_mul(s)
    w = 0
    while k > 0:
        rhs = ref.point_add(rhs, tab[w][k & 15])
        k >>= 4
        w += 1
    return ref.compress(rhs) == rs


def _host_batch_verify(items: Sequence[BatchItem]) -> list[bool]:
    return [verify(pk, msg, sig) for pk, msg, sig in items]


_batch_verifier: BatchVerifier = _host_batch_verify


def set_batch_verifier(backend: BatchVerifier | None) -> None:
    global _batch_verifier
    _batch_verifier = backend if backend is not None else _host_batch_verify


def batch_verify(items: Sequence[BatchItem]) -> list[bool]:
    if not items:
        return []
    return _batch_verifier(items)


class SignatureService:
    """Async signing actor, mirroring fastcrypto's SignatureService used by
    Header::new / Vote::new (/root/reference/types/src/primary.rs:130-148,
    269-286). Signing is cheap on host, so this is a thin async wrapper that
    preserves the reference's request/response shape."""

    def __init__(self, keypair: KeyPair) -> None:
        self._keypair = keypair

    @property
    def public(self) -> bytes:
        return self._keypair.public

    async def request_signature(self, digest: bytes) -> bytes:
        return self._keypair.sign(digest)

    def sign(self, digest: bytes) -> bytes:
        return self._keypair.sign(digest)


async def asleep0() -> None:
    await asyncio.sleep(0)
