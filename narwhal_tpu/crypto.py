"""Protocol and network cryptography.

The reference selects its crypto by type alias over the fastcrypto traits
(/root/reference/crypto/src/lib.rs:29-46): protocol keys = BLS12-381
(aggregatable), network keys = ed25519, digests = blake2b-256. The comment at
crypto/src/lib.rs:19-27 demands the codebase stay generic over the trait seam —
that seam is exactly where a TPU batch-verifier plugs in.

TPU-first redesign: the protocol scheme here is **ed25519 multi-signature**
rather than BLS aggregation. Certificates carry a vector of ed25519 signatures
aligned with a signer bitmap (the reference carries one aggregate BLS signature
plus the same bitmap, /root/reference/types/src/primary.rs:386-644). Rationale:
ed25519 verification batches perfectly onto wide SIMD/TPU lanes (independent
double-scalar multiplications over a single curve), whereas BLS pairings are a
poor fit for the MXU/VPU; the bandwidth cost (64 bytes/signer vs 48 total) is
noise next to batch payloads. The verifier interface below is the pluggable
seam: `set_batch_verifier` installs the TPU backend (narwhal_tpu.tpu.verifier)
with the host OpenSSL path as the always-present fallback.

Host primitives are OpenSSL-backed via the `cryptography` package (native
speed); the canonical digest is SHA-256 (see digest256).
"""

from __future__ import annotations

import asyncio
import hashlib
from dataclasses import dataclass
from typing import Callable, Sequence

from cryptography.exceptions import InvalidSignature
from cryptography.hazmat.primitives.asymmetric.ed25519 import (
    Ed25519PrivateKey,
    Ed25519PublicKey,
)

from .bounded_cache import BoundedCache

DIGEST_LEN = 32
PUBLIC_KEY_LEN = 32
SIGNATURE_LEN = 64


def digest256(data: bytes) -> bytes:
    """The canonical 256-bit content digest.

    The reference hashes with blake2b-256 everywhere (fastcrypto blake2b);
    we deliberately use SHA-256: with hardware SHA extensions it measures
    ~2x blake2b's throughput on this host path, and batch hashing is a
    first-order term in the worker's byte budget (every payload byte is
    digested at least twice committee-wide). The choice is an internal
    canonical-format decision — nothing in the protocol depends on the
    hash algorithm beyond collision resistance."""
    return hashlib.sha256(data).digest()


@dataclass(frozen=True)
class KeyPair:
    """An ed25519 keypair. `public` is the 32-byte raw public key, which is
    also the authority's protocol name (reference: PublicKey = BLS pubkey used
    as the authority identifier throughout config/committee)."""

    public: bytes
    _private: Ed25519PrivateKey

    @staticmethod
    def generate() -> "KeyPair":
        priv = Ed25519PrivateKey.generate()
        return KeyPair(public=_raw_public(priv.public_key()), _private=priv)

    @staticmethod
    def from_seed(seed: bytes) -> "KeyPair":
        """Deterministic keypair from a 32-byte seed (test fixtures; the
        reference offers a seeded-RNG CommitteeFixture,
        /root/reference/test_utils/src/lib.rs:602-793)."""
        if len(seed) != 32:
            seed = hashlib.blake2b(seed, digest_size=32).digest()
        priv = Ed25519PrivateKey.from_private_bytes(seed)
        return KeyPair(public=_raw_public(priv.public_key()), _private=priv)

    def sign(self, message: bytes) -> bytes:
        return self._private.sign(message)

    def private_bytes(self) -> bytes:
        from cryptography.hazmat.primitives import serialization as ser

        return self._private.private_bytes(
            ser.Encoding.Raw, ser.PrivateFormat.Raw, ser.NoEncryption()
        )


def _raw_public(pub: Ed25519PublicKey) -> bytes:
    from cryptography.hazmat.primitives import serialization as ser

    return pub.public_bytes(ser.Encoding.Raw, ser.PublicFormat.Raw)


_PUB_CACHE: dict[bytes, Ed25519PublicKey] = {}


def _pub(public_key: bytes) -> Ed25519PublicKey:
    obj = _PUB_CACHE.get(public_key)
    if obj is None:
        obj = Ed25519PublicKey.from_public_bytes(public_key)
        if len(_PUB_CACHE) < 1 << 16:
            _PUB_CACHE[public_key] = obj
    return obj


# Verified-signature cache: verification is a deterministic pure function
# of (pk, msg, sig), so results can be shared process-wide. Two real dedup
# sources: a single node verifies the same vote signatures at vote receipt
# and AGAIN inside the assembled certificate it later receives; a
# multi-node-per-host process verifies every broadcast once per hosted
# node (the N=50 profile: 1.03M OpenSSL verifies, 27% of the window's CPU,
# overwhelmingly duplicates). Thread-safe (verify runs on executor
# threads via AsyncVerifierPool); only digest-sized messages are cached so
# data-plane payloads can't blow the budget.
_VERIFY_CACHE = BoundedCache(max_entries=1 << 17)
_VERIFY_CACHE_MAX_MSG = 256


def verify(public_key: bytes, message: bytes, signature: bytes) -> bool:
    """Single ed25519 verification (host path)."""
    key = (public_key, message, signature)
    hit = _VERIFY_CACHE.get(key)
    if hit is not None:
        return hit
    try:
        _pub(public_key).verify(signature, message)
        ok = True
    except (InvalidSignature, ValueError):
        ok = False
    if len(message) <= _VERIFY_CACHE_MAX_MSG:
        _VERIFY_CACHE.put(key, ok)
    return ok


# ---------------------------------------------------------------------------
# Batch verification seam (the TPU offload boundary).
#
# A batch item is (public_key, message, signature). The installed backend
# returns a list[bool] of the same length. The host fallback loops over
# OpenSSL; the TPU backend (narwhal_tpu/tpu/verifier.py) coalesces items into
# fixed-shape device batches. This mirrors the north-star seam: worker
# quorum_waiter and primary Certificate::verify push verifies through here.
# ---------------------------------------------------------------------------

BatchItem = tuple[bytes, bytes, bytes]
BatchVerifier = Callable[[Sequence[BatchItem]], list[bool]]


def _host_batch_verify(items: Sequence[BatchItem]) -> list[bool]:
    return [verify(pk, msg, sig) for pk, msg, sig in items]


_batch_verifier: BatchVerifier = _host_batch_verify


def set_batch_verifier(backend: BatchVerifier | None) -> None:
    global _batch_verifier
    _batch_verifier = backend if backend is not None else _host_batch_verify


def batch_verify(items: Sequence[BatchItem]) -> list[bool]:
    if not items:
        return []
    return _batch_verifier(items)


class SignatureService:
    """Async signing actor, mirroring fastcrypto's SignatureService used by
    Header::new / Vote::new (/root/reference/types/src/primary.rs:130-148,
    269-286). Signing is cheap on host, so this is a thin async wrapper that
    preserves the reference's request/response shape."""

    def __init__(self, keypair: KeyPair) -> None:
        self._keypair = keypair

    @property
    def public(self) -> bytes:
        return self._keypair.public

    async def request_signature(self, digest: bytes) -> bytes:
        return self._keypair.sign(digest)

    def sign(self, digest: bytes) -> bytes:
        return self._keypair.sign(digest)


async def asleep0() -> None:
    await asyncio.sleep(0)
