"""Inter-role RPC messages with a tag registry.

Reference message enums: PrimaryMessage / PrimaryWorkerMessage /
WorkerPrimaryMessage (/root/reference/types/src/primary.rs:646-789) and the
worker<->worker plane (/root/reference/types/src/worker.rs:17-32), carried by
anemo services generated in /root/reference/types/build.rs:42-121.

Every message is a dataclass with a unique TAG, canonical encode/decode, and
is registered for the RPC layer's dispatch. Reliable sends are acked request/
response pairs (the anemo RPC analog); messages that expect data back define a
response type.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .bounded_cache import BoundedCache
from .codec import Reader, Writer
from .config import Committee
from .crypto import DIGEST_LEN, PUBLIC_KEY_LEN, SIGNATURE_LEN
from .types import Batch, Certificate, Digest, Header, PublicKey, Round, Vote, WorkerId

REGISTRY: dict[int, type] = {}


def message(tag: int):
    def deco(cls):
        assert tag not in REGISTRY, f"duplicate message tag {tag}"
        cls.TAG = tag
        REGISTRY[tag] = cls
        return cls

    return deco


def encode_message(msg) -> tuple[int, bytes]:
    # Broadcasts and retries encode the same object repeatedly (a batch goes
    # to every peer and each reliable-send attempt re-encodes); memoize the
    # wire form on the instance.
    cached = getattr(msg, "_encoded", None)
    if cached is not None:
        return cached
    w = Writer()
    msg.encode(w)
    encoded = (msg.TAG, w.finish())
    try:
        msg._encoded = encoded
    except AttributeError:
        pass  # slotted/frozen types just skip the memo
    return encoded


# Process-wide decode cache. A broadcast's wire bytes arrive once per
# LINK: every node hosted in this process decodes an identical body, and a
# single node re-decodes identical bodies on retry/re-delivery. The N=50
# profile measured message decode at ~30% of the host's CPU
# (Certificate.decode alone 208 s cumulative of a 630 s window), nearly
# all of it duplicates. Decoded messages are immutable by convention —
# nothing in the codebase mutates a received message (encode memoization
# is the one benign exception) — so identical (tag, body) pairs can share
# one decoded object. Keyed by the raw bytes (hashed once per received
# frame, C-speed), bounded by a byte budget with FIFO eviction
# (BoundedCache: thread-safe, shared with the crypto/store caches).
_DECODE_CACHE = BoundedCache(max_bytes=64 << 20)
_DECODE_MAX_BODY = 1 << 16  # don't pin data-plane (batch) bodies


def decode_message(tag: int, body: bytes):
    key = (tag, body)
    cached = _DECODE_CACHE.get(key)
    if cached is not None:
        return cached
    cls = REGISTRY.get(tag)
    if cls is None:
        raise ValueError(f"unknown message tag {tag}")
    r = Reader(body)
    msg = cls.decode(r)
    r.done()
    if len(body) <= _DECODE_MAX_BODY:
        # The (tag, body) key tuple pins the raw body bytes alongside the
        # decoded object (which aliases/copies roughly the same bytes), so
        # one entry holds ~2x the body in memory; charge both sides against
        # the byte budget or the cache runs ~2x over its nominal bound.
        _DECODE_CACHE.put(key, msg, weight=2 * len(body))
    return msg


def _enc_digest(w: Writer, d: Digest) -> None:
    w.raw(d)


def _dec_digest(r: Reader) -> Digest:
    return r.raw(DIGEST_LEN)


# ---------------------------------------------------------------------------
# Generic
# ---------------------------------------------------------------------------


@message(0)
@dataclass
class Ack:
    """Empty reliable-delivery acknowledgment."""

    def encode(self, w: Writer) -> None:
        pass

    @staticmethod
    def decode(r: Reader) -> "Ack":
        return Ack()


# ---------------------------------------------------------------------------
# Primary <-> Primary (types/src/primary.rs:646-700)
# ---------------------------------------------------------------------------


@message(1)
@dataclass
class HeaderMsg:
    header: Header

    def encode(self, w: Writer) -> None:
        self.header.encode(w)

    @staticmethod
    def decode(r: Reader) -> "HeaderMsg":
        return HeaderMsg(Header.decode(r))


@message(2)
@dataclass
class VoteMsg:
    vote: Vote

    def encode(self, w: Writer) -> None:
        self.vote.encode(w)

    @staticmethod
    def decode(r: Reader) -> "VoteMsg":
        return VoteMsg(Vote.decode(r))


@message(3)
@dataclass
class CertificateMsg:
    certificate: Certificate

    def encode(self, w: Writer) -> None:
        self.certificate.encode(w)

    @staticmethod
    def decode(r: Reader) -> "CertificateMsg":
        return CertificateMsg(Certificate.decode(r))


@message(72)
@dataclass
class CertificateRefMsg:
    """Compact-certificate broadcast WITHOUT the header body
    (Parameters.cert_format="compact"): every peer that voted already
    stores the header, so the announcement carries only its digest plus
    the half-aggregated proof — cutting the dominant O(N) control-plane
    bytes (header parents + per-signer signatures) from every certificate
    broadcast. Receivers rebuild the Certificate from their header store
    and fall back to fetching the full certificate from the origin
    (CertificatesBatchRequest -> Helper) on miss. Replaces the capability
    the reference gets from O(1) BLS certificates
    (/root/reference/types/src/primary.rs:386-644)."""

    header_digest: Digest
    round: Round
    epoch: Epoch
    origin: PublicKey
    signers: tuple[int, ...]
    rs: tuple[bytes, ...]  # 32-byte nonce points
    agg_s: bytes  # 32-byte aggregate scalar

    @staticmethod
    def from_certificate(cert: Certificate) -> "CertificateRefMsg":
        assert cert.is_compact
        return CertificateRefMsg(
            cert.header.digest,
            cert.round,
            cert.epoch,
            cert.origin,
            cert.signers,
            cert.signatures,
            cert.agg_s,
        )

    def rebuild(self, header: Header) -> Certificate:
        return Certificate(header, self.signers, self.rs, self.agg_s)

    def encode(self, w: Writer) -> None:
        w.raw(self.header_digest)
        w.u64(self.round)
        w.u64(self.epoch)
        w.raw(self.origin)
        w.seq(self.signers, lambda w_, i: w_.u32(i))
        w.seq(self.rs, lambda w_, r: w_.raw(r))
        w.raw(self.agg_s)

    @staticmethod
    def decode(r: Reader) -> "CertificateRefMsg":
        return CertificateRefMsg(
            r.raw(DIGEST_LEN),
            r.u64(),
            r.u64(),
            r.raw(PUBLIC_KEY_LEN),
            tuple(r.seq(lambda r_: r_.u32())),
            tuple(r.seq(lambda r_: r_.raw(32))),
            r.raw(32),
        )


@message(4)
@dataclass
class CertificatesRequest:
    """Ask a peer for specific certificates; peer replies with loose
    CertificateMsg sends (reference PrimaryMessage::CertificatesRequest,
    helper.rs:82-99)."""

    digests: tuple[Digest, ...]
    requestor: PublicKey

    def encode(self, w: Writer) -> None:
        w.seq(self.digests, _enc_digest)
        w.raw(self.requestor)

    @staticmethod
    def decode(r: Reader) -> "CertificatesRequest":
        return CertificatesRequest(
            tuple(r.seq(_dec_digest)), r.raw(PUBLIC_KEY_LEN)
        )


@message(5)
@dataclass
class CertificatesBatchRequest:
    """Block-synchronizer bulk fetch; RPC with CertificatesBatchResponse."""

    digests: tuple[Digest, ...]
    requestor: PublicKey = b"\0" * 32

    def encode(self, w: Writer) -> None:
        w.seq(self.digests, _enc_digest)
        w.raw(self.requestor)

    @staticmethod
    def decode(r: Reader) -> "CertificatesBatchRequest":
        return CertificatesBatchRequest(tuple(r.seq(_dec_digest)), r.raw(PUBLIC_KEY_LEN))


@message(6)
@dataclass
class CertificatesBatchResponse:
    """(digest, certificate|None) pairs (reference CertificateDigestsResponse)."""

    certificates: tuple[tuple[Digest, Certificate | None], ...]

    def encode(self, w: Writer) -> None:
        def enc(w_: Writer, item) -> None:
            digest, cert = item
            w_.raw(digest)
            if cert is None:
                w_.u8(0)
            else:
                w_.u8(1)
                cert.encode(w_)

        w.seq(self.certificates, enc)

    @staticmethod
    def decode(r: Reader) -> "CertificatesBatchResponse":
        def dec(r_: Reader):
            digest = _dec_digest(r_)
            return (digest, Certificate.decode(r_) if r_.u8() else None)

        return CertificatesBatchResponse(tuple(r.seq(dec)))


@message(7)
@dataclass
class CertificatesRangeRequest:
    """Catch-up: digests of certificates in rounds (from, to] per authority
    (block_synchronizer SynchronizeRange)."""

    from_round: Round
    to_round: Round
    requestor: PublicKey = b"\0" * 32

    def encode(self, w: Writer) -> None:
        w.u64(self.from_round)
        w.u64(self.to_round)
        w.raw(self.requestor)

    @staticmethod
    def decode(r: Reader) -> "CertificatesRangeRequest":
        return CertificatesRangeRequest(r.u64(), r.u64(), r.raw(PUBLIC_KEY_LEN))


@message(8)
@dataclass
class CertificatesRangeResponse:
    digests: tuple[Digest, ...]

    def encode(self, w: Writer) -> None:
        w.seq(self.digests, _enc_digest)

    @staticmethod
    def decode(r: Reader) -> "CertificatesRangeResponse":
        return CertificatesRangeResponse(tuple(r.seq(_dec_digest)))


@message(9)
@dataclass
class PayloadAvailabilityRequest:
    digests: tuple[Digest, ...]
    requestor: PublicKey = b"\0" * 32

    def encode(self, w: Writer) -> None:
        w.seq(self.digests, _enc_digest)
        w.raw(self.requestor)

    @staticmethod
    def decode(r: Reader) -> "PayloadAvailabilityRequest":
        return PayloadAvailabilityRequest(tuple(r.seq(_dec_digest)), r.raw(PUBLIC_KEY_LEN))


@message(10)
@dataclass
class PayloadAvailabilityResponse:
    available: tuple[tuple[Digest, bool], ...]

    def encode(self, w: Writer) -> None:
        def enc(w_: Writer, item) -> None:
            w_.raw(item[0])
            w_.u8(1 if item[1] else 0)

        w.seq(self.available, enc)

    @staticmethod
    def decode(r: Reader) -> "PayloadAvailabilityResponse":
        def dec(r_: Reader):
            return (_dec_digest(r_), bool(r_.u8()))

        return PayloadAvailabilityResponse(tuple(r.seq(dec)))


@message(73)
@dataclass
class RelayMsg:
    """Fanout-tree broadcast envelope (primary/fanout.py). The origin sends
    its header/certificate announcement to its direct children in a
    deterministic, stake-weighted per-round tree instead of all-to-all;
    every receiver re-derives the same tree from (epoch, round, origin) and
    forwards the UNCHANGED envelope to its own children, so the origin's
    per-round egress is O(fanout) rather than O(N). The inner message rides
    as raw (tag, body) wire bytes — relays never re-encode, and the ack id
    every hop agrees on is digest256(tag_le16 || body)."""

    origin: PublicKey  # the broadcasting authority (tree root)
    round: Round
    epoch: int
    inner_tag: int
    inner_body: bytes

    def encode(self, w: Writer) -> None:
        w.raw(self.origin)
        w.u64(self.round)
        w.u64(self.epoch)
        w.u16(self.inner_tag)
        w.bytes(self.inner_body)

    @staticmethod
    def decode(r: Reader) -> "RelayMsg":
        return RelayMsg(
            r.raw(PUBLIC_KEY_LEN), r.u64(), r.u64(), r.u16(), r.bytes()
        )

    def inner(self):
        return decode_message(self.inner_tag, self.inner_body)

    @property
    def ack_id(self) -> Digest:
        from .crypto import digest256

        return digest256(self.inner_tag.to_bytes(2, "little") + self.inner_body)


@message(74)
@dataclass
class RelayAckMsg:
    """Receipt confirmation a relay RECEIVER sends back to the tree's origin
    (direct children are covered by the relay RPC ack itself). Peers the
    origin has not heard from within relay_fallback_timeout get the original
    message by direct reliable send — the fallback that preserves
    reliable-broadcast semantics when a relay node crashes. The acker is
    authenticated by the handshake-verified peer identity; the carried name
    is only trusted on unauthenticated (bare-test) meshes."""

    ack_id: Digest
    acker: PublicKey

    def encode(self, w: Writer) -> None:
        w.raw(self.ack_id)
        w.raw(self.acker)

    @staticmethod
    def decode(r: Reader) -> "RelayAckMsg":
        return RelayAckMsg(r.raw(DIGEST_LEN), r.raw(PUBLIC_KEY_LEN))


@message(75)
@dataclass
class DeltaHeaderMsg:
    """Header announcement on a wire diet (Parameters.header_wire="delta").

    Carries only the (digest, worker_id) payload pairs added since the
    sender's last header (in this codebase a header's payload map IS the
    per-round delta — the proposer clears its digest buffer at every seal),
    and ref-encodes the O(N) parent set: parents of a round-r header are
    round r-1 certificates, which every peer already received via the
    certificate broadcast, so 2 bytes of committee index replace each 32-byte
    digest. The receiver reconstructs the full Header from its recent
    certificate index (primary/delta.py), checks the reconstruction against
    the carried header_digest (collision resistance makes a verified match
    byte-exact), and runs the normal signature/sanitize path. Any
    unresolvable parent or digest mismatch triggers the full-map resync path
    (HeaderResyncRequest, keyed off the receiver's last-seen round)."""

    author: PublicKey
    round: Round
    epoch: int
    header_digest: Digest
    payload: tuple[tuple[Digest, WorkerId], ...]  # pairs added since last header
    parent_indices: tuple[int, ...]  # committee dense indices of parent origins
    signature: bytes

    def encode(self, w: Writer) -> None:
        w.raw(self.author)
        w.u64(self.round)
        w.u64(self.epoch)
        w.raw(self.header_digest)

        def enc_pair(w_: Writer, item) -> None:
            w_.raw(item[0])
            w_.u32(item[1])

        w.seq(self.payload, enc_pair)
        w.seq(self.parent_indices, lambda w_, i: w_.u16(i))
        w.bytes(self.signature)

    @staticmethod
    def decode(r: Reader) -> "DeltaHeaderMsg":
        return DeltaHeaderMsg(
            r.raw(PUBLIC_KEY_LEN),
            r.u64(),
            r.u64(),
            r.raw(DIGEST_LEN),
            tuple(r.seq(lambda r_: (r_.raw(DIGEST_LEN), r_.u32()))),
            tuple(r.seq(lambda r_: r_.u16())),
            r.bytes(),
        )


@message(76)
@dataclass
class HeaderResyncRequest:
    """Full-map resync for a delta header the receiver could not
    reconstruct: ask the AUTHOR for the full header by digest, keyed off the
    receiver's last-seen round for that author so the response can also
    carry the author's intervening headers (the receiver is behind by more
    than one round exactly when parents stop resolving)."""

    header_digest: Digest
    author: PublicKey
    since_round: Round  # receiver's last-seen round for this author
    requestor: PublicKey = b"\0" * 32

    def encode(self, w: Writer) -> None:
        w.raw(self.header_digest)
        w.raw(self.author)
        w.u64(self.since_round)
        w.raw(self.requestor)

    @staticmethod
    def decode(r: Reader) -> "HeaderResyncRequest":
        return HeaderResyncRequest(
            r.raw(DIGEST_LEN), r.raw(PUBLIC_KEY_LEN), r.u64(), r.raw(PUBLIC_KEY_LEN)
        )


@message(77)
@dataclass
class HeaderResyncResponse:
    """Full headers answering a HeaderResyncRequest: the requested header
    plus any of the author's own headers after since_round it still holds
    (bounded). Receivers feed every entry through the normal sanitize path —
    a byzantine responder can only send headers that fail verification."""

    headers: tuple[Header, ...]

    def encode(self, w: Writer) -> None:
        w.seq(self.headers, lambda w_, h: h.encode(w_))

    @staticmethod
    def decode(r: Reader) -> "HeaderResyncResponse":
        return HeaderResyncResponse(tuple(r.seq(Header.decode)))


@message(78)
@dataclass
class CertificateDeltaMsg:
    """Full-format certificate broadcast WITHOUT the embedded header body
    (the header_wire="delta" analog of CertificateRefMsg): every peer that
    voted already stores the header — a round's header bytes otherwise
    travel every link twice (HeaderMsg, then again inside CertificateMsg).
    Receivers rebuild the Certificate from their header store and fall back
    to fetching the full certificate from the origin on miss (same
    resolution path as CertificateRefMsg)."""

    header_digest: Digest
    round: Round
    epoch: int
    origin: PublicKey
    signers: tuple[int, ...]
    signatures: tuple[bytes, ...]  # 64-byte ed25519 signatures

    @staticmethod
    def from_certificate(cert: Certificate) -> "CertificateDeltaMsg":
        assert not cert.is_compact
        return CertificateDeltaMsg(
            cert.header.digest,
            cert.round,
            cert.epoch,
            cert.origin,
            cert.signers,
            cert.signatures,
        )

    def rebuild(self, header: Header) -> Certificate:
        return Certificate(header, self.signers, self.signatures)

    def encode(self, w: Writer) -> None:
        w.raw(self.header_digest)
        w.u64(self.round)
        w.u64(self.epoch)
        w.raw(self.origin)
        # u16 committee indices: dense ids, and this message exists to
        # shave broadcast bytes.
        w.seq(self.signers, lambda w_, i: w_.u16(i))
        w.seq(self.signatures, lambda w_, s: w_.raw(s))

    @staticmethod
    def decode(r: Reader) -> "CertificateDeltaMsg":
        return CertificateDeltaMsg(
            r.raw(DIGEST_LEN),
            r.u64(),
            r.u64(),
            r.raw(PUBLIC_KEY_LEN),
            tuple(r.seq(lambda r_: r_.u16())),
            tuple(r.seq(lambda r_: r_.raw(64))),
        )


@message(79)
@dataclass
class Relay2Msg:
    """Slim fanout-tree envelope (the wire-diet successor of RelayMsg —
    which stays accepted): the origin is a 2-byte committee index, round and
    epoch shrink to u32/u16, and the inner announcement rides as a
    purpose-built compact body instead of a self-describing message, so the
    envelope stops re-shipping fields the announcement also carries:

      kind 1 — delta header: header_digest | parents BITMAP (one bit per
               committee index at round-1) | 64-byte signature | payload
               (digest, u16 worker) pairs; author/round/epoch come from the
               envelope. ~150 B at N=50 vs ~260 for DeltaHeaderMsg-in-RelayMsg.
      kind 2 — compact certificate: header_digest | agg_s | signer BITMAP |
               the 32-byte R nonces in signer order. Saves the duplicated
               origin/round/epoch and the u32-per-signer index list of
               CertificateRefMsg (~190 B/certificate/link at N=50).
      kind 0 — generic: u16 inner tag | raw body (any announcement the slim
               kinds cannot express: full HeaderMsg fallbacks, full-format
               CertificateMsg/CertificateDeltaMsg).

    Decoding kinds 1/2 back into DeltaHeaderMsg/CertificateRefMsg needs the
    committee (index->key, bitmap width) and happens in primary/fanout.py;
    receivers then run the EXACT resolution paths the fat forms take. The
    ack id every hop agrees on covers the whole envelope identity."""

    origin_index: int  # committee dense index of the broadcasting authority
    round: Round
    epoch: int
    kind: int  # 0 generic | 1 delta header | 2 compact certificate
    body: bytes

    def encode(self, w: Writer) -> None:
        w.u16(self.origin_index)
        w.u32(self.round)
        w.u16(self.epoch)
        w.u8(self.kind)
        w.raw(self.body)

    @staticmethod
    def decode(r: Reader) -> "Relay2Msg":
        return Relay2Msg(r.u16(), r.u32(), r.u16(), r.u8(), r.rest())

    @property
    def ack_id(self) -> Digest:
        from .crypto import digest256

        return digest256(
            b"R2"
            + self.origin_index.to_bytes(2, "little")
            + self.round.to_bytes(4, "little")
            + self.epoch.to_bytes(2, "little")
            + bytes([self.kind])
            + self.body
        )


@message(81)
@dataclass
class Vote2Msg:
    """Slim vote (the wire-diet successor of VoteMsg, which stays
    accepted): a vote always travels to the HEADER AUTHOR, who can
    reconstruct round/epoch/origin from the header digest (its own current
    header, or its header store) — so the wire carries only the digest,
    the voter, and the signature (128 B vs 180). The rebuilt Vote's signed
    message is a pure function of the reconstructed fields, so a forged or
    misdirected slim vote simply fails signature verification."""

    header_digest: Digest
    author: PublicKey  # the voter
    signature: bytes  # 64-byte ed25519

    @staticmethod
    def from_vote(vote: Vote) -> "Vote2Msg":
        return Vote2Msg(vote.header_digest, vote.author, vote.signature)

    def rebuild(self, header: Header) -> Vote:
        return Vote(
            self.header_digest,
            header.round,
            header.epoch,
            header.author,
            self.author,
            self.signature,
        )

    def encode(self, w: Writer) -> None:
        w.raw(self.header_digest)
        w.raw(self.author)
        w.raw(self.signature)

    @staticmethod
    def decode(r: Reader) -> "Vote2Msg":
        return Vote2Msg(
            r.raw(DIGEST_LEN), r.raw(PUBLIC_KEY_LEN), r.raw(SIGNATURE_LEN)
        )


@message(80)
@dataclass
class RelayAck2Msg:
    """Slim receipt confirmation for Relay2Msg broadcasts, sent as a
    fire-and-forget KIND_ONEWAY frame (no RPC Ack response — delivery of
    the ack itself is best-effort by design: a lost ack costs the origin
    one fallback direct send). The acker is the handshake-verified peer on
    authenticated meshes; the carried committee index is only trusted on
    open (bare-test) meshes, like RelayAckMsg's name field."""

    ack_id: Digest
    acker_index: int

    def encode(self, w: Writer) -> None:
        w.raw(self.ack_id)
        w.u16(self.acker_index)

    @staticmethod
    def decode(r: Reader) -> "RelayAck2Msg":
        return RelayAck2Msg(r.raw(DIGEST_LEN), r.u16())


# ---------------------------------------------------------------------------
# Telemetry plane: scrape + flight-recorder dump over the public typed API
# (ConsensusApi routes these, so they're fabric-reachable under simnet; the
# gRPC edge exposes the same payloads for interop scrapers).
# ---------------------------------------------------------------------------


@message(82)
@dataclass
class TelemetryScrapeMsg:
    """Request the node's Prometheus text exposition."""

    def encode(self, w: Writer) -> None:
        pass

    @staticmethod
    def decode(r: Reader) -> "TelemetryScrapeMsg":
        return TelemetryScrapeMsg()


@message(83)
@dataclass
class TelemetryScrapeResponse:
    """Prometheus exposition-format text (# HELP/# TYPE + samples)."""

    text: str

    def encode(self, w: Writer) -> None:
        w.bytes(self.text.encode())

    @staticmethod
    def decode(r: Reader) -> "TelemetryScrapeResponse":
        return TelemetryScrapeResponse(r.bytes().decode())


@message(84)
@dataclass
class FlightDumpMsg:
    """Request the node's flight-recorder dump (bounded structured event
    ring + span edges). max_events=0 means the full ring."""

    max_events: int = 0

    def encode(self, w: Writer) -> None:
        w.u32(self.max_events)

    @staticmethod
    def decode(r: Reader) -> "FlightDumpMsg":
        return FlightDumpMsg(r.u32())


@message(85)
@dataclass
class FlightDumpResponse:
    """Self-contained JSON flight-recorder dump (tracing.Tracer.dump),
    sort_keys-canonical so dumps diff and snapshot deterministically."""

    payload: bytes

    def encode(self, w: Writer) -> None:
        w.bytes(self.payload)

    @staticmethod
    def decode(r: Reader) -> "FlightDumpResponse":
        return FlightDumpResponse(r.bytes())


# ---------------------------------------------------------------------------
# Primary -> Worker (types/src/primary.rs:702-750)
# ---------------------------------------------------------------------------


@message(20)
@dataclass
class SynchronizeMsg:
    """Fetch these batches from the target authority's same-id worker
    (worker/src/synchronizer.rs:77-384)."""

    digests: tuple[Digest, ...]
    target: PublicKey

    def encode(self, w: Writer) -> None:
        w.seq(self.digests, _enc_digest)
        w.raw(self.target)

    @staticmethod
    def decode(r: Reader) -> "SynchronizeMsg":
        return SynchronizeMsg(tuple(r.seq(_dec_digest)), r.raw(PUBLIC_KEY_LEN))


@message(21)
@dataclass
class CleanupMsg:
    round: Round

    def encode(self, w: Writer) -> None:
        w.u64(self.round)

    @staticmethod
    def decode(r: Reader) -> "CleanupMsg":
        return CleanupMsg(r.u64())


@message(22)
@dataclass
class RequestBatchMsg:
    digest: Digest

    def encode(self, w: Writer) -> None:
        w.raw(self.digest)

    @staticmethod
    def decode(r: Reader) -> "RequestBatchMsg":
        return RequestBatchMsg(_dec_digest(r))


@message(25)
@dataclass
class RequestBatchesMsg:
    """Coalesced batch fetch: every digest a requester is missing from ONE
    worker rides a single RPC instead of one round trip each. The worker
    answers from one coalesced store read with RequestedBatchesMsg, so the
    commit-to-execution data plane pays RTT per (worker, certificate) group
    rather than per batch. Digests answer in request order."""

    digests: tuple[Digest, ...]

    def encode(self, w: Writer) -> None:
        w.seq(self.digests, _enc_digest)

    @staticmethod
    def decode(r: Reader) -> "RequestBatchesMsg":
        return RequestBatchesMsg(tuple(r.seq(_dec_digest)))


@message(23)
@dataclass
class DeleteBatchesMsg:
    digests: tuple[Digest, ...]

    def encode(self, w: Writer) -> None:
        w.seq(self.digests, _enc_digest)

    @staticmethod
    def decode(r: Reader) -> "DeleteBatchesMsg":
        return DeleteBatchesMsg(tuple(r.seq(_dec_digest)))


@message(26)
@dataclass
class BackpressureMsg:
    """Primary -> own workers: the downstream (consensus/executor) backlog
    level in [0, 1], pushed every backpressure_poll_interval. The worker's
    ingest gate folds it into admission decisions (pacing.IngestGate) so
    client-facing ingest sheds/blocks BEFORE the backlog grows unboundedly.
    Fixed-point basis points on the wire; best-effort delivery — a worker
    that stops hearing levels fails open (BackpressureState.stale_after)."""

    level_bp: int  # level * 10_000, clamped to [0, 10_000]

    def encode(self, w: Writer) -> None:
        w.u16(self.level_bp)

    @staticmethod
    def decode(r: Reader) -> "BackpressureMsg":
        return BackpressureMsg(r.u16())

    @staticmethod
    def from_level(level: float) -> "BackpressureMsg":
        return BackpressureMsg(int(max(0.0, min(1.0, level)) * 10_000))

    @property
    def level(self) -> float:
        return self.level_bp / 10_000


@message(24)
@dataclass
class ReconfigureMsg:
    """kind: 'new_epoch' | 'update_committee' | 'shutdown'; committee as JSON
    (ReconfigureNotification, types/src/primary.rs:646-668)."""

    kind: str
    committee_json: str = ""

    def encode(self, w: Writer) -> None:
        w.bytes(self.kind.encode())
        w.bytes(self.committee_json.encode())

    @staticmethod
    def decode(r: Reader) -> "ReconfigureMsg":
        return ReconfigureMsg(r.bytes().decode(), r.bytes().decode())

    def committee(self) -> Committee | None:
        return Committee.from_json(self.committee_json) if self.committee_json else None


# ---------------------------------------------------------------------------
# Worker -> Primary (types/src/worker.rs WorkerPrimaryMessage)
# ---------------------------------------------------------------------------


@message(30)
@dataclass
class OurBatchMsg:
    digest: Digest
    worker_id: WorkerId

    def encode(self, w: Writer) -> None:
        w.raw(self.digest)
        w.u32(self.worker_id)

    @staticmethod
    def decode(r: Reader) -> "OurBatchMsg":
        return OurBatchMsg(_dec_digest(r), r.u32())


@message(31)
@dataclass
class OthersBatchMsg:
    digest: Digest
    worker_id: WorkerId

    def encode(self, w: Writer) -> None:
        w.raw(self.digest)
        w.u32(self.worker_id)

    @staticmethod
    def decode(r: Reader) -> "OthersBatchMsg":
        return OthersBatchMsg(_dec_digest(r), r.u32())


@message(32)
@dataclass
class RequestedBatchMsg:
    """Batch fetch response. Carries the *serialized* batch so the server
    side never decodes/re-encodes transactions (found=False for a miss);
    requesters decode once via `transactions`."""

    digest: Digest
    serialized_batch: bytes
    found: bool = True

    def encode(self, w: Writer) -> None:
        w.raw(self.digest)
        w.u8(1 if self.found else 0)
        w.bytes(self.serialized_batch)

    @staticmethod
    def decode(r: Reader) -> "RequestedBatchMsg":
        digest = _dec_digest(r)
        found = r.u8() == 1
        return RequestedBatchMsg(digest, r.bytes(), found)

    @property
    def transactions(self) -> tuple[bytes, ...]:
        if not self.found:
            return ()
        return Batch.from_bytes(self.serialized_batch).transactions


@message(35)
@dataclass
class RequestedBatchesMsg:
    """Response to RequestBatchesMsg: one (digest, found, serialized_batch)
    entry per requested digest, in request order, each byte-identical to what
    the equivalent single RequestBatchMsg would have returned (misses carry
    found=False and empty bytes). The server never decodes the stored wire
    bytes; verification (serialized_batch_digest) is the requester's."""

    batches: tuple[tuple[Digest, bool, bytes], ...]

    def encode(self, w: Writer) -> None:
        def enc(w_: Writer, item) -> None:
            digest, found, raw = item
            w_.raw(digest)
            w_.u8(1 if found else 0)
            w_.bytes(raw)

        w.seq(self.batches, enc)

    @staticmethod
    def decode(r: Reader) -> "RequestedBatchesMsg":
        def dec(r_: Reader):
            digest = _dec_digest(r_)
            found = r_.u8() == 1
            return (digest, found, r_.bytes())

        return RequestedBatchesMsg(tuple(r.seq(dec)))


@message(33)
@dataclass
class DeletedBatchesMsg:
    digests: tuple[Digest, ...]

    def encode(self, w: Writer) -> None:
        w.seq(self.digests, _enc_digest)

    @staticmethod
    def decode(r: Reader) -> "DeletedBatchesMsg":
        return DeletedBatchesMsg(tuple(r.seq(_dec_digest)))


@message(34)
@dataclass
class WorkerErrorMsg:
    error: str

    def encode(self, w: Writer) -> None:
        w.bytes(self.error.encode())

    @staticmethod
    def decode(r: Reader) -> "WorkerErrorMsg":
        return WorkerErrorMsg(r.bytes().decode())


# ---------------------------------------------------------------------------
# Worker <-> Worker (types/src/worker.rs:17-32)
# ---------------------------------------------------------------------------


@message(40)
@dataclass
class WorkerBatchMsg:
    """Batch dissemination. Carries the serialized batch so the receiver can
    digest the wire bytes directly (serialized_batch_digest,
    types/src/worker.rs:44-62). The message body IS the serialized batch
    (no length wrapper): encoding a broadcast is zero-copy — the memoized
    wire form aliases the batch bytes instead of duplicating them."""

    serialized_batch: bytes

    def encode(self, w: Writer) -> None:
        w.raw(self.serialized_batch)

    @staticmethod
    def decode(r: Reader) -> "WorkerBatchMsg":
        return WorkerBatchMsg(r.rest())

    def batch(self) -> Batch:
        return Batch.from_bytes(self.serialized_batch)


@message(41)
@dataclass
class WorkerBatchRequest:
    digests: tuple[Digest, ...]

    def encode(self, w: Writer) -> None:
        w.seq(self.digests, _enc_digest)

    @staticmethod
    def decode(r: Reader) -> "WorkerBatchRequest":
        return WorkerBatchRequest(tuple(r.seq(_dec_digest)))


@message(42)
@dataclass
class WorkerBatchResponse:
    batches: tuple[bytes, ...]  # serialized batches

    def encode(self, w: Writer) -> None:
        w.seq(self.batches, lambda w_, b: w_.bytes(b))

    @staticmethod
    def decode(r: Reader) -> "WorkerBatchResponse":
        return WorkerBatchResponse(tuple(r.seq(lambda r_: r_.bytes())))


# ---------------------------------------------------------------------------
# Client -> Worker transactions (the tonic Transactions service analog,
# worker/src/worker.rs:352-423)
# ---------------------------------------------------------------------------


@message(50)
@dataclass
class SubmitTransactionMsg:
    transaction: bytes

    def encode(self, w: Writer) -> None:
        w.bytes(self.transaction)

    @staticmethod
    def decode(r: Reader) -> "SubmitTransactionMsg":
        return SubmitTransactionMsg(r.bytes())


@message(51)
@dataclass
class SubmitTransactionStreamMsg:
    """Batched client submission (the streaming variant).

    Decoded lazily: the ingest path validates the frames structurally
    (types.validate_tx_frames) and forwards the undecoded chunk straight into
    batch sealing — the burst's wire form IS the batch's wire form, so no
    per-transaction split ever happens on the worker."""

    transactions: tuple[bytes, ...] = ()
    raw: bytes | None = None  # full wire body: u32 count | frames

    def encode(self, w: Writer) -> None:
        if self.raw is not None:
            w.raw(self.raw)
        else:
            w.bytes_seq(self.transactions)

    @staticmethod
    def decode(r: Reader) -> "SubmitTransactionStreamMsg":
        return SubmitTransactionStreamMsg((), r.rest())

    @property
    def count(self) -> int:
        if self.raw is None:
            return len(self.transactions)
        import struct

        (n,) = struct.unpack_from("<I", self.raw, 0)
        return n

    @property
    def frames(self) -> bytes:
        """The per-tx frames without the leading count word."""
        if self.raw is None:
            w = Writer()
            self.encode(w)
            return w.finish()[4:]
        return self.raw[4:]

    @property
    def txs(self) -> tuple[bytes, ...]:
        """Materialized transactions (tests/low-rate tools only)."""
        if self.raw is None:
            return self.transactions
        r = Reader(self.raw)
        out = tuple(r.bytes_seq())
        r.done()
        return out


# ---------------------------------------------------------------------------
# Public consensus API (the tonic Validator / Proposer / Configuration
# services, /root/reference/types/proto/narwhal.proto:127-152 served by
# primary/src/grpc_server/). "Collection" = a certificate's payload.
# ---------------------------------------------------------------------------


@message(60)
@dataclass
class GetCollectionsRequest:
    digests: tuple[Digest, ...]

    def encode(self, w: Writer) -> None:
        w.seq(self.digests, _enc_digest)

    @staticmethod
    def decode(r: Reader) -> "GetCollectionsRequest":
        return GetCollectionsRequest(tuple(r.seq(_dec_digest)))


@message(61)
@dataclass
class GetCollectionsResponse:
    """Per requested digest: (digest, batches, error). `batches` is a tuple
    of (batch_digest, transactions); `error` is "" on success."""

    results: tuple[tuple[Digest, tuple[tuple[Digest, tuple[bytes, ...]], ...], str], ...]

    def encode(self, w: Writer) -> None:
        def enc_batch(w_: Writer, item) -> None:
            _enc_digest(w_, item[0])
            w_.seq(item[1], lambda w2, t: w2.bytes(t))

        def enc(w_: Writer, item) -> None:
            _enc_digest(w_, item[0])
            w_.seq(item[1], enc_batch)
            w_.bytes(item[2].encode())

        w.seq(self.results, enc)

    @staticmethod
    def decode(r: Reader) -> "GetCollectionsResponse":
        def dec_batch(r_: Reader):
            return (_dec_digest(r_), tuple(r_.seq(lambda r2: r2.bytes())))

        def dec(r_: Reader):
            return (
                _dec_digest(r_),
                tuple(r_.seq(dec_batch)),
                r_.bytes().decode(),
            )

        return GetCollectionsResponse(tuple(r.seq(dec)))


@message(62)
@dataclass
class RemoveCollectionsRequest:
    digests: tuple[Digest, ...]

    def encode(self, w: Writer) -> None:
        w.seq(self.digests, _enc_digest)

    @staticmethod
    def decode(r: Reader) -> "RemoveCollectionsRequest":
        return RemoveCollectionsRequest(tuple(r.seq(_dec_digest)))


@message(63)
@dataclass
class ReadCausalRequest:
    digest: Digest

    def encode(self, w: Writer) -> None:
        _enc_digest(w, self.digest)

    @staticmethod
    def decode(r: Reader) -> "ReadCausalRequest":
        return ReadCausalRequest(_dec_digest(r))


@message(64)
@dataclass
class ReadCausalResponse:
    digests: tuple[Digest, ...]

    def encode(self, w: Writer) -> None:
        w.seq(self.digests, _enc_digest)

    @staticmethod
    def decode(r: Reader) -> "ReadCausalResponse":
        return ReadCausalResponse(tuple(r.seq(_dec_digest)))


@message(65)
@dataclass
class RoundsRequest:
    public_key: PublicKey

    def encode(self, w: Writer) -> None:
        w.raw(self.public_key)

    @staticmethod
    def decode(r: Reader) -> "RoundsRequest":
        return RoundsRequest(r.raw(PUBLIC_KEY_LEN))


@message(66)
@dataclass
class RoundsResponse:
    oldest_round: Round
    newest_round: Round

    def encode(self, w: Writer) -> None:
        w.u64(self.oldest_round)
        w.u64(self.newest_round)

    @staticmethod
    def decode(r: Reader) -> "RoundsResponse":
        return RoundsResponse(r.u64(), r.u64())


@message(67)
@dataclass
class NodeReadCausalRequest:
    public_key: PublicKey
    round: Round

    def encode(self, w: Writer) -> None:
        w.raw(self.public_key)
        w.u64(self.round)

    @staticmethod
    def decode(r: Reader) -> "NodeReadCausalRequest":
        return NodeReadCausalRequest(r.raw(PUBLIC_KEY_LEN), r.u64())


@message(68)
@dataclass
class NewNetworkInfoRequest:
    """(epoch, [(public_key, stake, primary_address)])."""

    epoch: int
    validators: tuple[tuple[PublicKey, int, str], ...]

    def encode(self, w: Writer) -> None:
        w.u64(self.epoch)

        def enc(w_: Writer, item) -> None:
            w_.raw(item[0])
            w_.u64(item[1])
            w_.bytes(item[2].encode())

        w.seq(self.validators, enc)

    @staticmethod
    def decode(r: Reader) -> "NewNetworkInfoRequest":
        def dec(r_: Reader):
            return (r_.raw(PUBLIC_KEY_LEN), r_.u64(), r_.bytes().decode())

        return NewNetworkInfoRequest(r.u64(), tuple(r.seq(dec)))


@message(69)
@dataclass
class GetPrimaryAddressRequest:
    def encode(self, w: Writer) -> None:
        pass

    @staticmethod
    def decode(r: Reader) -> "GetPrimaryAddressRequest":
        return GetPrimaryAddressRequest()


@message(70)
@dataclass
class GetPrimaryAddressResponse:
    address: str

    def encode(self, w: Writer) -> None:
        w.bytes(self.address.encode())

    @staticmethod
    def decode(r: Reader) -> "GetPrimaryAddressResponse":
        return GetPrimaryAddressResponse(r.bytes().decode())


@message(71)
@dataclass
class NewEpochRequest:
    epoch: int

    def encode(self, w: Writer) -> None:
        w.u64(self.epoch)

    @staticmethod
    def decode(r: Reader) -> "NewEpochRequest":
        return NewEpochRequest(r.u64())
