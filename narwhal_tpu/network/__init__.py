from .auth import AuthError, Credentials, Peer, cached_allow_sets, committee_resolver
from .pool import LanePool, node_pool, register_node_pool, unregister_node_pool
from .rpc import (
    LANE_PRIMARY,
    NetworkClient,
    PeerClient,
    PeerLink,
    RetryConfig,
    RpcError,
    RpcLaneUnavailable,
    RpcServer,
    RpcTimeout,
    WireCounters,
    worker_lane,
)

__all__ = [
    "AuthError",
    "Credentials",
    "LANE_PRIMARY",
    "LanePool",
    "NetworkClient",
    "Peer",
    "PeerClient",
    "PeerLink",
    "RetryConfig",
    "RpcError",
    "RpcLaneUnavailable",
    "RpcServer",
    "RpcTimeout",
    "WireCounters",
    "cached_allow_sets",
    "committee_resolver",
    "node_pool",
    "register_node_pool",
    "unregister_node_pool",
    "worker_lane",
]
