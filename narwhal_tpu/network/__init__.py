from .rpc import (
    NetworkClient,
    PeerClient,
    RetryConfig,
    RpcError,
    RpcServer,
)

__all__ = [
    "NetworkClient",
    "PeerClient",
    "RetryConfig",
    "RpcError",
    "RpcServer",
]
