from .auth import AuthError, Credentials, Peer, cached_allow_sets, committee_resolver
from .rpc import (
    NetworkClient,
    PeerClient,
    RetryConfig,
    RpcError,
    RpcServer,
    RpcTimeout,
    WireCounters,
)

__all__ = [
    "AuthError",
    "Credentials",
    "NetworkClient",
    "Peer",
    "PeerClient",
    "RetryConfig",
    "RpcError",
    "RpcServer",
    "RpcTimeout",
    "WireCounters",
    "cached_allow_sets",
    "committee_resolver",
]
