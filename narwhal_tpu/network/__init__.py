from .auth import AuthError, Credentials, Peer, committee_resolver
from .rpc import (
    NetworkClient,
    PeerClient,
    RetryConfig,
    RpcError,
    RpcServer,
)

__all__ = [
    "AuthError",
    "Credentials",
    "NetworkClient",
    "Peer",
    "PeerClient",
    "RetryConfig",
    "RpcError",
    "RpcServer",
    "committee_resolver",
]
