"""Mutual transport authentication for the validator mesh.

Reference: the anemo network gives every peer an ed25519 identity — a
`PeerId` derived from its network key — and mutually-authenticated TLS
(/root/reference/network/src/p2p.rs:26-158; worker keys registered as known
peers at /root/reference/worker/src/worker.rs:137-146). Connections from
unknown identities never reach the validator-internal RPC handlers, and all
post-handshake traffic is protected by the TLS channel.

Here the same guarantee comes from a signed authenticated key exchange plus
per-frame AEAD:

1. The server opens with a nonce, its network key and an ephemeral X25519
   public key; the client answers with its network key, a nonce, its own
   ephemeral key and an ed25519 signature over the whole transcript; the
   server signs the transcript back. Both signatures bind the ephemeral
   keys to the committee identities (config.Authority.network_key for
   primaries, WorkerInfo.name for workers), so a relay cannot substitute
   its own ephemerals.
2. X25519(eph, eph') gives a shared secret only the two endpoints know;
   per-direction AES-256-GCM keys are derived from it and the transcript,
   and every subsequent frame body is encrypted and authenticated with a
   counter nonce and the frame header as associated data. An on-path
   attacker can therefore neither read, inject, replay nor reorder frames
   after the handshake.

Routes attach `allow` predicates on the verified identity (control-plane
frames accept only the node's own primary, etc. — the authorization matrix
lives in worker.py / primary.py). Public edges (tx ingest, the consensus
API) stay unauthenticated, exactly like the reference's tonic gRPC plane.

"""

from __future__ import annotations

import asyncio
import hashlib
import os
from dataclasses import dataclass
from typing import Callable, Optional

try:
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
        X25519PublicKey,
    )
    from cryptography.hazmat.primitives import serialization as _ser

    _HAVE_OPENSSL = True
except ImportError:  # pragma: no cover - exercised only without OpenSSL
    _HAVE_OPENSSL = False
    _ser = None

from ..crypto import KeyPair, verify
from ..types import PublicKey

HS_TIMEOUT = 5.0
MAC_LEN = 16  # AES-GCM authentication tag appended to every sealed body
# v5: the frame header grew a lane byte (rpc._FRAME_HDR) which is part of
# the AEAD associated data — both ends must speak the same header layout.
_CLIENT_DOMAIN = b"narwhal-hs-client-v5"
_SERVER_DOMAIN = b"narwhal-hs-server-v5"

# Handshake frame kinds (share the RPC frame header; rid/tag are zero).
KIND_HELLO = 3  # server -> client: nonce_s(32) | server_pub(32) | server_eph(32)
KIND_AUTH = 4  # client -> client_pub(32) | nonce_c(32) | client_eph(32) | sig(64)
KIND_AUTH_OK = 5  # server -> client: sig(64)


class AuthError(Exception):
    pass


# -- no-OpenSSL fallbacks ----------------------------------------------------
#
# Containers without the `cryptography` bindings still need the mesh to
# authenticate: X25519 is the RFC 7748 montgomery ladder over Python ints
# (handshake-only, two scalarmults per connection), and the per-frame AEAD is
# a keyed-blake2b keystream XOR with an encrypt-then-MAC 16-byte tag — the
# same seal/open framing as AES-GCM, used symmetrically by both endpoints of
# an in-process mesh, so the wire stays self-consistent. Both sides must run
# the same build; that is always true for the single-container clusters this
# fallback exists for.

_X_P = 2**255 - 19


def _x25519_scalarmult(k_bytes: bytes, u_bytes: bytes) -> bytes:
    k = int.from_bytes(k_bytes, "little")
    k &= (1 << 254) - 8
    k |= 1 << 254
    u = int.from_bytes(u_bytes, "little") & ((1 << 255) - 1)
    x1, x2, z2, x3, z3, swap = u, 1, 0, u, 1, 0
    for t in reversed(range(255)):
        kt = (k >> t) & 1
        if swap ^ kt:
            x2, x3, z2, z3 = x3, x2, z3, z2
        swap = kt
        A = (x2 + z2) % _X_P
        AA = A * A % _X_P
        B = (x2 - z2) % _X_P
        BB = B * B % _X_P
        E = (AA - BB) % _X_P
        C = (x3 + z3) % _X_P
        Dm = (x3 - z3) % _X_P
        DA = Dm * A % _X_P
        CB = C * B % _X_P
        x3 = (DA + CB) % _X_P
        x3 = x3 * x3 % _X_P
        z3 = (DA - CB) % _X_P
        z3 = z3 * z3 % _X_P * x1 % _X_P
        x2 = AA * BB % _X_P
        z2 = E * ((AA + 121665 * E) % _X_P) % _X_P
    if swap:
        x2, z2 = x3, z3
    return (x2 * pow(z2, _X_P - 2, _X_P) % _X_P).to_bytes(32, "little")


class _RefX25519PublicKey:
    __slots__ = ("_raw",)

    def __init__(self, raw: bytes):
        self._raw = raw

    @staticmethod
    def from_public_bytes(raw: bytes) -> "_RefX25519PublicKey":
        return _RefX25519PublicKey(raw)


class _RefX25519PrivateKey:
    __slots__ = ("_k", "_pub")

    def __init__(self, k: bytes):
        self._k = k
        self._pub = _x25519_scalarmult(k, (9).to_bytes(32, "little"))

    @staticmethod
    def generate() -> "_RefX25519PrivateKey":
        # Draw through the module entropy seam (resolved at call time, so
        # set_entropy() installed later still governs): when the reference
        # backend is aliased as X25519PrivateKey, seeded scenarios must get
        # deterministic ephemeral keys here too.
        return _RefX25519PrivateKey(_entropy(32))

    def public_key(self) -> _RefX25519PublicKey:
        return _RefX25519PublicKey(self._pub)

    def exchange(self, peer: _RefX25519PublicKey) -> bytes:
        return _x25519_scalarmult(self._k, peer._raw)


class _HashAEAD:
    """Encrypt-then-MAC AEAD on keyed blake2b: CTR keystream XOR for
    confidentiality, 16-byte keyed tag over (nonce, aad, ciphertext) for
    integrity. Interface-compatible with AESGCM's encrypt/decrypt."""

    __slots__ = ("_key",)

    def __init__(self, key: bytes):
        self._key = key

    def _stream(self, nonce: bytes, n: int) -> bytes:
        out = bytearray()
        ctr = 0
        while len(out) < n:
            out += hashlib.blake2b(
                nonce + ctr.to_bytes(8, "little"), key=self._key, digest_size=64
            ).digest()
            ctr += 1
        return bytes(out[:n])

    def _tag(self, nonce: bytes, aad: bytes, ct: bytes) -> bytes:
        return hashlib.blake2b(
            len(aad).to_bytes(8, "little") + aad + nonce + ct,
            key=self._key,
            digest_size=MAC_LEN,
        ).digest()

    def encrypt(self, nonce: bytes, data: bytes, aad: bytes) -> bytes:
        ct = bytes(a ^ b for a, b in zip(data, self._stream(nonce, len(data))))
        return ct + self._tag(nonce, aad, ct)

    def decrypt(self, nonce: bytes, ct_tag: bytes, aad: bytes) -> bytes:
        import hmac as _hmac

        if len(ct_tag) < MAC_LEN:
            raise AuthError("sealed frame shorter than its tag")
        ct, tag = ct_tag[:-MAC_LEN], ct_tag[-MAC_LEN:]
        if not _hmac.compare_digest(tag, self._tag(nonce, aad, ct)):
            raise AuthError("frame AEAD authentication failed")
        return bytes(a ^ b for a, b in zip(ct, self._stream(nonce, len(ct))))


if not _HAVE_OPENSSL:
    X25519PrivateKey = _RefX25519PrivateKey
    X25519PublicKey = _RefX25519PublicKey


# Handshake entropy seam. Production draws from os.urandom; the simnet
# scenario runner installs a seeded stream so handshake nonces/ephemerals —
# and therefore the whole wire transcript — replay bit-identically per
# seed. (Simulated committees run inside one trusted process; deterministic
# ephemerals there cost nothing security-wise and buy exact replay.)
_entropy = os.urandom


def set_entropy(fn) -> "Callable[[int], bytes]":
    """Install a bytes-producing entropy source (n -> n bytes); returns
    the previous one so harnesses can restore it."""
    global _entropy
    previous = _entropy
    _entropy = fn if fn is not None else os.urandom
    return previous


def _eph_private_key():
    """A fresh X25519 ephemeral from the entropy seam (both the OpenSSL
    and the in-tree backend accept raw 32-byte scalars)."""
    raw = _entropy(32)
    if _HAVE_OPENSSL:
        return X25519PrivateKey.from_private_bytes(raw)
    return X25519PrivateKey(raw)


@dataclass
class Peer:
    """Identity of the remote end of a connection, as seen by handlers:
    `key` is the handshake-verified network public key, or None on
    unauthenticated (public-plane) servers."""

    addr: str
    key: Optional[PublicKey] = None

    def __str__(self) -> str:  # handlers log the peer; keep it readable
        return self.addr


class Session:
    """Per-connection frame protection: independent AES-256-GCM keys and
    counter nonces for each direction. Every frame body is encrypted and
    authenticated (AEAD) with the frame header as associated data — the
    full confidentiality+authenticity of the reference's TLS channel, at
    AES-NI speed (~10 GB/s on this host vs ~1.5 GB/s for hash-based MACs)."""

    def __init__(self, send_key: bytes, recv_key: bytes):
        if _HAVE_OPENSSL:
            from cryptography.hazmat.primitives.ciphers.aead import AESGCM

            self._send = AESGCM(send_key)
            self._recv = AESGCM(recv_key)
        else:
            self._send = _HashAEAD(send_key)
            self._recv = _HashAEAD(recv_key)
        self._send_seq = 0
        self._recv_seq = 0

    @staticmethod
    def _aad(kind: int, rid: int, tag: int, lane: int = 0) -> bytes:
        return (
            bytes([kind])
            + rid.to_bytes(8, "little")
            + tag.to_bytes(2, "little")
            + bytes([lane])
        )

    def seal_body(
        self, kind: int, rid: int, tag: int, body: bytes, lane: int = 0
    ) -> bytes:
        """Encrypt+authenticate a frame body; returns ciphertext||tag(16).
        The counter nonce is unique per (key, direction) by construction."""
        nonce = self._send_seq.to_bytes(12, "little")
        self._send_seq += 1
        return self._send.encrypt(nonce, body, self._aad(kind, rid, tag, lane))

    def open_body(
        self, kind: int, rid: int, tag: int, ct: bytes, lane: int = 0
    ) -> bytes:
        """Decrypt+verify; raises AuthError on any tampering, injection,
        replay or reordering (the nonce is the expected sequence number)."""
        if _HAVE_OPENSSL:
            from cryptography.exceptions import InvalidTag
        else:
            InvalidTag = AuthError

        nonce = self._recv_seq.to_bytes(12, "little")
        try:
            body = self._recv.decrypt(nonce, ct, self._aad(kind, rid, tag, lane))
        except InvalidTag:
            raise AuthError("frame AEAD authentication failed") from None
        self._recv_seq += 1
        return body


class Credentials:
    """A node's network identity plus its view of who should answer at each
    mesh address. `resolve(addr)` returns the expected network key for a
    mesh address (primary_address / worker_address) or None for public
    endpoints — None skips the handshake entirely."""

    def __init__(
        self,
        keypair: KeyPair,
        resolve: Callable[[str], Optional[PublicKey]],
    ):
        self.keypair = keypair
        self.resolve = resolve


def committee_resolver(get_committee, get_worker_cache) -> Callable[[str], Optional[PublicKey]]:
    """Resolve mesh addresses against the *current* committee/worker-cache
    (callables, so epoch changes are picked up live): primary addresses map
    to Authority.network_key, worker mesh addresses to WorkerInfo.name.
    Transaction-ingest addresses are deliberately absent (public plane)."""

    def resolve(addr: str) -> Optional[PublicKey]:
        committee = get_committee()
        for auth in committee.authorities.values():
            if auth.primary_address == addr:
                return auth.network_key
        worker_cache = get_worker_cache()
        if worker_cache is not None:
            for workers in worker_cache.workers.values():
                for info in workers.values():
                    if info.worker_address == addr:
                        return info.name
        return None

    return resolve


def cached_allow_sets(holder, committee, worker_cache, build):
    """Identity-keyed memo of a node's allowed-key frozensets: the hot
    protocol plane pays two `is` compares per frame instead of an O(N)
    rebuild, and an epoch change (which swaps the committee/worker-cache
    objects) invalidates the cache. The cache tuple holds strong references
    to the keyed objects — keying on id() could serve a stale set to a new
    committee allocated at a recycled address after the old one is freed.

    `build()` returns the tuple of frozensets for the current objects; the
    same tuple shape is returned on every call. The memo is stored on
    `holder._auth_cache`."""
    cached = getattr(holder, "_auth_cache", None)
    if cached is None or cached[0] is not committee or cached[1] is not worker_cache:
        cached = (committee, worker_cache, build())
        holder._auth_cache = cached
    return cached[2]


def _raw_x25519_pub(priv) -> bytes:
    if not _HAVE_OPENSSL:
        return priv.public_key()._raw
    return priv.public_key().public_bytes(_ser.Encoding.Raw, _ser.PublicFormat.Raw)


def _transcript(
    nonce_s: bytes, nonce_c: bytes, server_pub: bytes, client_pub: bytes,
    server_eph: bytes, client_eph: bytes,
) -> bytes:
    return hashlib.blake2b(
        nonce_s + nonce_c + server_pub + client_pub + server_eph + client_eph,
        digest_size=32,
    ).digest()


def _derive_keys(shared: bytes, transcript: bytes) -> tuple[bytes, bytes]:
    """(client->server key, server->client key)."""
    c2s = hashlib.blake2b(shared + transcript + b"c2s", digest_size=32).digest()
    s2c = hashlib.blake2b(shared + transcript + b"s2c", digest_size=32).digest()
    return c2s, s2c


async def client_handshake(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    credentials: Credentials,
    expected_key: PublicKey,
    read_frame,
    write_frame,
) -> Session:
    """Client half: await HELLO, check the server presents the key the
    committee lists for this address, run the signed X25519 exchange and
    return the frame-MAC session. Raises AuthError on any mismatch."""
    kind, _, _, _, body = await asyncio.wait_for(read_frame(reader), HS_TIMEOUT)
    if kind != KIND_HELLO or len(body) != 96:
        raise AuthError("peer did not open with a handshake HELLO")
    nonce_s, server_pub, server_eph = body[:32], body[32:64], body[64:]
    if server_pub != expected_key:
        raise AuthError("server identity does not match committee network key")
    client_pub = credentials.keypair.public
    nonce_c = _entropy(32)
    eph_priv = _eph_private_key()
    client_eph = _raw_x25519_pub(eph_priv)
    transcript = _transcript(
        nonce_s, nonce_c, server_pub, client_pub, server_eph, client_eph
    )
    sig = credentials.keypair.sign(_CLIENT_DOMAIN + transcript)
    write_frame(writer, KIND_AUTH, 0, 0, client_pub + nonce_c + client_eph + sig)
    await writer.drain()
    kind, _, _, _, body = await asyncio.wait_for(read_frame(reader), HS_TIMEOUT)
    if kind != KIND_AUTH_OK or len(body) != 64:
        raise AuthError("server rejected handshake")
    if not verify(server_pub, _SERVER_DOMAIN + transcript, body):
        raise AuthError("server handshake signature invalid")
    shared = eph_priv.exchange(X25519PublicKey.from_public_bytes(server_eph))
    c2s, s2c = _derive_keys(shared, transcript)
    return Session(send_key=c2s, recv_key=s2c)


async def server_handshake(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    keypair: KeyPair,
    read_frame,
    write_frame,
) -> tuple[PublicKey, Session]:
    """Server half: send HELLO with our ephemeral, verify the client's
    signed transcript, sign it back. Returns the client's verified network
    key and the frame-MAC session."""
    nonce_s = _entropy(32)
    server_pub = keypair.public
    eph_priv = _eph_private_key()
    server_eph = _raw_x25519_pub(eph_priv)
    write_frame(writer, KIND_HELLO, 0, 0, nonce_s + server_pub + server_eph)
    await writer.drain()
    kind, _, _, _, body = await asyncio.wait_for(read_frame(reader), HS_TIMEOUT)
    if kind != KIND_AUTH or len(body) != 160:
        raise AuthError("client did not authenticate")
    client_pub, nonce_c, client_eph, sig = (
        body[:32],
        body[32:64],
        body[64:96],
        body[96:],
    )
    transcript = _transcript(
        nonce_s, nonce_c, server_pub, client_pub, server_eph, client_eph
    )
    if not verify(client_pub, _CLIENT_DOMAIN + transcript, sig):
        raise AuthError("client handshake signature invalid")
    write_frame(writer, KIND_AUTH_OK, 0, 0, keypair.sign(_SERVER_DOMAIN + transcript))
    await writer.drain()
    shared = eph_priv.exchange(X25519PublicKey.from_public_bytes(client_eph))
    c2s, s2c = _derive_keys(shared, transcript)
    return client_pub, Session(send_key=s2c, recv_key=c2s)
