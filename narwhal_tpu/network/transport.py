"""Transport provider seam — where simnet swaps the socket layer out.

`rpc.py` opens client connections and server listeners through this module
instead of calling asyncio directly. In production nothing changes: the
calls delegate straight to `asyncio.open_connection` / `asyncio.start_server`.
Under the simulation harness (narwhal_tpu/simnet), `install(fabric)` routes
both through an in-memory fabric: the same length-prefixed, AEAD-sealed
frames flow over seeded virtual-latency queues instead of TCP sockets, so a
whole committee — hundreds of nodes — fits in one process with zero file
descriptors spent on the mesh.

The seam is process-global on purpose: a simulated committee is by
definition one process sharing one fabric, and the swap must catch every
connection the protocol opens (including lazy reconnects rounds later)
without threading a handle through every actor.
"""

from __future__ import annotations

import asyncio

_fabric = None


def install(fabric) -> None:
    """Route all connection setup through `fabric` (a simnet SimFabric:
    anything with `open_connection(host, port, limit=)` and
    `start_server(cb, host, port, limit=)` coroutines)."""
    global _fabric
    if _fabric is not None and fabric is not _fabric:
        raise RuntimeError("a simnet transport fabric is already installed")
    _fabric = fabric


def uninstall() -> None:
    global _fabric
    _fabric = None


def active():
    """The installed fabric, or None when running over real sockets."""
    return _fabric


def simnet_active() -> bool:
    return _fabric is not None


async def open_connection(host: str, port: int, *, limit: int):
    """(reader, writer) to host:port — via the fabric when one is installed,
    else a real TCP connection."""
    fabric = _fabric
    if fabric is not None:
        return await fabric.open_connection(host, port, limit=limit)
    return await asyncio.open_connection(host, port, limit=limit)
