"""Asyncio TCP RPC mesh — the validator-internal communication backend.

Reference: anemo QUIC with bincode codec wrapped by P2pNetwork
(/root/reference/network/src/p2p.rs:26-360) offering unreliable_send
(fire-once), send (retry forever with exponential backoff, cancel-on-drop)
and broadcast/lucky_broadcast policies (/root/reference/network/src/traits.rs:10-94),
with per-peer BoundedExecutor concurrency caps
(/root/reference/network/src/bounded_executor.rs:46-153) and RetryConfig
(/root/reference/network/src/retry.rs:9-60).

TPU-native deployment keeps this plane on the host NIC (DCN/ethernet): BFT
messages must stay per-validator-signed point-to-point — ICI collectives are
trust-free only inside one operator's pod (SURVEY §5.9). Transport is
length-prefixed frames over TCP with persistent auto-reconnecting peer
connections; every send is an acked request/response, so reliable-send stake
counting (QuorumWaiter) works exactly as in the reference.

Frame layout: u32 body_len | u8 kind(REQ/RESP/ERR/ONEWAY) | u64 request_id |
u16 msg_tag | u8 lane | payload.

The lane byte is the multiplexing key of the CONNECTION POOL
(network/pool.py): all of a node pair's role lanes — the primary<->primary
plane (lane 0) and every worker mesh lane (lane 1+worker_id) — share ONE
authenticated framed stream, the anemo one-QUIC-connection-per-peer model.
The server side dispatches each frame to the lane's handler table; the
FrameSender drains per-lane queues round-robin so a saturated bulk lane
(batch relay) cannot starve a latency-critical one (votes). Pooled
connections are also BIDIRECTIONAL: the acceptor sends its own requests
over the accepted stream (PeerLink) — the request/response kinds travel in
opposite directions per rid namespace, so both endpoints' rid counters stay
independent — which is what takes an in-process N-node committee from
O(N^2 * lanes) sockets to one per unordered node pair.
"""

from __future__ import annotations

import asyncio
import hashlib
import itertools
import logging
import random
import struct
from typing import Awaitable, Callable, Iterable

from ..bounded_cache import BoundedCache
from ..channels import CancelOnDrop
from ..messages import Ack, decode_message, encode_message
from . import transport
from .auth import (
    KIND_HELLO,
    MAC_LEN,
    AuthError,
    Credentials,
    Peer,
    Session,
    client_handshake,
    server_handshake,
)

logger = logging.getLogger("narwhal.network")

_FRAME_HDR = struct.Struct("<IBQHB")  # len, kind, rid, tag, lane
KIND_REQ = 0
KIND_RESP = 1
KIND_ERR = 2
# Fire-and-forget request: the server dispatches the handler but writes NO
# response frame, and the client tracks no rid. For high-frequency lanes
# whose delivery is guaranteed by an APPLICATION-level mechanism (the relay
# plane: origin-side ack tracking + direct fallback), the per-frame Ack
# response and the retry-on-deadline resends of the RPC layer are pure
# overhead — measured at N=50 they were ~10% of all control-plane bytes.
# (KIND_HELLO = 3 lives in auth.py.)
KIND_ONEWAY = 4

MAX_FRAME = 64 << 20  # 64 MiB, > max batch size with generous headroom
MAX_TASK_CONCURRENCY = 500  # per-peer cap (network/src/lib.rs:54)

# Lane ids (the u8 lane byte of the frame header): lane 0 is the
# primary<->primary plane, lane 1+wid is worker mesh lane wid. Legacy
# (non-pooled) connections always carry lane 0 — the server they dial is
# the single role that owns the address, so the byte is redundant there.
LANE_PRIMARY = 0


def worker_lane(worker_id: int) -> int:
    return 1 + worker_id


# ERR body a pool-accepting server answers when a frame names a lane whose
# role is not co-hosted in its process (a split primary/worker deployment):
# the client falls back to a direct connection to the role's own address.
LANE_UNAVAILABLE = b"lane-unavailable"


class RpcError(Exception):
    pass


class RpcTimeout(RpcError):
    """The request deadline fired after the transport was up — the peer is
    slow (or the deadline too tight), not gone. Reliable-send escalates its
    per-attempt deadline only for this class; connect-refused and other
    transport failures are instant and must not inflate later deadlines."""


class RpcLaneUnavailable(RpcError):
    """The pooled endpoint does not co-host the target lane (split
    deployment); NetworkClient reroutes to a direct legacy connection."""


class RetryConfig:
    """Exponential backoff (network/src/retry.rs:9-60). max_elapsed=None
    retries forever (the reliable-send policy, p2p.rs:37-41)."""

    def __init__(
        self,
        initial: float = 0.05,
        multiplier: float = 1.5,
        max_interval: float = 5.0,
        max_elapsed: float | None = 30.0,
        jitter: float = 0.1,
    ):
        self.initial = initial
        self.multiplier = multiplier
        self.max_interval = max_interval
        self.max_elapsed = max_elapsed
        self.jitter = jitter

    def delays(self):
        delay = self.initial
        elapsed = 0.0
        while True:
            # Reconnect jitter rides the scenario-seeded global stream
            # (scenario.py seeds `random` per plan), so replays see the
            # same backoff schedule; outside simnet jitter spread is the
            # entire point and determinism is irrelevant.
            d = delay * (1.0 + random.uniform(-self.jitter, self.jitter))  # lint: allow(unseeded-random)
            yield d
            elapsed += d
            if self.max_elapsed is not None and elapsed >= self.max_elapsed:
                return
            delay = min(delay * self.multiplier, self.max_interval)


def _pack(kind: int, rid: int, tag: int, body: bytes, lane: int = 0) -> bytes:
    return _FRAME_HDR.pack(len(body), kind, rid, tag, lane) + body


class WireStats:
    """Process-wide wire counters: every frame written/read by every peer
    link in this process (an in-process committee's WHOLE control plane).
    Two integer adds per frame — cheap enough to stay always-on; the
    benchmark harness samples `snapshot()` around its measurement window
    to report bytes-per-round (the metric the compact-certificate wire
    form exists to move) and frames-per-drain (the metric the write
    coalescer exists to move)."""

    frames_sent = 0
    bytes_sent = 0
    frames_received = 0
    bytes_received = 0
    # Write-coalescing accounting: one "drain" = one socket flush covering
    # every frame queued on that connection at that moment.
    drains = 0
    frames_per_drain: dict[int, int] = {}  # power-of-two bucket -> drains

    @classmethod
    def record_drain(cls, frames: int) -> None:
        cls.drains += 1
        bucket = 1
        while bucket < frames:
            bucket <<= 1
        cls.frames_per_drain[bucket] = cls.frames_per_drain.get(bucket, 0) + 1

    @classmethod
    def snapshot(cls) -> dict:
        return {
            "frames_sent": cls.frames_sent,
            "bytes_sent": cls.bytes_sent,
            "frames_received": cls.frames_received,
            "bytes_received": cls.bytes_received,
            "drains": cls.drains,
            "frames_per_drain": dict(sorted(cls.frames_per_drain.items())),
        }


class WireCounters:
    """Per-ROLE wire accounting (one instance per primary/worker network,
    unlike the process-wide WireStats): every frame the role writes or
    reads, bucketed by message type AND lane, surfaced as the registry
    counters `wire_bytes_{sent,received}_total{msg_type=,lane=}` and
    `wire_frames_{sent,received}_total{msg_type=,lane=}` — the lane
    dimension makes the pool's per-lane interleaving observable (is the
    vote lane moving while the batch lane saturates?). Plain integer totals
    (`bytes_sent`/`bytes_received`) ride along for cheap deltas — the
    core's per-round egress gauge reads them once per round. Cost per frame
    is two int adds + one cached labels() lookup."""

    __slots__ = (
        "bytes_sent",
        "bytes_received",
        "frames_sent",
        "frames_received",
        "_sent_bytes_m",
        "_recv_bytes_m",
        "_sent_frames_m",
        "_recv_frames_m",
        "_label_cache",
        "_sent_children",
        "_recv_children",
    )

    def __init__(self, registry=None):
        self.bytes_sent = 0
        self.bytes_received = 0
        self.frames_sent = 0
        self.frames_received = 0
        self._sent_bytes_m = self._recv_bytes_m = None
        self._sent_frames_m = self._recv_frames_m = None
        self._label_cache: dict[tuple[int, int], tuple[str, str]] = {}
        # Labelled-child cache: (tag, lane) -> (bytes child, frames child).
        # labels() re-stringifies + re-hashes on every call; at N=200 the
        # four per-frame lookups are a top-of-profile tax, so we resolve
        # each (tag, lane) pair once and bump the child values directly.
        self._sent_children: dict[tuple[int, int], tuple] = {}
        self._recv_children: dict[tuple[int, int], tuple] = {}
        if registry is not None:
            self._sent_bytes_m = registry.counter(
                "wire_bytes_sent_total",
                "Wire bytes written by this role, by message type and lane",
                labels=("msg_type", "lane"),
            )
            self._recv_bytes_m = registry.counter(
                "wire_bytes_received_total",
                "Wire bytes read by this role, by message type and lane",
                labels=("msg_type", "lane"),
            )
            self._sent_frames_m = registry.counter(
                "wire_frames_sent_total",
                "Frames written by this role, by message type and lane",
                labels=("msg_type", "lane"),
            )
            self._recv_frames_m = registry.counter(
                "wire_frames_received_total",
                "Frames read by this role, by message type and lane",
                labels=("msg_type", "lane"),
            )

    def _labels(self, tag: int, lane: int) -> tuple[str, str]:
        pair = self._label_cache.get((tag, lane))
        if pair is None:
            from ..messages import REGISTRY

            cls = REGISTRY.get(tag)
            name = cls.__name__ if cls is not None else f"tag{tag}"
            pair = (name, str(lane))
            self._label_cache[(tag, lane)] = pair
        return pair

    def record_sent(self, tag: int, wire_len: int, lane: int = 0) -> None:
        self.bytes_sent += wire_len
        self.frames_sent += 1
        if self._sent_bytes_m is not None:
            pair = self._sent_children.get((tag, lane))
            if pair is None:
                name, lane_s = self._labels(tag, lane)
                pair = (
                    self._sent_bytes_m.labels(name, lane_s),
                    self._sent_frames_m.labels(name, lane_s),
                )
                self._sent_children[(tag, lane)] = pair
            pair[0].value += wire_len
            pair[1].value += 1.0

    def record_received(self, tag: int, wire_len: int, lane: int = 0) -> None:
        self.bytes_received += wire_len
        self.frames_received += 1
        if self._recv_bytes_m is not None:
            pair = self._recv_children.get((tag, lane))
            if pair is None:
                name, lane_s = self._labels(tag, lane)
                pair = (
                    self._recv_bytes_m.labels(name, lane_s),
                    self._recv_frames_m.labels(name, lane_s),
                )
                self._recv_children[(tag, lane)] = pair
            pair[0].value += wire_len
            pair[1].value += 1.0


def _write_frame(
    writer: asyncio.StreamWriter,
    kind: int,
    rid: int,
    tag: int,
    body: bytes,
    session: Session | None = None,
    counters: WireCounters | None = None,
    lane: int = 0,
) -> None:
    # Two writes instead of one concatenated buffer: batch frames are large
    # (hundreds of KB) and the header+body copy showed up at high rates.
    # On authenticated connections the body is AEAD-sealed (AES-GCM,
    # counter nonce, header as AAD); seal+write happen without an await in
    # between so the nonce sequence matches the wire order.
    if session is not None:
        ct = session.seal_body(kind, rid, tag, body, lane)
        writer.write(_FRAME_HDR.pack(len(ct), kind, rid, tag, lane))
        writer.write(ct)
        wire_len = _FRAME_HDR.size + len(ct)
    else:
        writer.write(_FRAME_HDR.pack(len(body), kind, rid, tag, lane))
        if body:
            writer.write(body)
        wire_len = _FRAME_HDR.size + len(body)
    WireStats.frames_sent += 1
    WireStats.bytes_sent += wire_len
    if counters is not None:
        counters.record_sent(tag, wire_len, lane)


class _FrameBuffer:
    """Write-capture shim for FrameSender's inline fast path: collects the
    header/body writes `_write_frame` emits so a whole burst can reach the
    transport as one buffer."""

    __slots__ = ("parts",)

    def __init__(self) -> None:
        self.parts: list[bytes] = []

    def write(self, data: bytes) -> None:
        self.parts.append(data)


async def _read_frame(
    reader: asyncio.StreamReader,
    session: Session | None = None,
    counters: WireCounters | None = None,
) -> tuple[int, int, int, int, bytes]:
    hdr = await reader.readexactly(_FRAME_HDR.size)
    length, kind, rid, tag, lane = _FRAME_HDR.unpack(hdr)
    if length > MAX_FRAME:
        raise RpcError(f"frame of {length} bytes exceeds cap")
    body = await reader.readexactly(length) if length else b""
    WireStats.frames_received += 1
    WireStats.bytes_received += _FRAME_HDR.size + length
    if counters is not None:
        counters.record_received(tag, _FRAME_HDR.size + length, lane)
    if session is not None:
        if length < MAC_LEN:
            raise RpcError("unauthenticated frame on authenticated connection")
        body = session.open_body(kind, rid, tag, body, lane)  # AuthError on forgery
    return kind, rid, tag, lane, body


class FrameSender:
    """Per-connection write coalescer with PER-LANE flow control: frames
    enqueue synchronously into their lane's queue; a single drainer task
    interleaves the lane queues ROUND-ROBIN (one frame per non-empty lane
    per pass) and packs the interleaved burst into `writer.write` calls
    followed by ONE `drain()`. Nagle without the delay — nothing ever waits
    for more traffic, but whatever is already pending when the socket
    flushes shares that flush, so an N-frame burst (a broadcast fan-in, a
    server's concurrent responses) costs one syscall round-trip instead
    of N.

    The round-robin is the pool's fairness mechanism: on a multiplexed
    connection, a saturated bulk lane (a worker's batch relay backlog)
    cannot starve a latency-critical lane (the primary's votes) — a vote
    enqueued behind 50 queued batch frames departs after at most one frame
    per OTHER lane, not after the whole backlog. Fairness is per-frame
    (frames are never fragmented), so the worst-case holdup is one maximum-
    size in-flight frame per competing lane.

    AEAD sealing happens at WRITE time in interleaved order, so the
    session's counter-nonce sequence always matches the wire order (the
    invariant `_write_frame` documents). Post-handshake, a connection's
    frames MUST all go through its sender — a second writer would fork the
    nonce sequence.

    Queue depth is bounded by the callers: client requests are capped by
    their own timeouts/retry handles, server responses by the per-
    connection dispatch semaphore (MAX_TASK_CONCURRENCY).

    Transports whose writers advertise `sync_drain` (the simnet fabric's
    duck-typed writer: no kernel buffer, drain() is a no-op) take an
    inline fast path instead: frames are packed and written synchronously
    from send(), one fabric transmit per drain, NO drainer task at all.
    Under a co-hosted simulation that removes one ensure_future + wakeup
    per write burst — a first-order term of the profiled loop churn."""

    __slots__ = (
        "_writer",
        "_session",
        "_on_error",
        "_queues",
        "_depth",
        "_task",
        "_closed",
        "_counters",
        "_inline",
    )

    def __init__(
        self,
        writer: asyncio.StreamWriter,
        session: Session | None = None,
        on_error: Callable[[Exception], None] | None = None,
        counters: WireCounters | None = None,
    ):
        self._writer = writer
        self._session = session
        self._on_error = on_error
        self._counters = counters
        # lane -> FIFO of (kind, rid, tag, body). Insertion-ordered dict:
        # the round-robin cycles lanes in first-traffic order, which is
        # deterministic under the seeded simnet schedule.
        self._queues: dict[int, list[tuple[int, int, int, bytes]]] = {}
        self._depth = 0
        self._task: asyncio.Task | None = None
        self._closed = False
        self._inline = bool(getattr(writer, "sync_drain", False))

    def send(
        self, kind: int, rid: int, tag: int, body: bytes, lane: int = 0
    ) -> None:
        """Enqueue one frame (never blocks). Raises RpcError if the
        transport already failed."""
        if self._closed:
            raise RpcError("connection closed")
        queue = self._queues.get(lane)
        if queue is None:
            queue = self._queues[lane] = []
        queue.append((kind, rid, tag, body))
        self._depth += 1
        if self._inline:
            self._drain_inline()
        elif self._task is None or self._task.done():
            self._task = asyncio.ensure_future(self._drain_loop())

    def _take_interleaved(self) -> list[tuple[int, int, int, int, bytes]]:
        """Snapshot and clear the lane queues as ONE round-robin-interleaved
        batch: pass k takes the k-th frame of every lane that still has
        one. Single-lane connections (the common legacy case) reduce to the
        old FIFO order with no extra copying beyond the append loop."""
        queues = [
            (lane, q) for lane, q in self._queues.items() if q
        ]
        if not queues:
            return []
        if len(queues) == 1:
            lane, q = queues[0]
            self._queues[lane] = []
            self._depth = 0
            return [(kind, rid, tag, lane, body) for kind, rid, tag, body in q]
        batch: list[tuple[int, int, int, int, bytes]] = []
        depth = max(len(q) for _, q in queues)
        for k in range(depth):
            for lane, q in queues:
                if k < len(q):
                    kind, rid, tag, body = q[k]
                    batch.append((kind, rid, tag, lane, body))
        for lane, _ in queues:
            self._queues[lane] = []
        self._depth = 0
        return batch

    def _drain_inline(self) -> None:
        """Synchronous drain for no-buffer transports: seal in interleaved
        order (same nonce invariant as the task path) and hand the packed
        burst to the writer as ONE write."""
        try:
            while self._depth:
                batch = self._take_interleaved()
                buf = _FrameBuffer()
                for kind, rid, tag, lane, body in batch:
                    _write_frame(
                        buf, kind, rid, tag, body, self._session,
                        self._counters, lane,
                    )
                WireStats.record_drain(len(batch))
                # _FrameBuffer is a per-drain local scratch buffer: created,
                # filled and read inside this one call frame (creator
                # pattern) — the class is shared, the instance never is.
                parts = buf.parts  # lint: allow(multi-task-mutation)
                self._writer.write(
                    parts[0] if len(parts) == 1 else b"".join(parts)
                )
        except (ConnectionError, OSError) as e:
            self._closed = True
            self._queues.clear()
            self._depth = 0
            if self._on_error is not None:
                self._on_error(e)

    async def _drain_loop(self) -> None:
        try:
            while self._depth:
                batch = self._take_interleaved()
                for kind, rid, tag, lane, body in batch:
                    _write_frame(
                        self._writer, kind, rid, tag, body, self._session,
                        self._counters, lane,
                    )
                WireStats.record_drain(len(batch))
                # Frames enqueued while this drain awaits ride the next
                # iteration — one flush each for whatever coalesced.
                await self._writer.drain()
        except (ConnectionError, OSError) as e:
            self._closed = True
            # Connection is dead: frames enqueued during the failed drain
            # are deliberately dropped with it (there is nowhere to send
            # them) — losing a concurrent enqueue here is the semantics.
            self._queues.clear()  # lint: allow(await-interleaved-rmw)
            self._depth = 0  # lint: allow(await-interleaved-rmw)
            if self._on_error is not None:
                self._on_error(e)

    def close(self) -> None:
        self._closed = True
        self._queues.clear()
        self._depth = 0
        if self._task is not None and not self._task.done():
            self._task.cancel()


class PeerClient:
    """Persistent connection to one peer address with request/response
    correlation and lazy reconnect. With credentials + an expected key the
    connection is mutually authenticated before any request flows."""

    def __init__(
        self,
        address: str,
        credentials: Credentials | None = None,
        counters: WireCounters | None = None,
    ):
        self.address = address
        self._credentials = credentials
        self._counters = counters
        self._writer: asyncio.StreamWriter | None = None
        self._sender: FrameSender | None = None
        self._reader_task: asyncio.Task | None = None
        self._pending: dict[int, asyncio.Future] = {}
        self._rid = itertools.count(1)
        self._lock = asyncio.Lock()
        self._session: Session | None = None

    async def _connect(self) -> None:
        async with self._lock:
            if self._writer is not None:
                return
            host, port = self.address.rsplit(":", 1)
            # Through the transport seam: real TCP normally, the simnet
            # in-memory fabric when one is installed (simnet/fabric.py).
            reader, writer = await transport.open_connection(
                host, int(port), limit=MAX_FRAME + 1024
            )
            # Resolve the expected identity at connect time so reconnects
            # after an epoch change see the current committee's keys.
            expected_key = (
                self._credentials.resolve(self.address)
                if self._credentials is not None
                else None
            )
            session = None
            if self._credentials is not None and expected_key is not None:
                try:
                    session = await client_handshake(
                        reader,
                        writer,
                        self._credentials,
                        expected_key,
                        _read_frame,
                        _write_frame,
                    )
                except (AuthError, asyncio.TimeoutError, asyncio.IncompleteReadError) as e:
                    writer.close()
                    raise RpcError(f"handshake with {self.address} failed: {e}") from e
            self._session = session
            # The whole connect sequence is serialized by self._lock (with
            # an early return when another task won the race), so this
            # check-then-act cannot interleave with a second connect.
            self._writer = writer  # lint: allow(await-interleaved-rmw)
            self._sender = FrameSender(
                writer,
                session,
                on_error=lambda e: self._teardown(
                    RpcError(f"send to {self.address} failed: {e}")
                ),
                counters=self._counters,
            )
            self._reader_task = asyncio.ensure_future(self._read_loop(reader, session))

    async def _read_loop(
        self, reader: asyncio.StreamReader, session: Session | None
    ) -> None:
        try:
            while True:
                # Legacy single-lane connection: the lane byte is read (and
                # AAD-verified) but carries no routing — everything is lane 0.
                kind, rid, tag, _lane, body = await _read_frame(
                    reader, session, self._counters
                )
                if kind == KIND_HELLO and session is None:
                    # The server demands a handshake we are not configured
                    # for: fail every pending request immediately instead of
                    # letting them time out one by one.
                    logger.warning(
                        "%s requires an authenticated handshake but this "
                        "client has no credentials for it",
                        self.address,
                    )
                    self._teardown(
                        RpcError(
                            f"{self.address} requires an authenticated "
                            "handshake (no credentials resolve this address)"
                        )
                    )
                    return
                fut = self._pending.pop(rid, None)
                if fut is None or fut.done():
                    continue
                if kind == KIND_RESP:
                    try:
                        fut.set_result(decode_message(tag, body))
                    except Exception as e:  # decode error
                        fut.set_exception(RpcError(str(e)))
                elif kind == KIND_ERR:
                    fut.set_exception(RpcError(body.decode(errors="replace")))
        except (asyncio.IncompleteReadError, ConnectionError, OSError, RpcError, AuthError) as e:
            logger.debug("connection to %s lost: %r", self.address, e)
        finally:
            self._teardown(RpcError(f"connection to {self.address} lost"))

    def _teardown(self, exc: Exception) -> None:
        if self._sender is not None:
            self._sender.close()
        self._sender = None
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:  # lint: allow(no-silent-except)
                pass  # best-effort close of an already-failed transport
        self._writer = None
        # Cancel the read loop unless teardown IS the read loop's own
        # finally: on a half-open transport (peer gone silently, no EOF
        # delivered) the reader would otherwise survive close() parked in
        # _read_frame forever — the dropped-handle shutdown-wedge class.
        reader_task, self._reader_task = self._reader_task, None
        if reader_task is not None and not reader_task.done():
            try:
                current = asyncio.current_task()
            except RuntimeError:
                current = None
            if reader_task is not current:
                reader_task.cancel()
        self._session = None
        pending, self._pending = self._pending, {}
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(exc)

    async def request(self, msg, timeout: float | None = 10.0):
        """Send a request frame, await the peer's response (Ack for oneway
        handlers). Raises RpcError/OSError on transport failure.

        The frame goes through the connection's FrameSender: concurrent
        requests on one link (a broadcast burst, QuorumWaiter fan-out)
        share a single socket flush instead of awaiting one drain() each;
        transport failures surface through the pending future (the sender's
        on_error tears the connection down, failing every in-flight rid)."""
        if self._sender is None:
            await self._connect()
        rid = next(self._rid)
        tag, body = encode_message(msg)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        try:
            self._sender.send(KIND_REQ, rid, tag, body)
            return await asyncio.wait_for(fut, timeout)
        except (ConnectionError, OSError) as e:
            # Register/await/cleanup idiom: each task pops only the rid it
            # registered itself — concurrent requests touch disjoint keys.
            self._pending.pop(rid, None)  # lint: allow(await-interleaved-rmw)
            self._teardown(RpcError(str(e)))
            raise RpcError(f"send to {self.address} failed: {e}") from e
        except RpcError:
            self._pending.pop(rid, None)
            raise
        except asyncio.TimeoutError:
            self._pending.pop(rid, None)
            raise RpcTimeout(f"request to {self.address} timed out")

    async def oneway(self, msg) -> None:
        """Enqueue a fire-and-forget frame (KIND_ONEWAY): no response, no
        rid, no retry. The frame rides the same FrameSender (coalesced
        writes, in-order AEAD sealing); a torn connection surfaces as
        RpcError/OSError from the connect, and silently dropped frames are
        the CALLER's contract — only use this where an application-level
        mechanism (relay fallback) already guarantees delivery."""
        if self._sender is None:
            await self._connect()
        tag, body = encode_message(msg)
        try:
            self._sender.send(KIND_ONEWAY, 0, tag, body)
        except (ConnectionError, OSError) as e:
            self._teardown(RpcError(str(e)))
            raise RpcError(f"send to {self.address} failed: {e}") from e

    def close(self) -> None:
        self._teardown(RpcError("client closed"))


# Post-handshake marker frame a pool dialer sends as the FIRST frame of a
# new connection (KIND_HELLO is unused after the handshake): it tells the
# accepting server "this is a multiplexed pool link — adopt it for your own
# outbound traffic too". A server without a pool (knob off, old deployment)
# simply ignores the frame and serves the connection as a legacy single-lane
# client, so mixed-knob committees degrade gracefully instead of breaking.
POOL_HELLO = b"pool-link/1"


class PeerLink:
    """One multiplexed, BIDIRECTIONAL authenticated connection to a peer
    node: every lane of the node pair (primary plane + each worker plane)
    shares this socket, and BOTH endpoints issue requests over it — each
    side keeps its own rid namespace, and the frame `kind` disambiguates
    direction (REQ/ONEWAY frames are the remote's calls into our lanes,
    RESP/ERR are answers to ours).

    A link never dials: the pool (network/pool.py) owns connection
    establishment, the crossed-dial survivor rule, reconnect, and lane
    dispatch. The link owns one live socket: the demux read loop, the
    pending-rid table for outbound requests, the per-connection dispatch
    semaphore for inbound ones, and teardown (which fails every in-flight
    rid so the caller's retry path — NetworkClient.send — re-acquires a
    fresh link from the pool: the in-flight retry handoff)."""

    __slots__ = (
        "pool",
        "peer_pk",
        "address",
        "peer",
        "dialed",
        "closed",
        "_writer",
        "_session",
        "_counters",
        "_sender",
        "_pending",
        "_rid",
        "_read_task",
        "_sem",
        "_tasks",
    )

    def __init__(
        self,
        pool,
        peer_pk,
        address: str,
        writer: asyncio.StreamWriter,
        session: Session | None,
        counters: WireCounters | None = None,
        dialed: bool = True,
        sender: FrameSender | None = None,
    ):
        self.pool = pool
        self.peer_pk = peer_pk
        self.address = address
        self.peer = Peer(address, peer_pk)
        self.dialed = dialed
        self.closed = False
        self._writer = writer
        self._session = session
        self._counters = counters
        # The adopted (server) side reuses the sender _on_connection already
        # created for this writer — a second FrameSender on one writer would
        # fork the AEAD nonce sequence.
        self._sender = sender or FrameSender(
            writer,
            session,
            on_error=lambda e: self._teardown(
                RpcError(f"send on pooled link to {self.address} failed: {e}")
            ),
            counters=counters,
        )
        self._pending: dict[int, asyncio.Future] = {}
        self._rid = itertools.count(1)
        self._read_task: asyncio.Task | None = None
        self._sem = asyncio.Semaphore(MAX_TASK_CONCURRENCY)
        self._tasks: set[asyncio.Task] = set()

    @property
    def sender(self) -> FrameSender:
        """The link's single FrameSender — lane servers write their
        responses through it (one writer per connection: the nonce-order
        invariant)."""
        return self._sender

    def start(self, reader: asyncio.StreamReader) -> None:
        """Dialed side: spawn the demux loop as a background task. (The
        adopted side awaits run() directly from _on_connection so the
        connection's lifetime stays tied to the accept task.)"""
        self._read_task = asyncio.ensure_future(self.run(reader))

    def send_pool_hello(self) -> None:
        self._sender.send(KIND_HELLO, 0, 0, POOL_HELLO)

    async def run(self, reader: asyncio.StreamReader) -> None:
        try:
            while True:
                kind, rid, tag, lane, body = await _read_frame(
                    reader, self._session, self._counters
                )
                if kind == KIND_REQ or kind == KIND_ONEWAY:
                    # Inbound call into one of our lanes: same bounded
                    # concurrency model as RpcServer._on_connection.
                    await self._sem.acquire()
                    t = asyncio.ensure_future(
                        self.pool.dispatch(
                            self, lane, rid, tag, body,
                            oneway=kind == KIND_ONEWAY,
                        )
                    )
                    self._tasks.add(t)
                    t.add_done_callback(
                        lambda t_: (self._tasks.discard(t_), self._sem.release())
                    )
                    continue
                fut = self._pending.pop(rid, None)
                if fut is None or fut.done():
                    continue
                if kind == KIND_RESP:
                    try:
                        fut.set_result(decode_message(tag, body))
                    except Exception as e:  # decode error
                        fut.set_exception(RpcError(str(e)))
                elif kind == KIND_ERR:
                    if body == LANE_UNAVAILABLE:
                        fut.set_exception(
                            RpcLaneUnavailable(
                                f"{self.address} does not co-host the lane"
                            )
                        )
                    else:
                        fut.set_exception(RpcError(body.decode(errors="replace")))
        except (asyncio.IncompleteReadError, ConnectionError, OSError, RpcError, AuthError) as e:
            logger.debug("pooled link to %s lost: %r", self.address, e)
        finally:
            self._teardown(RpcError(f"pooled link to {self.address} lost"))

    def respond(self, kind: int, rid: int, tag: int, body: bytes, lane: int) -> None:
        """Write one response frame on behalf of a lane server (same-lane
        response: the reply rides the queue of the lane it answers)."""
        self._sender.send(kind, rid, tag, body, lane)

    async def request(self, msg, lane: int, timeout: float | None = 10.0):
        """Send a request frame on `lane`, await the peer's response.
        Raises RpcLaneUnavailable when the peer answers that the lane's
        role is not co-hosted behind this connection (split deployment)."""
        if self.closed:
            raise RpcError(f"pooled link to {self.address} closed")
        rid = next(self._rid)
        tag, body = encode_message(msg)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        try:
            self._sender.send(KIND_REQ, rid, tag, body, lane)
            return await asyncio.wait_for(fut, timeout)
        except (ConnectionError, OSError) as e:
            # Register/await/cleanup idiom: each task pops only the rid it
            # registered itself — concurrent requests touch disjoint keys.
            self._pending.pop(rid, None)  # lint: allow(await-interleaved-rmw)
            self._teardown(RpcError(str(e)))
            raise RpcError(f"send to {self.address} failed: {e}") from e
        except RpcError:
            self._pending.pop(rid, None)
            raise
        except asyncio.TimeoutError:
            self._pending.pop(rid, None)
            raise RpcTimeout(f"request to {self.address} (lane {lane}) timed out")

    async def oneway(self, msg, lane: int) -> None:
        """Fire-and-forget frame on `lane` (same caller contract as
        PeerClient.oneway: delivery is the application's problem)."""
        if self.closed:
            raise RpcError(f"pooled link to {self.address} closed")
        tag, body = encode_message(msg)
        try:
            self._sender.send(KIND_ONEWAY, 0, tag, body, lane)
        except (ConnectionError, OSError) as e:
            self._teardown(RpcError(str(e)))
            raise RpcError(f"send to {self.address} failed: {e}") from e

    def _teardown(self, exc: Exception) -> None:
        if self.closed:
            return
        self.closed = True
        self._sender.close()
        try:
            self._writer.close()
        except Exception:  # lint: allow(no-silent-except)
            pass  # best-effort close of an already-failed transport
        read_task, self._read_task = self._read_task, None
        if read_task is not None and not read_task.done():
            try:
                current = asyncio.current_task()
            except RuntimeError:
                current = None
            if read_task is not current:
                read_task.cancel()
        for t in list(self._tasks):
            t.cancel()
        pending, self._pending = self._pending, {}
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(exc)
        # Deregister LAST: once the pool forgets this link, the next
        # link_for() dials fresh — pending rids are already failed, so the
        # caller's retry lands on the new connection, never this one.
        self.pool.discard(self)

    def close(self) -> None:
        self._teardown(RpcError(f"pooled link to {self.address} closed"))


Handler = Callable[[object, Peer], Awaitable[object | None]]


def ALLOW_ANY(peer: "Peer") -> bool:
    """Explicit opt-out of route-level authorization on an authenticated
    server: any handshake-verified peer may call the route."""
    return True


class RpcServer:
    """Listens for peers and dispatches requests to handlers by message tag.

    Handlers receive (message, Peer) and return a response message or
    None (=> Ack). Handler exceptions become ERR frames, like anemo's status
    responses. Concurrency is bounded per connection.

    With `auth_keypair` set the server requires the mutual handshake on
    every connection (the anemo PeerId model): unauthenticated sockets never
    reach a handler, and routes may further restrict the verified identity
    with an `allow(peer)` predicate — the reference rejects unknown peers at
    the network layer (network/src/p2p.rs:26-158)."""

    def __init__(
        self,
        max_concurrency: int = MAX_TASK_CONCURRENCY,
        auth_keypair=None,
        counters: WireCounters | None = None,
        pool=None,
        dedup_cache_bytes: int = 32 << 20,
    ):
        self._handlers: dict[
            int, tuple[Handler, Callable[[Peer], bool] | None, Handler | None]
        ] = {}
        self._server: asyncio.AbstractServer | None = None
        self._max_concurrency = max_concurrency
        self._writers: set[asyncio.StreamWriter] = set()
        self._auth_keypair = auth_keypair
        self._counters = counters
        # The node's LanePool, set only on the LISTENER server (the primary's,
        # bound at the pooled address): connections whose first frame is the
        # POOL_HELLO marker are adopted into it as bidirectional PeerLinks.
        self._pool = pool
        self._dedup_cache_bytes = dedup_cache_bytes
        self._dedup: BoundedCache | None = None

    def route(self, msg_cls, handler: Handler, allow=None, dedup=None) -> None:
        # Deny-by-default on authenticated servers: the handshake only proves
        # the peer holds *a* key, not that the key is known to the committee
        # (the reference rejects unknown peers at the network layer via
        # anemo's known-peers set). A route registered without an identity
        # predicate would silently be world-open, so require one — ALLOW_ANY
        # documents a deliberate opt-out.
        if self._auth_keypair is not None and allow is None:
            raise ValueError(
                f"route {msg_cls.__name__}: authenticated servers are "
                "deny-by-default; pass allow= (or ALLOW_ANY to open the "
                "route to any handshake-verified peer)"
            )
        # `dedup` opts the route into digest-keyed duplicate suppression:
        # when an identical body (same tag, same bytes) arrives again while
        # still in the bounded cache, the codec decode and the full handler
        # are SKIPPED and `dedup(first_decoded_msg, peer)` runs instead —
        # the cheap bookkeeping path (ack the sender, note the extra copy)
        # for fan-out planes where every committee member relays the same
        # payload N-1 times (RelayMsg/Relay2Msg). The authorization
        # predicate still runs per copy.
        if dedup is not None and self._dedup is None:
            self._dedup = BoundedCache(max_bytes=self._dedup_cache_bytes)
        self._handlers[msg_cls.TAG] = (handler, allow, dedup)

    async def start(self, host: str, port: int) -> int:
        # Simnet path first: the fabric owns the whole address namespace
        # (no real ports, no placeholders, no fd budget) — every frame this
        # server reads still goes through the same handshake/AEAD/dispatch
        # code below, just over in-memory streams.
        fabric = transport.active()
        if fabric is not None:
            self._server = await fabric.start_server(
                self._on_connection, host, port, limit=MAX_FRAME + 1024
            )
            return self._server.sockets[0].getsockname()[1]
        # reuse_port lets the bind coexist with the allocator's SO_REUSEPORT
        # placeholder (config.get_available_port), which reserves
        # pre-assigned ports against ephemeral collisions; the placeholder
        # never listens, so all connections land here. But blanket
        # reuse_port would also let two misconfigured servers (duplicate
        # addresses in a committee file, the same node started twice)
        # silently co-bind and nondeterministically split connections — so
        # only co-bind ports that are actually known to be placeheld:
        # either by this process's allocator, or by a harness parent that
        # assigned our ports and advertises its placeholders via
        # NARWHAL_PLACEHELD_PORTS ("all" or a comma-separated list). Any
        # other duplicate fails fast with EADDRINUSE.
        from ..config import port_is_placeheld

        reuse = port != 0 and port_is_placeheld(port)
        # A pre-assigned port can transiently collide (TIME_WAIT, an
        # ephemeral outbound connection): retry briefly before giving up.
        for attempt in range(5):
            try:
                self._server = await asyncio.start_server(
                    self._on_connection, host, port, limit=MAX_FRAME + 1024,
                    reuse_port=reuse,
                )
                break
            except OSError:
                if attempt == 4:
                    raise
                await asyncio.sleep(0.2 * (attempt + 1))
        bound = self._server.sockets[0].getsockname()[1]
        # The allocator's placeholder has done its job once we hold the
        # listening socket; dropping it returns the fd (a long-lived
        # process building many clusters would otherwise hold up to a
        # window's worth of placeholder fds against the ulimit). Marking
        # the port bound also strikes it from any parent's spawn-time
        # NARWHAL_PLACEHELD_PORTS advertisement, so a second server on the
        # same port in this process fails fast instead of co-binding.
        from ..config import mark_port_bound, release_port

        release_port(bound)
        mark_port_bound(bound)
        return bound

    @property
    def port(self) -> int:
        return self._server.sockets[0].getsockname()[1]

    async def _on_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peername = writer.get_extra_info("peername")
        peer_addr = f"{peername[0]}:{peername[1]}" if peername else "?"
        peer = Peer(peer_addr)
        self._writers.add(writer)
        sem = asyncio.Semaphore(self._max_concurrency)
        tasks: set[asyncio.Task] = set()
        session: Session | None = None
        sender: FrameSender | None = None
        try:
            if self._auth_keypair is not None:
                try:
                    # Written once here, before the pool/dispatch tasks that
                    # read it can exist (adoption happens frames later).
                    peer.key, session = await server_handshake(  # lint: allow(multi-task-mutation)
                        reader, writer, self._auth_keypair, _read_frame, _write_frame
                    )
                except (AuthError, asyncio.TimeoutError, asyncio.IncompleteReadError) as e:
                    logger.debug("Rejected unauthenticated peer %s: %s", peer_addr, e)
                    return
            # Responses coalesce per connection: concurrent handlers that
            # complete in the same window share one socket flush.
            sender = FrameSender(writer, session, counters=self._counters)
            first = True
            while True:
                kind, rid, tag, lane, body = await _read_frame(
                    reader, session, self._counters
                )
                if (
                    first
                    and kind == KIND_HELLO
                    and body == POOL_HELLO
                    and self._pool is not None
                    and peer.key is not None
                ):
                    # Pool dialer announcing itself (always its first frame,
                    # so this sender has written nothing yet and can be
                    # handed to the link without forking the nonce stream).
                    # adopt() returns the link's demux loop coroutine — or
                    # None if the peer key is unknown to the pool — and we
                    # await it HERE so the connection's lifetime stays tied
                    # to this accept task.
                    link_run = self._pool.adopt(peer, reader, writer, session, sender)
                    if link_run is not None:
                        sender = None  # the link owns teardown now
                        await link_run
                        return
                first = False
                if kind != KIND_REQ and kind != KIND_ONEWAY:
                    continue
                if lane != LANE_PRIMARY:
                    # Non-adopted connections reach exactly one role — the
                    # one that bound this address — so a lane-routed frame
                    # here means the remote pooled to a server whose pool is
                    # off (mixed-knob committee). Tell it to fall back to a
                    # direct connection instead of dispatching to the wrong
                    # handler table.
                    if kind == KIND_REQ:
                        sender.send(KIND_ERR, rid, 0, LANE_UNAVAILABLE, lane)
                    continue
                await sem.acquire()
                t = asyncio.ensure_future(
                    self._dispatch(
                        sender, rid, tag, body, peer, oneway=kind == KIND_ONEWAY
                    )
                )
                tasks.add(t)
                t.add_done_callback(lambda t_: (tasks.discard(t_), sem.release()))
        except (asyncio.IncompleteReadError, ConnectionError, OSError, RpcError, AuthError) as e:
            logger.debug("peer %s disconnected: %r", peer_addr, e)
        finally:
            # Each connection task discards only its own writer (added once
            # at accept): concurrent connections touch disjoint elements.
            self._writers.discard(writer)  # lint: allow(await-interleaved-rmw)
            if sender is not None:
                sender.close()
            for t in tasks:
                t.cancel()
            try:
                writer.close()
            except Exception:  # lint: allow(no-silent-except)
                pass  # best-effort close of an already-failed transport

    async def dispatch_frame(
        self,
        sender: FrameSender,
        rid: int,
        tag: int,
        body: bytes,
        peer: Peer,
        oneway: bool,
        lane: int,
    ) -> None:
        """Pool entry point: dispatch one frame that arrived on a
        multiplexed PeerLink into this lane server's handler table. The
        response (if any) is written back on the SAME lane so replies ride
        the queue of the plane they answer."""
        await self._dispatch(sender, rid, tag, body, peer, oneway=oneway, lane=lane)

    async def _dispatch(
        self,
        sender: FrameSender,
        rid: int,
        tag: int,
        body: bytes,
        peer: Peer,
        oneway: bool = False,
        lane: int = LANE_PRIMARY,
    ) -> None:
        try:
            entry = self._handlers.get(tag)
            if entry is None:
                raise RpcError(f"no handler for tag {tag}")
            handler, allow, dedup = entry
            if allow is not None and not allow(peer):
                raise RpcError(f"unauthorized peer for tag {tag}")
            if dedup is not None:
                # Digest-keyed duplicate shortcut, keyed on the RAW body so
                # the duplicate never reaches the codec: in the relay fan-out
                # every committee member forwards the same payload, so all
                # but the first arrival pay only a blake2b over bytes already
                # in cache-warm memory plus the route's bookkeeping handler.
                key = (tag, hashlib.blake2b(body, digest_size=16).digest())
                cached = self._dedup.get(key)
                if cached is not None:
                    resp = await dedup(cached, peer)
                else:
                    msg = decode_message(tag, body)
                    # First write wins in BoundedCache, so a concurrent
                    # decode of the same body settles on one canonical
                    # message object; weight tracks the encoded size the
                    # entry is standing in for.
                    self._dedup.put(key, msg, weight=len(body) + 64)
                    resp = await handler(msg, peer)
            else:
                msg = decode_message(tag, body)
                resp = await handler(msg, peer)
            if oneway:
                # Fire-and-forget frame: the handler ran, nothing to write
                # back (any returned value is discarded by contract).
                return
            if resp is None:
                resp = Ack()
            rtag, rbody = encode_message(resp)
            out = (KIND_RESP, rid, rtag, rbody)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            # The peer sees the failure as an ERR frame; keep local
            # visibility too — a handler bug otherwise only surfaces as
            # remote retry noise.
            logger.debug("handler for tag %d raised: %r", tag, e)
            if oneway:
                return
            out = (KIND_ERR, rid, 0, str(e).encode())
        try:
            sender.send(*out, lane)
        except RpcError as e:
            logger.debug("response to %s dropped (peer gone): %r", peer.addr, e)

    async def stop(self) -> None:
        if self._server is not None:
            try:
                bound = self._server.sockets[0].getsockname()[1]
            except (IndexError, OSError):
                bound = None
            self._server.close()
            # Drop live connections: wait_closed() (3.12+) waits for every
            # connection handler, which would otherwise run until the peer
            # hangs up.
            for w in list(self._writers):
                try:
                    w.close()
                except Exception:  # lint: allow(no-silent-except)
                    pass  # best-effort close during server stop
            await self._server.wait_closed()
            if bound is not None:
                # A later bind of this port (node restart) may again
                # co-bind through a parent's still-live placeholder.
                from ..config import mark_port_unbound

                mark_port_unbound(bound)


class _PooledPeer:
    """PeerClient-shaped facade over a pooled lane: request/oneway acquire
    the live PeerLink for the peer NODE from the pool (dialing or waiting
    out a reconnect as needed) and tag frames with this peer's lane.

    If the pooled endpoint ever answers RpcLaneUnavailable — the lane's
    role is not co-hosted behind the pooled address (split primary/worker
    deployment) — the facade permanently falls back to a direct legacy
    connection to the role's own address; the pool only ever multiplexes
    what is actually behind one process."""

    __slots__ = ("_pool", "_peer_pk", "_lane", "address", "_credentials", "_counters", "_legacy")

    def __init__(self, pool, peer_pk, lane, address, credentials, counters):
        self._pool = pool
        self._peer_pk = peer_pk
        self._lane = lane
        self.address = address
        self._credentials = credentials
        self._counters = counters
        self._legacy: PeerClient | None = None

    def _fall_back(self) -> PeerClient:
        logger.info(
            "pooled endpoint for %s does not co-host lane %d; "
            "falling back to a direct connection",
            self.address,
            self._lane,
        )
        self._legacy = PeerClient(self.address, self._credentials, self._counters)
        return self._legacy

    async def request(self, msg, timeout: float | None = 10.0):
        if self._legacy is not None:
            return await self._legacy.request(msg, timeout)
        try:
            link = await self._pool.link_for(self._peer_pk)
            return await link.request(msg, self._lane, timeout)
        except RpcLaneUnavailable:
            return await self._fall_back().request(msg, timeout)

    async def oneway(self, msg) -> None:
        if self._legacy is not None:
            return await self._legacy.oneway(msg)
        # A oneway to a non-co-hosted lane is logged and dropped by the
        # remote (no response frame exists to carry the lane error); the
        # first REQUEST on this lane flips the facade to the legacy path.
        link = await self._pool.link_for(self._peer_pk)
        await link.oneway(msg, self._lane)

    def close(self) -> None:
        # The pool owns its links' lifecycles; only a fallback is ours.
        if self._legacy is not None:
            self._legacy.close()


class NetworkClient:
    """The P2pNetwork facade (/root/reference/network/src/p2p.rs:26-158):
    cached per-peer clients + the three send policies. With credentials,
    every connection to an address the committee/worker-cache knows is
    mutually authenticated; unknown addresses (public endpoints) connect
    plain. With a LanePool, addresses the pool can place (a committee
    role of a known node) route over the node pair's ONE multiplexed
    connection instead of a dedicated socket."""

    def __init__(
        self,
        retry: RetryConfig | None = None,
        credentials: Credentials | None = None,
        counters: WireCounters | None = None,
        pool=None,
    ):
        self._peers: dict[str, PeerClient | _PooledPeer] = {}
        self._retry = retry or RetryConfig(max_elapsed=None)
        self._send_tasks: set[asyncio.Task] = set()
        self._credentials = credentials
        self._counters = counters
        self._pool = pool

    def attach_pool(self, pool) -> None:
        """Late pool attachment for assemblies whose pool is created after
        this client (a Worker joining the node pool at spawn). Only
        addresses resolved AFTER attachment route through the pool."""
        self._pool = pool

    def peer(self, address: str) -> PeerClient | _PooledPeer:
        client = self._peers.get(address)
        if client is None:
            if self._pool is not None:
                target = self._pool.lookup(address)
                if target is not None:
                    peer_pk, lane = target
                    client = _PooledPeer(
                        self._pool, peer_pk, lane, address,
                        self._credentials, self._counters,
                    )
            if client is None:
                client = PeerClient(address, self._credentials, self._counters)
            self._peers[address] = client
        return client

    async def request(self, address: str, msg, timeout: float | None = 10.0):
        """One attempt RPC with a typed response."""
        return await self.peer(address).request(msg, timeout)

    async def unreliable_send(self, address: str, msg, timeout: float | None = 5.0) -> bool:
        """Fire once; True iff delivered+acked (UnreliableNetwork,
        traits.rs:10-40)."""
        try:
            await self.peer(address).request(msg, timeout)
            return True
        except (RpcError, OSError):
            return False

    async def oneway_send(self, address: str, msg) -> bool:
        """Fire-and-forget: one KIND_ONEWAY frame, no response awaited, no
        retry. True iff the frame was enqueued on a live connection. For
        lanes with their own application-level delivery guarantee (the
        relay plane's origin fallback) — a lost frame there costs one
        fallback direct send, never correctness."""
        try:
            await self.peer(address).oneway(msg)
            return True
        except (RpcError, OSError):
            return False

    def send(self, address: str, msg, timeout: float | None = 10.0) -> CancelOnDrop:
        """Reliable send: background task retrying forever with backoff until
        the peer acks; returns a cancellable handle whose await yields True
        (ReliableNetwork, traits.rs:42-94 + p2p.rs:37-41)."""

        async def attempt_forever():
            delays = self._retry.delays()
            attempt_timeout = timeout
            while True:
                try:
                    await self.peer(address).request(msg, attempt_timeout)
                    return True
                except (RpcError, OSError) as e:
                    timed_out = isinstance(e, (RpcTimeout, asyncio.TimeoutError))
                    try:
                        delay = next(delays)
                    except StopIteration:
                        raise RpcError(f"retries to {address} exhausted: {e}") from e
                    await asyncio.sleep(delay)
                    if attempt_timeout is None:
                        continue
                    if timed_out:
                        # A deadline miss on a loaded host usually means
                        # the peer is SLOW, not gone — resending on a fixed
                        # deadline re-executes the handler and multiplies
                        # load (measured at N=50: ~300k frames per
                        # committed round, mostly retries). Escalate the
                        # per-attempt deadline so a slow-but-alive peer is
                        # retried into success, not congestion collapse.
                        attempt_timeout = min(attempt_timeout * 2.0, timeout * 8.0)
                    else:
                        # Connection-refused and friends fail instantly:
                        # they say nothing about the peer's SPEED, so a
                        # burst of them (node restarting) must not leave
                        # later attempts stuck at an 8x deadline once the
                        # peer is back. Reset to the configured deadline.
                        attempt_timeout = timeout

        task = asyncio.ensure_future(attempt_forever())
        self._send_tasks.add(task)
        task.add_done_callback(self._send_tasks.discard)
        return CancelOnDrop(task)

    def broadcast(self, addresses: Iterable[str], msg) -> list[CancelOnDrop]:
        return [self.send(a, msg) for a in addresses]

    async def unreliable_broadcast(self, addresses: Iterable[str], msg) -> list[bool]:
        return list(
            await asyncio.gather(*(self.unreliable_send(a, msg) for a in addresses))
        )

    async def lucky_broadcast(self, addresses: list[str], msg, nodes: int) -> list[bool]:
        """Random-subset broadcast (LuckyNetwork, traits.rs:70-94)."""
        # Deliberate draw from the scenario-seeded global stream
        # (scenario.py seeds `random` per plan): the "lucky" subset is
        # meant to be random AND replayable under the same seed.
        chosen = random.sample(addresses, min(nodes, len(addresses)))  # lint: allow(unseeded-random)
        return await self.unreliable_broadcast(chosen, msg)

    def close(self) -> None:
        for t in self._send_tasks:
            t.cancel()
        for p in self._peers.values():
            p.close()
        self._peers.clear()
