"""Per-peer-pair connection pool — ONE multiplexed stream per node pair.

Reference: anemo keeps a single QUIC connection per peer and multiplexes
every RPC over it (SURVEY.md network layer); our per-role×lane TCP mesh
instead opened O(N^2 * (1+W)) sockets — real-socket N=100 died at ~19.8k
fds against RLIMIT_NOFILE 20000.

The LanePool is the node-level owner of that one connection per peer:

- **Lanes.** Every role plane of a node pair — primary<->primary (lane 0)
  and each worker mesh lane (lane 1+worker_id) — shares the pooled stream;
  the u8 lane byte of the frame header (rpc.py) routes each frame to the
  lane's registered RpcServer handler table, and the FrameSender drains
  lane queues round-robin so bulk lanes cannot starve votes.

- **Bidirectional.** The accepting side ADOPTS an inbound pool connection
  (announced by the POOL_HELLO marker frame) as a PeerLink of its own and
  sends its requests back over it: one socket per UNORDERED node pair, not
  per direction. That halves the mesh again — the difference between
  ~19.8k and ~10k fds at N=100 under a 20k rlimit.

- **Crossed dials.** Two nodes may dial each other simultaneously at boot.
  The canonical connection is the one dialed by the LOWER network key
  (evaluated identically at both ends); the higher side defers its dial by
  `pool_passive_dial_delay` to make the race rare, and when it still
  happens the loser is linger-closed (`pool_linger`) so in-flight
  responses drain.

- **Reconnect.** One dead socket now takes out every lane to that peer.
  The pool owns re-establishment: a torn link deregisters itself and fails
  its in-flight rids, the caller's retry policy (NetworkClient.send)
  re-acquires `link_for()` which dials fresh — the in-flight retry
  handoff. Nothing is silently resent; exactly-once-per-ack semantics stay
  with the application retry layer.

- **Split deployments.** The pool assumes a node's roles are co-hosted
  behind its primary address (cluster.py runs them in one process). A
  pooled endpoint that does NOT co-host a lane answers LANE_UNAVAILABLE
  and the caller permanently falls back to a direct legacy connection for
  that address, so physically split primary/worker deployments keep
  working — they just keep their dedicated sockets.
"""

from __future__ import annotations

import asyncio
import logging

from . import transport
from .auth import AuthError, Credentials, Peer, client_handshake
from .rpc import (
    KIND_ERR,
    LANE_PRIMARY,
    LANE_UNAVAILABLE,
    MAX_FRAME,
    PeerLink,
    RpcError,
    WireCounters,
    _read_frame,
    _write_frame,
    worker_lane,
)

logger = logging.getLogger("narwhal.network")


class LanePool:
    """One node's end of the pooled mesh: the live PeerLink per peer node,
    the lane -> RpcServer dispatch table, dial/adopt/reconnect policy."""

    def __init__(
        self,
        own_key,
        credentials: Credentials,
        get_committee,
        get_worker_cache=None,
        counters: WireCounters | None = None,
        passive_dial_delay: float = 0.2,
        linger: float = 1.0,
    ):
        # `own_key` is the node's NETWORK public key — the identity the
        # handshake proves, and the key links are indexed by.
        self.own_key = own_key
        self._credentials = credentials
        self._get_committee = get_committee
        self._get_worker_cache = get_worker_cache
        self._counters = counters
        self._passive_delay = passive_dial_delay
        self._linger = linger
        self._lanes: dict[int, object] = {}  # lane -> RpcServer
        self._links: dict[bytes, PeerLink] = {}  # peer network key -> link
        self._dial_locks: dict[bytes, asyncio.Lock] = {}
        self._adopted: dict[bytes, asyncio.Event] = {}
        self._map_cache = None
        self._closed = False
        # Observability for the O(N) claim: how many pooled links this
        # node ever held at once, and how many it established in total.
        self.peak_links = 0
        self.links_opened = 0

    # -- lane registry ----------------------------------------------------

    def register_lane(self, lane: int, server) -> None:
        """Attach a co-hosted role's RpcServer as the handler table for
        `lane`. Frames arriving on pooled links with this lane id dispatch
        here (same-lane responses)."""
        self._lanes[lane] = server

    def unregister_lane(self, lane: int) -> None:
        self._lanes.pop(lane, None)

    def has_lane(self, lane: int) -> bool:
        return lane in self._lanes

    # -- address placement ------------------------------------------------

    def _maps(self):
        """(address -> (peer network key, lane), network key -> pooled dial
        address) for the CURRENT committee/worker-cache — identity-keyed
        memo, rebuilt when an epoch change swaps the config objects."""
        committee = self._get_committee()
        worker_cache = (
            self._get_worker_cache() if self._get_worker_cache is not None else None
        )
        cached = self._map_cache
        if cached is None or cached[0] is not committee or cached[1] is not worker_cache:
            by_addr: dict[str, tuple[bytes, int]] = {}
            dial: dict[bytes, str] = {}
            for auth in committee.authorities.values():
                by_addr[auth.primary_address] = (auth.network_key, LANE_PRIMARY)
                dial[auth.network_key] = auth.primary_address
            if worker_cache is not None:
                for auth_pk, workers in worker_cache.workers.items():
                    auth = committee.authorities.get(auth_pk)
                    if auth is None:
                        continue
                    for wid, info in workers.items():
                        # Only the validator mesh address — the transaction
                        # ingest endpoint stays on the public plane.
                        by_addr[info.worker_address] = (
                            auth.network_key,
                            worker_lane(wid),
                        )
            cached = self._map_cache = (committee, worker_cache, by_addr, dial)
        return cached[2], cached[3]

    def lookup(self, address: str) -> tuple[bytes, int] | None:
        """(peer network key, lane) when `address` is a committee role the
        pool can place behind the peer node's one connection; None routes
        the caller to a legacy dedicated connection."""
        return self._maps()[0].get(address)

    def dial_address(self, peer_key) -> str | None:
        return self._maps()[1].get(peer_key)

    # -- link lifecycle ---------------------------------------------------

    async def link_for(self, peer_key) -> PeerLink:
        """The live link to `peer_key`, establishing one if needed. The
        higher-keyed side of a pair first waits `pool_passive_dial_delay`
        for the peer's inbound connection (the canonical one) before
        dialing itself."""
        if self._closed:
            raise RpcError("connection pool closed")
        link = self._links.get(peer_key)
        if link is not None and not link.closed:
            return link
        lock = self._dial_locks.setdefault(peer_key, asyncio.Lock())
        async with lock:
            link = self._links.get(peer_key)
            if link is not None and not link.closed:
                return link
            if (
                self._passive_delay > 0
                and peer_key != self.own_key
                and bytes(self.own_key) > bytes(peer_key)
            ):
                event = self._adopted.setdefault(peer_key, asyncio.Event())
                event.clear()
                try:
                    await asyncio.wait_for(event.wait(), self._passive_delay)
                except asyncio.TimeoutError:  # lint: allow(no-silent-except)
                    pass  # grace period expired: the peer never dialed, we do
                link = self._links.get(peer_key)
                if link is not None and not link.closed:
                    return link
            return await self._dial(peer_key)

    async def _dial(self, peer_key) -> PeerLink:
        address = self.dial_address(peer_key)
        if address is None:
            raise RpcError("peer has no pooled address in the current committee")
        host, port = address.rsplit(":", 1)
        reader, writer = await transport.open_connection(
            host, int(port), limit=MAX_FRAME + 1024
        )
        try:
            session = await client_handshake(
                reader, writer, self._credentials, peer_key, _read_frame, _write_frame
            )
        except (AuthError, asyncio.TimeoutError, asyncio.IncompleteReadError) as e:
            writer.close()
            raise RpcError(f"pool handshake with {address} failed: {e}") from e
        link = PeerLink(
            self, peer_key, address, writer, session, self._counters, dialed=True
        )
        link.send_pool_hello()
        link.start(reader)
        self._register(peer_key, link)
        return link

    def _register(self, peer_key, link: PeerLink) -> None:
        old = self._links.get(peer_key)
        self._links[peer_key] = link
        self.links_opened += 1
        self.peak_links = max(self.peak_links, len(self._links))
        event = self._adopted.setdefault(peer_key, asyncio.Event())
        event.set()
        if old is not None and not old.closed and old is not link:
            # Crossed dial (or stale link superseded by a reconnect): give
            # responses already in flight on the loser a moment to drain,
            # then tear it down. Its pending rids fail into the callers'
            # retry paths, which re-acquire THIS link.
            try:
                asyncio.get_running_loop().call_later(self._linger, old.close)
            except RuntimeError:
                old.close()

    def adopt(self, peer: Peer, reader, writer, session, sender):
        """Take over an inbound pool connection from RpcServer's accept
        path. Returns the link's demux-loop coroutine for the accept task
        to await (tying the connection's lifetime to it), or None when the
        peer's key is not a committee node (the server then keeps serving
        it as a legacy connection)."""
        peer_key = peer.key
        if peer_key != self.own_key and self.dial_address(peer_key) is None:
            return None
        link = PeerLink(
            self,
            peer_key,
            peer.addr,
            writer,
            session,
            self._counters,
            dialed=False,
            sender=sender,
        )
        if peer_key == self.own_key:
            # Self-link: the node pools to itself (worker -> own primary,
            # primary -> own worker). The DIALED end is the send path and
            # is already registered by the dialer; this accepted end only
            # serves dispatch — registering it would make the node talk to
            # itself over two half-links.
            pass
        else:
            existing = self._links.get(peer_key)
            crossed_loser = (
                existing is not None
                and not existing.closed
                and existing.dialed
                and bytes(self.own_key) < bytes(peer_key)
            )
            if not crossed_loser:
                # Either no link yet (use the peer's), or ours must yield:
                # the canonical connection is the one dialed by the lower
                # key, and the peer's key is lower (or our existing link is
                # itself a stale adoption superseded by this reconnect).
                self._register(peer_key, link)
            # else: our own dial is canonical; serve this inbound link's
            # dispatch until the peer (the loser's dialer) closes it.
        return link.run(reader)

    def discard(self, link: PeerLink) -> None:
        """Called from the link's teardown: forget it if it is the
        registered one (a superseded loser just disappears)."""
        if self._links.get(link.peer_pk) is link:
            del self._links[link.peer_pk]
            event = self._adopted.get(link.peer_pk)
            if event is not None:
                event.clear()

    # -- inbound dispatch -------------------------------------------------

    async def dispatch(
        self, link: PeerLink, lane: int, rid: int, tag: int, body: bytes, oneway: bool
    ) -> None:
        """Route one inbound frame to the lane's co-hosted server. A lane
        nobody registered (split deployment) answers LANE_UNAVAILABLE so
        the caller falls back to a direct connection."""
        server = self._lanes.get(lane)
        if server is None:
            if oneway:
                logger.debug(
                    "dropping oneway frame for non-co-hosted lane %d from %s",
                    lane,
                    link.address,
                )
            else:
                try:
                    link.respond(KIND_ERR, rid, 0, LANE_UNAVAILABLE, lane)
                except RpcError:  # lint: allow(no-silent-except)
                    pass  # link died under the reply; the caller's rid fails
            return
        await server.dispatch_frame(
            link.sender, rid, tag, body, link.peer, oneway, lane
        )

    def close(self) -> None:
        self._closed = True
        for link in list(self._links.values()):
            link.close()
        self._links.clear()
        self._lanes.clear()


# Process-global registry of co-hosted node pools, keyed by AUTHORITY
# public key (the protocol identity both Primary and Worker know): the
# Primary — holder of the node's network keypair — creates and registers
# the pool; co-hosted Workers look it up at spawn and register their lanes.
# A Worker that finds no pool (standalone/split deployment, pooling off)
# runs legacy dedicated connections.
_NODE_POOLS: dict[bytes, LanePool] = {}


def register_node_pool(name, pool: LanePool) -> None:
    # Overwrite is deliberate: a restarted node (NodeRestarter) registers
    # its fresh pool over the dead one.
    _NODE_POOLS[name] = pool


def node_pool(name) -> LanePool | None:
    return _NODE_POOLS.get(name)


def unregister_node_pool(name, pool: LanePool) -> None:
    """Remove `pool` from the registry — only if it is still the current
    one (a restarted node's fresh pool must survive the old one's late
    shutdown)."""
    if _NODE_POOLS.get(name) is pool:
        del _NODE_POOLS[name]
