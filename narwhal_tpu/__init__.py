"""narwhal_tpu — a TPU-native DAG mempool + BFT consensus framework.

A from-scratch re-design of Narwhal & Tusk (reference: erwanor/narwhal at
/root/reference, Rust) for TPU hardware: asyncio actor runtime, canonical
binary codec, ed25519 multi-signature certificates whose verification batches
onto a JAX/Pallas verifier, and consensus ordering expressed as vectorized
adjacency-tensor walks over a dense [rounds x authorities] DAG window.
"""

__version__ = "0.1.0"
