"""Node assembly: wire storage, primary, consensus, executor and workers.

Reference: /root/reference/node/src/lib.rs — NodeStorage::reopen :43-124,
Node::spawn_primary :134-282 (internal_consensus=true => Bullshark + executor
under partial synchrony; false => the external Dag service under asynchrony),
spawn_consensus :284-370, spawn_workers :373-407; NodeRestarter
(node/src/restarter.rs:18-) tears the node down and respawns it on committee
change with a fresh store per epoch.
"""

from __future__ import annotations

import asyncio
import logging
import os

from .channels import Channel, drain_cancelled, metered_channel
from .config import Committee, ConfigError, Parameters, WorkerCache
from .consensus import Bullshark, Consensus, Dag, Tusk
from .consensus.metrics import ConsensusMetrics
from .crypto import KeyPair, SignatureService
from .executor import (
    ExecutionIndices,
    ExecutionState,
    Executor,
    get_restored_consensus_output,
)
from .metrics import Registry
from .primary import NetworkModel, Primary
from .primary.api_server import ConsensusApi
from .primary.block_remover import BlockRemover
from .primary.block_synchronizer import BlockSynchronizer
from .primary.block_waiter import BlockWaiter
from .stores import NodeStorage
from .tracing import Tracer
from .types import ConsensusOutput, PublicKey
from .worker import Worker

logger = logging.getLogger("narwhal.node")


class SimpleExecutionState(ExecutionState):
    """No-op application persisting its execution cursor in the node's store
    (/root/reference/node/src/execution_state.rs:9-60)."""

    def __init__(self, storage: NodeStorage | None = None):
        self._cf = (
            storage.engine.column_family("execution_indices")
            if storage is not None
            else None
        )
        self._indices = ExecutionIndices()

    async def handle_consensus_transaction(self, output, indices, transaction):
        self._indices = indices
        if self._cf is not None:
            self._cf.put(b"indices", indices.to_bytes())
        return b""

    async def load_execution_indices(self) -> ExecutionIndices:
        if self._cf is not None:
            raw = self._cf.get(b"indices")
            if raw is not None:
                self._indices = ExecutionIndices.from_bytes(raw)
        return self._indices


class PrimaryNode:
    """One authority's primary role: Primary + Consensus + Executor
    (Node::spawn_primary, node/src/lib.rs:134-282)."""

    def __init__(
        self,
        keypair: KeyPair,
        committee: Committee,
        worker_cache: WorkerCache,
        parameters: Parameters,
        storage: NodeStorage,
        execution_state: ExecutionState | None = None,
        internal_consensus: bool = True,
        consensus_protocol: str = "bullshark",
        registry: Registry | None = None,
        crypto_backend: str = "cpu",  # cpu | pool | tpu
        dag_backend: str = "cpu",  # cpu | tpu
        dag_shards: int = 1,  # devices on the mesh's 'auth' axis (tpu backend)
        verify_shards: int = 1,  # devices on the verifier's 'data' axis (tpu)
        network_keypair: KeyPair | None = None,
        commit_tap=None,  # callable(ConsensusOutput): simnet oracle hook
    ):
        self.keypair = keypair
        self.name: PublicKey = keypair.public
        self.committee = committee
        self.worker_cache = worker_cache
        self.parameters = parameters
        self.storage = storage
        self.registry = registry or Registry()
        self.internal_consensus = internal_consensus
        # One tracer + flight recorder per node, shared by every role-level
        # metrics object (worker seal spans live on the WorkerNode's own
        # tracer): span emission is keyed on the same causal digests on
        # every node, so cross-stage waterfalls stitch without new wire
        # bytes. Off (zero-overhead ring of instants only) unless
        # NARWHAL_TRACE=1.
        self.tracer = Tracer(node=f"primary-{self.name.hex()[:8]}")
        # Group-commit instruments (fused-WAL group size / flush latency).
        storage.engine.attach_metrics(self.registry)
        # Registered at assembly (not inside the monitor coroutine) so the
        # metrics catalog extractor sees the full surface without spawning.
        self._backpressure_gauge = self.registry.gauge(
            "node_backpressure_level",
            "Downstream backlog level pushed to our workers (max of channel "
            "occupancy, commit-latency-vs-target, and commit-stall signals)",
        )

        # Channels between the three subsystems (node/src/lib.rs:150-192),
        # depth-gauged like the reference's porcelain metrics (lib.rs:168-192).
        def chan(name: str, capacity: int) -> Channel:
            return metered_channel(self.registry, "node", name, capacity)

        self.tx_new_certificates = chan("new_certificates", 10_000)
        self.tx_committed_certificates = chan("committed_certificates", 10_000)
        self.tx_consensus_output = chan("consensus_output", 10_000)
        self.tx_execution_output = chan("execution_output", 10_000)
        # Accepted-certificate tap -> speculative payload prefetcher: batch
        # digests are known at DAG acceptance, rounds before commit, so the
        # executor can warm its temp batch store off the critical path.
        # NARWHAL_PREFETCH_BUDGET (bytes) overrides the committee file;
        # budget 0 disables the prefetcher and the tap entirely.
        prefetch_budget = int(
            os.environ.get(
                "NARWHAL_PREFETCH_BUDGET",
                getattr(parameters, "prefetch_budget", 64 << 20),
            )
        )
        self.tx_accepted_certificates = (
            chan("accepted_certificates", 10_000)
            if internal_consensus and prefetch_budget > 0
            else None
        )

        # Crypto backend (the --crypto-backend flag of SURVEY §7.8c):
        #   cpu  — inline host verification in the Core (reference
        #          behavior) for full-format committees; under the compact
        #          default it gains the async stage below so certificate
        #          proofs batch (see the cert_format branch)
        #   pool — async coalescing stage over the host library
        #   tpu  — async coalescing stage over the TPU batch kernel
        # The accept set is a COMMITTEE-WIDE parameter (Parameters.
        # verify_rule), validated here at startup: the host library is
        # cofactorless ("strict"), the TPU msm batch kernel is RFC-8032
        # cofactored — a committee mixing the two can permanently disagree
        # on adversarially crafted torsion signatures.
        # Committee-wide knobs are validated here at assembly with
        # ConfigError — operator mistakes must stop the boot symmetrically
        # (a verify_rule typo used to fall through to backend-specific
        # errors while cert_format failed fast).
        rule = getattr(parameters, "verify_rule", "strict")
        if rule not in ("strict", "cofactored"):
            raise ConfigError(
                f"parameters.verify_rule must be strict|cofactored, got {rule!r}"
            )
        # cert_format is committee-wide wire format: a typo silently
        # behaving as the non-default form would mix certificate wire forms
        # instead of failing fast (advisor r4). Compact is the default on
        # EVERY backend (each has a batched cofactored proof-verify path);
        # 'full' is the opt-out, and all nodes accept both forms on the
        # wire regardless.
        cert_format = getattr(parameters, "cert_format", "compact")
        if cert_format not in ("full", "compact"):
            raise ConfigError(
                f"parameters.cert_format must be full|compact, got {cert_format!r}"
            )
        # header_wire only selects what WE send (every node accepts both
        # forms), but a typo silently behaving as "full" would quietly
        # forfeit the wire diet — fail fast like cert_format.
        header_wire = getattr(parameters, "header_wire", "full")
        if header_wire not in ("full", "delta"):
            raise ConfigError(
                f"parameters.header_wire must be full|delta, got {header_wire!r}"
            )
        if rule == "cofactored" and crypto_backend != "tpu":
            raise ConfigError(
                "parameters.verify_rule=cofactored: only the tpu crypto "
                f"backend implements the cofactored PER-ITEM accept set (got "
                f"crypto_backend={crypto_backend!r}). Use --crypto-backend "
                "tpu on every node, or set verify_rule=strict. (Compact "
                "certificate proofs are cofactored on every backend and do "
                "not require this rule.)"
            )
        if verify_shards > 1 and crypto_backend != "tpu":
            raise ConfigError(
                f"--verify-shards {verify_shards} requires --crypto-backend "
                f"tpu (got {crypto_backend!r})"
            )
        crypto_pool = None
        if crypto_backend == "tpu":
            from .tpu.verifier import AsyncVerifierPool, VerifyService

            if rule == "cofactored":
                logger.warning(
                    "verify_rule=cofactored: EVERY node in this "
                    "committee must run --crypto-backend tpu; a cpu/pool "
                    "node (strict rule) in the same committee is a "
                    "consensus-split hazard on crafted torsion signatures"
                )
            mode = "msm" if rule == "cofactored" else "item"
            try:
                # ONE pipelined service per process: every node on this
                # host shares flushes, so the device link RTT is paid per
                # merged batch, not per protocol hop (the VERDICT r3
                # crypto=tpu stall at N=20). --verify-shards N spreads
                # every flush over an N-device 'data' mesh
                # (verifier.data_mesh); bucket divisibility is validated
                # inside the TpuVerifier constructor, so a mis-sized mesh
                # fails the boot, not the first dispatch.
                crypto_pool = VerifyService.shared(mode, shards=verify_shards)
            except ConfigError:
                # Mis-sized shard count / bad mesh: operator error, never
                # fallback. Plain ValueErrors from inside jax/TpuVerifier
                # device init are ENVIRONMENTAL and fall through to the
                # documented strict-rule host-crypto degradation below.
                raise
            except Exception:
                # Under the cofactored rule the device path is mandatory: a
                # host fallback would run the STRICT accept set — a
                # consensus-split hazard (safety beats liveness; the node
                # refuses to start instead). Strict-rule nodes degrade to
                # the host pool, which implements the same accept set.
                if rule == "cofactored":
                    raise RuntimeError(
                        "TPU verifier unavailable but the committee's "
                        "verify rule requires it (host fallback implements "
                        "a different accept set); refusing to start"
                    )
                logger.exception(
                    "TPU verifier unavailable; degrading to the host pool "
                    "(same strict accept set)"
                )
                crypto_pool = AsyncVerifierPool()
        elif crypto_backend == "pool":
            from .tpu.verifier import AsyncVerifierPool

            crypto_pool = AsyncVerifierPool()
        elif cert_format == "compact":
            # cpu backend under the compact default: certificate proofs
            # must ride the batched aggregate lane, not per-certificate
            # inline host verification in the Core — the verifier stage's
            # concurrent submissions coalesce into one
            # host_batch_verify_aggregates MSM per flush (certificate
            # GROUPS per dispatch, the non-TPU analog of the device group
            # lane). Headers/votes share the stage's host batch path, same
            # strict accept set as inline verification.
            from .tpu.verifier import AsyncVerifierPool

            crypto_pool = AsyncVerifierPool()
        self.crypto_pool = crypto_pool

        self.primary = Primary(
            self.name,
            SignatureService(keypair),
            committee,
            worker_cache,
            parameters,
            storage,
            self.tx_new_certificates,
            self.tx_committed_certificates,
            network_model=(
                NetworkModel.PARTIALLY_SYNCHRONOUS
                if internal_consensus
                else NetworkModel.ASYNCHRONOUS
            ),
            registry=self.registry,
            crypto_pool=crypto_pool,
            network_keypair=network_keypair,
            tracer=self.tracer,
        )

        self.consensus: Consensus | None = None
        self.executor: Executor | None = None
        self.dag: Dag | None = None
        self._dag_backend = dag_backend
        self.execution_state = execution_state or SimpleExecutionState(storage)
        if dag_shards > 1 and dag_backend != "tpu":
            raise ValueError(
                f"--dag-shards {dag_shards} requires --dag-backend tpu "
                f"(got {dag_backend!r})"
            )
        if internal_consensus:
            # --dag-backend tpu: the commit walk runs on device via the
            # adjacency-tensor kernels (SURVEY §7.8c; the reference's
            # consensus/src/utils.rs:11-101 hot loop, vectorized).
            if dag_backend == "tpu":
                from .tpu.dag_kernels import TpuBullshark, TpuTusk

                protocol_cls = {"bullshark": TpuBullshark, "tusk": TpuTusk}[
                    consensus_protocol
                ]
                # --dag-shards > 1: shard the committee axis of the window
                # over an 'auth' device mesh (ICI collectives). The CPU
                # fallback only helps when the host platform is forced to
                # multiple virtual devices (tests/dryrun set
                # xla_force_host_platform_device_count); a plain single-chip
                # host raises rather than silently degrading, and falling
                # back from a too-small accelerator platform is logged so
                # no benchmark silently attributes CPU numbers to the chip.
                mesh = None
                if dag_shards > 1:
                    import jax
                    import numpy as _np
                    from jax.sharding import Mesh

                    devs = jax.devices()
                    if len(devs) < dag_shards:
                        cpus = jax.devices("cpu")
                        if len(cpus) < dag_shards:
                            raise ValueError(
                                f"--dag-shards {dag_shards} exceeds available "
                                f"devices ({len(devs)} {devs[0].platform}, "
                                f"{len(cpus)} cpu)"
                            )
                        logger.warning(
                            "--dag-shards %d exceeds the %d-device %s "
                            "backend; sharding over %d virtual CPU devices "
                            "instead",
                            dag_shards, len(devs), devs[0].platform, dag_shards,
                        )
                        devs = cpus
                    mesh = Mesh(_np.array(devs[:dag_shards]), ("auth",))
                protocol = protocol_cls(
                    committee, storage.consensus_store, parameters.gc_depth,
                    mesh=mesh,
                )
            else:
                protocol_cls = {"bullshark": Bullshark, "tusk": Tusk}[
                    consensus_protocol
                ]
                protocol = protocol_cls(
                    committee, storage.consensus_store, parameters.gc_depth
                )
            self.consensus_metrics = ConsensusMetrics(
                self.registry, tracer=self.tracer
            )
            self.consensus = Consensus(
                committee,
                protocol,
                storage.consensus_store,
                storage.certificate_store,
                self.tx_new_certificates,
                self.tx_committed_certificates,
                self.tx_consensus_output,
                self.primary.tx_reconfigure,
                parameters.gc_depth,
                self.consensus_metrics,
                tx_accepted=self.tx_accepted_certificates,
                commit_tap=commit_tap,
            )
            self.executor = Executor(
                self.name,
                worker_cache,
                storage,
                self.execution_state,
                self.primary.network,
                self.tx_consensus_output,
                self.tx_execution_output,
                registry=self.registry,
                rx_accepted=self.tx_accepted_certificates,
                gc_depth=parameters.gc_depth,
                prefetch_budget=prefetch_budget,
                tracer=self.tracer,
            )
        else:
            # External consensus: the Dag service consumes the certificate
            # stream and serves causal queries (node/src/lib.rs:198-213).
            # With --dag-backend tpu, ReadCausal/NodeReadCausal run as one
            # device reach_mask dispatch over the dense window.
            self.dag = Dag(
                committee,
                self.tx_new_certificates,
                backend=dag_backend,
                metrics=ConsensusMetrics(self.registry),
            )

        # Block services + the public consensus API (primary/src/grpc_server).
        self.block_synchronizer = BlockSynchronizer(
            self.name,
            committee,
            worker_cache,
            storage.certificate_store,
            storage.payload_store,
            self.primary.network,
            parameters,
            tx_loopback=self.primary.tx_primary_messages,
            # Catch-up verification rides the same batched lane as live
            # traffic (advisor r4: compact-cert catch-up must not fall back
            # to pure-Python aggregate verification on tpu-backend nodes).
            crypto_pool=crypto_pool,
        )
        self.block_waiter = BlockWaiter(
            self.name,
            worker_cache,
            storage.certificate_store,
            self.primary.network,
            self.block_synchronizer,
        )
        self.block_remover = BlockRemover(
            self.name,
            worker_cache,
            storage.certificate_store,
            storage.header_store,
            storage.payload_store,
            self.primary.network,
            dag=self.dag,
        )
        self.api = ConsensusApi(
            self.name,
            committee,
            self.block_waiter,
            self.block_remover,
            dag=self.dag,
            registry=self.registry,
            tracer=self.tracer,
        )
        # The interoperable public edge (tonic parity): gRPC services over
        # the same seams, mounted on consensus_api_grpc_address.
        from .grpc_api import GrpcPublicApi

        self.grpc_api = GrpcPublicApi(
            self.name,
            committee,
            self.block_waiter,
            self.block_remover,
            dag=self.dag,
            registry=self.registry,
            tracer=self.tracer,
        )
        self.api_address: str = ""
        self.grpc_api_address: str = ""
        self._tasks: list[asyncio.Task] = []

    @property
    def address(self) -> str:
        return self.primary.address

    async def spawn(self) -> None:
        restored: list[ConsensusOutput] = []
        if self.internal_consensus:
            restored = await get_restored_consensus_output(
                self.storage.consensus_store,
                self.storage.certificate_store,
                self.execution_state,
            )
            if restored:
                logger.info("Replaying %d consensus outputs after restart", len(restored))
        await self.primary.spawn()
        if self.consensus is not None:
            self._tasks.append(self.consensus.spawn())
        if self.executor is not None:
            self._tasks.extend(await self.executor.spawn(restored))
        if self.dag is not None:
            self._tasks.append(self.dag.spawn())
        if self.internal_consensus:
            # End-to-end admission control: sample the commit/execution
            # backlog and push the level to our own workers so their
            # client-facing ingest can shed/block before the backlog grows
            # without bound (the worker fails open if these pushes stop).
            self._tasks.append(asyncio.ensure_future(self._backpressure_monitor()))
        # gRPC owns the configured public address (tonic parity); the typed
        # TCP api binds an ephemeral port for in-framework clients. Under
        # the simnet transport the typed api rides the fabric like every
        # other RpcServer, but grpc.aio binds REAL sockets — skipped there,
        # keeping simulated committees at zero sockets (the interop edge is
        # meaningless inside a simulation anyway).
        from .network import transport as _transport

        self.api.set_primary_address(self.primary.address)
        self.api_address = await self.api.spawn("127.0.0.1:0")
        if _transport.simnet_active():
            self.grpc_api_address = ""
        else:
            self.grpc_api.set_primary_address(self.primary.address)
            self.grpc_api_address = await self.grpc_api.spawn(
                self.parameters.consensus_api_grpc_address
            )
        # Restart catch-up (block_synchronizer/mod.rs:75-83 SynchronizeRange):
        # collect certificates peers accumulated while we were down.
        last_round = self.storage.certificate_store.last_round()
        if last_round > 0:
            async def catch_up() -> None:
                try:
                    fetched = await self.block_synchronizer.synchronize_range(
                        last_round
                    )
                    if fetched:
                        logger.info(
                            "Catch-up: fetched %d certificates past round %d",
                            len(fetched),
                            last_round,
                        )
                except Exception:
                    logger.debug("restart catch-up failed", exc_info=True)

            self._tasks.append(asyncio.ensure_future(catch_up()))

    async def _backpressure_monitor(self) -> None:
        """Executor backlog -> consensus runner -> primary -> worker ingest:
        the push leg of the admission-control loop. The level folds channel
        occupancy, the commit-stage latency EWMA vs commit_latency_target,
        and a commit-stall detector (pacing.backpressure_level — measured
        overload on this class of host is service-time saturation with
        shallow channels, so depth alone is blind). Delivery is best-effort
        unreliable_send every poll interval — workers treat a silent
        primary as level 0 after backpressure_stale_after (fail open), so
        this task can die without wedging client ingest."""
        from . import clock
        from .config import env_float
        from .messages import BackpressureMsg
        from .pacing import backpressure_level

        gauge = self._backpressure_gauge
        interval = self.parameters.backpressure_poll_interval
        target = env_float(
            "NARWHAL_COMMIT_LATENCY_TARGET", self.parameters.commit_latency_target
        )
        channels = [
            self.tx_new_certificates,
            self.tx_consensus_output,
            self.tx_execution_output,
            # Primary-side saturation: a deep protocol-ingest or
            # pending-digest queue means the core/proposer can't keep up
            # even before consensus output backs up.
            self.primary.tx_primary_messages,
            self.primary.tx_our_digests,
        ]
        if self.executor is not None:
            channels.append(self.executor.tx_executor)
        channel_names = (
            "new_certificates",
            "consensus_output",
            "execution_output",
            "primary_messages",
            "our_digests",
            "executor_core",
        )
        commit_counter = self.consensus_metrics.committed_certificates
        commit_timer = self.consensus_metrics.commit_timer
        last_committed = commit_counter.get()
        last_commit_t = clock.now()
        # Dump-on-anomaly: the first poll that sees the commit pipeline
        # silent for stall_after seconds snapshots every live flight
        # recorder (re-armed when commits resume, so a long outage yields
        # one dump per stall episode, not one per poll).
        stall_after = env_float(
            "NARWHAL_COMMIT_STALL_AFTER", max(5.0, 10.0 * target)
        )
        stall_armed = True
        while True:
            committed = commit_counter.get()
            if committed != last_committed:
                last_committed, last_commit_t = committed, clock.now()
                stall_armed = True
            stale = (clock.now() - last_commit_t) if committed > 0 else None
            level = backpressure_level(
                (ch.occupancy() for ch in channels),
                # Monitoring read of the stage timers' EWMA: a one-tick
                # stale value only delays the admission level by one poll
                # interval — racy-read-tolerant by design.
                commit_timer.ewma,  # lint: allow(multi-task-mutation)
                stale,
                target,
                self.parameters.backpressure_high_watermark,
            )
            gauge.set(level)
            # Flight-recorder breadcrumb: channel occupancy + admission
            # level each poll, always on (instants ride the bounded ring
            # regardless of NARWHAL_TRACE).
            self.tracer.instant(
                "backpressure",
                level=round(level, 4),
                committed=committed,
                occupancy={
                    n: ch.qsize() for n, ch in zip(channel_names, channels)
                },
            )
            if stall_armed and stale is not None and stale > stall_after:
                stall_armed = False
                from . import tracing

                tracing.on_anomaly(
                    f"commit_stall node={self.name.hex()[:8]} "
                    f"stale={stale:.1f}s committed={committed}"
                )
            msg = BackpressureMsg.from_level(level)
            workers = self.worker_cache.our_workers(self.name).values()
            await asyncio.gather(
                *(
                    self.primary.network.unreliable_send(
                        info.worker_address, msg, timeout=interval
                    )
                    for info in workers
                )
            )
            await asyncio.sleep(interval)

    async def shutdown(self) -> None:
        # Park this node's flight recorder in the module archive first:
        # post-mortem dumps (test hooks, scenario teardown) must survive
        # the tracer's owner being garbage collected.
        self.tracer.archive()
        for t in self._tasks:
            t.cancel()
        await drain_cancelled(self._tasks, who="primary-node")
        await self.api.shutdown()
        await self.grpc_api.shutdown()
        await self.primary.shutdown()
        if self.crypto_pool is not None:
            # AsyncVerifierPool drains its in-flight batch tasks; the
            # process-shared VerifyService makes this a deliberate no-op
            # (other co-hosted nodes keep using it).
            await self.crypto_pool.close()
        if self._dag_backend == "tpu":
            # Bounded-join this node's background window prewarm compiles
            # (off-loop: the join blocks). A prewarm thread that outlived
            # its node contends with the successor's foreground traces for
            # XLA's compiler locks — the PR-1 stabilization failure mode,
            # previously handled only at interpreter exit.
            from .tpu.dag_kernels import join_prewarm_threads

            await asyncio.get_running_loop().run_in_executor(
                None, lambda: join_prewarm_threads(30.0)
            )
        self.storage.close()


class WorkerNode:
    """One authority's worker role (Node::spawn_workers, lib.rs:373-407)."""

    def __init__(
        self,
        name: PublicKey,
        worker_id: int,
        committee: Committee,
        worker_cache: WorkerCache,
        parameters: Parameters,
        storage: NodeStorage,
        registry: Registry | None = None,
        benchmark: bool = False,
        network_keypair: KeyPair | None = None,
    ):
        self.registry = registry or Registry()
        self.storage = storage
        self.tracer = Tracer(node=f"worker-{name.hex()[:8]}-{worker_id}")
        self.worker = Worker(
            name,
            worker_id,
            committee,
            worker_cache,
            parameters,
            storage.batch_store,
            registry=self.registry,
            benchmark=benchmark,
            network_keypair=network_keypair,
            tracer=self.tracer,
        )

    async def spawn(self) -> None:
        await self.worker.spawn()

    async def shutdown(self) -> None:
        self.tracer.archive()
        await self.worker.shutdown()
        self.storage.close()


class NodeRestarter:
    """Tear down and respawn a primary on committee change
    (/root/reference/node/src/restarter.rs:18-): each epoch gets a fresh
    in-memory store unless a store factory is provided."""

    def __init__(
        self,
        keypair: KeyPair,
        worker_cache: WorkerCache,
        parameters: Parameters,
        store_factory=None,
        execution_state_factory=None,
        network_keypair: KeyPair | None = None,
    ):
        self.keypair = keypair
        self.worker_cache = worker_cache
        self.parameters = parameters
        self.network_keypair = network_keypair
        self.store_factory = store_factory or (lambda epoch: NodeStorage(None))
        self.execution_state_factory = execution_state_factory
        self.node: PrimaryNode | None = None

    async def start(self, committee: Committee) -> PrimaryNode:
        storage = self.store_factory(committee.epoch)
        execution_state = (
            self.execution_state_factory(storage)
            if self.execution_state_factory
            else None
        )
        self.node = PrimaryNode(
            self.keypair,
            committee,
            self.worker_cache,
            self.parameters,
            storage,
            execution_state=execution_state,
            network_keypair=self.network_keypair,
        )
        await self.node.spawn()
        return self.node

    async def restart(self, new_committee: Committee) -> PrimaryNode:
        if self.node is not None:
            await self.node.shutdown()
        return await self.start(new_committee)
