"""Causal commit tracing and the per-node flight recorder.

The reference debugs its pipeline with per-crate Prometheus metrics; those
aggregate. What the two diagnosis-starved problems in ROADMAP.md (the
`test_partial_committee_change` contention flake and the multi-chip
host-epilogue cap) both need is the *causal* record: where did one specific
certificate's time go, across roles and across the host/device boundary.

This module is that record, in two bounded pieces:

* **Spans** — the per-certificate waterfall. The trace context is the
  digest chain the protocol already carries on the wire (batch digest →
  header digest → certificate digest), so tracing adds ZERO wire bytes:
  `link` events recorded where the chain hops (batch digests folded into a
  proposed header, a header certified) let `waterfall()` stitch per-stage
  spans (seal / propose / certify / commit / execute, plus the device-plane
  sub-spans from tpu/pipeline.py) into one end-to-end timeline per
  certificate, joining across the dumps of every node that touched it.
  Span timestamps come from `clock.now()` — the running loop's time — so
  under simnet's virtual clock a seeded scenario produces a bit-identical
  traced event log on every run.

* **Flight recorder** — a bounded ring (`collections.deque`) of structured
  events per node: span closes, causal links, and `instant` events
  (channel-occupancy snapshots, backpressure/pacing state transitions)
  that record regardless of the trace switch because they are off the hot
  path and are exactly what a post-mortem needs. `dump()` is a
  self-contained JSON-able dict; `on_anomaly()` archives every live
  tracer's ring into a bounded module-level archive (and optionally to
  NARWHAL_FLIGHT_DIR) so commit-stall detectors, simnet oracles and the
  pytest failure hook can attach the evidence to the failure they report.

Overhead discipline: span recording on the hot path is gated by
`Tracer.enabled` (NARWHAL_TRACE, default off) — when disabled the only cost
at an instrumented site is one attribute read and a falsy branch. When
enabled, `sampled(key)` decides deterministically from the digest bytes
(NARWHAL_TRACE_SAMPLE in (0,1]), so a sampled run traces the SAME
certificates on every node — partial waterfalls never happen — and a
seeded simnet replay samples identically.
"""

from __future__ import annotations

import collections
import json
import os
import weakref

from .clock import now as _now

# Ordered ring of recently archived dumps (nodes that shut down, anomaly
# snapshots): bounded so a long test session cannot grow without limit.
ARCHIVE: collections.deque = collections.deque(maxlen=64)

# Every constructed tracer, weakly — the dump surface for "all hosted
# nodes" consumers (conftest failure hook, anomaly triggers) without tying
# tracer lifetime to this module.
_LIVE: "weakref.WeakSet[Tracer]" = weakref.WeakSet()

# Cluster-incarnation generation: successive in-process clusters reuse
# node labels AND certificate digests (seeded fixtures), so a live-tracer
# dump that mixed incarnations would stitch spans from a PRIOR cluster
# into the current one (the diagnosed test_live_cluster_scrape flake).
# Each tracer records the generation current at its construction;
# `live_dumps`/`on_anomaly` only touch the current generation. Cluster
# boot bumps this via `new_generation()`.
_GENERATION: int = 0


def new_generation() -> int:
    """Start a new tracer incarnation; previously constructed tracers
    become invisible to `live_dumps`/`on_anomaly` (their rings stay
    reachable through direct references and the archive)."""
    global _GENERATION
    _GENERATION += 1
    return _GENERATION


def _env_flag(name: str, default: str = "0") -> bool:
    return os.environ.get(name, default) not in ("", "0", "false", "no")


class Tracer:
    """One node's span recorder + flight ring.

    `enabled`/`sample`/`ring` default from the environment at construction
    (NARWHAL_TRACE, NARWHAL_TRACE_SAMPLE, NARWHAL_FLIGHT_RING) so a whole
    in-process committee flips together without plumbing flags through
    every constructor.

    Concurrency discipline: many tasks append to `events` (every stage
    timer close, every instant), and the live-dump RPC handler reads it
    concurrently — the ring is safe because appends are single-statement
    (atomic under cooperative scheduling: no await between deciding to
    record and recording) and every reader snapshots copy-on-read
    (`dump()` does `list(self.events)` and serializes BEFORE its caller's
    next yield point). Do not hold a live reference to `events` across an
    await. Span ordering sanity (one window per key per stage, so the
    waterfall's earliest-t0 pick cannot land on a late re-opened window
    after ring eviction) is the stage timers' job: see
    pacing.StageTimer's closed-key latch."""

    __slots__ = ("node", "enabled", "events", "anomalies", "_threshold",
                 "generation", "__weakref__")

    def __init__(
        self,
        node: str = "",
        enabled: bool | None = None,
        sample: float | None = None,
        ring: int | None = None,
    ):
        self.node = node
        self.enabled = (
            _env_flag("NARWHAL_TRACE") if enabled is None else enabled
        )
        if sample is None:
            sample = float(os.environ.get("NARWHAL_TRACE_SAMPLE", "1.0"))
        # Deterministic digest-based sampling: a key is traced iff its
        # first 4 bytes, read big-endian, fall under sample * 2^32. Every
        # node makes the same decision for the same digest.
        self._threshold = int(max(0.0, min(1.0, sample)) * 0x1_0000_0000)
        if ring is None:
            ring = int(os.environ.get("NARWHAL_FLIGHT_RING", "4096"))
        self.events: collections.deque = collections.deque(maxlen=max(16, ring))
        self.anomalies: list[str] = []
        self.generation = _GENERATION
        _LIVE.add(self)

    # -- hot path ----------------------------------------------------------

    def sampled(self, key: bytes) -> bool:
        """Deterministic per-digest sampling decision (callers gate on
        `enabled` first; this never reads the clock or the environment)."""
        if self._threshold >= 0x1_0000_0000:
            return True
        return int.from_bytes(key[:4], "big") < self._threshold

    def span(self, stage: str, key: bytes, t0: float, t1: float, attrs=None):
        """One closed span: stage `stage` of causal key `key` ran [t0, t1].
        Appended at CLOSE time only — an open span costs nothing but its
        caller-held t0."""
        self.events.append(("span", stage, key.hex(), t0, t1, attrs))

    def link(self, stage: str, parent: bytes, child: bytes) -> None:
        """The causal key hops: `parent`'s journey continues under `child`
        (batch digest -> header digest at propose, header digest ->
        certificate digest at certify)."""
        self.events.append(("link", stage, parent.hex(), child.hex()))

    # -- flight recorder (off the hot path; always records) ----------------

    def instant(self, kind: str, **attrs) -> None:
        """A point-in-time flight event: occupancy snapshot, backpressure
        level transition, pacing mode change, anomaly marker."""
        self.events.append(("instant", kind, _now(), attrs or None))

    def anomaly(self, reason: str, **attrs) -> None:
        """Record an anomaly marker and archive this tracer's ring."""
        self.anomalies.append(reason)
        self.instant("anomaly", reason=reason, **attrs)
        _archive(self.dump())

    # -- dump surface ------------------------------------------------------

    def dump(self, max_events: int | None = None) -> dict:
        """Self-contained, JSON-able snapshot of the ring."""
        events = list(self.events)
        if max_events is not None and max_events > 0:
            events = events[-max_events:]
        return {
            "node": self.node,
            "trace_enabled": self.enabled,
            "ring_capacity": self.events.maxlen,
            "anomalies": list(self.anomalies),
            "events": events,
        }

    def archive(self) -> None:
        """Push this tracer's dump into the module archive (node shutdown:
        the ring must outlive the node for post-teardown diagnosis)."""
        if self.events or self.anomalies:
            _archive(self.dump())


def _archive(dump: dict) -> None:
    ARCHIVE.append(dump)
    out_dir = os.environ.get("NARWHAL_FLIGHT_DIR", "")
    if out_dir:
        try:
            os.makedirs(out_dir, exist_ok=True)
            path = os.path.join(
                out_dir, f"flight-{dump.get('node') or 'node'}-{len(ARCHIVE)}.json"
            )
            with open(path, "w") as f:
                json.dump(dump, f, sort_keys=True)
        except OSError:
            pass  # diagnosis must never take the node down


def live_dumps(max_events: int | None = None) -> list[dict]:
    """Dump every live tracer of the CURRENT cluster incarnation (all
    hosted nodes of an in-process committee), stable-ordered by node
    label. Tracers from a prior incarnation are excluded even while still
    referenced — their spans describe a different cluster's history."""
    return sorted(
        (
            t.dump(max_events)
            for t in _LIVE
            if t.generation == _GENERATION
        ),
        key=lambda d: d["node"],
    )


def all_dumps(max_events: int | None = None) -> list[dict]:
    """Live rings plus the bounded archive of already-torn-down nodes."""
    return list(ARCHIVE) + live_dumps(max_events)


def on_anomaly(reason: str) -> list[dict]:
    """Dump-on-anomaly trigger: snapshot every live ring into the archive,
    tagged with the reason, and return the dumps (what an oracle or a
    commit-stall detector attaches to its report)."""
    dumps = []
    for t in list(_LIVE):
        if t.generation != _GENERATION:
            continue
        t.anomalies.append(reason)
        dumps.append(t.dump())
    for d in dumps:
        d = dict(d)
        d["anomaly"] = reason
        _archive(d)
    return dumps


def clear_archive() -> None:
    ARCHIVE.clear()


# -- waterfall reconstruction ----------------------------------------------


def waterfall(dumps: list[dict]) -> dict[str, dict]:
    """Stitch span + link events from any number of node dumps into
    per-certificate waterfalls.

    Returns {certificate_digest_hex: {"stages": {stage: [t0, t1]}, ...}}
    where the stages of batches folded into the certificate's header (seal,
    propose, and the device sub-spans) are re-keyed under the certificate
    via the recorded link chain. Each stage keeps the earliest-opening span
    observed for that key across all dumps."""
    spans: dict[str, dict[str, tuple[float, float]]] = {}
    parent_of: dict[str, list[str]] = {}  # child key -> parent keys
    for d in dumps:
        for ev in d.get("events", ()):
            # Dumps arrive over RPC from possibly-older nodes: skip any
            # event too short for its kind instead of raising mid-stitch.
            if ev[0] == "span" and len(ev) >= 5:
                _, stage, key, t0, t1 = ev[:5]
                best = spans.setdefault(key, {})
                if stage not in best or t0 < best[stage][0]:
                    best[stage] = (t0, t1)
            elif ev[0] == "link" and len(ev) >= 4:
                _, _stage, parent, child = ev[:4]
                if parent != child:  # a self-link stitches nothing
                    parent_of.setdefault(child, []).append(parent)

    def ancestors(key: str, seen: set[str]) -> list[str]:
        # Iterative DFS with a seen-set: a cyclic link chain (two nodes
        # disagreeing about direction) or an arbitrarily deep one (ring
        # overflow splitting chains) degrades to a partial lineage instead
        # of looping or blowing the stack.
        out: list[str] = []
        stack = list(parent_of.get(key, ()))
        while stack:
            p = stack.pop(0)
            if p in seen:
                continue
            seen.add(p)
            out.append(p)
            stack[:0] = parent_of.get(p, ())
        return out

    # Roots = keys that are nobody's parent (certificate digests) OR keys
    # with a terminal stage recorded. Commit/execute close on the
    # certificate digest, so any key carrying those stages is a root.
    children = {p for ps in parent_of.values() for p in ps}
    out: dict[str, dict] = {}
    for key, stages in spans.items():
        terminal = "commit" in stages or "execute" in stages
        if key in children and not terminal:
            continue
        merged = dict(stages)
        lineage = ancestors(key, {key})
        for a in lineage:
            for stage, window in spans.get(a, {}).items():
                if stage not in merged or window[0] < merged[stage][0]:
                    merged[stage] = window
        out[key] = {
            "stages": {s: [t0, t1] for s, (t0, t1) in sorted(merged.items())},
            "ancestors": lineage,
        }
    return out


def stage_percentiles(dumps: list[dict]) -> dict[str, dict]:
    """Per-stage duration p50/p95 over every span in the dumps — the
    `--trace-waterfall` artifact's summary table."""
    by_stage: dict[str, list[float]] = {}
    for d in dumps:
        for ev in d.get("events", ()):
            if ev[0] == "span":
                by_stage.setdefault(ev[1], []).append(ev[4] - ev[3])
    out = {}
    for stage, samples in sorted(by_stage.items()):
        samples.sort()
        n = len(samples)
        out[stage] = {
            "count": n,
            "p50_ms": round(samples[n // 2] * 1000, 3),
            "p95_ms": round(samples[min(n - 1, int(0.95 * n))] * 1000, 3),
            "max_ms": round(samples[-1] * 1000, 3),
        }
    return out
