"""Adaptive pacing + end-to-end admission control — latency as a controlled
quantity.

Bullshark commits a leader every 2 DAG rounds, so the protocol floor at the
default delays is a few hundred ms — yet measured e2e p50 under load is tens
of seconds. The whole gap is queueing: fixed seal/propose timers waste the
idle capacity (a lone transaction waits the full `max_batch_delay` +
`max_header_delay` even when every queue is empty), and unbounded ingest lets
backlog grow without limit once offered load exceeds capacity. This module
holds the three pieces that close it:

* `PacingController` — one shared controller drives the effective seal delay
  (worker/batch_maker.py) and header delay (primary/proposer.py): near the
  configured floor when the channel-depth EWMA says queues are shallow
  (latency mode), climbing monotonically toward the configured ceiling as
  occupancy rises (throughput mode — bigger batches amortize the per-seal
  crypto/broadcast cost exactly when the system needs throughput).

* `BackpressureState` + `IngestGate` — the end-to-end admission-control
  signal: the primary samples its executor/consensus backlog and pushes the
  level to its own workers (messages.BackpressureMsg); the worker's
  client-facing ingest consults the gate and, past the high watermark,
  either sheds with an explicit RESOURCE_EXHAUSTED or blocks the submitter —
  overload degrades to bounded latency instead of unbounded backlog.

* `StageTimer` — bounded id→t0 maps feeding the `*_stage_latency_seconds`
  histograms, so a committed transaction's journey (ingest → seal → propose
  → certify → commit → execute) is decomposable per stage instead of one
  opaque end-to-end number.

Everything here is plain event-loop Python — no locks, no tasks of its own;
the owning actors call in from their existing select loops.
"""

from __future__ import annotations

import asyncio
from typing import Callable, Iterable

from .clock import now as _now

# The string the wire carries when ingest sheds: typed-RPC clients see it as
# the RpcError text of the ERR frame, gRPC clients as the status detail of
# StatusCode.RESOURCE_EXHAUSTED. Clients match on the prefix.
RESOURCE_EXHAUSTED = "RESOURCE_EXHAUSTED"


class IngestOverloadError(Exception):
    """Raised by IngestGate.admit() under the shed policy: the caller must
    surface it to the client verbatim (the RPC server turns handler
    exceptions into ERR frames, so the prefix travels the wire as-is)."""

    def __init__(self, detail: str):
        super().__init__(f"{RESOURCE_EXHAUSTED}: {detail}")


def _clamp01(v: float) -> float:
    return 0.0 if v < 0.0 else (1.0 if v > 1.0 else v)


class PacingController:
    """Maps queue occupancy to an effective delay in [floor, ceiling].

    `sources` are zero-argument callables returning occupancy in [0, 1]
    (Channel.occupancy bound methods are the intended substrate). Each
    `delay()` call samples every source, folds the max into an EWMA, and
    interpolates:

        occupancy <= low   -> floor    (latency mode: seal/propose asap)
        occupancy >= high  -> ceiling  (throughput mode: configured delay)
        in between         -> linear, so the response is monotone

    The EWMA (not the instantaneous max) is what interpolation reads:
    occupancy at these channels is sawtoothed by burst drains, and pacing on
    the raw value would oscillate between modes within one burst.
    """

    def __init__(
        self,
        ceiling: float,
        floor: float = 0.005,
        low_occupancy: float = 0.05,
        high_occupancy: float = 0.5,
        ewma_alpha: float = 0.2,
        sources: Iterable[Callable[[], float]] = (),
        gauge=None,  # optional Gauge: the EWMA occupancy, for dashboards
    ):
        if ceiling <= floor:
            # A ceiling at/under the floor means the operator asked for a
            # delay smaller than the adaptive floor: honor it verbatim.
            floor = ceiling
        if high_occupancy <= low_occupancy:
            high_occupancy = low_occupancy + 1e-6
        self.ceiling = ceiling
        self.floor = floor
        self.low = low_occupancy
        self.high = high_occupancy
        self.alpha = ewma_alpha
        self._sources: list[Callable[[], float]] = list(sources)
        self._gauge = gauge
        self._ewma = 0.0

    def add_source(self, source: Callable[[], float]) -> None:
        self._sources.append(source)

    def observe(self, sample: float | None = None) -> float:
        """Fold one occupancy sample (default: max over the sources) into
        the EWMA and return the new EWMA."""
        if sample is None:
            sample = max((_clamp01(s()) for s in self._sources), default=0.0)
        else:
            sample = _clamp01(sample)
        self._ewma += self.alpha * (sample - self._ewma)
        if self._gauge is not None:
            self._gauge.set(self._ewma)
        return self._ewma

    def delay(self) -> float:
        """The effective seal/propose delay for the current occupancy."""
        occ = self.observe()
        if occ <= self.low:
            return self.floor
        if occ >= self.high:
            return self.ceiling
        frac = (occ - self.low) / (self.high - self.low)
        return self.floor + (self.ceiling - self.floor) * frac


class BackpressureState:
    """The downstream-backlog level a worker hears from its primary.

    `update(level)` is called by the BackpressureMsg handler; `level()` is
    what the IngestGate folds into its admission decision. Two safeguards:

    * hysteresis — `overloaded()` trips at >= high and releases only at
      <= low, so a level hovering at the watermark doesn't flap admission
      per request;
    * staleness fail-open — a level older than `stale_after` seconds reads
      as 0.0: if the primary dies (or the push path breaks), the worker
      must not shed client traffic forever on a stale signal.
    """

    def __init__(
        self,
        high: float = 0.75,
        low: float = 0.5,
        stale_after: float = 2.0,
        gauge=None,
        clock: Callable[[], float] = _now,
    ):
        self.high = high
        self.low = max(0.0, min(low, high))
        self.stale_after = stale_after
        self._gauge = gauge
        self._clock = clock
        self._level = 0.0
        self._updated_at = clock() - stale_after  # born stale: fail open
        self._overloaded = False

    def update(self, level: float) -> None:
        self._level = _clamp01(level)
        self._updated_at = self._clock()
        if self._gauge is not None:
            self._gauge.set(self._level)

    def level(self) -> float:
        if self._clock() - self._updated_at > self.stale_after:
            return 0.0
        return self._level

    def overloaded(self) -> bool:
        lvl = self.level()
        if self._overloaded:
            if lvl <= self.low:
                self._overloaded = False
        elif lvl >= self.high:
            self._overloaded = True
        return self._overloaded


class IngestGate:
    """Admission control at the worker's client-facing ingest.

    The admission level is the max of the local ingest-queue occupancy
    (`local_sources`, usually the batch-maker channel) and the downstream
    level pushed by the primary (`downstream`). Hysteresis mirrors
    BackpressureState: the gate trips at >= high and re-admits at <= low.

    Policies (Parameters.ingest_policy / NARWHAL_INGEST_POLICY):
      shed  — `admit()` raises IngestOverloadError (RESOURCE_EXHAUSTED on
              the wire) immediately; the client decides whether to retry.
      block — `admit()` waits (bounded by `block_timeout`) for the level to
              fall below the low watermark, exerting TCP-level backpressure
              through the connection's dispatch semaphore; on timeout it
              sheds anyway, so latency stays bounded under either policy.
      off   — every submission admits (the seed behavior: unbounded queue).
    """

    POLICIES = ("shed", "block", "off")

    def __init__(
        self,
        policy: str = "shed",
        local_sources: Iterable[Callable[[], float]] = (),
        downstream: BackpressureState | None = None,
        high: float = 0.75,
        low: float = 0.5,
        block_timeout: float = 5.0,
        block_poll: float = 0.02,
        metrics=None,  # WorkerMetrics (ingest_shed / ingest_blocked_seconds)
    ):
        if policy not in self.POLICIES:
            raise ValueError(
                f"ingest policy must be one of {self.POLICIES}, got {policy!r}"
            )
        self.policy = policy
        self.local_sources = list(local_sources)
        self.downstream = downstream
        self.high = high
        self.low = max(0.0, min(low, high))
        self.block_timeout = block_timeout
        self.block_poll = block_poll
        self.metrics = metrics
        self._overloaded = False

    def level(self) -> float:
        lvl = max((_clamp01(s()) for s in self.local_sources), default=0.0)
        if self.downstream is not None:
            lvl = max(lvl, self.downstream.level())
        return lvl

    def admits(self) -> bool:
        """One hysteresis-filtered admission decision (no policy applied)."""
        lvl = self.level()
        if self._overloaded:
            if lvl <= self.low:
                self._overloaded = False
        elif lvl >= self.high:
            self._overloaded = True
        return not self._overloaded

    async def admit(self) -> None:
        """Gate one client submission according to the policy."""
        if self.policy == "off" or self.admits():
            return
        if self.policy == "block":
            t0 = _now()
            deadline = t0 + self.block_timeout
            while _now() < deadline:
                await asyncio.sleep(self.block_poll)
                if self.admits():
                    if self.metrics is not None:
                        self.metrics.ingest_blocked_seconds.observe(
                            _now() - t0
                        )
                    return
            # Fall through: blocking past the timeout would just move the
            # unbounded queue into the RPC layer — shed instead.
        if self.metrics is not None:
            self.metrics.ingest_shed.inc()
        raise IngestOverloadError(
            f"ingest overloaded (level {self.level():.2f} >= {self.high}); "
            "retry later or lower the offered rate"
        )


class StageTimer:
    """One pipeline stage's latency: `start(key)` stamps, `stop(key)`
    closes the span and observes its duration into the stage's histogram
    child. The pending map is bounded — keys that never stop (certificates
    that never commit, headers GC'd mid-flight) are evicted oldest-first
    instead of leaking.

    The timer is ALSO the span layer's close site (tracing.Tracer): a
    single `close()` both emits the causal span (when tracing is enabled
    and the key samples in) and observes the histogram, so the stage
    histograms are derived from span closes by construction — no double
    bookkeeping, and the equivalence is pinned by test.

    One span window per key: once a key closes, a later `start()` for the
    same key is a no-op (bounded recently-closed latch). Without this, a
    straggler re-propose/re-deliver after the stage already closed mints
    a SECOND, later span for the same key — and if the first span has
    been evicted from the trace ring, the waterfall's earliest-t0 rule
    picks the bogus window, producing causality inversions (a certify
    span that "starts" after its own commit)."""

    def __init__(
        self,
        histogram,  # metrics.Histogram with a ("stage",) label
        stage: str,
        max_pending: int = 8192,
        clock: Callable[[], float] = _now,
        ewma_alpha: float = 0.2,
        tracer=None,  # tracing.Tracer: span sink for this stage's closes
        max_closed: int = 4096,
    ):
        self._child = histogram.labels(stage)
        self._stage = stage
        self._max = max_pending
        self._clock = clock
        self._pending: dict = {}
        self._closed: dict = {}  # insertion-ordered set of closed keys
        self._max_closed = max_closed
        self._tracer = tracer
        # Recent-latency EWMA alongside the histogram: the histogram's
        # sum/count is a lifetime mean, useless as a control signal — the
        # backpressure monitor reads this instead (None until first stop).
        self.ewma: float | None = None
        self._alpha = ewma_alpha

    def start(self, key) -> None:
        pending = self._pending
        if key in pending:
            return  # first sighting wins; re-delivery must not reset t0
        if key in self._closed:
            return  # one span window per key; no re-open after close
        while len(pending) >= self._max:
            pending.pop(next(iter(pending)))
        pending[key] = self._clock()

    def stop(self, key) -> float | None:
        t0 = self._pending.pop(key, None)
        if t0 is None:
            return None
        return self.close(key, t0)

    def _latch_closed(self, key) -> None:
        closed = self._closed
        if key in closed:
            return
        while len(closed) >= self._max_closed:
            closed.pop(next(iter(closed)))
        closed[key] = None

    def close(self, key, t0: float) -> float:
        """Close the stage span opened at t0 for `key`: emit the trace span
        and derive the histogram observation from the same close. Callers
        that learn the key only at the end of the stage (batch seal: the
        digest exists once the batch is sealed) call this directly."""
        self._latch_closed(key)
        t1 = self._clock()
        tracer = self._tracer
        if (
            tracer is not None
            and tracer.enabled
            and isinstance(key, bytes)
            and tracer.sampled(key)
        ):
            tracer.span(self._stage, key, t0, t1)
        self.observe(t1 - t0)
        return t1 - t0

    def observe(self, seconds: float) -> None:
        """Directly record a latency measured elsewhere (same histogram)."""
        self._child.observe(seconds)
        self.ewma = (
            seconds
            if self.ewma is None
            else self.ewma + self._alpha * (seconds - self.ewma)
        )


def backpressure_level(
    occupancies: Iterable[float],
    commit_latency_ewma: float | None,
    seconds_since_commit: float | None,
    latency_target: float,
    high_watermark: float,
) -> float:
    """The admission level a primary pushes to its workers, folding three
    overload signals (the 1-core overload measurements showed why depth
    alone is blind):

    * channel occupancy — catches a *deep* queue (executor lagging
      consensus, a slow app state machine);
    * commit-stage latency vs target — catches *service-time* saturation,
      where rounds take seconds but every channel stays shallow because
      items are huge aggregates (batches, certificates). Scaled so the
      EWMA hitting the target lands exactly on the high watermark;
    * commit stall — under collapse the committee stops committing
      entirely, so there is no fresh EWMA to read: no commit for longer
      than the target pins the level at 1.0 until progress resumes.
    """
    level = max((_clamp01(o) for o in occupancies), default=0.0)
    if latency_target > 0:
        if commit_latency_ewma is not None:
            level = max(
                level, _clamp01(high_watermark * commit_latency_ewma / latency_target)
            )
        if seconds_since_commit is not None and seconds_since_commit > latency_target:
            level = 1.0
    return level
