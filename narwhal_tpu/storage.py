"""Persistent storage: WAL-backed column-family store with notify_read.

The reference persists everything in RocksDB through the typed-store crate:
9 column families opened at /root/reference/node/src/lib.rs:53-123, a generic
Store<K,V> with read/write/remove/notify_read/iter, and a CertificateStore
with a (round, digest) secondary index plus a blocking notify_read pub/sub
(/root/reference/storage/src/certificate_store.rs:28-331) — the primitive all
"waiter" components are built on.

TPU-native design: node state is small (digests, headers, certs — payload
batches are the only bulk data), so we use an in-memory hash table per column
family backed by an append-only write-ahead log for durability. Recovery
replays the WAL; a torn tail record is discarded, giving atomic write_batch.
This trades RocksDB's compaction machinery for zero-dependency simplicity;
`compact()` rewrites the log when garbage exceeds a threshold (GC deletes
from consensus would otherwise grow it unboundedly).

Two interchangeable backends share the byte-identical on-disk format: the
pure-Python engine below, and the native C++ engine (native/
storage_engine.cpp via narwhal_tpu/native.py, the analog of the reference's
RocksDB C++ core). The native one is used when it builds/loads; set
NARWHAL_NATIVE=0 to force Python. The notify_read waiter plane always lives
in Python (it is event-loop state, not storage).
"""

from __future__ import annotations

import asyncio
import os
import struct
import zlib
from typing import Iterable, Iterator

_HDR = struct.Struct("<II")  # payload_len, crc32


class StorageEngine:
    """One per node, holding every column family (the RocksDB instance
    analog). path=None runs purely in memory (tests)."""

    def __init__(self, path: str | None, use_native: bool | None = None):
        self._path = path
        self._cfs: dict[str, "ColumnFamily"] = {}
        self._log = None
        self._cf_ids: dict[str, int] = {}
        self._dirty_bytes = 0
        self._append_count = 0
        self._native = None
        if use_native is None:
            use_native = os.environ.get("NARWHAL_NATIVE", "1") != "0"
        if path is not None:
            os.makedirs(path, exist_ok=True)
        if use_native:
            try:
                from .native import NativeEngine

                self._native = NativeEngine(path)
            except (RuntimeError, OSError):
                self._native = None
        if self._native is not None:
            return
        if path is not None:
            os.makedirs(path, exist_ok=True)
            self._log_path = os.path.join(path, "wal.log")
            self._replay()
            self._log = open(self._log_path, "ab")

    def column_family(self, name: str) -> "ColumnFamily":
        cf = self._cfs.get(name)
        if cf is None:
            cf = ColumnFamily(name, self)
            self._cfs[name] = cf
            self._cf_ids.setdefault(name, len(self._cf_ids))
        return cf

    # -- WAL --------------------------------------------------------------
    def _replay(self) -> None:
        if not os.path.exists(self._log_path):
            return
        with open(self._log_path, "rb") as f:
            data = f.read()
        pos = 0
        valid_end = 0
        while pos + _HDR.size <= len(data):
            plen, crc = _HDR.unpack_from(data, pos)
            body_end = pos + _HDR.size + plen
            if body_end > len(data):
                break
            body = data[pos + _HDR.size : body_end]
            if zlib.crc32(body) != crc:
                break
            self._apply_record(body)
            pos = body_end
            valid_end = pos
        if valid_end < len(data):
            # torn tail: truncate so future appends start at a clean boundary
            with open(self._log_path, "ab") as f:
                f.truncate(valid_end)

    def _apply_record(self, body: bytes) -> None:
        pos = 0
        (count,) = struct.unpack_from("<I", body, pos)
        pos += 4
        for _ in range(count):
            op, name_len = struct.unpack_from("<BH", body, pos)
            pos += 3
            name = body[pos : pos + name_len].decode()
            pos += name_len
            (klen,) = struct.unpack_from("<I", body, pos)
            pos += 4
            key = body[pos : pos + klen]
            pos += klen
            cf = self.column_family(name)
            if op == 0:
                (vlen,) = struct.unpack_from("<I", body, pos)
                pos += 4
                value = body[pos : pos + vlen]
                pos += vlen
                cf._data[key] = value
            else:
                cf._data.pop(key, None)

    def _append(self, ops: list[tuple[int, str, bytes, bytes]]) -> None:
        if self._log is None:
            return
        body = self._encode_ops(ops)
        self._log.write(_HDR.pack(len(body), zlib.crc32(body)) + body)
        self._log.flush()
        self._dirty_bytes += len(body)
        self._append_count += 1
        # Compaction check is amortized: only every 4096 appends, and only
        # once the log is large, do we pay for a live-size scan.
        if self._dirty_bytes > (64 << 20) and self._append_count % 4096 == 0:
            if self._dirty_bytes > 2 * self._live_size_estimate():
                self.compact()

    def _live_size_estimate(self) -> int:
        return sum(
            sum(len(k) + len(v) for k, v in cf._data.items())
            for cf in self._cfs.values()
        )

    def compact(self) -> None:
        """Rewrite the WAL with only live entries."""
        if self._log is None:
            return
        tmp = self._log_path + ".tmp"
        with open(tmp, "wb") as f:
            for cf in self._cfs.values():
                for key, value in cf._data.items():
                    nb = cf.name.encode()
                    body = (
                        struct.pack("<I", 1)
                        + struct.pack("<BH", 0, len(nb))
                        + nb
                        + struct.pack("<I", len(key))
                        + key
                        + struct.pack("<I", len(value))
                        + value
                    )
                    f.write(_HDR.pack(len(body), zlib.crc32(body)) + body)
        self._log.close()
        os.replace(tmp, self._log_path)
        self._log = open(self._log_path, "ab")
        self._dirty_bytes = self._live_size_estimate()

    @staticmethod
    def _encode_ops(ops: list[tuple[int, str, bytes, bytes]]) -> bytes:
        parts = [struct.pack("<I", len(ops))]
        for op, name, key, value in ops:
            nb = name.encode()
            parts.append(struct.pack("<BH", op, len(nb)))
            parts.append(nb)
            parts.append(struct.pack("<I", len(key)))
            parts.append(key)
            if op == 0:
                parts.append(struct.pack("<I", len(value)))
                parts.append(value)
        return b"".join(parts)

    def write_batch(self, puts: list[tuple["ColumnFamily", bytes, bytes]], deletes: list[tuple["ColumnFamily", bytes]] = ()) -> None:
        """Atomic multi-CF write (reference: rocksdb WriteBatch used by
        CertificateStore.write, storage/src/certificate_store.rs:55-120)."""
        ops = [(0, cf.name, key, value) for cf, key, value in puts]
        ops += [(1, cf.name, key, b"") for cf, key in deletes]
        if self._native is not None:
            self._native.write_batch(self._encode_ops(ops))
        else:
            for cf, key, value in puts:
                cf._data[key] = value
            for cf, key in deletes:
                cf._data.pop(key, None)
            self._append(ops)
        for cf, key, value in puts:
            cf._notify(key, value)

    def close(self) -> None:
        if self._log is not None:
            self._log.close()
            self._log = None
        if self._native is not None:
            self._native.close()
            self._native = None


class ColumnFamily:
    """Generic byte KV map with notify_read
    (typed-store Store<K,V> analog)."""

    def __init__(self, name: str, engine: StorageEngine):
        self.name = name
        self._engine = engine
        self._native = engine._native  # shared handle; None => dict backend
        self._nname = name.encode()
        self._data: dict[bytes, bytes] = {}
        self._waiters: dict[bytes, list[asyncio.Future]] = {}

    # -- sync ops ---------------------------------------------------------
    def put(self, key: bytes, value: bytes) -> None:
        self._engine.write_batch([(self, key, value)])

    def put_all(self, items: Iterable[tuple[bytes, bytes]]) -> None:
        self._engine.write_batch([(self, k, v) for k, v in items])

    def get(self, key: bytes) -> bytes | None:
        if self._native is not None:
            return self._native.get(self._nname, key)
        return self._data.get(key)

    def get_all(self, keys: Iterable[bytes]) -> list[bytes | None]:
        return [self.get(k) for k in keys]

    def contains(self, key: bytes) -> bool:
        if self._native is not None:
            return self._native.contains(self._nname, key)
        return key in self._data

    def delete(self, key: bytes) -> None:
        self._engine.write_batch([], [(self, key)])

    def delete_all(self, keys: Iterable[bytes]) -> None:
        self._engine.write_batch([], [(self, k) for k in keys])

    def iter(self) -> Iterator[tuple[bytes, bytes]]:
        if self._native is not None:
            return iter(self._native.items(self._nname))
        return iter(list(self._data.items()))

    def keys(self) -> list[bytes]:
        if self._native is not None:
            return [k for k, _ in self._native.items(self._nname)]
        return list(self._data)

    def __len__(self) -> int:
        if self._native is not None:
            return self._native.len(self._nname)
        return len(self._data)

    # -- notify_read ------------------------------------------------------
    async def notify_read(self, key: bytes) -> bytes:
        """Return the value, blocking until someone writes it
        (storage/src/certificate_store.rs:138-160). Cancellation-safe: a
        cancelled waiter is pruned on the next notify."""
        val = self.get(key)
        if val is not None:
            return val
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._waiters.setdefault(key, []).append(fut)
        try:
            return await fut
        finally:
            lst = self._waiters.get(key)
            if lst is not None:
                try:
                    lst.remove(fut)
                except ValueError:
                    pass
                if not lst:
                    self._waiters.pop(key, None)

    def _notify(self, key: bytes, value: bytes) -> None:
        for fut in self._waiters.pop(key, []):
            if not fut.done():
                fut.set_result(value)
