"""Persistent storage: WAL-backed column-family store with notify_read.

The reference persists everything in RocksDB through the typed-store crate:
9 column families opened at /root/reference/node/src/lib.rs:53-123, a generic
Store<K,V> with read/write/remove/notify_read/iter, and a CertificateStore
with a (round, digest) secondary index plus a blocking notify_read pub/sub
(/root/reference/storage/src/certificate_store.rs:28-331) — the primitive all
"waiter" components are built on.

TPU-native design: node state is small (digests, headers, certs — payload
batches are the only bulk data), so we use an in-memory hash table per column
family backed by an append-only write-ahead log for durability. Recovery
replays the WAL; a torn tail record is discarded, giving atomic write_batch.
This trades RocksDB's compaction machinery for zero-dependency simplicity;
`compact()` rewrites the log when garbage exceeds a threshold (GC deletes
from consensus would otherwise grow it unboundedly).

Two interchangeable backends share the byte-identical on-disk format: the
pure-Python engine below, and the native C++ engine (native/
storage_engine.cpp via narwhal_tpu/native.py, the analog of the reference's
RocksDB C++ core). The native one is used when it builds/loads; set
NARWHAL_NATIVE=0 to force Python. The notify_read waiter plane always lives
in Python (it is event-loop state, not storage).

Group commit: the async write API (`ColumnFamily.put_async`,
`StorageEngine.write_batch_async`) coalesces every write enqueued while a
flush is in flight into ONE fused WAL record with ONE flush — the RocksDB
WAL group-commit discipline. Callers get the shared commit future of their
group; on the pure-Python backend the memtable (and notify_read waiters)
see the write immediately, so only durability waits for the group. A torn
tail of a fused record discards the WHOLE group on replay — group commits
are crash-atomic exactly like `write_batch`. The sync API keeps its
seed semantics (append + flush before returning) for tests and replay
tooling; when a group is pending, a sync write first persists the group's
ops ahead of its own so WAL order always matches memtable apply order.
"""

from __future__ import annotations

import asyncio
import os
import struct
import threading
import time
import zlib
from typing import Iterable, Iterator

_HDR = struct.Struct("<II")  # payload_len, crc32


class StorageStats:
    """Process-wide group-commit counters (the WireStats analog for the
    storage plane): every fused group committed by every engine in this
    process. The benchmark harness samples `snapshot()` around its window
    to report ops-per-flush — the quantity group commit exists to move."""

    groups_committed = 0
    ops_committed = 0
    max_group_ops = 0
    flush_seconds_total = 0.0

    @classmethod
    def record_group(cls, ops: int, flush_seconds: float) -> None:
        cls.groups_committed += 1
        cls.ops_committed += ops
        if ops > cls.max_group_ops:
            cls.max_group_ops = ops
        cls.flush_seconds_total += flush_seconds

    @classmethod
    def snapshot(cls) -> dict:
        return {
            "groups_committed": cls.groups_committed,
            "ops_committed": cls.ops_committed,
            "max_group_ops": cls.max_group_ops,
            "flush_seconds_total": round(cls.flush_seconds_total, 6),
        }


class _CommitGroup:
    """One pending fused commit: ops accumulate until the committer drains
    the group; every enqueuer shares `future` (resolved after the single
    flush)."""

    __slots__ = ("future", "ops", "notifies")

    def __init__(self, loop: asyncio.AbstractEventLoop):
        self.future: asyncio.Future = loop.create_future()
        self.ops: list[tuple[int, str, bytes, bytes]] = []
        # Native backend only: puts applied (and notified) at commit time.
        self.notifies: list[tuple] = []


class StorageEngine:
    """One per node, holding every column family (the RocksDB instance
    analog). path=None runs purely in memory (tests)."""

    def __init__(
        self,
        path: str | None,
        use_native: bool | None = None,
        fsync: bool | None = None,
    ):
        self._path = path
        # Durability level of a WAL flush. Default (seed semantics):
        # flush() drains the userspace buffer to the OS — survives process
        # crash. fsync=True (or NARWHAL_WAL_FSYNC=1) adds os.fsync — survives
        # machine crash; ~1000x more expensive per call, which is exactly
        # the cost group commit amortizes (one fsync per fused group).
        if fsync is None:
            fsync = os.environ.get("NARWHAL_WAL_FSYNC", "0") == "1"
        self._fsync = fsync
        self._cfs: dict[str, "ColumnFamily"] = {}
        self._log = None
        self._cf_ids: dict[str, int] = {}
        self._dirty_bytes = 0
        self._append_count = 0
        self._native = None
        # Group-commit state: the open group, the committer draining it,
        # and the loop they belong to (a test's fresh loop must not await a
        # future created on a dead one).
        self._group: _CommitGroup | None = None
        self._commit_task: asyncio.Task | None = None
        self._commit_loop: asyncio.AbstractEventLoop | None = None
        # Serializes flush/compact across the loop thread and the
        # committer's executor thread (compact swaps the file object out
        # from under an in-flight flush otherwise).
        self._io_lock = threading.Lock()
        # Optional Prometheus instruments (attach_metrics).
        self._m_group_size = None
        self._m_flush_seconds = None
        if use_native is None:
            use_native = os.environ.get("NARWHAL_NATIVE", "1") != "0"
        if path is not None:
            os.makedirs(path, exist_ok=True)
        if use_native:
            try:
                from .native import NativeEngine

                self._native = NativeEngine(path)
            except (RuntimeError, OSError):
                self._native = None
        if self._native is not None:
            return
        if path is not None:
            os.makedirs(path, exist_ok=True)
            self._log_path = os.path.join(path, "wal.log")
            self._replay()
            self._log = open(self._log_path, "ab")

    def column_family(self, name: str) -> "ColumnFamily":
        cf = self._cfs.get(name)
        if cf is None:
            cf = ColumnFamily(name, self)
            self._cfs[name] = cf
            self._cf_ids.setdefault(name, len(self._cf_ids))
        return cf

    # -- WAL --------------------------------------------------------------
    def _replay(self) -> None:
        if not os.path.exists(self._log_path):
            return
        with open(self._log_path, "rb") as f:
            data = f.read()
        pos = 0
        valid_end = 0
        while pos + _HDR.size <= len(data):
            plen, crc = _HDR.unpack_from(data, pos)
            body_end = pos + _HDR.size + plen
            if body_end > len(data):
                break
            body = data[pos + _HDR.size : body_end]
            if zlib.crc32(body) != crc:
                break
            self._apply_record(body)
            pos = body_end
            valid_end = pos
        if valid_end < len(data):
            # torn tail: truncate so future appends start at a clean boundary
            with open(self._log_path, "ab") as f:
                f.truncate(valid_end)

    def _apply_record(self, body: bytes) -> None:
        pos = 0
        (count,) = struct.unpack_from("<I", body, pos)
        pos += 4
        for _ in range(count):
            op, name_len = struct.unpack_from("<BH", body, pos)
            pos += 3
            name = body[pos : pos + name_len].decode()
            pos += name_len
            (klen,) = struct.unpack_from("<I", body, pos)
            pos += 4
            key = body[pos : pos + klen]
            pos += klen
            cf = self.column_family(name)
            if op == 0:
                (vlen,) = struct.unpack_from("<I", body, pos)
                pos += 4
                value = body[pos : pos + vlen]
                pos += vlen
                cf._data[key] = value
            else:
                cf._data.pop(key, None)

    def _append(self, ops: list[tuple[int, str, bytes, bytes]]) -> None:
        if self._log is None:
            return
        self._append_body(self._encode_ops(ops))
        self._flush_log()

    def _append_body(self, body: bytes) -> None:
        """Buffered append of one record WITHOUT flushing (the flush is the
        syscall group commit amortizes)."""
        self._log.write(_HDR.pack(len(body), zlib.crc32(body)) + body)
        self._dirty_bytes += len(body)
        self._append_count += 1
        # Compaction check is amortized: only every 4096 appends, and only
        # once the log is large, do we pay for a live-size scan.
        if self._dirty_bytes > (64 << 20) and self._append_count % 4096 == 0:
            if self._dirty_bytes > 2 * self._live_size_estimate():
                self.compact()

    def _flush_log(self) -> None:
        """Flush the WAL buffer (plus fsync at the machine-crash durability
        level); safe from the committer's executor thread (compact() swaps
        the file object under the same lock)."""
        with self._io_lock:
            if self._log is not None:
                self._log.flush()
                if self._fsync:
                    os.fsync(self._log.fileno())

    # -- group commit ------------------------------------------------------
    def write_batch_async(
        self,
        puts: list[tuple["ColumnFamily", bytes, bytes]],
        deletes: list[tuple["ColumnFamily", bytes]] = (),
    ) -> asyncio.Future:
        """Group-commit variant of `write_batch`: enqueue the ops onto the
        current commit group and return the group's shared commit future
        (resolved once the fused WAL record is flushed — off the event
        loop). On the Python backend the memtable applies (and notify_read
        waiters fire) immediately, so readers never wait on durability; the
        native backend applies at commit. Requires a running event loop."""
        loop = asyncio.get_running_loop()
        puts = list(puts)
        deletes = list(deletes)
        if self._native is None:
            for cf, key, value in puts:
                cf._data[key] = value
            for cf, key in deletes:
                cf._data.pop(key, None)
            for cf, key, value in puts:
                cf._notify(key, value)
            if self._log is None:  # in-memory: trivially "durable"
                fut = loop.create_future()
                fut.set_result(None)
                return fut
        ops = [(0, cf.name, key, value) for cf, key, value in puts]
        ops += [(1, cf.name, key, b"") for cf, key in deletes]
        grp = self._group
        if grp is None or self._commit_loop is not loop:
            grp = self._group = _CommitGroup(loop)
        grp.ops.extend(ops)
        if self._native is not None:
            grp.notifies.extend(puts)
        if (
            self._commit_task is None
            or self._commit_task.done()
            or self._commit_loop is not loop
        ):
            self._commit_loop = loop
            self._commit_task = loop.create_task(self._run_committer())
        return grp.future

    async def _run_committer(self) -> None:
        """Drain commit groups one fused record + one flush at a time.
        While a flush runs in the executor the loop is free, so writes
        issued meanwhile pile into the NEXT group — coalescing deepens
        exactly when the WAL is busiest (group commit's core property)."""
        loop = asyncio.get_running_loop()
        while self._group is not None and self._group.ops:
            grp, self._group = self._group, None
            n_ops = len(grp.ops)
            t0 = time.perf_counter()
            try:
                if self._native is not None:
                    body = self._encode_ops(grp.ops)
                    # ctypes releases the GIL: append+flush runs truly off
                    # the loop.
                    await loop.run_in_executor(
                        None, self._native.write_batch, body
                    )
                    for cf, key, value in grp.notifies:
                        cf._notify(key, value)
                else:
                    # Encode+buffered-append on the loop (cheap memcpy,
                    # keeps WAL order loop-ordered); only the flush — the
                    # syscall — leaves the loop.
                    self._append_body(self._encode_ops(grp.ops))
                    await loop.run_in_executor(None, self._flush_log)
            except Exception as e:
                if not grp.future.done():
                    grp.future.set_exception(e)
                continue
            dt = time.perf_counter() - t0
            StorageStats.record_group(n_ops, dt)
            if self._m_group_size is not None:
                self._m_group_size.observe(n_ops)
                self._m_flush_seconds.observe(dt)
            if not grp.future.done():
                grp.future.set_result(None)

    def attach_metrics(self, registry) -> None:
        """Register the group-commit instruments on a node's registry
        (group size / WAL flush latency histograms)."""
        self._m_group_size = registry.histogram(
            "storage_commit_group_size",
            "ops per fused group-commit WAL record",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512),
        )
        self._m_flush_seconds = registry.histogram(
            "storage_wal_flush_seconds",
            "wall seconds per group-commit WAL flush",
        )

    def _live_size_estimate(self) -> int:
        return sum(
            sum(len(k) + len(v) for k, v in cf._data.items())
            for cf in self._cfs.values()
        )

    def compact(self) -> None:
        """Rewrite the WAL with only live entries."""
        if self._log is None:
            return
        tmp = self._log_path + ".tmp"
        with open(tmp, "wb") as f:
            for cf in self._cfs.values():
                for key, value in cf._data.items():
                    nb = cf.name.encode()
                    body = (
                        struct.pack("<I", 1)
                        + struct.pack("<BH", 0, len(nb))
                        + nb
                        + struct.pack("<I", len(key))
                        + key
                        + struct.pack("<I", len(value))
                        + value
                    )
                    f.write(_HDR.pack(len(body), zlib.crc32(body)) + body)
        with self._io_lock:  # an executor flush must not race the swap
            self._log.close()
            os.replace(tmp, self._log_path)
            self._log = open(self._log_path, "ab")
        self._dirty_bytes = self._live_size_estimate()

    @staticmethod
    def _encode_ops(ops: list[tuple[int, str, bytes, bytes]]) -> bytes:
        parts = [struct.pack("<I", len(ops))]
        for op, name, key, value in ops:
            nb = name.encode()
            parts.append(struct.pack("<BH", op, len(nb)))
            parts.append(nb)
            parts.append(struct.pack("<I", len(key)))
            parts.append(key)
            if op == 0:
                parts.append(struct.pack("<I", len(value)))
                parts.append(value)
        return b"".join(parts)

    def write_batch(self, puts: list[tuple["ColumnFamily", bytes, bytes]], deletes: list[tuple["ColumnFamily", bytes]] = ()) -> None:
        """Atomic multi-CF write (reference: rocksdb WriteBatch used by
        CertificateStore.write, storage/src/certificate_store.rs:55-120).
        Synchronous seed semantics: durable (appended + flushed) before
        returning. A pending commit group is persisted FIRST so the WAL
        record order always matches the memtable apply order."""
        self._drain_pending_group_sync()
        ops = [(0, cf.name, key, value) for cf, key, value in puts]
        ops += [(1, cf.name, key, b"") for cf, key in deletes]
        if self._native is not None:
            self._native.write_batch(self._encode_ops(ops))
        else:
            for cf, key, value in puts:
                cf._data[key] = value
            for cf, key in deletes:
                cf._data.pop(key, None)
            self._append(ops)
        for cf, key, value in puts:
            cf._notify(key, value)

    def _drain_pending_group_sync(self) -> None:
        """Persist + resolve the open commit group inline (loop-thread
        callers only — sync writes and close())."""
        grp, self._group = self._group, None
        if grp is None or not grp.ops:
            return
        if self._native is not None:
            self._native.write_batch(self._encode_ops(grp.ops))
            for cf, key, value in grp.notifies:
                cf._notify(key, value)
        elif self._log is not None:
            self._append_body(self._encode_ops(grp.ops))
            self._flush_log()
        StorageStats.record_group(len(grp.ops), 0.0)
        if self._m_group_size is not None:
            self._m_group_size.observe(len(grp.ops))
        if not grp.future.done():
            grp.future.set_result(None)

    def close(self) -> None:
        # A group still open at shutdown (already visible in the memtable)
        # must not silently lose its WAL record: persist it inline.
        self._drain_pending_group_sync()
        if self._commit_task is not None and not self._commit_task.done():
            self._commit_task.cancel()
        self._commit_task = None
        with self._io_lock:
            if self._log is not None:
                self._log.close()
                self._log = None
        if self._native is not None:
            self._native.close()
            self._native = None


class ColumnFamily:
    """Generic byte KV map with notify_read
    (typed-store Store<K,V> analog)."""

    def __init__(self, name: str, engine: StorageEngine):
        self.name = name
        self._engine = engine
        self._native = engine._native  # shared handle; None => dict backend
        self._nname = name.encode()
        self._data: dict[bytes, bytes] = {}
        self._waiters: dict[bytes, list[asyncio.Future]] = {}

    # -- sync ops ---------------------------------------------------------
    def put(self, key: bytes, value: bytes) -> None:
        self._engine.write_batch([(self, key, value)])

    def put_all(self, items: Iterable[tuple[bytes, bytes]]) -> None:
        self._engine.write_batch([(self, k, v) for k, v in items])

    # -- group-commit (async) ops -----------------------------------------
    def put_async(self, key: bytes, value: bytes) -> asyncio.Future:
        """Enqueue onto the engine's commit group; returns the shared
        commit future (await it for durability — the memtable already sees
        the write on the Python backend)."""
        return self._engine.write_batch_async([(self, key, value)])

    def put_all_async(self, items: Iterable[tuple[bytes, bytes]]) -> asyncio.Future:
        return self._engine.write_batch_async([(self, k, v) for k, v in items])

    def get(self, key: bytes) -> bytes | None:
        if self._native is not None:
            return self._native.get(self._nname, key)
        return self._data.get(key)

    def get_all(self, keys: Iterable[bytes]) -> list[bytes | None]:
        return [self.get(k) for k in keys]

    def contains(self, key: bytes) -> bool:
        if self._native is not None:
            return self._native.contains(self._nname, key)
        return key in self._data

    def delete(self, key: bytes) -> None:
        self._engine.write_batch([], [(self, key)])

    def delete_all(self, keys: Iterable[bytes]) -> None:
        self._engine.write_batch([], [(self, k) for k in keys])

    def iter(self) -> Iterator[tuple[bytes, bytes]]:
        if self._native is not None:
            return iter(self._native.items(self._nname))
        return iter(list(self._data.items()))

    def keys(self) -> list[bytes]:
        if self._native is not None:
            return [k for k, _ in self._native.items(self._nname)]
        return list(self._data)

    def __len__(self) -> int:
        if self._native is not None:
            return self._native.len(self._nname)
        return len(self._data)

    # -- notify_read ------------------------------------------------------
    async def notify_read(self, key: bytes) -> bytes:
        """Return the value, blocking until someone writes it
        (storage/src/certificate_store.rs:138-160). Cancellation-safe: a
        cancelled waiter is pruned on the next notify."""
        val = self.get(key)
        if val is not None:
            return val
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._waiters.setdefault(key, []).append(fut)
        try:
            return await fut
        finally:
            lst = self._waiters.get(key)
            if lst is not None:
                try:
                    lst.remove(fut)
                except ValueError:
                    pass
                if not lst:
                    # Register/await/cleanup idiom: each waiter removes
                    # only its own future, and the empty-list pop re-checks
                    # the CURRENT list after the await — a waiter that
                    # registered at the yield point repopulates the key.
                    self._waiters.pop(key, None)  # lint: allow(await-interleaved-rmw)

    def _notify(self, key: bytes, value: bytes) -> None:
        for fut in self._waiters.pop(key, []):
            if not fut.done():
                fut.set_result(value)
