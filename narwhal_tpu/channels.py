"""Metered bounded channels + bounded future pools — the actor plumbing.

Reference: metered mpsc channels whose depth is a prometheus gauge
(/root/reference/types/src/metered_channel.rs:15-259) and semaphore-bounded
future queues (/root/reference/types/src/bounded_future_queue.rs:17-156).
Every inter-actor edge in the primary/worker is one of these
(primary/src/primary.rs:104-151 creates 16+).
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, Generic, TypeVar

from .metrics import Gauge

T = TypeVar("T")

DEFAULT_CHANNEL_CAPACITY = 1_000


def metered_channel(registry, role: str, name: str, capacity: int) -> "Channel":
    """A Channel with its depth gauge registered as
    `<role>_channel_<name>_depth` (SURVEY §5.6: every inter-task channel is
    a gauge; types/src/metered_channel.rs:15-259). The single naming seam
    for node/primary/worker channel metrics."""
    return Channel(
        capacity,
        gauge=registry.gauge(
            f"{role}_channel_{name}_depth",
            f"depth of the {role}'s {name} channel",
        ),
    )


class Channel(Generic[T]):
    """Bounded mpsc with a depth gauge."""

    def __init__(self, capacity: int = DEFAULT_CHANNEL_CAPACITY, gauge: Gauge | None = None):
        self._q: asyncio.Queue[T] = asyncio.Queue(maxsize=capacity)
        self._capacity = max(1, capacity)
        self._gauge = gauge

    @property
    def capacity(self) -> int:
        return self._capacity

    def depth(self) -> int:
        """Items currently queued — the observability hook the pacing
        controller and backpressure monitor read (alongside the per-channel
        depth gauge metered_channel registers)."""
        return self._q.qsize()

    def occupancy(self) -> float:
        """depth/capacity in [0, 1]: the unit every pacing/admission
        watermark is expressed in, so channels of different capacities feed
        one controller without per-channel scaling."""
        return self._q.qsize() / self._capacity

    async def send(self, item: T) -> None:
        await self._q.put(item)
        if self._gauge:
            self._gauge.set(self._q.qsize())

    def try_send(self, item: T) -> bool:
        try:
            self._q.put_nowait(item)
        except asyncio.QueueFull:
            return False
        if self._gauge:
            self._gauge.set(self._q.qsize())
        return True

    async def send_many(self, items) -> None:
        """Enqueue a burst with at most one suspension point per full queue:
        items slot in via put_nowait while capacity lasts and only block
        when the queue is actually full, and the depth gauge updates once
        per burst instead of once per item. The executor's batch drain uses
        this so applying a staged batch costs zero per-transaction channel
        hops."""
        for item in items:
            try:
                self._q.put_nowait(item)
            except asyncio.QueueFull:
                if self._gauge:
                    self._gauge.set(self._q.qsize())
                await self._q.put(item)
        if self._gauge:
            self._gauge.set(self._q.qsize())

    async def recv(self) -> T:
        item = await self._q.get()
        if self._gauge:
            self._gauge.set(self._q.qsize())
        return item

    def try_recv(self) -> T | None:
        try:
            item = self._q.get_nowait()
        except asyncio.QueueEmpty:
            return None
        if self._gauge:
            self._gauge.set(self._q.qsize())
        return item

    def qsize(self) -> int:
        return self._q.qsize()

    def empty(self) -> bool:
        return self._q.empty()


class Watch(Generic[T]):
    """Single-value broadcast channel with change notification — tokio's
    watch, used for the reconfigure signal observed by every actor's select
    loop (see §3.5 of SURVEY; state_handler.rs:100-172)."""

    def __init__(self, initial: T):
        self._value = initial
        self._version = 0
        self._event = asyncio.Event()

    @property
    def value(self) -> T:
        return self._value

    @property
    def version(self) -> int:
        return self._version

    def send(self, value: T) -> None:
        self._value = value
        self._version += 1
        self._event.set()
        self._event = asyncio.Event()

    async def changed(self, seen_version: int) -> tuple[T, int]:
        """Wait until the version advances past seen_version; returns
        (value, version)."""
        while self._version <= seen_version:
            event = self._event
            await event.wait()
        return self._value, self._version


class Subscriber(Generic[T]):
    """Cursor over a Watch for select-loop style consumption."""

    def __init__(self, watch: Watch[T]):
        self._watch = watch
        self._seen = watch.version

    async def changed(self) -> T:
        value, self._seen = await self._watch.changed(self._seen)
        return value

    def peek(self) -> T:
        return self._watch.value


class BoundedExecutor:
    """Caps concurrent spawned tasks per peer
    (/root/reference/network/src/bounded_executor.rs:46-153)."""

    def __init__(self, capacity: int):
        self._sem = asyncio.Semaphore(capacity)
        self._tasks: set[asyncio.Task] = set()

    async def spawn(self, coro: Awaitable) -> asyncio.Task:
        await self._sem.acquire()
        return self._track(coro)

    def try_spawn(self, coro) -> asyncio.Task | None:
        if self._sem.locked():
            # asyncio.Semaphore has no try_acquire; locked() means value==0
            if isinstance(coro, Awaitable):
                asyncio.ensure_future(coro).cancel()
            return None
        # non-blocking acquire: value > 0 so this cannot suspend
        self._sem._value -= 1  # noqa: SLF001 - mirrored from Semaphore.acquire fast path
        return self._track(coro)

    def _track(self, coro) -> asyncio.Task:
        task = asyncio.ensure_future(coro)
        self._tasks.add(task)

        def _done(t: asyncio.Task) -> None:
            self._tasks.discard(t)
            self._sem.release()
            if not t.cancelled() and t.exception() is not None:
                pass  # swallowed like the reference's detached tasks

        task.add_done_callback(_done)
        return task

    async def shutdown(self) -> None:
        for t in list(self._tasks):
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)


class BoundedFuturesOrdered:
    """Semaphore-bounded ordered future pool
    (/root/reference/types/src/bounded_future_queue.rs): push blocks when full,
    results pop in push order."""

    def __init__(self, capacity: int):
        self._capacity = capacity
        self._queue: asyncio.Queue[asyncio.Task] = asyncio.Queue(maxsize=capacity)
        self._live: set[asyncio.Task] = set()

    async def push(self, coro: Awaitable) -> None:
        task = asyncio.ensure_future(coro)
        self._live.add(task)
        task.add_done_callback(self._live.discard)
        await self._queue.put(task)

    async def next(self):
        task = await self._queue.get()
        return await task

    def cancel_all(self) -> None:
        """Cancel every pushed future that has not completed yet; the pool
        owner must call this on teardown or in-flight work outlives it."""
        for task in list(self._live):
            task.cancel()

    def qsize(self) -> int:
        return self._queue.qsize()


async def drain_cancelled(tasks, timeout: float = 10.0, who: str = "") -> None:
    """Await already-cancelled tasks with a deadline. A task that ignores
    its cancellation (e.g. parked on a cancel-immune executor handoff) must
    not wedge shutdown forever — the reference aborts its tokio tasks and
    moves on; we warn and abandon. asyncio.wait neither re-cancels nor
    blocks past the timeout."""
    import logging

    live = [t for t in tasks if not t.done()]
    if not live:
        return
    _, stuck = await asyncio.wait(live, timeout=timeout)
    if stuck:
        logging.getLogger("narwhal.channels").warning(
            "%s shutdown: abandoning %d task(s) that ignored cancellation",
            who or "task",
            len(stuck),
        )


class CancelOnDrop:
    """Handle whose destruction cancels the underlying task
    (/root/reference/network/src/lib.rs:27-47)."""

    def __init__(self, task: asyncio.Task):
        self.task = task

    def cancel(self) -> None:
        self.task.cancel()

    def __await__(self):
        return self.task.__await__()
