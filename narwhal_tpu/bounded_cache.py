"""One bounded-cache primitive for the process-wide hot-path caches.

Three subsystems keep insertion-order (FIFO-evicted) caches: decoded wire
messages (`messages.decode_message`, byte-budgeted), verified signatures
(`crypto.verify`, entry-bounded) and decoded store objects
(`stores.CertificateStore`/`HeaderStore`, entry-bounded). They share this
implementation so the eviction logic — and its THREAD-SAFETY — lives in
one place: `crypto.verify` runs on executor threads (AsyncVerifierPool
dispatches `_host_batch_verify` via run_in_executor), where two concurrent
evictions over a plain dict double-delete keys and raise KeyError.
"""

from __future__ import annotations

import threading


class BoundedCache:
    """Thread-safe insertion-order cache with FIFO eviction.

    `max_entries` bounds the number of keys; `max_bytes` (with per-put
    `weight`) bounds a byte budget — either or both may be set. Eviction
    drops the oldest entries until the new item fits. Values must be
    immutable/shared-safe: a `get` returns the same object to every
    caller.
    """

    __slots__ = ("_map", "_weights", "_lock", "_max_entries", "_max_bytes", "_bytes")

    def __init__(self, max_entries: int = 0, max_bytes: int = 0):
        if not max_entries and not max_bytes:
            raise ValueError("BoundedCache needs max_entries and/or max_bytes")
        self._map: dict = {}
        self._weights: dict = {}
        self._lock = threading.Lock()
        self._max_entries = max_entries
        self._max_bytes = max_bytes
        self._bytes = 0

    def get(self, key):
        with self._lock:
            return self._map.get(key)

    def put(self, key, value, weight: int = 0) -> None:
        with self._lock:
            if key in self._map:
                return  # deterministic values: first write wins
            if self._max_bytes and weight > self._max_bytes:
                # An entry that cannot fit even in an empty cache must not
                # be admitted — evicting the whole working set for it would
                # both blow the byte budget and trash every warm entry.
                return
            while self._map and (
                (self._max_entries and len(self._map) >= self._max_entries)
                or (self._max_bytes and self._bytes + weight > self._max_bytes)
            ):
                old = next(iter(self._map))  # FIFO: oldest insertion
                del self._map[old]
                self._bytes -= self._weights.pop(old, 0)
            self._map[key] = value
            if weight:
                self._weights[key] = weight
                self._bytes += weight

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._map

    @property
    def total_bytes(self) -> int:
        return self._bytes
