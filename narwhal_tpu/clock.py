"""The injected clock — every actor's single source of elapsed time.

Actors (primary/, worker/, consensus/, executor/, network/) must never read
the wall clock directly (`time.time()`, `time.monotonic()`, `loop.time()`):
under the simnet harness (narwhal_tpu/simnet) the whole committee runs on a
virtual-clock event loop whose `loop.time()` is simulated time, and a single
stray `time.monotonic()` would mix wall time into pacing deadlines, retry
backoffs and stage latencies — silently breaking both the determinism and
the zero-wall-clock-wait property of simulated scenarios. The
`no-wall-clock-in-actors` lint rule enforces the discipline; this module is
the sanctioned read path.

`now()` returns the running event loop's time (monotonic seconds; virtual
under simnet, `time.monotonic()`-based otherwise) and falls back to
`time.monotonic()` off-loop, so synchronous construction-time stamps keep
working in plain scripts and tests.
"""

from __future__ import annotations

import asyncio
import time


def now() -> float:
    """Monotonic seconds on the actor clock: the running loop's time when
    inside a loop (virtual under simnet), else `time.monotonic()`."""
    try:
        return asyncio.get_running_loop().time()
    except RuntimeError:
        return time.monotonic()
