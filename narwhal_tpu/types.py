"""Wire types: Batch, Header, Vote, Certificate and inter-role messages.

Reference data model: /root/reference/types/src/primary.rs:32-789 (Batch :32-73,
Header :75-256, Vote :258-384, Certificate :386-644, message enums :646-789)
and /root/reference/types/src/worker.rs:17-62.

TPU-first deltas from the reference:
  * Certificates carry an ed25519 signature *vector* + signer index list
    instead of one aggregate BLS signature + roaring bitmap (see crypto.py for
    the rationale); verification is a batch verify over the vote digests —
    the exact shape the TPU verifier consumes.
  * All digests are SHA-256 of the canonical codec encoding (crypto.digest256;
    the reference uses blake2b-256 — see the rationale there), so the
    reference's `serialized_batch_digest` zero-copy optimization
    (/root/reference/types/src/worker.rs:44-62) holds by construction: hashing
    the wire bytes IS hashing the batch.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from functools import cached_property
from types import MappingProxyType
from typing import Iterable, Mapping

from .bounded_cache import BoundedCache
from .codec import CodecError, Reader, Writer
from .crypto import DIGEST_LEN, PUBLIC_KEY_LEN, SIGNATURE_LEN, digest256, verify

Digest = bytes  # 32 bytes
PublicKey = bytes  # 32 bytes
WorkerId = int
Round = int
Epoch = int


class DagError(Exception):
    """Protocol-level rejection, mirroring /root/reference/types/src/error.rs:46-93."""


class InvalidEpoch(DagError):
    pass


class TooOld(DagError):
    pass


class InvalidSignatureError(DagError):
    pass


class QuorumNotReached(DagError):
    pass


class UnknownWorker(DagError):
    pass


# ---------------------------------------------------------------------------
# Batch
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Batch:
    """A list of opaque transactions (/root/reference/types/src/primary.rs:32-73)."""

    transactions: tuple[bytes, ...]

    def encode(self, w: Writer) -> None:
        w.seq(self.transactions, lambda w_, t: w_.bytes(t))

    @staticmethod
    def decode(r: Reader) -> "Batch":
        return Batch(tuple(r.seq(lambda r_: r_.bytes())))

    def to_bytes(self) -> bytes:
        w = Writer()
        self.encode(w)
        return w.finish()

    @staticmethod
    def from_bytes(data: bytes) -> "Batch":
        r = Reader(data)
        b = Batch.decode(r)
        r.done()
        return b

    @cached_property
    def digest(self) -> Digest:
        return digest256(self.to_bytes())

    @property
    def size_bytes(self) -> int:
        return sum(len(t) for t in self.transactions)


def serialized_batch_digest(wire_bytes: bytes) -> Digest:
    """Digest a serialized batch without deserializing it — the worker receive
    path optimization (/root/reference/types/src/worker.rs:44-62). Valid
    because Batch.digest hashes exactly the canonical wire encoding."""
    return digest256(wire_bytes)


_U32 = struct.Struct("<I")


def validate_tx_frames(frames: bytes, count: int) -> None:
    """Structurally validate a chunk of `count` length-prefixed transactions
    (the body of a client burst / batch, minus its leading count word).

    Client bursts flow through batching and dissemination in wire form — this
    walk (two unpacks per tx, no copies) is the only per-transaction work the
    trusted path does, and it keeps a malformed client chunk from ever
    reaching a sealed batch (where it would poison executor decode
    committee-wide)."""
    pos, end = 0, len(frames)
    unpack = _U32.unpack_from
    for _ in range(count):
        if pos + 4 > end:
            raise CodecError("truncated transaction chunk")
        (n,) = unpack(frames, pos)
        pos += 4 + n
        if pos > end:
            raise CodecError("transaction overruns chunk")
    if pos != end:
        raise CodecError("trailing bytes in transaction chunk")


def assemble_serialized_batch(count: int, frame_parts: list[bytes]) -> bytes:
    """Concatenate validated tx chunks into a canonical serialized Batch:
    u32 count | per-tx (u32 len | bytes). Identical bytes to
    Batch(txs).to_bytes() — the seal path never touches individual
    transactions."""
    return _U32.pack(count) + b"".join(frame_parts)


def iter_serialized_batch_txs(wire_bytes: bytes):
    """Yield (offset, length) of each transaction inside a serialized batch
    without copying — the benchmark sample scan."""
    (count,) = _U32.unpack_from(wire_bytes, 0)
    pos = 4
    unpack = _U32.unpack_from
    for _ in range(count):
        (n,) = unpack(wire_bytes, pos)
        pos += 4
        yield pos, n
        pos += n


@dataclass(frozen=True)
class SealedBatch:
    """A sealed batch in wire form: what the worker pipeline actually moves.

    The reference's BatchMaker hands `Batch` values around and re-serializes
    at each edge; here the serialized form is the value (sealed once, hashed
    once, broadcast as-is) and `Batch` is only materialized where individual
    transactions are needed (the executor)."""

    serialized: bytes
    count: int

    @cached_property
    def digest(self) -> Digest:
        return digest256(self.serialized)

    @property
    def size_bytes(self) -> int:
        # Payload bytes excluding the count word and per-tx length prefixes.
        return len(self.serialized) - 4 - 4 * self.count

    @cached_property
    def transactions(self) -> tuple[bytes, ...]:
        return Batch.from_bytes(self.serialized).transactions


# ---------------------------------------------------------------------------
# Header
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Header:
    """A round-r proposal (/root/reference/types/src/primary.rs:75-256).

    payload maps BatchDigest -> WorkerId; parents are certificate digests of
    round r-1. The digest covers everything but the signature; the signature
    covers the digest.
    """

    author: PublicKey
    round: Round
    epoch: Epoch
    payload: Mapping[Digest, WorkerId]
    parents: frozenset[Digest]
    signature: bytes = b""

    def _encode_core(self, w: Writer) -> None:
        w.raw(self.author)
        w.u64(self.round)
        w.u64(self.epoch)
        w.sorted_map(
            dict(self.payload),
            lambda w_, k: w_.raw(k),
            lambda w_, v: w_.u32(v),
        )
        w.seq(sorted(self.parents), lambda w_, p: w_.raw(p))

    @cached_property
    def digest(self) -> Digest:
        w = Writer()
        self._encode_core(w)
        return digest256(w.finish())

    def encode(self, w: Writer) -> None:
        self._encode_core(w)
        w.bytes(self.signature)

    @staticmethod
    def decode(r: Reader) -> "Header":
        author = r.raw(PUBLIC_KEY_LEN)
        rnd = r.u64()
        epoch = r.u64()
        # Decoded headers are shared process-wide by the decode caches
        # (messages._DECODE_CACHE and the store caches): every hosted node
        # sees the SAME object, so the payload must be read-only — one
        # node writing through it would corrupt every other node's view
        # (ADVICE r5 medium). MappingProxyType keeps dict-speed reads.
        payload = MappingProxyType(
            r.map(lambda r_: r_.raw(DIGEST_LEN), lambda r_: r_.u32())
        )
        parents = frozenset(r.seq(lambda r_: r_.raw(DIGEST_LEN)))
        signature = r.bytes()
        return Header(author, rnd, epoch, payload, parents, signature)

    def to_bytes(self) -> bytes:
        w = Writer()
        self.encode(w)
        return w.finish()

    @staticmethod
    def from_bytes(data: bytes) -> "Header":
        r = Reader(data)
        h = Header.decode(r)
        r.done()
        return h

    @staticmethod
    def build(
        author: PublicKey,
        round: Round,
        epoch: Epoch,
        payload: Mapping[Digest, WorkerId],
        parents: Iterable[Digest],
        signer,
    ) -> "Header":
        """Reference Header::new signs via the SignatureService
        (/root/reference/types/src/primary.rs:130-148).

        The payload is canonicalized (sorted by batch digest) at construction
        so local iteration order matches the wire encoding (Writer.sorted_map)
        — executors on every node, including the author and its post-crash
        replay, walk batches in the same order."""
        canonical = MappingProxyType(dict(sorted(payload.items())))
        h = Header(author, round, epoch, canonical, frozenset(parents))
        return Header(
            author, round, epoch, canonical, frozenset(parents), signer.sign(h.digest)
        )

    def verify(self, committee, worker_cache, check_signature: bool = True) -> None:
        """Mirrors Header::verify (/root/reference/types/src/primary.rs:180-233):
        epoch, authority known + has stake, worker ids valid, signature.
        `check_signature=False` runs only the structural checks — callers
        batching signatures elsewhere (the TPU verification stage) use it
        together with `signature_item()`."""
        if self.epoch != committee.epoch:
            raise InvalidEpoch(f"header epoch {self.epoch} != {committee.epoch}")
        if committee.stake(self.author) == 0:
            raise DagError(f"unknown authority {self.author.hex()[:16]}")
        for digest, worker_id in self.payload.items():
            if not worker_cache.has_worker(self.author, worker_id):
                raise UnknownWorker(f"worker {worker_id} not in cache")
        if check_signature and not verify(self.author, self.digest, self.signature):
            raise InvalidSignatureError("bad header signature")

    def signature_item(self) -> tuple[bytes, bytes, bytes]:
        """(pubkey, message, signature) for batch verification."""
        return (self.author, self.digest, self.signature)


# ---------------------------------------------------------------------------
# Vote
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Vote:
    """A signed endorsement of a header
    (/root/reference/types/src/primary.rs:258-384). origin = header author,
    author = the voter."""

    header_digest: Digest
    round: Round
    epoch: Epoch
    origin: PublicKey
    author: PublicKey
    signature: bytes = b""

    def _encode_core(self, w: Writer) -> None:
        w.raw(self.header_digest)
        w.u64(self.round)
        w.u64(self.epoch)
        w.raw(self.origin)
        w.raw(self.author)

    @cached_property
    def digest(self) -> Digest:
        w = Writer()
        self._encode_core(w)
        return digest256(w.finish())

    def encode(self, w: Writer) -> None:
        self._encode_core(w)
        w.bytes(self.signature)

    @staticmethod
    def decode(r: Reader) -> "Vote":
        return Vote(
            r.raw(DIGEST_LEN),
            r.u64(),
            r.u64(),
            r.raw(PUBLIC_KEY_LEN),
            r.raw(PUBLIC_KEY_LEN),
            r.bytes(),
        )

    @staticmethod
    def for_header(header: "Header", author: PublicKey, signer) -> "Vote":
        v = Vote(header.digest, header.round, header.epoch, header.author, author)
        return Vote(
            v.header_digest, v.round, v.epoch, v.origin, v.author, signer.sign(v.digest)
        )

    def verify(self, committee, check_signature: bool = True) -> None:
        """Vote::verify (/root/reference/types/src/primary.rs:344-371)."""
        if self.epoch != committee.epoch:
            raise InvalidEpoch(f"vote epoch {self.epoch} != {committee.epoch}")
        if committee.stake(self.author) == 0:
            raise DagError(f"unknown voter {self.author.hex()[:16]}")
        if check_signature and not verify(self.author, self.digest, self.signature):
            raise InvalidSignatureError("bad vote signature")

    def signature_item(self) -> tuple[bytes, bytes, bytes]:
        """(pubkey, message, signature) for batch verification."""
        return (self.author, self.digest, self.signature)


def vote_digest(
    header_digest: Digest, round: Round, epoch: Epoch, origin: PublicKey, author: PublicKey
) -> Digest:
    """Digest a vote without constructing it — used by certificate batch
    verification to rebuild each signer's signed message."""
    w = Writer()
    w.raw(header_digest)
    w.u64(round)
    w.u64(epoch)
    w.raw(origin)
    w.raw(author)
    return digest256(w.finish())


# ---------------------------------------------------------------------------
# Certificate
# ---------------------------------------------------------------------------

# Domain separator for the half-aggregation Fiat-Shamir weights. Versioned:
# changing anything about the transcript encoding must change this tag.
_AGG_DOMAIN = b"narwhal-tpu-halfagg-v1"


def aggregate_weights(
    header_digest: Digest, signers: tuple[int, ...], rs: tuple[bytes, ...]
) -> list[int]:
    """128-bit Fiat-Shamir weights z_i for certificate half-aggregation,
    bound to the whole transcript (header digest, signer set, every nonce
    point R_i). Deterministic, so verifier and aggregator agree; transcript-
    bound, so an adversary cannot craft per-signature errors that cancel —
    the soundness argument of Schnorr/EdDSA half-aggregation (Chalkias,
    Garillot, Kondi, Nikolaenko: "Non-interactive half-aggregation of EdDSA
    and variants", public construction; original implementation)."""
    import hashlib

    w = Writer()
    w.raw(_AGG_DOMAIN)
    w.raw(header_digest)
    w.seq(signers, lambda w_, i: w_.u32(i))
    w.seq(rs, lambda w_, r: w_.raw(r))
    base = hashlib.sha512(w.finish()).digest()
    return [
        int.from_bytes(
            hashlib.sha512(base + i.to_bytes(4, "little")).digest()[:16], "little"
        )
        for i in range(len(signers))
    ]


def host_verify_aggregate(
    items: list[tuple[bytes, bytes, bytes]], zs: list[int], agg_s: int
) -> bool:
    """Per-item host (pure-Python) check of ONE half-aggregated certificate:
    [8]([agg_s]B - sum([z_i k_i]A_i) - sum([z_i]R_i)) == identity, with
    k_i = SHA512(R_i || A_i || m_i) mod L. Cofactored, matching the device
    msm rule. Deliberately naive (~one double-and-add scalar-mul per term):
    this is the readable reference the batched verifier below is tested
    against, and the authoritative last-resort fallback of the device
    group lane (tpu/verifier.collect_groups). Production host paths go
    through `host_batch_verify_aggregates`, which amortizes one
    bucket-method MSM across many certificates."""
    from .tpu import ed25519_ref as ref

    acc = ref.IDENTITY
    for (pk, msg, r_bytes), z in zip(items, zs):
        a = ref.decompress(pk)
        r = ref.decompress(r_bytes)
        if a is None or r is None:
            return False
        k = ref.sha512_mod_l(r_bytes, pk, msg)
        acc = ref.point_add(acc, ref.point_mul(z * k % ref.L, a))
        acc = ref.point_add(acc, ref.point_mul(z % ref.L, r))
    acc = ref.point_add(ref.point_mul(agg_s % ref.L, ref.G), ref.point_neg(acc))
    for _ in range(3):  # cofactor 8
        acc = ref.point_double(acc)
    return ref.point_equal(acc, ref.IDENTITY)


# One aggregate-verification group, the unit `Certificate.aggregate_group`
# produces: ([(pubkey, message, R_i)], fiat-shamir weights z_i, agg scalar).
AggregateGroup = tuple[list[tuple[bytes, bytes, bytes]], list[int], int]


def _msm(terms: list[tuple[int, tuple]]):
    """Multi-scalar multiplication sum([s_i]P_i) over the ed25519_ref group
    via the bucket (Pippenger) method: per c-bit window, points land in
    2^c - 1 buckets (one add each) and the buckets collapse with ~2^(c+1)
    adds, so the per-point cost is ~ceil(253/c) adds instead of a full
    double-and-add ladder — the amortization that makes the host batched
    compact-verify path fast. Scalars must be reduced mod L."""
    from .tpu import ed25519_ref as ref

    n = len(terms)
    if n == 0:
        return ref.IDENTITY
    # Window width minimizing the add count: ceil(253/c) windows each cost
    # ~n bucket adds + ~2^(c+1) collapse adds.
    c = min(range(3, 13), key=lambda w: -(-253 // w) * (n + (1 << (w + 1))))
    mask = (1 << c) - 1
    nwin = -(-253 // c)  # scalars < L < 2^253
    point_add, point_double = ref.point_add, ref.point_double
    acc = ref.IDENTITY
    for w in range(nwin - 1, -1, -1):
        for _ in range(c):
            acc = point_double(acc)
        shift = w * c
        buckets: list = [None] * (1 << c)
        for s, p in terms:
            d = (s >> shift) & mask
            if d:
                b = buckets[d]
                buckets[d] = p if b is None else point_add(b, p)
        running = None
        total = None
        for d in range(mask, 0, -1):
            b = buckets[d]
            if b is not None:
                running = b if running is None else point_add(running, b)
            if running is not None:
                total = running if total is None else point_add(total, running)
        if total is not None:
            acc = point_add(acc, total)
    return acc


# Decompressed-point cache for signer public keys: a committee is a handful
# of keys whose points recur in EVERY certificate forever, and decompression
# (one ~255-bit pow) is the floor of the batched proof check. R nonce points
# are fresh per signature and never cached.
_PK_POINT_CACHE = BoundedCache(max_entries=1 << 12)


def _decompress_pk(pk: bytes):
    from .tpu import ed25519_ref as ref

    pt = _PK_POINT_CACHE.get(pk)
    if pt is None:
        pt = ref.decompress(pk)
        _PK_POINT_CACHE.put(pk, pt if pt is not None else False)
    return None if pt is False else pt


def _group_msm_terms(
    items: list[tuple[bytes, bytes, bytes]], zs: list[int]
) -> list[tuple[bytes, int, tuple]] | None:
    """The MSM terms of one group's -sum([z_i k_i]A_i) - sum([z_i]R_i)
    (negated so the verification sum targets the identity) as
    (point-identity key, scalar, point) triples, or None when any point
    fails to decompress — the same rejection `host_verify_aggregate`
    applies. The key (the compressed encoding) lets the combined batch
    check accumulate scalars per DISTINCT point: signer keys repeat in
    every certificate of a flush, so a batch of G groups over a quorum of
    Q signers carries ~Q + G*Q distinct points, not 2*G*Q."""
    from .tpu import ed25519_ref as ref

    terms: list[tuple[bytes, int, tuple]] = []
    for (pk, msg, r_bytes), z in zip(items, zs):
        a = _decompress_pk(pk)
        r = ref.decompress(r_bytes)
        if a is None or r is None:
            return None
        k = ref.sha512_mod_l(r_bytes, pk, msg)
        terms.append((pk, -(z * k), a))
        terms.append((r_bytes, -z, r))
    return terms


def _cofactored_identity(point) -> bool:
    """[8]point == identity (extended coordinates: X = 0 and Y = Z)."""
    from .tpu import ed25519_ref as ref

    for _ in range(3):
        point = ref.point_double(point)
    return point[0] % ref.P == 0 and (point[1] - point[2]) % ref.P == 0


def _verify_group_msm(
    items: list[tuple[bytes, bytes, bytes]], zs: list[int], agg_s: int
) -> bool:
    """Deterministic single-group check via one MSM — the exact equation of
    `host_verify_aggregate` (bit-equal verdicts, asserted by tests), ~4x
    faster, and the bisect step of the batched verifier below."""
    from .tpu import ed25519_ref as ref

    rows = _group_msm_terms(items, zs)
    if rows is None:
        return False
    terms = [(s % ref.L, p) for _, s, p in rows]
    terms.append((agg_s % ref.L, ref.G))
    return _cofactored_identity(_msm(terms))


# Aggregate-verdict cache: a compact certificate's proof check is a pure
# deterministic function of (items, zs, agg_s), and in a multi-node-per-host
# process EVERY hosted node verifies the same broadcast proof — the exact
# dedup the per-item _VERIFY_CACHE exploits for full signatures (the N=50
# profile: verification overwhelmingly duplicates). Keyed by a digest of the
# whole group transcript; thread-safe (verification runs on executor
# threads).
_AGG_VERDICT_CACHE = BoundedCache(max_entries=1 << 15)


# Entropy seam for the batched verifier's outer combination weights.
# Production draws from os.urandom (the adversary must not predict the
# weights); simnet's seeded scenarios install a deterministic stream so a
# replayed run performs bit-identical group arithmetic — same contract as
# `network.auth.set_entropy` for handshake nonces. The weights never
# influence VERDICTS (a failed combined check bisects deterministically),
# so this seam is about reproducible execution, not correctness.
def _default_weight_entropy(n: int) -> bytes:
    import os

    # This IS the seam's production default: seeded scenarios replace it
    # via set_weight_entropy; everything else must draw through it.
    return os.urandom(n)  # lint: allow(raw-entropy)


_weight_entropy = _default_weight_entropy


def set_weight_entropy(fn) -> "object":
    """Install an entropy source for the batch verifier's outer weights;
    returns the previous source so callers can restore it (pass None to
    reset to os.urandom)."""
    global _weight_entropy
    prev = _weight_entropy
    _weight_entropy = fn if fn is not None else _default_weight_entropy
    return prev


def _aggregate_cache_key(
    items: list[tuple[bytes, bytes, bytes]], zs: list[int], agg_s: int
) -> bytes:
    import hashlib

    h = hashlib.sha256()
    for pk, msg, r in items:
        h.update(pk)
        h.update(msg)
        h.update(r)
    for z in zs:
        h.update(z.to_bytes(16, "little"))
    h.update((agg_s % (1 << 256)).to_bytes(32, "little"))
    return h.digest()


def host_batch_verify_aggregates(groups: list[AggregateGroup]) -> list[bool]:
    """Batched cofactored verification of half-aggregated certificate
    proofs on the host — the randomized-linear-combination batch rule the
    device msm lane runs, in pure Python over ONE bucket-method MSM:

      [8]( [sum_g w_g s_g]B - sum_g w_g (sum_i [z_i k_i]A_i + [z_i]R_i) )
        == identity

    with a fresh 128-bit outer weight w_g per group per call (os.urandom —
    the adversary must not predict them, so adversarially related groups
    cannot cancel each other). One MSM serves every group in the dispatch,
    so the per-signature cost falls with batch size (>=5x the per-item
    `host_verify_aggregate` at batch >= 32 — benchmark/microbench.py
    --compact-verify).

    Verdicts are verdict-equivalent to per-item cofactored verification and
    DETERMINISTIC despite the random weights: a failed combined check
    bisects to the deterministic single-group MSM (the same equation
    `host_verify_aggregate` evaluates), so no group's fate ever depends on
    its batch-mates — one adversarial certificate costs its own group a
    solo check, never the honest groups' acceptance (the r4-advisor
    amplification rule, host edition). Groups with undecodable points are
    rejected before the combined dispatch. Results are memoized in the
    process-wide aggregate-verdict cache."""
    from .tpu import ed25519_ref as ref

    ok = [False] * len(groups)
    pending: list[tuple[int, list[tuple[bytes, int, tuple]], int, bytes]] = []
    for g, (items, zs, s_agg) in enumerate(groups):
        key = _aggregate_cache_key(items, zs, s_agg)
        hit = _AGG_VERDICT_CACHE.get(key)
        if hit is not None:
            ok[g] = hit
            continue
        rows = _group_msm_terms(items, zs)
        if rows is None:
            _AGG_VERDICT_CACHE.put(key, False)
            continue
        pending.append((g, rows, s_agg, key))

    if not pending:
        return ok
    if len(pending) > 1:
        # Accumulate scalars per DISTINCT point across every group: the
        # signer keys A_i recur in every certificate of the flush, so the
        # combined MSM carries each committee key once with the summed
        # (w_g z_i k_i) scalar — cutting the term count nearly in half at
        # quorum scale (sound under the random linear combination: scalars
        # on one point are additive).
        by_point: dict[bytes, list] = {}
        sum_s = 0
        for _, rows, s_agg, _key in pending:
            w = int.from_bytes(_weight_entropy(16), "little")
            sum_s += w * s_agg
            for pkey, s, p in rows:
                entry = by_point.get(pkey)
                if entry is None:
                    by_point[pkey] = [w * s, p]
                else:
                    entry[0] += w * s
        combined = [(s % ref.L, p) for s, p in by_point.values()]
        combined.append((sum_s % ref.L, ref.G))
        if _cofactored_identity(_msm(combined)):
            for g, _rows, _s, key in pending:
                ok[g] = True
                _AGG_VERDICT_CACHE.put(key, True)
            return ok
    # Single group, or the combined check failed: deterministic per-group
    # verdicts (same equation, no outer weights).
    for g, rows, s_agg, key in pending:
        terms = [(s % ref.L, p) for _, s, p in rows]
        terms.append((s_agg % ref.L, ref.G))
        verdict = _cofactored_identity(_msm(terms))
        ok[g] = verdict
        _AGG_VERDICT_CACHE.put(key, verdict)
    return ok


@dataclass(frozen=True)
class Certificate:
    """A header plus a quorum of votes
    (/root/reference/types/src/primary.rs:386-644). The reference stores one
    aggregate BLS signature + a roaring bitmap of signers; we store the signer
    committee-indices (sorted) and the matching ed25519 vote signatures —
    batch-verifiable in one TPU call. The certificate digest depends only on
    the header (as in the reference), so certificates assembled from different
    vote subsets dedup to the same identity.

    Two wire forms (the `agg_s` field discriminates):

    - FULL: `signatures[i]` is signer i's 64-byte ed25519 vote signature.
    - COMPACT (half-aggregated, Parameters.cert_format="compact"): the
      per-vote scalars s_i are collapsed into one 32-byte `agg_s` =
      sum(z_i * s_i) mod L under Fiat-Shamir weights z_i bound to the whole
      transcript (aggregate_weights), and `signatures[i]` keeps only the
      32-byte R_i nonce point. This is Schnorr/EdDSA half-aggregation: the
      proof shrinks from 64 to ~32 bytes per signer — the capability the
      reference gets from BLS aggregation (O(1) certs,
      /root/reference/crypto/src/bls12377/mod.rs:45-120), recovered
      TPU-first: the verification equation
        [8]([agg_s]B - sum([z_i k_i]A_i) - sum([z_i]R_i)) == identity
      is EXACTLY the random-linear-combination shape the msm batch kernel
      computes, so devices verify compact certificates natively (and many
      of them fused in one dispatch under an outer random combination)."""

    header: Header
    signers: tuple[int, ...] = ()
    signatures: tuple[bytes, ...] = ()
    agg_s: bytes = b""

    @property
    def is_compact(self) -> bool:
        return len(self.agg_s) == 32

    @property
    def round(self) -> Round:
        return self.header.round

    @property
    def epoch(self) -> Epoch:
        return self.header.epoch

    @property
    def origin(self) -> PublicKey:
        return self.header.author

    @cached_property
    def digest(self) -> Digest:
        w = Writer()
        w.raw(b"CERT")
        w.raw(self.header.digest)
        return digest256(w.finish())

    def encode(self, w: Writer) -> None:
        self.header.encode(w)
        w.seq(self.signers, lambda w_, i: w_.u32(i))
        if self.is_compact:
            w.u8(1)
            w.seq(self.signatures, lambda w_, s: w_.raw(s))  # 32B R_i each
            w.raw(self.agg_s)
        else:
            w.u8(0)
            w.seq(self.signatures, lambda w_, s: w_.raw(s))

    @staticmethod
    def decode(r: Reader) -> "Certificate":
        header = Header.decode(r)
        signers = tuple(r.seq(lambda r_: r_.u32()))
        form = r.u8()
        if form == 1:
            rs = tuple(r.seq(lambda r_: r_.raw(32)))
            agg_s = r.raw(32)
            return Certificate(header, signers, rs, agg_s)
        if form != 0:
            raise CodecError(f"unknown certificate form {form}")
        sigs = tuple(r.seq(lambda r_: r_.raw(SIGNATURE_LEN)))
        return Certificate(header, signers, sigs)

    def to_bytes(self) -> bytes:
        w = Writer()
        self.encode(w)
        return w.finish()

    @staticmethod
    def from_bytes(data: bytes) -> "Certificate":
        r = Reader(data)
        c = Certificate.decode(r)
        r.done()
        return c

    @staticmethod
    def genesis(committee) -> list["Certificate"]:
        """One empty certificate per authority at round 0
        (/root/reference/types/src/primary.rs:402-420)."""
        return [
            Certificate(
                Header(author=pk, round=0, epoch=committee.epoch, payload={}, parents=frozenset())
            )
            for pk in committee.authorities
        ]

    def is_genesis(self) -> bool:
        return self.round == 0

    def _signer_checks(self, committee) -> tuple[bytes, ...] | None:
        """Shared structural checks: epoch, genesis well-formedness, arity,
        duplicate signers, index range, quorum stake. Returns the signer
        public keys in order (None for genesis)."""
        if self.epoch != committee.epoch:
            raise InvalidEpoch(f"certificate epoch {self.epoch} != {committee.epoch}")
        if self.is_genesis():
            if self not in Certificate.genesis(committee):
                raise DagError("malformed genesis certificate")
            return None
        if len(self.signers) != len(self.signatures):
            raise DagError("signer/signature arity mismatch")
        # Duplicate/range validation and the O(N) key+stake walk are
        # memoized per (committee, signer tuple): in the relay fan-out the
        # same certificate reaches every member N-1 times and each copy
        # used to re-pay the walk (a top-3 term of the N=200 wall).
        try:
            pks, stake = committee.signer_group(self.signers)
        except ValueError as e:
            raise DagError(str(e)) from e
        if stake < committee.quorum_threshold():
            raise QuorumNotReached(
                f"certificate carries {stake} stake < quorum {committee.quorum_threshold()}"
            )
        return pks

    def structural_verify(self, committee) -> None:
        """Only the structural/stake checks (epoch, arity, duplicate
        signers, quorum) — for callers whose signatures were already
        batch-verified elsewhere (the Core's preverified path). Works for
        both wire forms without recomputing messages or Fiat-Shamir
        weights."""
        self._signer_checks(committee)

    def verify_items(self, committee) -> list[tuple[bytes, bytes, bytes]]:
        """Structural checks + return the (pubkey, message, signature) batch
        to verify. Mirrors Certificate::verify
        (/root/reference/types/src/primary.rs:487-537): epoch, quorum stake of
        signers, then the signature check — here a batch of per-voter ed25519
        verifies instead of one aggregate-verify. FULL form only; compact
        certificates expose `aggregate_group` instead."""
        if self.is_compact:
            raise DagError("compact certificate has no per-item signatures")
        pks = self._signer_checks(committee)
        if pks is None:
            return []
        return [
            (
                pk,
                vote_digest(
                    self.header.digest, self.round, self.epoch, self.origin, pk
                ),
                sig,
            )
            for pk, sig in zip(pks, self.signatures)
        ]

    def aggregate_group(
        self, committee
    ) -> tuple[list[tuple[bytes, bytes, bytes]], list[int], int] | None:
        """Structural checks + the half-aggregation verification group:
        ([(pubkey, message, R)], fiat-shamir weights z_i, agg scalar). None
        for genesis. The check to perform is
          [8]([agg_s]B - sum([z_i k_i]A_i) - sum([z_i]R_i)) == identity
        with k_i = SHA512(R_i || A_i || m_i) mod L."""
        if not self.is_compact:
            raise DagError("aggregate_group on a full certificate")
        pks = self._signer_checks(committee)
        if pks is None:
            return None
        zs = aggregate_weights(self.header.digest, self.signers, self.signatures)
        items = [
            (
                pk,
                vote_digest(
                    self.header.digest, self.round, self.epoch, self.origin, pk
                ),
                r,
            )
            for pk, r in zip(pks, self.signatures)
        ]
        return items, zs, int.from_bytes(self.agg_s, "little")

    @staticmethod
    def compact_from_votes(
        header: "Header",
        signers: tuple[int, ...],
        signatures: tuple[bytes, ...],
        committee=None,
    ) -> "Certificate":
        """Half-aggregate a quorum of full 64-byte vote signatures into a
        compact certificate (the assembly-side counterpart of
        `aggregate_group`; Parameters.cert_format="compact").

        When the assembling node passes its `committee`, the aggregate
        verdict is pre-seeded into the process-wide cache IF every
        constituent full signature is already known-valid (a True entry in
        crypto's verified-signature cache — vote receipt verified them, or
        a co-hosted signer seeded them at sign time). That is sound: a
        strictly (cofactorless) valid signature satisfies
        [s_i]B - [k_i]A_i - R_i == identity exactly, so any z-weighted sum
        of valid equations satisfies the cofactored aggregate equation.
        Every co-hosted peer's verify of this certificate then hits the
        cache instead of paying the MSM."""
        from .tpu.ed25519_ref import L

        rs = tuple(sig[:32] for sig in signatures)
        zs = aggregate_weights(header.digest, signers, rs)
        agg = 0
        for z, sig in zip(zs, signatures):
            agg += z * int.from_bytes(sig[32:64], "little")
        cert = Certificate(header, signers, rs, (agg % L).to_bytes(32, "little"))
        if committee is not None:
            cert._seed_aggregate_verdict(committee, signatures)
        return cert

    def aggregate_proof_key(self, committee) -> bytes:
        """Content key for the aggregate-verdict FRONT cache: one hash
        over the certificate's raw proof fields plus the committee's
        memoized transcript digest. The proof verdict is a pure function
        of exactly these inputs (the Fiat-Shamir weights and every vote
        message derive from them), so equal keys mean equal verdicts —
        but unlike `_aggregate_cache_key` this never rebuilds the
        per-signer transcript, so a cache HIT costs O(certificate bytes)
        hashing instead of O(signers) vote-digest/weight recomputation.
        At co-hosting scale that is the difference: every hosted peer
        (and every relay duplicate) of a broadcast pays one flat hash."""
        from .crypto import digest256

        parts = [
            b"narwhal-agg-front-v1",
            committee.transcript_digest(),
            self.header.digest,
            int(self.round).to_bytes(8, "little"),
            int(self.epoch).to_bytes(8, "little"),
            self.origin,
            len(self.signers).to_bytes(4, "little"),
        ]
        parts.extend(int(i).to_bytes(4, "little") for i in self.signers)
        parts.extend(self.signatures)
        parts.append(self.agg_s)
        return digest256(b"".join(parts))

    def cached_aggregate_verdict(self, committee) -> bool | None:
        """Process-wide known verdict for this compact proof under this
        committee, or None. True/False only certify the PROOF MATH —
        callers still run the structural checks (`_signer_checks`) and
        the header's own verification."""
        return _AGG_VERDICT_CACHE.get(self.aggregate_proof_key(committee))

    def record_aggregate_verdict(self, committee, verdict: bool) -> None:
        """Publish a decided proof verdict under the front key (called by
        whoever paid for the MSM: the verifier stage, `verify`, or the
        assembler's seeding path)."""
        _AGG_VERDICT_CACHE.put(self.aggregate_proof_key(committee), bool(verdict))

    def _seed_aggregate_verdict(self, committee, full_signatures) -> None:
        from .crypto import _VERIFY_CACHE

        try:
            group = self.aggregate_group(committee)
        except DagError:
            return
        if group is None:
            return
        items, zs, s_agg = group
        for (pk, msg, _r), sig in zip(items, full_signatures):
            if _VERIFY_CACHE.get((pk, msg, sig)) is not True:
                return
        _AGG_VERDICT_CACHE.put(_aggregate_cache_key(items, zs, s_agg), True)
        self.record_aggregate_verdict(committee, True)

    def verify(self, committee, worker_cache) -> None:
        if self.is_compact:
            verdict = self.cached_aggregate_verdict(committee)
            if verdict is not None:
                # Front-cache hit: the proof math for this exact
                # (certificate content, committee) pair is already decided
                # somewhere in the process. Structural checks and the
                # header's own verification still run — only the
                # per-signer transcript rebuild and the MSM are skipped.
                if self._signer_checks(committee) is None:
                    return
                self.header.verify(committee, worker_cache)
                if not verdict:
                    raise InvalidSignatureError("aggregate certificate proof invalid")
                return
            group = self.aggregate_group(committee)
            if group is None:
                return
            self.header.verify(committee, worker_cache)
            # Single-group dispatch of the batched verifier: same verdict
            # as host_verify_aggregate (deterministic MSM), ~4x cheaper,
            # and shared with every co-hosted node via the process-wide
            # aggregate-verdict cache — the Core's loopback re-verification
            # of block-synchronizer fetches becomes a cache hit.
            ok = host_batch_verify_aggregates([group])[0]
            self.record_aggregate_verdict(committee, ok)
            if not ok:
                raise InvalidSignatureError("aggregate certificate proof invalid")
            return
        items = self.verify_items(committee)
        if not items:
            return
        self.header.verify(committee, worker_cache)
        from .crypto import batch_verify

        if not all(batch_verify(items)):
            raise InvalidSignatureError("certificate vote signature invalid")

    # DAG affiliation (reference: Affiliated for Certificate,
    # /root/reference/types/src/primary.rs:633-644): parents are hash
    # pointers; certificates with empty payload are compressible.
    def parent_digests(self) -> frozenset[Digest]:
        return self.header.parents

    def compressible(self) -> bool:
        return not self.header.payload


# ---------------------------------------------------------------------------
# Consensus output / sequence numbers
# ---------------------------------------------------------------------------

SequenceNumber = int


@dataclass(frozen=True)
class ConsensusOutput:
    """An ordered certificate with its global consensus index
    (/root/reference/types/src/consensus.rs:14-40)."""

    certificate: Certificate
    consensus_index: SequenceNumber


@dataclass(frozen=True)
class ReconfigureNotification:
    """Committee change / shutdown broadcast on the reconfigure watch channel
    (/root/reference/types/src/primary.rs:646-668 ReconfigureNotification).
    kind: 'new_epoch' | 'update_committee' | 'shutdown'."""

    kind: str
    committee: object | None = None
