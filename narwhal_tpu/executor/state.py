"""Execution progress indices — the exactly-once replay cursor.

Reference: /root/reference/executor/src/state.rs:13-64 — ExecutionIndices
{next_certificate_index, next_batch_index, next_transaction_index} persisted
by the application inside handle_consensus_transaction so a crash resumes at
the exact transaction boundary.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..codec import Reader, Writer


@dataclass(frozen=True)
class ExecutionIndices:
    next_certificate_index: int = 0
    next_batch_index: int = 0
    next_transaction_index: int = 0

    def next(
        self, total_batches: int, total_transactions: int
    ) -> "ExecutionIndices":
        """Advance past one transaction (state.rs:30-55): roll batch/
        certificate counters when their last element executes."""
        tx_done = self.next_transaction_index + 1 == total_transactions
        batch_done = tx_done and self.next_batch_index + 1 == total_batches
        return ExecutionIndices(
            next_certificate_index=self.next_certificate_index + (1 if batch_done else 0),
            next_batch_index=0 if batch_done else self.next_batch_index + (1 if tx_done else 0),
            next_transaction_index=0 if tx_done else self.next_transaction_index + 1,
        )

    def check_next_transaction_index(
        self, certificate_index: int, batch_index: int, transaction_index: int
    ) -> bool:
        """True iff (cert, batch, tx) is exactly the next transaction to
        execute (state.rs:57-64)."""
        return (
            certificate_index == self.next_certificate_index
            and batch_index == self.next_batch_index
            and transaction_index == self.next_transaction_index
        )

    def to_bytes(self) -> bytes:
        w = Writer()
        w.u64(self.next_certificate_index)
        w.u64(self.next_batch_index)
        w.u64(self.next_transaction_index)
        return w.finish()

    @staticmethod
    def from_bytes(data: bytes) -> "ExecutionIndices":
        r = Reader(data)
        out = ExecutionIndices(r.u64(), r.u64(), r.u64())
        r.done()
        return out
