"""The execution Core: exactly-once transaction application across crashes.

Reference: /root/reference/executor/src/core.rs:30-260 — for each ordered
certificate, executes its batches transaction by transaction, skipping
anything at or before the persisted ExecutionIndices (crash replay),
distinguishing client errors (bad transaction: skip and advance) from node
errors (halt), and cleaning the temp batch store per certificate.

Batching delta from the reference: a staged batch's transactions drain as
one burst — application results buffer locally and flush to the output
channel with a single `send_many` per batch instead of one awaited channel
hop per transaction. The replay cursor still advances per transaction
(`ExecutionIndices.next` after every applied tx), so the skip-below-watermark
crash-replay semantics are byte-for-byte those of the per-tx loop.
"""

from __future__ import annotations

import asyncio
import logging

from ..channels import Channel
from ..stores import BatchStore
from ..types import Batch, ConsensusOutput
from .state import ExecutionIndices

logger = logging.getLogger("narwhal.executor")


class ExecutionStateError(Exception):
    """Node-level execution failure: halt (core.rs:86-127 node errors)."""


class ClientExecutionError(Exception):
    """Transaction-level failure: skip the transaction and advance."""


class ExecutionState:
    """The application interface (/root/reference/executor/src/lib.rs:47-78).

    Implementations persist ExecutionIndices atomically with their own state
    inside handle_consensus_transaction."""

    async def handle_consensus_transaction(
        self, output: ConsensusOutput, indices: ExecutionIndices, transaction: bytes
    ):
        raise NotImplementedError

    async def load_execution_indices(self) -> ExecutionIndices:
        raise NotImplementedError

    def ask_consensus_write_lock(self) -> bool:
        return False

    def release_consensus_write_lock(self) -> None:
        pass


class ExecutorCore:
    def __init__(
        self,
        execution_state: ExecutionState,
        temp_batch_store: BatchStore,
        rx_subscriber: Channel,  # (output, batches, t_commit) staged
        tx_output: Channel | None = None,  # (outcome, transaction) to the app
        metrics=None,  # ExecutorMetrics (repo-specific progress counters)
    ):
        self.metrics = metrics
        self.execution_state = execution_state
        self.temp_batch_store = temp_batch_store
        self.rx_subscriber = rx_subscriber
        self.tx_output = tx_output
        self.execution_indices = ExecutionIndices()
        self._task: asyncio.Task | None = None

    def spawn(self) -> asyncio.Task:
        self._task = asyncio.ensure_future(self.run())
        return self._task

    async def run(self) -> None:
        self.execution_indices = await self.execution_state.load_execution_indices()
        try:
            while True:
                output, batches, t_commit = await self.rx_subscriber.recv()
                await self.execute_certificate(output, batches)
                if self.metrics is not None and t_commit is not None:
                    # Span-unified close: one call emits both the execute
                    # stage histogram sample and (when tracing) the span
                    # terminating this certificate's waterfall.
                    dt = self.metrics.execute_timer.close(
                        output.certificate.digest, t_commit
                    )
                    self.metrics.commit_to_exec_latency.observe(dt)
        except asyncio.CancelledError:
            raise
        except Exception:
            # Node-level failure (core.rs:86-127): execution halts while the
            # rest of the node keeps running — make that loudly visible.
            logger.critical("execution halted on node error", exc_info=True)
            raise

    async def execute_certificate(
        self, output: ConsensusOutput, batches: dict[bytes, Batch] | None = None
    ) -> None:
        """(core.rs:129-259). `batches` is the subscriber's in-memory staging;
        the temp store is only a fallback (e.g. crash replay paths)."""
        certificate = output.certificate
        # Sorted by batch digest: matches the canonical wire order so every
        # node (author included, before and after a crash) executes batches
        # identically regardless of local dict insertion order.
        payload = sorted(certificate.header.payload.items())
        total_batches = len(payload)
        for batch_index, (digest, _worker_id) in enumerate(payload):
            if batch_index < self.execution_indices.next_batch_index:
                continue  # crash replay: batch already fully executed
            batch = (batches or {}).get(digest)
            if batch is None:
                raw = self.temp_batch_store.read(digest)
                if raw is None:
                    raise ExecutionStateError(
                        f"staged batch {digest.hex()[:16]} missing from temp store"
                    )
                batch = Batch.from_bytes(raw)
            await self._execute_batch(output, batch, total_batches)
        if total_batches == 0:
            # Empty certificate: still advances the certificate cursor.
            self.execution_indices = ExecutionIndices(
                next_certificate_index=self.execution_indices.next_certificate_index + 1
            )
        if self.metrics is not None:
            self.metrics.executed_certificates.inc()
        self.temp_batch_store.delete_all(d for d, _ in payload)

    async def _execute_batch(
        self, output: ConsensusOutput, batch: Batch, total_batches: int
    ) -> None:
        """Burst drain: apply the whole batch in one tight loop, buffering
        (result, transaction) pairs and flushing them with one send_many.
        The cursor advances per applied transaction, so a crash anywhere
        mid-batch replays from exactly the next unapplied transaction —
        and the flush runs in a finally so results applied before a crash
        still reach the output channel exactly once (replay skips them
        below the watermark and never re-emits)."""
        total_transactions = len(batch.transactions)
        outbox: list | None = [] if self.tx_output is not None else None
        try:
            for tx_index, transaction in enumerate(batch.transactions):
                if tx_index < self.execution_indices.next_transaction_index:
                    continue  # crash replay
                next_indices = self.execution_indices.next(
                    total_batches, total_transactions
                )
                try:
                    result = await self.execution_state.handle_consensus_transaction(
                        output, next_indices, transaction
                    )
                    if outbox is not None:
                        outbox.append((result, transaction))
                    if self.metrics is not None:
                        self.metrics.executed_transactions.inc()
                except ClientExecutionError as e:
                    logger.debug("skipping bad transaction: %s", e)
                self.execution_indices = next_indices
        finally:
            if outbox:
                await self.tx_output.send_many(outbox)
