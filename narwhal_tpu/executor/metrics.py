"""Executor progress counters surfaced on the node registry.

The reference's executor/src/metrics.rs carries only channel-depth gauges
(covered here by the node's metered channels); these applied-work counters
and the commit-to-execution data-plane instruments (prefetch hit rate,
fetch RPCs per certificate, payload bytes fetched, commit->exec latency)
are repo-specific additions for operator dashboards and tests."""

from __future__ import annotations

from ..metrics import Registry

# Fetch RPCs issued per committed certificate: the coalesced data plane
# targets <= one per (worker, certificate) group, so the interesting
# resolution is small integer counts, not the latency-shaped defaults.
_RPC_BUCKETS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0)


class ExecutorMetrics:
    def __init__(self, registry: Registry, tracer=None):
        self.tracer = tracer
        self.executed_transactions = registry.counter(
            "executor_executed_transactions",
            "Transactions applied to the execution state",
        )
        self.executed_certificates = registry.counter(
            "executor_executed_certificates",
            "Certificates whose payload finished executing",
        )
        # -- commit-to-execution data plane --------------------------------
        self.prefetch_hits = registry.counter(
            "executor_prefetch_hits",
            "Committed batch digests already resident in the temp batch "
            "store at staging time (payload RTT off the critical path)",
        )
        self.prefetch_misses = registry.counter(
            "executor_prefetch_misses",
            "Committed batch digests that needed a worker fetch at staging",
        )
        self.prefetched_batches = registry.counter(
            "executor_prefetched_batches",
            "Batches speculatively warmed by the prefetcher before commit",
        )
        self.prefetch_resident_bytes = registry.gauge(
            "executor_prefetch_resident_bytes",
            "Bytes of unclaimed speculative payload held against the budget",
        )
        self.prefetch_evicted = registry.counter(
            "executor_prefetch_evicted",
            "Speculative payloads dropped by budget eviction or gc_depth GC",
        )
        self.fetch_rpcs_per_certificate = registry.histogram(
            "executor_fetch_rpcs_per_certificate",
            "Worker fetch RPCs issued to stage one committed certificate",
            buckets=_RPC_BUCKETS,
        )
        self.bytes_fetched = registry.counter(
            "executor_bytes_fetched",
            "Serialized payload bytes pulled from workers at staging time",
        )
        self.commit_to_exec_latency = registry.histogram(
            "executor_commit_to_exec_latency_seconds",
            "Consensus emitting an ordered certificate -> its payload fully "
            "applied to the execution state",
        )
        # Same quantity under the uniform *_stage_latency_seconds family so
        # the whole pipeline (seal -> propose -> certify -> commit ->
        # execute) reads as one labeled histogram set across roles.
        self.stage_latency = registry.histogram(
            "executor_stage_latency_seconds",
            "Per-stage pipeline latency in the executor (stage=execute: "
            "ordered certificate emitted -> payload fully applied)",
            labels=("stage",),
        )
        # Span-unified close site for the execute stage, keyed by the
        # committed certificate digest (the waterfall's terminal edge).
        from ..pacing import StageTimer

        self.execute_timer = StageTimer(
            self.stage_latency, "execute", tracer=tracer
        )
