"""Executor progress counters surfaced on the node registry.

The reference's executor/src/metrics.rs carries only channel-depth gauges
(covered here by the node's metered channels); these applied-work counters
are a repo-specific addition for operator dashboards and tests."""

from __future__ import annotations

from ..metrics import Registry


class ExecutorMetrics:
    def __init__(self, registry: Registry):
        self.executed_transactions = registry.counter(
            "executor_executed_transactions",
            "Transactions applied to the execution state",
        )
        self.executed_certificates = registry.counter(
            "executor_executed_certificates",
            "Certificates whose payload finished executing",
        )
