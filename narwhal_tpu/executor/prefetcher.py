"""Speculative payload prefetcher: warm the executor's temp batch store
rounds before commit.

Narwhal's core property is that consensus orders *digests* while payload
dissemination is off the critical path — but the executor used to start
fetching payload only AFTER the commit, re-serializing that path as
`RTT x batches` of commit latency. Batch digests are already known when a
certificate is *accepted* into the DAG, typically rounds before Bullshark
commits it; this actor subscribes to that accepted-certificate stream (a
non-blocking tap off the consensus runner's ingest) and pulls the payload in
the background with the same coalesced RequestBatchesMsg the subscriber
uses. At commit time the subscriber's store read is then usually a local hit
and payload RTT leaves the commit->execution path entirely.

Speculation is bounded two ways (BoundedCache-style exact accounting):

* a byte budget — unclaimed speculative payload never holds more than
  `budget_bytes` of the temp store; over budget, the oldest unclaimed entry
  is evicted (the subscriber transparently falls back to the coalesced
  fetch on a miss, so eviction can cost a round trip but never correctness);
* `gc_depth` — payload of a certificate that never commits (e.g. its branch
  lost) is deleted once the accepted round-front moves `gc_depth` rounds
  past it, exactly the DAG's own garbage horizon.

`claim()` is the ownership handoff: at commit the subscriber claims the
certificate's digests, removing them from this actor's accounting so budget
eviction and GC can never delete a committed-but-unexecuted payload out from
under the core (the core deletes them itself after applying).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Iterable

from ..channels import Channel
from ..config import WorkerCache
from ..messages import RequestBatchesMsg, RequestedBatchesMsg
from ..network import NetworkClient, RpcError
from ..stores import BatchStore
from ..types import Batch, Certificate, PublicKey, serialized_batch_digest

logger = logging.getLogger("narwhal.executor")

DEFAULT_PREFETCH_BUDGET = 64 << 20  # bytes of unclaimed speculative payload
# Speculative fetches are best-effort: a bounded number of quick attempts,
# never the subscriber's infinite retry — a miss costs a fetch at commit
# time, nothing more.
PREFETCH_ATTEMPTS = 2
PREFETCH_TIMEOUT = 5.0
PREFETCH_RETRY_DELAY = 0.2
# How many accepted certificates to drain per wakeup: a round's worth of
# acceptances shares RPCs (one per worker) instead of one wakeup each.
MAX_BURST = 64


class Prefetcher:
    def __init__(
        self,
        name: PublicKey,
        worker_cache: WorkerCache,
        network: NetworkClient,
        temp_batch_store: BatchStore,
        rx_accepted: Channel,  # Certificate, tapped off consensus ingest
        gc_depth: int = 50,
        budget_bytes: int = DEFAULT_PREFETCH_BUDGET,
        metrics=None,  # ExecutorMetrics
        attempts: int = PREFETCH_ATTEMPTS,
        fetch_timeout: float = PREFETCH_TIMEOUT,
        retry_delay: float = PREFETCH_RETRY_DELAY,
    ):
        self.name = name
        self.worker_cache = worker_cache
        self.network = network
        self.temp_batch_store = temp_batch_store
        self.rx_accepted = rx_accepted
        self.gc_depth = gc_depth
        self.budget_bytes = budget_bytes
        self.metrics = metrics
        self.attempts = attempts
        self.fetch_timeout = fetch_timeout
        self.retry_delay = retry_delay
        # digest -> (round, bytes); dict order IS the FIFO eviction order.
        self._entries: dict[bytes, tuple[int, int]] = {}
        self._bytes = 0
        self._inflight: set[bytes] = set()
        self._front_round = 0  # highest accepted round seen
        self._task: asyncio.Task | None = None

    def spawn(self) -> asyncio.Task:
        self._task = asyncio.ensure_future(self.run())
        return self._task

    # -- accounting --------------------------------------------------------

    @property
    def resident_bytes(self) -> int:
        return self._bytes

    def claim(self, digests: Iterable[bytes]) -> None:
        """Commit-time ownership handoff (called by the Subscriber): the
        execution path owns these digests now — stop accounting for them so
        eviction/GC can never drop a committed-but-unexecuted payload."""
        for d in digests:
            entry = self._entries.pop(d, None)
            if entry is not None:
                self._bytes -= entry[1]
            self._inflight.discard(d)
        self._update_gauge()

    def _admit(self, digest: bytes, round: int, size: int) -> None:
        self._entries[digest] = (round, size)
        self._bytes += size
        while self._bytes > self.budget_bytes and self._entries:
            self._evict(next(iter(self._entries)))  # FIFO: oldest unclaimed

    def _evict(self, digest: bytes) -> None:
        round_, size = self._entries.pop(digest)
        self._bytes -= size
        self.temp_batch_store.delete_all([digest])
        if self.metrics is not None:
            self.metrics.prefetch_evicted.inc()

    def _gc(self) -> None:
        """Drop speculative payload of certificates that never committed
        once the accepted front is gc_depth rounds past them — the same
        horizon the DAG itself garbage-collects at."""
        if self._front_round <= self.gc_depth:
            return
        horizon = self._front_round - self.gc_depth
        for d in [d for d, (r, _) in self._entries.items() if r <= horizon]:
            self._evict(d)

    def _update_gauge(self) -> None:
        if self.metrics is not None:
            self.metrics.prefetch_resident_bytes.set(self._bytes)

    # -- the actor ---------------------------------------------------------

    async def run(self) -> None:
        while True:
            certs: list[Certificate] = [await self.rx_accepted.recv()]
            while len(certs) < MAX_BURST:
                extra = self.rx_accepted.try_recv()
                if extra is None:
                    break
                certs.append(extra)
            try:
                await self._prefetch_burst(certs)
            except asyncio.CancelledError:
                raise
            except Exception:
                # Speculation must never take the executor down; the
                # subscriber's commit-time fetch is the correctness path.
                logger.debug("prefetch burst failed", exc_info=True)

    async def _prefetch_burst(self, certs: list[Certificate]) -> None:
        by_worker: dict[int, list[tuple[bytes, int]]] = {}
        for cert in certs:
            self._front_round = max(self._front_round, cert.round)
            for digest, worker_id in cert.header.payload.items():
                if (
                    digest in self._entries
                    or digest in self._inflight
                    or self.temp_batch_store.read(digest) is not None
                ):
                    continue
                self._inflight.add(digest)
                by_worker.setdefault(worker_id, []).append((digest, cert.round))
        self._gc()
        if by_worker:
            await asyncio.gather(
                *(
                    self._fetch_group(worker_id, wanted)
                    for worker_id, wanted in by_worker.items()
                )
            )
        self._update_gauge()

    async def _fetch_group(
        self, worker_id: int, wanted: list[tuple[bytes, int]]
    ) -> None:
        """One coalesced RPC (bounded attempts) for everything a burst of
        accepted certificates needs from one worker."""
        rounds = dict(wanted)
        remaining = dict.fromkeys(rounds)
        try:
            for attempt in range(self.attempts):
                try:
                    info = self.worker_cache.worker(self.name, worker_id)
                    # Bounded per-ATTEMPT retry over one coalesced request,
                    # not a per-item round trip.
                    # lint: allow(no-per-item-rpc-in-loop)
                    resp: RequestedBatchesMsg = await self.network.request(
                        info.worker_address,
                        RequestBatchesMsg(tuple(remaining)),
                        timeout=self.fetch_timeout,
                    )
                except KeyError as e:
                    logger.debug(
                        "prefetch skipped: unknown worker id %d (%s)",
                        worker_id,
                        e,
                    )
                    return
                except (RpcError, OSError) as e:
                    logger.debug(
                        "prefetch attempt %d from worker %d failed: %s",
                        attempt + 1,
                        worker_id,
                        e,
                    )
                    await asyncio.sleep(self.retry_delay)
                    continue
                for digest, found, raw in resp.batches:
                    if (
                        digest not in remaining
                        or not found
                        or serialized_batch_digest(raw) != digest
                    ):
                        continue
                    del remaining[digest]
                    if len(raw) > self.budget_bytes:
                        continue  # can't fit even alone; let commit fetch it
                    self.temp_batch_store.write(digest, raw)
                    self._admit(digest, rounds[digest], len(raw))
                    if self.metrics is not None:
                        self.metrics.prefetched_batches.inc()
                if not remaining:
                    return
                # Worker hasn't seen the rest yet (dissemination still in
                # flight): give it one short beat, then give up — the
                # commit-time fetch covers whatever speculation missed.
                await asyncio.sleep(self.retry_delay)
        finally:
            for d in rounds:
                self._inflight.discard(d)
