"""The executor: pulls ordered certificates and applies them to the app.

Reference crate: /root/reference/executor/ (see SURVEY §2.10). Assembly
mirrors Executor::spawn (executor/src/lib.rs:89-145): a Subscriber staging
payloads in consensus order feeding an ExecutorCore that applies transactions
exactly-once over crashes.
"""

from __future__ import annotations

import asyncio

from ..channels import Channel, metered_channel
from ..config import WorkerCache
from ..network import NetworkClient
from ..stores import CertificateStore, ConsensusStore, NodeStorage
from ..types import ConsensusOutput, PublicKey
from .metrics import ExecutorMetrics
from .core import (
    ClientExecutionError,
    ExecutionState,
    ExecutionStateError,
    ExecutorCore,
)
from .prefetcher import Prefetcher
from .state import ExecutionIndices
from .subscriber import Subscriber

__all__ = [
    "ClientExecutionError",
    "ExecutionIndices",
    "ExecutionState",
    "ExecutionStateError",
    "Executor",
    "ExecutorCore",
    "Prefetcher",
    "Subscriber",
    "get_restored_consensus_output",
]


async def get_restored_consensus_output(
    consensus_store: ConsensusStore,
    certificate_store: CertificateStore,
    execution_state: ExecutionState,
) -> list[ConsensusOutput]:
    """Crash recovery (/root/reference/executor/src/lib.rs:147-185): replay
    every sequenced certificate at or past the executor's certificate cursor."""
    indices = await execution_state.load_execution_indices()
    out: list[ConsensusOutput] = []
    for index, digest in consensus_store.read_sequenced_digests_after(
        indices.next_certificate_index
    ):
        certificate = certificate_store.read(digest)
        if certificate is not None:
            out.append(ConsensusOutput(certificate, index))
    return out


class Executor:
    """Subscriber + ExecutorCore pair (executor/src/lib.rs:89-145)."""

    def __init__(
        self,
        name: PublicKey,
        worker_cache: WorkerCache,
        storage: NodeStorage,
        execution_state: ExecutionState,
        network: NetworkClient,
        rx_consensus: Channel,
        tx_output: Channel | None = None,
        registry=None,
        rx_accepted: Channel | None = None,  # accepted-certificate tap
        gc_depth: int = 50,
        prefetch_budget: int | None = None,  # bytes; 0/None w/o tap disables
        tracer=None,
    ):
        metrics = (
            ExecutorMetrics(registry, tracer=tracer)
            if registry is not None
            else None
        )
        # Staged-payload hand-off (subscriber -> core), depth-gauged like
        # every other inter-actor edge: its occupancy is one of the signals
        # the node's backpressure monitor folds into the admission level.
        self.tx_executor = (
            metered_channel(registry, "executor", "core", 1_000)
            if registry is not None
            else Channel(1_000)
        )
        self.prefetcher: Prefetcher | None = None
        if rx_accepted is not None and (prefetch_budget is None or prefetch_budget > 0):
            self.prefetcher = Prefetcher(
                name,
                worker_cache,
                network,
                storage.temp_batch_store,
                rx_accepted,
                gc_depth=gc_depth,
                **(
                    {"budget_bytes": prefetch_budget}
                    if prefetch_budget is not None
                    else {}
                ),
                metrics=metrics,
            )
        self.subscriber = Subscriber(
            name,
            worker_cache,
            network,
            storage.temp_batch_store,
            rx_consensus,
            self.tx_executor,
            metrics=metrics,
            prefetcher=self.prefetcher,
        )
        self.core = ExecutorCore(
            execution_state,
            storage.temp_batch_store,
            self.tx_executor,
            tx_output,
            metrics=metrics,
        )
        self._tasks: list[asyncio.Task] = []

    async def spawn(
        self, restored: list[ConsensusOutput] | None = None
    ) -> list[asyncio.Task]:
        self._tasks = [self.subscriber.spawn(), self.core.spawn()]
        if self.prefetcher is not None:
            self._tasks.append(self.prefetcher.spawn())
        # Re-inject restored outputs ahead of live traffic (lib.rs:120-135).
        for output in restored or []:
            await self.subscriber.rx_consensus.send(output)
        return self._tasks

    def shutdown(self) -> None:
        for t in self._tasks:
            t.cancel()
