"""The Subscriber: downloads payloads of ordered certificates, in order.

Reference: /root/reference/executor/src/subscriber.rs:30-100 — receives
ConsensusOutput, fetches every batch of the certificate's payload (via
BlockCommand::GetBlock to the BlockWaiter in the reference; here by asking our
own workers `RequestBatch` directly over RPC) with infinite exponential
backoff, stages the batches in the temp batch store, and forwards outputs to
the execution core strictly in consensus order (BoundedFuturesOrdered).
"""

from __future__ import annotations

import asyncio
import logging

from ..channels import BoundedFuturesOrdered, Channel
from ..config import WorkerCache
from ..messages import RequestBatchMsg, RequestedBatchMsg
from ..network import NetworkClient, RpcError
from ..stores import BatchStore
from ..types import Batch, ConsensusOutput, PublicKey, serialized_batch_digest

logger = logging.getLogger("narwhal.executor")

MAX_PENDING_PAYLOADS = 1_000


class Subscriber:
    def __init__(
        self,
        name: PublicKey,
        worker_cache: WorkerCache,
        network: NetworkClient,
        temp_batch_store: BatchStore,
        rx_consensus: Channel,  # ConsensusOutput from the consensus runner
        tx_executor: Channel,  # ConsensusOutput, payload staged, to the core
    ):
        self.name = name
        self.worker_cache = worker_cache
        self.network = network
        self.temp_batch_store = temp_batch_store
        self.rx_consensus = rx_consensus
        self.tx_executor = tx_executor
        self._task: asyncio.Task | None = None

    def spawn(self) -> asyncio.Task:
        self._task = asyncio.ensure_future(self.run())
        return self._task

    async def _fetch_batch(self, digest: bytes, worker_id: int) -> Batch:
        """Fetch one batch from our own worker with infinite exponential
        backoff (subscriber.rs:65-72). The temp store is a cache; the batch
        itself is returned so the core never depends on store lifetime (two
        certificates may legitimately reference byte-identical batches, and
        the first one's cleanup must not starve the second)."""
        delay = 0.05
        while True:
            raw = self.temp_batch_store.read(digest)
            if raw is not None:
                return Batch.from_bytes(raw)
            try:
                info = self.worker_cache.worker(self.name, worker_id)
                resp: RequestedBatchMsg = await self.network.request(
                    info.worker_address, RequestBatchMsg(digest), timeout=10.0
                )
                if resp.found and serialized_batch_digest(resp.serialized_batch) == digest:
                    self.temp_batch_store.write(digest, resp.serialized_batch)
                    return Batch.from_bytes(resp.serialized_batch)
                # Worker doesn't have it yet (miss) or corrupt: retry.
            except (RpcError, OSError, KeyError) as e:
                logger.debug("batch fetch retry for %s: %s", digest.hex()[:16], e)
            await asyncio.sleep(delay)
            delay = min(delay * 2, 5.0)

    async def _stage(
        self, output: ConsensusOutput
    ) -> tuple[ConsensusOutput, dict[bytes, Batch]]:
        payload = output.certificate.header.payload
        batches: dict[bytes, Batch] = {}
        if payload:
            fetched = await asyncio.gather(
                *(self._fetch_batch(d, w) for d, w in payload.items())
            )
            batches = dict(zip(payload.keys(), fetched))
        return output, batches

    async def run(self) -> None:
        pending = BoundedFuturesOrdered(MAX_PENDING_PAYLOADS)

        async def forward():
            while True:
                output = await pending.next()
                await self.tx_executor.send(output)

        forwarder = asyncio.ensure_future(forward())
        try:
            while True:
                output: ConsensusOutput = await self.rx_consensus.recv()
                await pending.push(self._stage(output))
        finally:
            # Cancel staged fetches too: their infinite-backoff retry loops
            # would otherwise keep hitting workers (and writing into our
            # store) after the node shuts down or restarts.
            forwarder.cancel()
            pending.cancel_all()
