"""The Subscriber: downloads payloads of ordered certificates, in order.

Reference: /root/reference/executor/src/subscriber.rs:30-100 — receives
ConsensusOutput, fetches every batch of the certificate's payload with
infinite exponential backoff, stages the batches in the temp batch store, and
forwards outputs to the execution core strictly in consensus order
(BoundedFuturesOrdered).

Data-plane batching delta from the reference: all of a certificate's missing
digests that live on ONE worker ride a single coalesced RequestBatchesMsg
(one RPC + one coalesced store read on the worker) instead of one
RequestBatch round trip per digest, and the temp batch store doubles as the
prefetcher's warm cache — digests the Prefetcher staged while the certificate
was still climbing toward commit are local hits, taking payload RTT off the
commit->execution critical path entirely.
"""

from __future__ import annotations

import asyncio
import logging

from ..clock import now
from ..channels import BoundedFuturesOrdered, Channel
from ..config import WorkerCache
from ..messages import RequestBatchesMsg, RequestedBatchesMsg
from ..network import NetworkClient, RpcError
from ..stores import BatchStore
from ..types import Batch, ConsensusOutput, PublicKey, serialized_batch_digest

logger = logging.getLogger("narwhal.executor")

MAX_PENDING_PAYLOADS = 1_000
# Explicit backoff cap for the infinite fetch retry (subscriber.rs:65-72
# retries forever; the delay must not): doubling stops here.
MAX_FETCH_BACKOFF = 5.0
# After this many consecutive failed attempts for one fetch group the retry
# loop stops whispering at debug and escalates to a rate-limited warning —
# a misconfigured worker_id (KeyError) used to retry forever in silence.
ESCALATE_AFTER_ATTEMPTS = 5


class Subscriber:
    def __init__(
        self,
        name: PublicKey,
        worker_cache: WorkerCache,
        network: NetworkClient,
        temp_batch_store: BatchStore,
        rx_consensus: Channel,  # ConsensusOutput from the consensus runner
        tx_executor: Channel,  # (output, batches, t_commit) to the core
        metrics=None,  # ExecutorMetrics
        prefetcher=None,  # executor.prefetcher.Prefetcher (claim() on commit)
        fetch_timeout: float = 10.0,
        initial_backoff: float = 0.05,
        max_backoff: float = MAX_FETCH_BACKOFF,
    ):
        self.name = name
        self.worker_cache = worker_cache
        self.network = network
        self.temp_batch_store = temp_batch_store
        self.rx_consensus = rx_consensus
        self.tx_executor = tx_executor
        self.metrics = metrics
        self.prefetcher = prefetcher
        self.fetch_timeout = fetch_timeout
        self.initial_backoff = initial_backoff
        self.max_backoff = max_backoff
        self._task: asyncio.Task | None = None

    def spawn(self) -> asyncio.Task:
        self._task = asyncio.ensure_future(self.run())
        return self._task

    async def _fetch_group(
        self, worker_id: int, digests: list[bytes], stats: dict
    ) -> dict[bytes, Batch]:
        """Every digest this certificate is missing from ONE worker, fetched
        with a single coalesced RPC per attempt and infinite retry under a
        capped backoff (subscriber.rs:65-72). The temp store is a cache; the
        batches themselves are returned so the core never depends on store
        lifetime (two certificates may legitimately reference byte-identical
        batches, and the first one's cleanup must not starve the second)."""
        remaining: dict[bytes, None] = dict.fromkeys(digests)
        out: dict[bytes, Batch] = {}
        delay = self.initial_backoff
        attempt = 0
        while remaining:
            # Re-check the store every attempt: the prefetcher (or a sibling
            # certificate's fetch) may have landed a digest meanwhile.
            for d in list(remaining):
                raw = self.temp_batch_store.read(d)
                if raw is not None:
                    out[d] = Batch.from_bytes(raw)
                    del remaining[d]
            if not remaining:
                break
            attempt += 1
            failure: str | None = None
            try:
                info = self.worker_cache.worker(self.name, worker_id)
                resp: RequestedBatchesMsg = await self.network.request(
                    info.worker_address,
                    RequestBatchesMsg(tuple(remaining)),
                    timeout=self.fetch_timeout,
                )
                stats["rpcs"] += 1
                for digest, found, raw in resp.batches:
                    if (
                        digest in remaining
                        and found
                        and serialized_batch_digest(raw) == digest
                    ):
                        self.temp_batch_store.write(digest, raw)
                        out[digest] = Batch.from_bytes(raw)
                        del remaining[digest]
                        stats["bytes"] += len(raw)
                if remaining:
                    # Worker doesn't have them yet (miss) or corrupt: retry.
                    failure = f"{len(remaining)} digest(s) not yet available"
            except KeyError as e:
                # Unknown worker_id: a config/committee mismatch, not a
                # transient transport blip — it will never fix itself by
                # waiting, so it must not hide at debug level forever.
                failure = f"unknown worker id {worker_id}: {e}"
            except (RpcError, OSError) as e:
                stats["rpcs"] += 1
                failure = str(e)
            if failure is not None:
                if (
                    attempt >= ESCALATE_AFTER_ATTEMPTS
                    and attempt % ESCALATE_AFTER_ATTEMPTS == 0
                ):
                    logger.warning(
                        "batch fetch from worker %d still failing after "
                        "%d attempts (%s): %s",
                        worker_id,
                        attempt,
                        ", ".join(d.hex()[:16] for d in list(remaining)[:3]),
                        failure,
                    )
                else:
                    logger.debug(
                        "batch fetch retry (attempt %d, worker %d): %s",
                        attempt,
                        worker_id,
                        failure,
                    )
            if remaining:
                await asyncio.sleep(delay)
                delay = min(delay * 2, self.max_backoff)
        return out

    async def _stage(
        self, output: ConsensusOutput, t_commit: float
    ) -> tuple[ConsensusOutput, dict[bytes, Batch], float]:
        payload = output.certificate.header.payload
        batches: dict[bytes, Batch] = {}
        stats = {"rpcs": 0, "bytes": 0}
        if payload:
            # Local pass first: digests the prefetcher already staged (or a
            # previous certificate fetched) never touch the network.
            missing_by_worker: dict[int, list[bytes]] = {}
            hits = 0
            for digest, worker_id in payload.items():
                raw = self.temp_batch_store.read(digest)
                if raw is not None:
                    batches[digest] = Batch.from_bytes(raw)
                    hits += 1
                else:
                    missing_by_worker.setdefault(worker_id, []).append(digest)
            if self.metrics is not None:
                self.metrics.prefetch_hits.inc(hits)
                self.metrics.prefetch_misses.inc(len(payload) - hits)
            if missing_by_worker:
                fetched = await asyncio.gather(
                    *(
                        self._fetch_group(worker_id, digests, stats)
                        for worker_id, digests in missing_by_worker.items()
                    )
                )
                for group in fetched:
                    batches.update(group)
        if self.prefetcher is not None:
            # Ownership handoff: these digests now belong to the execution
            # path (the core deletes them after applying), so the prefetcher
            # must never budget-evict or GC them from under it.
            self.prefetcher.claim(payload.keys())
        if self.metrics is not None:
            self.metrics.fetch_rpcs_per_certificate.observe(stats["rpcs"])
            self.metrics.bytes_fetched.inc(stats["bytes"])
        return output, batches, t_commit

    async def run(self) -> None:
        pending = BoundedFuturesOrdered(MAX_PENDING_PAYLOADS)

        async def forward():
            while True:
                staged = await pending.next()
                await self.tx_executor.send(staged)

        forwarder = asyncio.ensure_future(forward())
        try:
            while True:
                output: ConsensusOutput = await self.rx_consensus.recv()
                await pending.push(self._stage(output, now()))
        finally:
            # Cancel staged fetches too: their infinite-backoff retry loops
            # would otherwise keep hitting workers (and writing into our
            # store) after the node shuts down or restarts.
            forwarder.cancel()
            pending.cancel_all()
