"""Process-wide kernel registry: one compile per (kernel, mesh shape).

Every jit entry point in tpu/ routes through here (enforced by the
narwhal-lint rule `no-untracked-jit`), for three reasons this repo paid
for separately before unifying them:

- **Compile dedupe.** Each `jax.jit(...)` call owns its own trace/compile
  cache, so two wrappers over the same kernel+mesh each pay the full
  multi-minute XLA compile (the MULTICHIP_r05 rc=124 bill: verifier.py's
  `_sharded_kernels` and dag_kernels' per-mesh jits were separate caches
  that could still double-compile through independent construction
  paths). The registry is the single map (kernel, mesh shape) -> compiled
  wrapper; every verifier/engine over the same mesh gets the SAME object.
- **Compile-wall accounting.** The first dispatch of a (kernel, mesh
  shape, operand shapes) tuple is trace + XLA compile + one execute;
  steady-state dispatches are milliseconds. The registry times every
  first dispatch and exposes `compile_walls()` so the dryrun/bench
  artifacts can attribute a slow run to the exact compile that ate it —
  the MULTICHIP timeline was reconstructed from slow_operation_alarm
  stderr; now it is part of the result JSON.
- **Buffer donation.** The device-resident window kernels (`roll_window`,
  `place_batch`) update [W, N, N] tensors in place semantically; without
  donation XLA must keep both generations live and copy. Donation is a
  per-kernel property, declared once at registration.

The persistent compilation cache (tpu/__init__.enable_compilation_cache,
opt-in via NARWHAL_JAX_CACHE_DIR for CPU targets) composes with this:
the registry guarantees one compile per process, the cache makes that
compile a deserialization in every process after the first.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Sequence

_LOCK = threading.Lock()
# kernel name -> TrackedKernel (the module-level, unsharded entry point)
_KERNELS: dict[str, "TrackedKernel"] = {}
# (kernel name, mesh key, spec signature) -> TrackedKernel (sharded wrapper)
_SHARDED: dict[tuple, "TrackedKernel"] = {}
# (kernel name, mesh desc, operand-shape signature) -> first-dispatch wall (s)
_WALLS: dict[tuple[str, str, str], float] = {}


def mesh_key(mesh) -> tuple:
    """Hashable identity of a mesh: devices + axis names + geometry."""
    if mesh is None:
        return ()
    return (
        tuple(mesh.devices.flat),
        tuple(mesh.axis_names),
        tuple(mesh.devices.shape),
    )


def mesh_desc(mesh) -> str:
    """Human/JSON-stable mesh shape label: '8:data', '4x2:data,auth',
    '1' for the unsharded single-device entry."""
    if mesh is None:
        return "1"
    dims = "x".join(str(d) for d in mesh.devices.shape)
    return f"{dims}:{','.join(mesh.axis_names)}"


def _shapes_sig(args: tuple, kwargs: dict) -> str:
    parts = []
    for a in args:
        shape = getattr(a, "shape", None)
        if shape is None:
            parts.append(type(a).__name__)
        else:
            dtype = getattr(a, "dtype", "?")
            parts.append(f"{dtype}[{','.join(map(str, shape))}]")
    for k in sorted(kwargs):
        parts.append(f"{k}={kwargs[k]!r}")
    return ";".join(parts)


class TrackedKernel:
    """A jit-compiled kernel that self-reports its compile walls.

    Callable like the jit wrapper; `__wrapped__` is the original Python
    function (the sharded builders re-jit it with shardings), `lower(...)`
    passes through for ahead-of-need prewarm compiles."""

    def __init__(self, name: str, fn: Callable, jit_fn, mesh=None):
        self.name = name
        self.__wrapped__ = getattr(fn, "__wrapped__", fn)
        self.__name__ = name
        self.__doc__ = fn.__doc__
        self._jit = jit_fn
        self._mesh_desc = mesh_desc(mesh)

    def __call__(self, *args, **kwargs):
        key = (self.name, self._mesh_desc, _shapes_sig(args, kwargs))
        if key in _WALLS:
            return self._jit(*args, **kwargs)
        t0 = time.perf_counter()
        out = self._jit(*args, **kwargs)
        wall = time.perf_counter() - t0
        with _LOCK:
            # First dispatch of this (kernel, mesh, shapes): trace + XLA
            # compile + one (async-dispatched) execute. Keep the first
            # observation — a racing second dispatch just hit the cache.
            _WALLS.setdefault(key, wall)
        return out

    def lower(self, *args, **kwargs):
        return self._jit.lower(*args, **kwargs)


def tracked_jit(arg=None, *, name: str | None = None, **jit_kwargs):
    """`@tracked_jit` / `@tracked_jit(name=..., static_argnames=...,
    donate_argnums=...)`: the registry's replacement for a module-level
    `@jax.jit` in tpu/. Registers the kernel by name so sharded variants
    (`sharded(...)`) and the compile-wall report can find it."""

    def wrap(fn: Callable) -> TrackedKernel:
        import jax

        kname = name or fn.__name__
        kernel = TrackedKernel(kname, fn, jax.jit(fn, **jit_kwargs))
        with _LOCK:
            # Registration runs once at module import (decoration time),
            # never inside a trace — the decorator is what MAKES the jit
            # root, it is not reachable from compiled code.
            # lint: allow(jit-purity)
            _KERNELS[kname] = kernel
        return kernel

    if callable(arg):  # bare @tracked_jit
        return wrap(arg)
    return wrap


def sharded(
    kernel,
    mesh,
    in_specs: Sequence,
    out_specs,
    *,
    static_argnames: Sequence[str] = (),
    donate_argnums: Sequence[int] = (),
) -> TrackedKernel:
    """The process-wide mesh-sharded wrapper for `kernel` (a TrackedKernel
    or plain function): ONE jit per (kernel, mesh identity, spec set), so
    every verifier/engine over the same mesh shares one compiled program
    instead of each paying its own multi-minute compile.

    `in_specs`/`out_specs` are PartitionSpecs (or None for replicated);
    they are bound to `mesh` here so callers never hand-build
    NamedShardings."""
    name = getattr(kernel, "name", None) or getattr(kernel, "__name__", repr(kernel))
    key = (
        name,
        mesh_key(mesh),
        repr(tuple(in_specs)),
        repr(out_specs),
        tuple(static_argnames),
        tuple(donate_argnums),
    )
    with _LOCK:
        cached = _SHARDED.get(key)
    if cached is not None:
        return cached
    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    def bind(spec):
        # PartitionSpec subclasses tuple: test for it BEFORE recursing so a
        # P("data", None) leaf isn't mistaken for a tuple of specs.
        if spec is None or isinstance(spec, P):
            return NamedSharding(mesh, spec if spec is not None else P())
        return tuple(bind(s) for s in spec)

    fn = getattr(kernel, "__wrapped__", kernel)
    jit_kwargs: dict[str, Any] = {
        "in_shardings": tuple(bind(s) for s in in_specs),
        "out_shardings": bind(out_specs),
    }
    if static_argnames:
        jit_kwargs["static_argnames"] = tuple(static_argnames)
    if donate_argnums:
        jit_kwargs["donate_argnums"] = tuple(donate_argnums)
    wrapper = TrackedKernel(name, fn, jax.jit(fn, **jit_kwargs), mesh=mesh)
    with _LOCK:
        # First construction wins (two threads racing the same key must
        # end up dispatching through the same wrapper).
        return _SHARDED.setdefault(key, wrapper)


def get_kernel(name: str) -> TrackedKernel:
    return _KERNELS[name]


def kernel_names() -> list[str]:
    with _LOCK:
        return sorted(_KERNELS)


def sharded_entries() -> int:
    with _LOCK:
        return len(_SHARDED)


def compile_walls() -> list[dict]:
    """Snapshot of every first-dispatch wall so far, one row per (kernel,
    mesh shape, operand shapes) — the dryrun/bench artifacts embed this."""
    with _LOCK:
        items = sorted(_WALLS.items())
    return [
        {"kernel": k, "mesh": m, "shapes": s, "wall_s": round(w, 3)}
        for (k, m, s), w in items
    ]


def compile_walls_by_shape() -> dict[str, float]:
    """Aggregate walls per (kernel, mesh shape) — the satellite contract:
    'compile walls per (kernel, mesh shape)'. Shape-level detail stays
    available via compile_walls()."""
    agg: dict[str, float] = {}
    for row in compile_walls():
        key = f"{row['kernel']}@{row['mesh']}"
        agg[key] = round(agg.get(key, 0.0) + row["wall_s"], 3)
    return agg
