"""Batched ed25519 verification on TPU: the north-star crypto kernel.

Replaces per-message host verification (the reference's ed25519-dalek calls
behind fastcrypto's `VerifyingKey`, /root/reference/crypto/src/lib.rs:29-46;
hot at `Certificate::verify`, /root/reference/types/src/primary.rs:487-537)
with one device dispatch per batch of signatures.

TPU-first design notes (see /opt/skills/guides/pallas_guide.md, SURVEY §7.8a):

- **Limb-major layout**: a field element batch is int32[NLIMB, B] — the
  batch axis fills the VPU's 128-wide lanes; limbs live on the sublane axis
  so carry shifts are row moves, not lane shuffles. (The transposed [B, 20]
  layout leaves 6/7 of every vector register empty.)
- **Field arithmetic mod p = 2^255-19 in radix 2^13**: 20 limbs. Products of
  13-bit limbs are 26-bit; a 20-term column sum stays under 2^31, so the
  whole multiplier runs in native int32 lanes — no 64-bit emulation.
- **Parallel carries**: overflow moves one limb up per vector round; fixed
  round counts with statically-proven bounds (below) restore the invariant.
- **Shared-doubling Straus**: Rcheck = [S]B + [k](-A) in one run of 252
  doublings + 2x64 windowed table additions under `lax.scan`; the B table is
  a host constant, the -A table is built on device. The extended-Edwards
  addition law is complete here, so identity entries need no branches.
- Verification matches the host library (cofactorless):
  encode([S]B - [k]A) == R, with canonicality prechecks on host.

Bound bookkeeping (all < 2^31):
  loose invariant: limbs in [0, LOOSE = 9500]
  mul columns: 20 * 9500^2 = 1.805e9; fold adds <= 1.94e9; 4 rounds -> ~8800
  add: <= 19000, 2 rounds -> <= 9409
  sub: a + 64p - b with 64p = [15168, 16382 x19] (every limb >= 15168 keeps
       differences positive), 3 rounds -> <= ~8801

The host wrapper lives in narwhal_tpu/tpu/verifier.py.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import enable_compilation_cache
from . import ed25519_ref as ref
from .kernel_registry import tracked_jit

enable_compilation_cache()

NLIMB = 20
RADIX = 13
MASK = (1 << RADIX) - 1
WINDOWS = 64  # 4-bit windows over 256-bit scalars, MSB first
LOOSE = 9500


def int_to_limbs(x: int) -> np.ndarray:
    return np.array([(x >> (RADIX * i)) & MASK for i in range(NLIMB)], np.int32)


def limbs_to_int(limbs) -> int:
    arr = np.asarray(limbs)
    if arr.ndim > 1:
        arr = arr[..., 0] if arr.shape[-1] == 1 else arr.squeeze()
    return sum(int(v) << (RADIX * i) for i, v in enumerate(arr))


def _col(x: int) -> np.ndarray:
    """Constant as a broadcastable [NLIMB, 1] column."""
    return int_to_limbs(x)[:, None]


_P_LIMBS = int_to_limbs(ref.P)
_D = _col(ref.D)
_2D = _col(2 * ref.D % ref.P)
_SQRT_M1 = _col(ref.SQRT_M1)
_ONE = _col(1)

# 64p = 2^261 - 1216 with every limb large: per-limb subtraction bias.
_SUB_BIAS = np.array([15168] + [16382] * (NLIMB - 1), np.int32)[:, None]
assert limbs_to_int(_SUB_BIAS) == 64 * ref.P

# Fixed-base window table: 16 small multiples of B in CACHED affine form
# (y+x, y−x, 2d·t mod p) — identity row is (1, 1, 0), Z == 1 implicitly, so
# each table add is the 7-mul pt_add_cached_z1.
_BT = np.zeros((16, 3, NLIMB), np.int32)
for _dd, (_x, _y, _t) in enumerate(ref.base_window_table()):
    _BT[_dd, 0] = int_to_limbs((_y + _x) % ref.P)
    _BT[_dd, 1] = int_to_limbs((_y - _x) % ref.P)
    _BT[_dd, 2] = int_to_limbs(2 * ref.D * _t % ref.P)


# ---------------------------------------------------------------------------
# Field ops: arrays are [NLIMB] or [NLIMB, B]; the limb axis is ALWAYS 0.
# ---------------------------------------------------------------------------


def _carry_round(r):
    """One parallel carry round; limb-19 overflow (2^260 == 608 mod p) wraps
    to limb 0 — a single rotated add, no scatter."""
    hi = r >> RADIX
    lo = r & MASK
    return lo + jnp.concatenate([608 * hi[-1:], hi[:-1]], axis=0)


def fe_add(a, b):
    return _carry_round(_carry_round(a + b))


def _bcast(const_col, like):
    """[NLIMB, 1] host constant, broadcast-ready against `like`'s shape
    (limb axis 0, any number of trailing batch axes)."""
    return jnp.asarray(const_col[:, 0]).reshape((NLIMB,) + (1,) * (like.ndim - 1))


def fe_sub(a, b):
    r = a + _bcast(_SUB_BIAS, b) - b
    return _carry_round(_carry_round(_carry_round(r)))


def fe_neg(a):
    return _carry_round(_carry_round(_bcast(_SUB_BIAS, a) - a))


def _fold_and_carry(cols: list):
    """39 school-book columns -> loose field element: fold the high half
    (2^260 == 608 mod p) by 13-bit split so nothing overflows int32, then 4
    parallel carry rounds (bounds in the module docstring)."""
    c_lo = jnp.stack(cols[:NLIMB], axis=0)
    zero = jnp.zeros_like(cols[0])
    c_hi = jnp.stack(cols[NLIMB:] + [zero], axis=0)
    d_lo = c_hi & MASK
    d_hi = c_hi >> RADIX
    up = jnp.concatenate([jnp.zeros_like(d_hi[:1]), d_hi[:-1]], axis=0)
    r = c_lo + 608 * d_lo + 608 * up
    for _ in range(4):
        r = _carry_round(r)
    return r


def fe_mul(a, b):
    # Row-wise school-book columns: c[k] = sum_{i+j=k} a_i * b_j. Each term
    # is one [B]-wide multiply-add — no dynamic slicing, pure VPU work.
    rows_a = [a[i] for i in range(NLIMB)]
    rows_b = [b[i] for i in range(NLIMB)]
    cols = []
    for k in range(2 * NLIMB - 1):
        lo = max(0, k - NLIMB + 1)
        hi = min(NLIMB - 1, k)
        s = rows_a[lo] * rows_b[k - lo]
        for i in range(lo + 1, hi + 1):
            s = s + rows_a[i] * rows_b[k - i]
        cols.append(s)
    return _fold_and_carry(cols)


def fe_sq(a):
    # Squaring: c[k] = 2 * sum_{i<j, i+j=k} a_i a_j (+ a_{k/2}^2) — the
    # doubled operand keeps products under 19000 * 9500 * 10 < 2^31.
    rows = [a[i] for i in range(NLIMB)]
    doubled = [r + r for r in rows]
    cols = []
    for k in range(2 * NLIMB - 1):
        lo = max(0, k - NLIMB + 1)
        hi = min(NLIMB - 1, k)
        terms = []
        i, j = lo, hi
        while i < j:
            terms.append(doubled[i] * rows[j])
            i += 1
            j -= 1
        if i == j:
            terms.append(rows[i] * rows[i])
        s = terms[0]
        for t in terms[1:]:
            s = s + t
        cols.append(s)
    return _fold_and_carry(cols)


def _carry_chain_exact(r):
    """Sequential full carry (canonicalization only — off the hot path)."""
    outs = []
    carry = jnp.zeros_like(r[0])
    for i in range(NLIMB):
        v = r[i] + carry
        outs.append(v & MASK)
        carry = v >> RADIX
    return jnp.stack(outs, axis=0), carry


def fe_canonical(a):
    """Full reduction to [0, p) from loose form."""
    for _ in range(2):
        a, overflow = _carry_chain_exact(a)
        top = a[NLIMB - 1]
        hi = (top >> 8) + (overflow << (RADIX - 8))
        a = a.at[NLIMB - 1].set(top & 0xFF)
        a = a.at[0].add(19 * hi)
    a, _ = _carry_chain_exact(a)
    for _ in range(2):  # value < 2^255 + eps: conditionally subtract p
        borrow = jnp.zeros_like(a[0])
        outs = []
        for i in range(NLIMB):
            v = a[i] - int(_P_LIMBS[i]) - borrow
            borrow = (v < 0).astype(jnp.int32)
            outs.append(v + (borrow << RADIX))
        sub = jnp.stack(outs, axis=0)
        a = jnp.where((borrow == 0), sub, a)
    return a


def fe_eq(a, b):
    return jnp.all(fe_canonical(a) == fe_canonical(b), axis=0)


def _ladder(z):
    """Shared exponentiation ladder: returns (z^(2^250-1), z^11)."""
    t0 = fe_sq(z)
    t1 = fe_sq(fe_sq(t0))
    t1 = fe_mul(z, t1)  # z^9
    t0 = fe_mul(t0, t1)  # z^11
    t2 = fe_sq(t0)
    t1 = fe_mul(t1, t2)  # z^31
    z11 = t0

    def times(x, n):
        if n <= 4:
            for _ in range(n):
                x = fe_sq(x)
            return x
        return lax.fori_loop(0, n, lambda _, v: fe_sq(v), x)

    t2 = times(t1, 5)
    t1 = fe_mul(t2, t1)  # 2^10-1
    t2 = times(t1, 10)
    t2 = fe_mul(t2, t1)  # 2^20-1
    t3 = times(t2, 20)
    t2 = fe_mul(t3, t2)  # 2^40-1
    t2 = times(t2, 10)
    t1 = fe_mul(t2, t1)  # 2^50-1
    t2 = times(t1, 50)
    t2 = fe_mul(t2, t1)  # 2^100-1
    t3 = times(t2, 100)
    t2 = fe_mul(t3, t2)  # 2^200-1
    t2 = times(t2, 50)
    t1 = fe_mul(t2, t1)  # 2^250-1
    return t1, z11


def fe_invert(z):
    t1, z11 = _ladder(z)
    for _ in range(5):
        t1 = fe_sq(t1)
    return fe_mul(t1, z11)  # z^(p-2)


def fe_pow22523(z):
    t1, _ = _ladder(z)
    t1 = fe_sq(fe_sq(t1))
    return fe_mul(t1, z)  # z^(2^252-3)


# ---------------------------------------------------------------------------
# Point ops: extended twisted-Edwards coordinates as (X, Y, Z, T) tuples of
# limb-major arrays. The addition law is complete on ed25519.
# ---------------------------------------------------------------------------


def pt_identity(batch_shape=()):
    zero = jnp.zeros((NLIMB,) + batch_shape, jnp.int32)
    one = zero.at[0].set(1)
    return (zero, one, one, zero)


def pt_add(p, q):
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = fe_mul(fe_sub(y1, x1), fe_sub(y2, x2))
    b = fe_mul(fe_add(y1, x1), fe_add(y2, x2))
    c = fe_mul(fe_mul(t1, _bcast(_2D, t1)), t2)
    d = fe_mul(fe_add(z1, z1), z2)
    e, f, g, h = fe_sub(b, a), fe_sub(d, c), fe_add(d, c), fe_add(b, a)
    return (fe_mul(e, f), fe_mul(g, h), fe_mul(f, g), fe_mul(e, h))


def pt_double(p):
    x1, y1, z1, _ = p
    a = fe_sq(x1)
    b = fe_sq(y1)
    c = fe_add(fe_sq(z1), fe_sq(z1))
    h = fe_add(a, b)
    e = fe_sub(h, fe_sq(fe_add(x1, y1)))
    g = fe_sub(a, b)
    f = fe_add(c, g)
    return (fe_mul(e, f), fe_mul(g, h), fe_mul(f, g), fe_mul(e, h))


def pt_neg(p):
    x, y, z, t = p
    return (fe_neg(x), y, z, fe_neg(t))


# Cached-form addition (the dalek/ref10 "cached point" trick): a table
# entry stored as (Y+X, Y−X, Z, 2D·T) turns the complete 9-mul pt_add into
# an 8-mul add — the (t1·2D)·t2 double-multiply collapses into one t1·t2d.
# Table entries are added ~100x each (once per window lane), so the one
# extra mul spent caching each entry buys back 64-96 muls per point.


def pt_cache(p):
    """Projective (X, Y, Z, T) -> cached (Y+X, Y−X, Z, 2D·T). All outputs
    stay inside the loose bound (add <= 9409, sub <= 8801, mul <= 8800)."""
    x, y, z, t = p
    return (fe_add(y, x), fe_sub(y, x), z, fe_mul(t, _bcast(_2D, t)))


def pt_add_cached(p, q):
    """p projective + q cached: 8 fe_muls (vs pt_add's 9)."""
    x1, y1, z1, t1 = p
    yp2, ym2, z2, t2d = q
    a = fe_mul(fe_sub(y1, x1), ym2)
    b = fe_mul(fe_add(y1, x1), yp2)
    c = fe_mul(t1, t2d)
    d = fe_mul(fe_add(z1, z1), z2)
    e, f, g, h = fe_sub(b, a), fe_sub(d, c), fe_add(d, c), fe_add(b, a)
    return (fe_mul(e, f), fe_mul(g, h), fe_mul(f, g), fe_mul(e, h))


def pt_add_cached_z1(p, q):
    """p projective + q cached with Z2 == 1 (affine table constants): the
    d term needs no multiply — 7 fe_muls."""
    x1, y1, z1, t1 = p
    yp2, ym2, t2d = q
    a = fe_mul(fe_sub(y1, x1), ym2)
    b = fe_mul(fe_add(y1, x1), yp2)
    c = fe_mul(t1, t2d)
    d = fe_add(z1, z1)
    e, f, g, h = fe_sub(b, a), fe_sub(d, c), fe_add(d, c), fe_add(b, a)
    return (fe_mul(e, f), fe_mul(g, h), fe_mul(f, g), fe_mul(e, h))


# ---------------------------------------------------------------------------
# Decompression and batched verification (limb-major, batch in the lanes).
# ---------------------------------------------------------------------------


def decompress(y_limbs, sign):
    """Recover x from canonical y [NLIMB, B] and sign [B]. Returns (point,
    valid[B])."""
    y2 = fe_sq(y_limbs)
    u = fe_sub(y2, jnp.asarray(_ONE))
    v = fe_add(fe_mul(y2, jnp.asarray(_D)), jnp.asarray(_ONE))
    v3 = fe_mul(fe_sq(v), v)
    v7 = fe_mul(fe_sq(v3), v)
    x = fe_mul(fe_mul(u, v3), fe_pow22523(fe_mul(u, v7)))
    vx2 = fe_mul(v, fe_sq(x))
    correct = fe_eq(vx2, u)
    flipped = fe_eq(vx2, fe_neg(u))
    valid = correct | flipped
    x = jnp.where(flipped, fe_mul(x, jnp.asarray(_SQRT_M1)), x)
    x_can = fe_canonical(x)
    x_zero = jnp.all(x_can == 0, axis=0)
    valid = valid & ~(x_zero & (sign == 1))
    parity = x_can[0] & 1
    x = jnp.where(parity != sign, fe_neg(x), x)
    one = jnp.zeros_like(x).at[0].set(1)
    return (x, y_limbs, one, fe_mul(x, y_limbs)), valid


def _select(table, digit):
    """table [16, NLIMB, B], digit [B] -> [NLIMB, B]: binary where-tree on
    the digit bits — (8+4+2+1) masked rows instead of the one-hot einsum's
    16 multiply-accumulate rows (~2x fewer lane ops per lookup)."""
    cur = table
    for bit in (3, 2, 1, 0):
        half = cur.shape[0] // 2
        take_hi = ((digit >> bit) & 1).astype(bool)[None, None, :]
        cur = jnp.where(take_hi, cur[half:], cur[:half])
    return cur[0]


def _select_const(table, digit):
    """table [16, NLIMB] (host constant), digit [B] -> [NLIMB, B]."""
    cur = jnp.broadcast_to(
        jnp.asarray(table)[:, :, None], (16, table.shape[1], digit.shape[0])
    )
    for bit in (3, 2, 1, 0):
        half = cur.shape[0] // 2
        take_hi = ((digit >> bit) & 1).astype(bool)[None, None, :]
        cur = jnp.where(take_hi, cur[half:], cur[:half])
    return cur[0]


@tracked_jit
def verify_batch_kernel(a_y, a_sign, r_y, r_sign, k_digits, s_digits):
    """Per-lane check of [S]B + [k](−A) against R, under BOTH rules:

    strict (cofactorless, the host library's): encode(Rcheck) == (r_y,
    r_sign); cofactored (RFC 8032 / dalek batch): [8](Rcheck − R) ==
    identity. Computing both in one pass costs one R decompression + four
    point ops (~10%) and lets the msm fallback use a DETERMINISTIC
    device-side cofactored verdict — no per-item host bigint recheck an
    attacker could amplify, no budget that would make verdicts depend on
    flush composition.

    Host-facing shapes (batch-leading): a_y/r_y int[B, NLIMB] canonical y
    limbs; a_sign/r_sign int[B]; k_digits/s_digits int[B, 64] 4-bit digits
    MSB-first. Narrow dtypes welcome — limbs fit int16 and digits int8, so
    the host sends ~3x fewer bytes over the device link; everything is
    widened to int32 lanes here. Returns (strict bool[B], cofactored
    bool[B]).
    """
    a_y = a_y.T.astype(jnp.int32)  # -> limb-major [NLIMB, B]
    r_y = r_y.T.astype(jnp.int32)
    a_sign = a_sign.astype(jnp.int32)
    r_sign = r_sign.astype(jnp.int32)
    k_digits = k_digits.T.astype(jnp.int32)  # -> [64, B]
    s_digits = s_digits.T.astype(jnp.int32)
    B = a_y.shape[1]

    a_point, valid = decompress(a_y, a_sign)

    # 16 cached multiples of -A built on device; 16 cached multiples of B
    # from the host. Every window add is then the 8-mul (device table) or
    # 7-mul (affine host table) cached form instead of the 9-mul pt_add.
    table_a = _pt_cached_table(pt_neg(a_point), B)
    ident = pt_identity((B,))

    def step(acc, digits):
        kd, sd = digits
        for _ in range(4):
            acc = pt_double(acc)
        qa = tuple(_select(table_a[i], kd) for i in range(4))
        acc = pt_add_cached(acc, qa)
        qb = (
            _select_const(_BT[:, 0], sd),
            _select_const(_BT[:, 1], sd),
            _select_const(_BT[:, 2], sd),
        )
        acc = pt_add_cached_z1(acc, qb)
        return acc, None

    acc, _ = lax.scan(step, ident, (k_digits, s_digits))

    zinv = fe_invert(acc[2])
    x = fe_mul(acc[0], zinv)
    y = fe_mul(acc[1], zinv)
    x_can = fe_canonical(x)
    ok_strict = fe_eq(y, r_y) & ((x_can[0] & 1) == r_sign) & valid

    # Cofactored verdict: [8](Rcheck + (−R)) == identity.
    r_point, r_valid = decompress(r_y, r_sign)
    diff = pt_add(acc, pt_neg(r_point))
    for _ in range(3):
        diff = pt_double(diff)
    ok_cof = fe_eq(diff[0], jnp.zeros_like(diff[0])) & fe_eq(diff[1], diff[2])
    return ok_strict, ok_cof & valid & r_valid


# ---------------------------------------------------------------------------
# Staged per-item verification: the monolithic trace split into three
# dispatchable stages. The monolith above compiles as ONE XLA module whose
# graph holds ~3.5 exponentiation-ladder instances (A decompress, R
# decompress, the final fe_invert) plus the 64-window scan — minutes of
# single-core LLVM per (kernel, mesh shape), the MULTICHIP_r05 rc=124
# bill. The staged pipeline compiles three bounded modules instead:
#
#   decompress (ONE ladder, dispatched twice: A then R — one compile
#   serves both point sets, and the msm pipeline reuses the same stage)
#   -> straus scan (table build + 64-window walk)
#   -> verdict (fe_invert ladder + strict/cofactored epilogue)
#
# Intermediates stay on device between stages (stacked [4, NLIMB, B]
# coordinate tensors, donated forward so XLA reuses the buffers); the
# per-lane arithmetic is IDENTICAL to the monolith — decompress, the scan
# body and the epilogue are the same functions, batched the same way — so
# verdicts are bit-equal (pinned by tests/test_multichip.py). The mesh-
# sharded verifier dispatches these; the single-chip path keeps the
# monolith (one dispatch per bucket matters through a high-RTT link).
# ---------------------------------------------------------------------------


@tracked_jit
def verify_decompress_kernel(y_rows, signs):
    """Stage 1: decompress one point set. y_rows int[B, NLIMB] canonical y
    limbs (host layout), signs int[B]. Returns (points int32[4, NLIMB, B]
    extended coords, valid bool[B]). Dispatched once for the A set and
    once for the R set — same shape, one compile."""
    y = y_rows.T.astype(jnp.int32)
    point, valid = decompress(y, signs.astype(jnp.int32))
    return jnp.stack(point, axis=0), valid


@tracked_jit
def verify_straus_kernel(a_pt, k_digits, s_digits):
    """Stage 2: the shared-doubling Straus walk. a_pt int32[4, NLIMB, B]
    decompressed A points; k_digits/s_digits int[B, 64] 4-bit MSB-first.
    Returns acc int32[4, NLIMB, B] = [S]B + [k](-A), projective."""
    a_point = tuple(a_pt[i] for i in range(4))
    k_digits = k_digits.T.astype(jnp.int32)
    s_digits = s_digits.T.astype(jnp.int32)
    B = a_pt.shape[2]

    table_a = _pt_cached_table(pt_neg(a_point), B)
    ident = pt_identity((B,))

    def step(acc, digits):
        kd, sd = digits
        for _ in range(4):
            acc = pt_double(acc)
        qa = tuple(_select(table_a[i], kd) for i in range(4))
        acc = pt_add_cached(acc, qa)
        qb = (
            _select_const(_BT[:, 0], sd),
            _select_const(_BT[:, 1], sd),
            _select_const(_BT[:, 2], sd),
        )
        acc = pt_add_cached_z1(acc, qb)
        return acc, None

    acc, _ = lax.scan(step, ident, (k_digits, s_digits))
    return jnp.stack(acc, axis=0)


@tracked_jit
def verify_verdict_kernel(acc_pt, r_pt, r_y, r_sign, a_valid, r_valid):
    """Stage 3: both verdicts from the scan accumulator and the
    decompressed R set — the monolith's epilogue verbatim. Returns
    (strict bool[B], cofactored bool[B])."""
    acc = tuple(acc_pt[i] for i in range(4))
    r_y_lm = r_y.T.astype(jnp.int32)
    r_sign = r_sign.astype(jnp.int32)

    zinv = fe_invert(acc[2])
    x = fe_mul(acc[0], zinv)
    y = fe_mul(acc[1], zinv)
    x_can = fe_canonical(x)
    ok_strict = fe_eq(y, r_y_lm) & ((x_can[0] & 1) == r_sign) & a_valid

    diff = pt_add(acc, pt_neg(tuple(r_pt[i] for i in range(4))))
    for _ in range(3):
        diff = pt_double(diff)
    ok_cof = fe_eq(diff[0], jnp.zeros_like(diff[0])) & fe_eq(diff[1], diff[2])
    return ok_strict, ok_cof & a_valid & r_valid


@tracked_jit(static_argnames=("chunk",))
def msm_window_kernel(pts, digits, chunk=128):
    """Staged msm stage 2: cached-table build from -P plus the window-lane
    accumulate over ONE point set (the monolith fused A and R into a
    single concatenated trace). pts int32[4, NLIMB, B] decompressed
    points, digits int[B, W]. Returns V int32[4, NLIMB, W] loose limbs per
    window lane. Under mesh sharding the batch axis is partitioned and V
    (no batch axis left) comes back replicated: per-device partial
    accumulates with one XLA-inserted cross-device reduce."""
    point = tuple(pts[i] for i in range(4))
    table = _pt_cached_table(pt_neg(point), pts.shape[2])
    v = _accumulate_windows(table, digits.astype(jnp.int32), chunk)
    return jnp.stack(v, axis=0)


# ---------------------------------------------------------------------------
# Random-linear-combination batch verification (one shared doubling chain).
#
# Per-item Straus pays 252 doublings + 128 table adds PER LANE. The batch
# equation  [Σ z_i S_i]B − Σ [z_i k_i]A_i − Σ [z_i]R_i == 0  (z_i random
# 128-bit, ed25519-dalek's batch rule) needs each point added into the sum
# ONCE per scalar window, with all doublings shared by the whole batch:
#
#   - window lanes: an accumulator [NLIMB, W, C] holds, per (window w,
#     chain c), Σ over that chain's points of digit·point — points stream
#     through in chunks of C (a lax.scan), one vectorized pt_add per chunk;
#   - chain reduction: log2(C) pairwise pt_adds;
#   - Horner: a log2(W) tree of (4·2^r doublings + add) collapses the
#     window lanes into Σ_w 16^(W-1-w) V_w — ~252 doublings total for the
#     ENTIRE batch instead of per signature;
#   - the R_i terms carry only the 128-bit z_i, so their accumulator has 32
#     window lanes instead of 64 (half the add work);
#   - the fixed-base [Σ z_i S_i]B term drops into the A accumulator's
#     window lanes as one extra add from the host B table.
#
# Net lane-op count per signature is ~2x below the per-item kernel (the
# decompression of R_i is the new cost; the 3200-fe-mul main loop shrinks
# to ~900). Soundness: a forged item passes only with probability ~2^-128
# over the verifier's choice of z_i. On failure the caller falls back to
# the per-item kernel to locate offenders (verifier.py).
# ---------------------------------------------------------------------------


def _select_lanes(table, digits):
    """table [16, NLIMB, C], digits [C, W] -> [NLIMB, W, C]: the binary
    where-tree of _select, broadcast so every window lane of every chain
    picks its own table row."""
    mask_src = digits.T  # [W, C]
    cur = table[:, :, None, :]  # [16, NLIMB, 1, C]
    for bit in (3, 2, 1, 0):
        half = cur.shape[0] // 2
        take_hi = ((mask_src >> bit) & 1).astype(bool)[None, None, :, :]
        cur = jnp.where(take_hi, cur[half:], cur[:half])
    return cur[0]


def _pt_cached_table(neg_p, batch):
    """16 multiples (identity, P, 2P, ... 15P) of each lane's point in
    CACHED form (Y+X, Y−X, Z, 2D·T): 4 coord arrays [16, NLIMB, B]. The
    chain itself runs on the cached base (8-mul adds); each emitted entry
    pays one extra mul (2D·T) so every later window add saves one."""
    base_c = pt_cache(neg_p)

    def next_multiple(prev, _):
        nxt = pt_add_cached(prev, base_c)
        return nxt, pt_cache(nxt)

    _, higher = lax.scan(next_multiple, neg_p, None, length=14)
    zero = jnp.zeros((NLIMB, batch), jnp.int32)
    one = zero.at[0].set(1)
    ident_c = (one, one, one, zero)  # cached identity: yp=ym=z=1, t2d=0
    return tuple(
        jnp.concatenate([ident_c[i][None], base_c[i][None], higher[i]], axis=0)
        for i in range(4)
    )


def _accumulate_windows(table, digits, chunk):
    """Stream the M points through the window-lane accumulator.

    table: 4 CACHED coords [16, NLIMB, M]; digits [M, W]. Returns V: 4
    projective coords [NLIMB, W] = per window lane, Σ_j digit_{j,w}·P_j.
    Every reduction is a fixed-shape scan so the compiled program stays
    one body per stage (the unrolled pairwise tree tripled compile time).
    """
    M, W = digits.shape
    C = min(chunk, M)
    S = M // C
    xs_table = tuple(
        t.reshape(16, NLIMB, S, C).transpose(2, 0, 1, 3) for t in table
    )  # each [S, 16, NLIMB, C]
    xs_digits = digits.reshape(S, C, W)

    def step(acc, xs):
        tab, dig = xs
        q = tuple(_select_lanes(tab[i], dig) for i in range(4))
        return pt_add_cached(acc, q), None

    acc0 = pt_identity((W, C))
    acc, _ = lax.scan(step, acc0, (jnp.stack(xs_table, 1), xs_digits))

    # Chain reduction [NLIMB, W, C] -> [NLIMB, W]: log2(C) halving rounds
    # expressed at FIXED width — each round adds the lane C/2^{r+1} to the
    # right of every live lane (dead lanes compute garbage that is never
    # read) — so the whole tree is one scan body with one pt_add.
    rounds = (C - 1).bit_length()
    offsets = jnp.asarray([C >> (r + 1) for r in range(rounds)], jnp.int32)

    def reduce_round(acc, off):
        idx = (jnp.arange(C, dtype=jnp.int32) + off) % C
        partner = tuple(jnp.take(a, idx, axis=-1) for a in acc)
        return pt_add(acc, partner), None

    acc, _ = lax.scan(reduce_round, acc, offsets)
    return tuple(a[..., 0] for a in acc)  # [NLIMB, W]


@tracked_jit(static_argnames=("chunk",))
def msm_accumulate_kernel(a_y, a_sign, r_y, r_sign, ak_digits, z_digits, chunk=128):
    """Device half of the batch check Σ [z_ik_i](−A_i) + Σ [z_i](−R_i):
    per-window point sums over the whole batch.

    Host-facing shapes: a_y/r_y int[B, NLIMB] canonical y limbs; signs
    int[B]; ak_digits int[B, 64] = 4-bit MSB-first digits of z_i·k_i mod L;
    z_digits int[B, 32] = digits of the 128-bit z_i. Zero rows are inert
    padding. Returns (V_a int32[4, NLIMB, 64], V_r int32[4, NLIMB, 32] —
    X/Y/Z/T loose limbs per window lane — and valid bool[B]).

    The A and R points share one decompress + cached-table build
    (concatenated batch axis) but run SEPARATE window accumulates: the R
    scalars are the raw 128-bit z_i, so their accumulator needs only 32
    window lanes — the r4 kernel zero-extended them to 64 and paid ~32
    inert 9-mul adds per R point (~16% of the whole kernel's multiplies).
    The host epilogue Horner-merges both lane sets (the last 32 windows of
    the chain take V_a[w] + V_r[w-32]) — see verifier.msm_epilogue_check;
    the ~300 sequential width-1 point ops of that chain would cost ~500 ms
    as sub-tile device work, vs ~2 ms of host bigint on the tiny readback.
    """
    ak_digits = ak_digits.astype(jnp.int32)
    z_digits = z_digits.astype(jnp.int32)
    B = a_y.shape[0]

    ys = jnp.concatenate([a_y.T, r_y.T], axis=1).astype(jnp.int32)  # [NLIMB, 2B]
    signs = jnp.concatenate([a_sign, r_sign]).astype(jnp.int32)

    points, valid = decompress(ys, signs)
    table = _pt_cached_table(pt_neg(points), 2 * B)
    table_a = tuple(t[..., :B] for t in table)
    table_r = tuple(t[..., B:] for t in table)
    v_a = _accumulate_windows(table_a, ak_digits, chunk)  # [NLIMB, 64] x4
    v_r = _accumulate_windows(table_r, z_digits, chunk)  # [NLIMB, 32] x4
    return jnp.stack(v_a, axis=0), jnp.stack(v_r, axis=0), valid[:B] & valid[B:]


def msm_field_muls_per_signature(batch: int, chunk: int = 128) -> float:
    """Analytic fe_mul-equivalent cost per signature of the msm path —
    the roofline denominator for BENCH utilization accounting (VERDICT r4
    item 2: place the kernel against the measured VPU fe_mul rate).

    An fe_sq counts at its limb-product ratio, 210/400 of an fe_mul (the
    schoolbook column sums; carries are included in both measured rates).
    Per SIGNATURE (one A point + one R point):

      decompress x2: the shared exponentiation ladder is 251 sq + ~12 mul
        (_ladder + pow22523), plus ~4 sq + ~9 mul of surrounding ops;
      cached table x2: 14 chain adds x 8 mul (pt_add_cached) + 15 cache
        muls (2D*T per emitted entry incl. the base);
      accumulate: one 8-mul cached add per window lane — 64 lanes for the
        A scalar (z*k mod L, 256-bit) + 32 for the R scalar (z, 128-bit);
      chain reduction: log2(C) pt_adds (9 mul) over (64+32)*C lanes,
        amortized over the bucket.

    The host Horner epilogue is not counted (it overlaps device compute in
    the pipelined flow and is measured separately by bench.py)."""
    sq = 210.0 / 400.0
    decompress = 2 * ((251 + 4) * sq + 21)
    table = 2 * (14 * 8 + 15)
    accumulate = 8 * (64 + 32)
    c = min(chunk, batch)
    rounds = (c - 1).bit_length()
    reduction = 9.0 * rounds * c * (64 + 32) / batch
    return decompress + table + accumulate + reduction


# ---------------------------------------------------------------------------
# Host-side packing helpers (numpy, vectorized over the batch).
# ---------------------------------------------------------------------------


def bytes_to_limbs(raw: np.ndarray) -> np.ndarray:
    """[B, 32] uint8 little-endian -> [B, NLIMB] int32 (sign bit cleared).

    Direct 3-byte gathers per limb (limb i = bits [13i, 13i+13), which span
    at most 3 bytes): ~60 vectorized ops total, ~10x faster than the
    unpackbits route — this runs in the host packing loop that bounds the
    pipelined verify rate."""
    raw32 = np.zeros((raw.shape[0], 33), np.int32)  # +1 zero column for i=19
    raw32[:, :32] = raw
    raw32[:, 31] &= 0x7F
    out = np.empty((raw.shape[0], NLIMB), np.int32)
    for i in range(NLIMB):
        bit = RADIX * i
        b, shift = bit >> 3, bit & 7
        val = raw32[:, b] | (raw32[:, b + 1] << 8)
        if shift + RADIX > 16:
            val |= raw32[:, b + 2] << 16
        out[:, i] = (val >> shift) & MASK
    return out


def bytes_to_digits(raw: np.ndarray) -> np.ndarray:
    """[B, 32] uint8 little-endian scalars -> [B, WINDOWS] 4-bit digits MSB
    first."""
    hi = (raw >> 4).astype(np.int32)
    lo = (raw & 0xF).astype(np.int32)
    digits = np.stack([lo, hi], axis=2).reshape(-1, 64)  # LSB-first nibbles
    return digits[:, ::-1]
