"""Batched ed25519 verification on TPU: the north-star crypto kernel.

Replaces per-message host verification (the reference's ed25519-dalek calls
behind fastcrypto's `VerifyingKey`, /root/reference/crypto/src/lib.rs:29-46;
hot at `Certificate::verify`, /root/reference/types/src/primary.rs:487-537)
with one device dispatch per batch of signatures.

TPU-first design notes (see /opt/skills/guides/pallas_guide.md and SURVEY §7.8a):

- **Field arithmetic mod p = 2^255-19 in radix 2^13**: 20 int32 limbs.
  Products of two 13-bit limbs are 26-bit; a 39-term school-book column sum
  stays under 2^31, so the whole multiplier runs in native int32 lanes on the
  VPU — no 64-bit emulation, no dynamic shapes. Static-shift partial products
  (an unrolled 20-tap convolution) vectorize across the batch axis.
- **Reduction** folds limb k+20 back with weight 608 (2^260 ≡ 19·2^5), then
  the bit-255 overflow with weight 19; limbs stay "almost reduced" (< 2p)
  except where equality tests require canonical form.
- **One traced scalar path, vmapped**: verification is written for a single
  signature and `jax.vmap`-ed, so XLA sees a fixed-shape [B, ...] program with
  a `lax.scan` over the 64 windowed-scalar steps.
- **Shared-doubling Straus**: Rcheck = [S]B + [k](-A) computed with one run
  of 252 doublings and 2x64 table additions (4-bit windows); the B table is a
  host-precomputed constant (ed25519_ref.base_window_table), the -A table is
  built on device (15 additions). The extended-Edwards addition law is
  complete on this curve, so identity entries need no branches — exactly the
  compiler-friendly control flow the MXU/VPU pipeline wants.
- Verification equation matches the host library (cofactorless):
  encode([S]B - [k]A) == R bytes, with canonicality prechecks on host.

The host wrapper lives in narwhal_tpu/tpu/verifier.py.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import ed25519_ref as ref

NLIMB = 20
RADIX = 13
MASK = (1 << RADIX) - 1
WINDOWS = 64  # 4-bit windows over 256-bit scalars, MSB first


def int_to_limbs(x: int) -> np.ndarray:
    return np.array([(x >> (RADIX * i)) & MASK for i in range(NLIMB)], np.int32)


def limbs_to_int(limbs) -> int:
    return sum(int(v) << (RADIX * i) for i, v in enumerate(np.asarray(limbs)))


_P_LIMBS = int_to_limbs(ref.P)
_2P_LIMBS = (2 * _P_LIMBS).astype(np.int32)
_D = int_to_limbs(ref.D)
_2D = int_to_limbs(2 * ref.D % ref.P)
_SQRT_M1 = int_to_limbs(ref.SQRT_M1)
_ONE = int_to_limbs(1)
_ZERO = int_to_limbs(0)

# Fixed-base window table: 16 small multiples of B in affine (x, y, x*y),
# identity at index 0 as (0, 1, 0) with its Z supplied as 1 on device.
_BT = np.zeros((16, 3, NLIMB), np.int32)
for _d, (_x, _y, _t) in enumerate(ref.base_window_table()):
    _BT[_d, 0] = int_to_limbs(_x)
    _BT[_d, 1] = int_to_limbs(_y)
    _BT[_d, 2] = int_to_limbs(_t)


# ---------------------------------------------------------------------------
# Field element ops. A field element is an int32[NLIMB] array in LOOSE form:
# limbs in [0, LOOSE] with LOOSE = 9500 (value may exceed 2^255; only
# congruence mod p is maintained). Carries are propagated by PARALLEL rounds
# (vector shift/mask/add, no 20-step sequential chain): one round moves every
# limb's overflow one position up at once, and the bounds below prove a fixed
# small number of rounds restores the loose invariant. This keeps the XLA
# graph small and the dependency chains short — the whole multiplier is ~50
# vector ops on int32 lanes.
#
# Bound bookkeeping (documented invariants, all < 2^31):
#   mul columns: 20 * LOOSE^2 = 1.805e9          (inputs loose)
#   mul fold:    col + 608*8191 + 608*(col>>13) <= 1.94e9
#   mul: 4 carry rounds -> limbs <= ~8800
#   add: inputs loose -> sum <= 19000, 2 rounds -> <= 9409
#   sub: a + 64p - b with 64p = [15168, 16382 x19] (all limbs >= 15168, so
#        every limb difference stays positive), 3 rounds -> <= ~8801
# ---------------------------------------------------------------------------

LOOSE = 9500


def _carry_round(r):
    """One parallel carry round over NLIMB limbs; limb-19 overflow (weight
    2^260 == 608 mod p) folds into limb 0."""
    hi = r >> RADIX
    lo = r & MASK
    up = jnp.concatenate([jnp.zeros_like(hi[..., :1]), hi[..., :-1]], axis=-1)
    return lo + up + 608 * jnp.where(
        jnp.arange(NLIMB) == 0, hi[..., NLIMB - 1 : NLIMB], 0
    )


def fe_add(a, b):
    r = a + b
    r = _carry_round(r)
    return _carry_round(r)


# 64p = 2^261 - 1216 expressed with every limb large (>= 15168): per-limb
# subtraction below never goes negative for loose inputs.
_SUB_BIAS = np.array([15168] + [16382] * (NLIMB - 1), np.int32)
assert limbs_to_int(_SUB_BIAS) == 64 * ref.P


def fe_sub(a, b):
    r = a + jnp.asarray(_SUB_BIAS) - b
    r = _carry_round(r)
    r = _carry_round(r)
    return _carry_round(r)


def fe_neg(a):
    r = jnp.asarray(_SUB_BIAS) - a
    r = _carry_round(r)
    return _carry_round(r)


def fe_mul(a, b):
    # School-book columns via static shifts: c[k] = sum_{i+j=k} a_i * b_j.
    c = jnp.zeros(a.shape[:-1] + (2 * NLIMB,), jnp.int32)
    for i in range(NLIMB):
        c = c.at[..., i : i + NLIMB].add(a[..., i : i + 1] * b)
    # Fold the high half down (2^260 == 608 mod p) without carrying the raw
    # columns first: split each high column into 13-bit lo + hi so that
    # 608*hi rides one limb up and nothing overflows int32 (c_39 == 0, so
    # the shifted d_hi never spills past limb 19).
    c_lo, c_hi = c[..., :NLIMB], c[..., NLIMB:]
    d_lo = c_hi & MASK
    d_hi = c_hi >> RADIX
    up = jnp.concatenate([jnp.zeros_like(d_hi[..., :1]), d_hi[..., :-1]], axis=-1)
    r = c_lo + 608 * d_lo + 608 * up
    for _ in range(4):
        r = _carry_round(r)
    return r


def fe_sq(a):
    return fe_mul(a, a)


def _carry_chain_exact(r):
    """Sequential full carry (canonicalization only — not on the hot path)."""
    outs = []
    carry = jnp.zeros_like(r[..., 0])
    for i in range(NLIMB):
        v = r[..., i] + carry
        outs.append(v & MASK)
        carry = v >> RADIX
    return jnp.stack(outs, axis=-1), carry


def fe_canonical(a):
    """Full reduction to [0, p) from loose form."""
    for _ in range(2):
        a, overflow = _carry_chain_exact(a)
        # Fold bits >= 255: limb 19 keeps its low 8 bits, the rest (plus the
        # 2^260-weight overflow) re-enters with weight 19.
        top = a[..., NLIMB - 1]
        hi = (top >> 8) + (overflow << (RADIX - 8))
        a = a.at[..., NLIMB - 1].set(top & 0xFF)
        a = a.at[..., 0].add(19 * hi)
    a, _ = _carry_chain_exact(a)
    for _ in range(2):  # value now < 2^255 + eps: conditionally subtract p
        borrow = jnp.zeros_like(a[..., 0])
        outs = []
        for i in range(NLIMB):
            v = a[..., i] - int(_P_LIMBS[i]) - borrow
            borrow = (v < 0).astype(jnp.int32)
            outs.append(v + (borrow << RADIX))
        sub = jnp.stack(outs, axis=-1)
        a = jnp.where((borrow == 0)[..., None], sub, a)
    return a


def fe_eq(a, b):
    """Equality of field values (canonicalizes both)."""
    return jnp.all(fe_canonical(a) == fe_canonical(b), axis=-1)


def fe_is_zero(a):
    return jnp.all(fe_canonical(a) == 0, axis=-1)


def _ladder(z):
    """Shared exponentiation ladder: returns (z^(2^250-1), z^11)."""
    t0 = fe_sq(z)  # z^2
    t1 = fe_sq(fe_sq(t0))  # z^8
    t1 = fe_mul(z, t1)  # z^9
    t0 = fe_mul(t0, t1)  # z^11
    t2 = fe_sq(t0)  # z^22
    t1 = fe_mul(t1, t2)  # z^31 = z^(2^5-1)
    z11 = t0

    def times(x, n):
        # fori_loop keeps the compiled graph small: one fe_sq body per chain
        # instead of n inlined copies (squarings are sequential regardless).
        if n <= 4:
            for _ in range(n):
                x = fe_sq(x)
            return x
        return lax.fori_loop(0, n, lambda _, v: fe_sq(v), x)

    t2 = times(t1, 5)
    t1 = fe_mul(t2, t1)  # z^(2^10-1)
    t2 = times(t1, 10)
    t2 = fe_mul(t2, t1)  # z^(2^20-1)
    t3 = times(t2, 20)
    t2 = fe_mul(t3, t2)  # z^(2^40-1)
    t2 = times(t2, 10)
    t1 = fe_mul(t2, t1)  # z^(2^50-1)
    t2 = times(t1, 50)
    t2 = fe_mul(t2, t1)  # z^(2^100-1)
    t3 = times(t2, 100)
    t2 = fe_mul(t3, t2)  # z^(2^200-1)
    t2 = times(t2, 50)
    t1 = fe_mul(t2, t1)  # z^(2^250-1)
    return t1, z11


def fe_invert(z):
    t1, z11 = _ladder(z)
    for _ in range(5):
        t1 = fe_sq(t1)  # z^(2^255-2^5)
    return fe_mul(t1, z11)  # z^(2^255-21) = z^(p-2)


def fe_pow22523(z):
    t1, _ = _ladder(z)
    t1 = fe_sq(fe_sq(t1))  # z^(2^252-4)
    return fe_mul(t1, z)  # z^(2^252-3)


# ---------------------------------------------------------------------------
# Point ops: extended twisted-Edwards coordinates, stacked as [4, NLIMB]
# rows (X, Y, Z, T). The addition law is complete on ed25519.
# ---------------------------------------------------------------------------


def pt(x, y, z, t):
    return jnp.stack([x, y, z, t], axis=-2)


def pt_identity():
    return pt(
        jnp.asarray(_ZERO), jnp.asarray(_ONE), jnp.asarray(_ONE), jnp.asarray(_ZERO)
    )


def pt_add(p, q):
    x1, y1, z1, t1 = p[..., 0, :], p[..., 1, :], p[..., 2, :], p[..., 3, :]
    x2, y2, z2, t2 = q[..., 0, :], q[..., 1, :], q[..., 2, :], q[..., 3, :]
    a = fe_mul(fe_sub(y1, x1), fe_sub(y2, x2))
    b = fe_mul(fe_add(y1, x1), fe_add(y2, x2))
    c = fe_mul(fe_mul(t1, jnp.asarray(_2D)), t2)
    d = fe_mul(fe_add(z1, z1), z2)
    e, f, g, h = fe_sub(b, a), fe_sub(d, c), fe_add(d, c), fe_add(b, a)
    return pt(fe_mul(e, f), fe_mul(g, h), fe_mul(f, g), fe_mul(e, h))


def pt_double(p):
    x1, y1, z1 = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    a = fe_sq(x1)
    b = fe_sq(y1)
    c = fe_add(fe_sq(z1), fe_sq(z1))
    h = fe_add(a, b)
    e = fe_sub(h, fe_sq(fe_add(x1, y1)))
    g = fe_sub(a, b)
    f = fe_add(c, g)
    return pt(fe_mul(e, f), fe_mul(g, h), fe_mul(f, g), fe_mul(e, h))


def pt_neg(p):
    return pt(fe_neg(p[..., 0, :]), p[..., 1, :], p[..., 2, :], fe_neg(p[..., 3, :]))


# ---------------------------------------------------------------------------
# Decompression and verification (single signature; vmapped below).
# ---------------------------------------------------------------------------


def decompress(y_limbs, sign):
    """Recover x from a (reduced-form) y and sign bit. Returns (point, valid)."""
    y2 = fe_sq(y_limbs)
    u = fe_sub(y2, jnp.asarray(_ONE))
    v = fe_add(fe_mul(y2, jnp.asarray(_D)), jnp.asarray(_ONE))
    v3 = fe_mul(fe_sq(v), v)
    v7 = fe_mul(fe_sq(v3), v)
    x = fe_mul(fe_mul(u, v3), fe_pow22523(fe_mul(u, v7)))
    vx2 = fe_mul(v, fe_sq(x))
    correct = fe_eq(vx2, u)
    flipped = fe_eq(vx2, fe_neg(u))
    valid = correct | flipped
    x = jnp.where(flipped[..., None], fe_mul(x, jnp.asarray(_SQRT_M1)), x)
    x_can = fe_canonical(x)
    x_zero = jnp.all(x_can == 0, axis=-1)
    valid = valid & ~(x_zero & (sign == 1))
    parity = x_can[..., 0] & 1
    x = jnp.where((parity != sign)[..., None], fe_neg(x), x)
    point = pt(x, y_limbs, jnp.asarray(_ONE), fe_mul(x, y_limbs))
    return point, valid


def _table_entry_affine(table, digit):
    """Extended point from an affine (x, y, t) table row; identity-safe
    because row 0 is (0, 1, 0) and Z is forced to 1."""
    row = jnp.take(table, digit, axis=0)  # [3, NLIMB]
    return pt(row[0], row[1], jnp.asarray(_ONE), row[2])


def verify_one(a_y, a_sign, r_y, r_sign, k_digits, s_digits):
    """Cofactorless check: encode([S]B + [k](-A)) == (r_y, r_sign).

    a_y/r_y: int32[NLIMB] reduced-form y coordinates (canonical, from host);
    *_sign: int32 scalars; k_digits/s_digits: int32[WINDOWS] 4-bit digits,
    MSB first. Returns bool.
    """
    a_point, valid = decompress(a_y, a_sign)
    neg_a = pt_neg(a_point)

    # 16 multiples of -A (device); 16 multiples of B (host constant).
    def next_multiple(prev, _):
        nxt = pt_add(prev, neg_a)
        return nxt, nxt

    _, higher = lax.scan(next_multiple, neg_a, None, length=14)  # 2A..15A
    table_a = jnp.concatenate(
        [pt_identity()[None], neg_a[None], higher], axis=0
    )  # [16, 4, NLIMB]
    table_b = jnp.asarray(_BT)  # [16, 3, NLIMB]

    def step(acc, digits):
        kd, sd = digits
        for _ in range(4):
            acc = pt_double(acc)
        acc = pt_add(acc, jnp.take(table_a, kd, axis=0))
        acc = pt_add(acc, _table_entry_affine(table_b, sd))
        return acc, None

    acc, _ = lax.scan(step, pt_identity(), (k_digits, s_digits))

    zinv = fe_invert(acc[2])
    x = fe_mul(acc[0], zinv)
    y = fe_mul(acc[1], zinv)
    x_can = fe_canonical(x)
    ok = fe_eq(y, r_y) & ((x_can[..., 0] & 1) == r_sign)
    return ok & valid


@functools.partial(jax.jit, static_argnames=())
def verify_batch_kernel(a_y, a_sign, r_y, r_sign, k_digits, s_digits):
    """[B]-batched verification; every argument's leading axis is the batch."""
    return jax.vmap(verify_one)(a_y, a_sign, r_y, r_sign, k_digits, s_digits)


# ---------------------------------------------------------------------------
# Host-side packing helpers (numpy, vectorized over the batch).
# ---------------------------------------------------------------------------


def bytes_to_limbs(raw: np.ndarray) -> np.ndarray:
    """[B, 32] uint8 little-endian -> [B, NLIMB] int32 (sign bit cleared)."""
    raw = raw.copy()
    raw[:, 31] &= 0x7F
    bits = np.unpackbits(raw, axis=1, bitorder="little")  # [B, 256]
    bits = np.pad(bits, ((0, 0), (0, NLIMB * RADIX - 256)))
    weights = (1 << np.arange(RADIX, dtype=np.int32))
    return (bits.reshape(-1, NLIMB, RADIX) * weights).sum(axis=2).astype(np.int32)


def bytes_to_digits(raw: np.ndarray) -> np.ndarray:
    """[B, 32] uint8 little-endian scalars -> [B, WINDOWS] 4-bit digits MSB
    first."""
    hi = (raw >> 4).astype(np.int32)
    lo = (raw & 0xF).astype(np.int32)
    digits = np.stack([lo, hi], axis=2).reshape(-1, 64)  # LSB-first nibbles
    return digits[:, ::-1]
