"""Pure-integer ed25519 group arithmetic: table generation + kernel oracle.

This is the host-side reference the JAX kernel (ed25519.py) is tested
against, and the generator of the fixed-base window tables it ships to the
device. Not a hot path: Python ints, readable RFC-8032 math.

Reference behavior being reproduced: the fastcrypto/ed25519-dalek verify the
reference uses for network identity and (in this framework) protocol
multisigs (/root/reference/crypto/src/lib.rs:29-46) — cofactorless
verification: [S]B == R + [k]A with k = SHA-512(R || A || M) mod L.
"""

from __future__ import annotations

import hashlib

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)

# Extended coordinates (X, Y, Z, T) with x = X/Z, y = Y/Z, T = XY/Z.
IDENTITY = (0, 1, 1, 0)


def fe_inv(x: int) -> int:
    return pow(x, P - 2, P)


def point_add(p, q):
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    A = (Y1 - X1) * (Y2 - X2) % P
    B = (Y1 + X1) * (Y2 + X2) % P
    C = 2 * T1 * T2 % P * D % P
    Dd = 2 * Z1 * Z2 % P
    E, F, G, H = B - A, Dd - C, Dd + C, B + A
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def point_double(p):
    X1, Y1, Z1, _ = p
    A = X1 * X1 % P
    B = Y1 * Y1 % P
    C = 2 * Z1 * Z1 % P
    H = A + B
    E = H - (X1 + Y1) * (X1 + Y1)
    G = A - B
    F = C + G
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def point_mul(s: int, p):
    q = IDENTITY
    while s > 0:
        if s & 1:
            q = point_add(q, p)
        p = point_double(p)
        s >>= 1
    return q


def point_neg(p):
    X, Y, Z, T = p
    return ((-X) % P, Y, Z, (-T) % P)


def point_equal(p, q):
    X1, Y1, Z1, _ = p
    X2, Y2, Z2, _ = q
    return (X1 * Z2 - X2 * Z1) % P == 0 and (Y1 * Z2 - Y2 * Z1) % P == 0


def affine(p) -> tuple[int, int]:
    X, Y, Z, _ = p
    zi = fe_inv(Z)
    return X * zi % P, Y * zi % P


def recover_x(y: int, sign: int) -> int | None:
    if y >= P:
        return None
    # RFC 8032 §5.1.3 single-exponentiation form: the candidate root of
    # x^2 = u/v is x = u v^3 (u v^7)^((P-5)/8) — identical to
    # (u/v)^((P+3)/8) (exponents differ by a multiple of P-1) without the
    # separate field inversion, halving the cost of every decompression
    # (one ~255-bit pow instead of two; decompression is the floor of the
    # host batched certificate-proof verifier).
    u = (y * y - 1) % P
    v = (D * y * y + 1) % P
    v3 = v * v % P * v % P
    x = u * v3 % P * pow(u * v3 % P * v3 % P * v % P, (P - 5) // 8, P) % P
    vx2 = v * x % P * x % P
    if vx2 != u:
        if vx2 != P - u:
            return None
        x = x * SQRT_M1 % P
    if x == 0 and sign:
        return None
    if x & 1 != sign:
        x = P - x
    return x


# Base point.
_GY = 4 * fe_inv(5) % P
_GX = recover_x(_GY, 0)
G = (_GX, _GY, 1, _GX * _GY % P)


def decompress(s: bytes):
    if len(s) != 32:
        return None
    y = int.from_bytes(s, "little")
    sign = y >> 255
    y &= (1 << 255) - 1
    x = recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % P)


def compress(p) -> bytes:
    x, y = affine(p)
    return int.to_bytes(y | ((x & 1) << 255), 32, "little")


def sha512_mod_l(*parts: bytes) -> int:
    h = hashlib.sha512()
    for part in parts:
        h.update(part)
    return int.from_bytes(h.digest(), "little") % L


def verify(public_key: bytes, message: bytes, signature: bytes) -> bool:
    """Cofactorless RFC-8032-style verification (the oracle for the kernel)."""
    if len(signature) != 64:
        return False
    a = decompress(public_key)
    if a is None:
        return False
    rs, sb = signature[:32], signature[32:]
    s = int.from_bytes(sb, "little")
    if s >= L:
        return False
    r_int = int.from_bytes(rs, "little")
    if (r_int & ((1 << 255) - 1)) >= P:  # non-canonical R encoding
        return False
    k = sha512_mod_l(rs, public_key, message)
    rhs = point_add(point_mul(s, G), point_mul(k, point_neg(a)))
    # rhs = [S]B - [k]A must encode exactly to R.
    return compress(rhs) == rs


def base_window_table(windows: int = 64, width: int = 16):
    """Affine multiples table for Straus: table[w][d] = affine(d * B) is NOT
    position-scaled — the kernel shares doublings between both scalars, so it
    only needs the 16 small multiples of B (and builds A's on device)."""
    out = []
    for d in range(width):
        pt = point_mul(d, G)
        if d == 0:
            out.append((0, 1, 0))  # identity in (x, y, t=x*y) affine form
        else:
            x, y = affine(pt)
            out.append((x, y, x * y % P))
    return out
