"""Vectorized DAG kernels: the consensus commit walk as adjacency tensors.

Reference hot loop: /root/reference/consensus/src/utils.rs:11-101 — per-commit
pointer-chasing DFS (order_dag), frontier filtering (linked) and per-round
leader support counting — all O(window x committee) sequential work on CPU.

TPU-first redesign (SURVEY §5.8, §7.8b): the DAG window is dense tensors
  present[W, N]   uint8 — certificate exists at (round offset, authority)
  parent [W, N, N] uint8 — parent[w, a, p] = cert (w, a) links (w-1, p)
  stakes [N]      int32
with W = round-window size (>= gc_depth + slack) and N = committee size.
Reachability from any certificate is a backward scan of N x N bitwise matmuls
(MXU/VPU work, no pointer chasing); leader support is one masked dot product.
Commit traversal must not pass *through* already-committed certificates
(the DFS skip in utils.rs:86-89), so propagation masks them out via
last_committed[N].

All kernels are jit-compiled with static shapes; round offsets and indices
are traced scalars so one compilation serves every call. `TpuBullshark`
wraps them behind the exact ConsensusProtocol interface and is
equivalence-tested against the host engine on random lossy DAGs
(tests/test_dag_kernels.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..config import Committee
from ..stores import ConsensusStore
from ..types import Certificate, ConsensusOutput, Digest, Round, SequenceNumber
from ..consensus.state import ConsensusState


@jax.jit
def reach_mask(parent, uncommitted, start_off, start_onehot):
    """Reachability mask [W, N]: certificates reachable from the start
    certificate by walking parent links down the window, propagating only
    through uncommitted certificates (the vectorized order_dag/linked core).

    parent: uint8 [W, N, N]; uncommitted: uint8 [W, N] (present & not yet
    committed); start_off: int32 round offset; start_onehot: uint8 [N].
    """
    W, N, _ = parent.shape

    def step(frontier_above, w):
        # frontier_above = mask row already computed for offset w+1
        links = jnp.take(parent, jnp.minimum(w + 1, W - 1), axis=0)  # [N, N]
        from_above = (links.astype(jnp.int32).T @ frontier_above.astype(jnp.int32)) > 0
        here = jnp.where(
            w == start_off,
            start_onehot.astype(bool),
            jnp.where(w < start_off, from_above, False),
        )
        here = here & uncommitted[w].astype(bool)
        # Certificates below the start that are committed must not relay the
        # frontier; `here` is already masked by uncommitted, and the start
        # row is the leader itself (always explored, like the DFS root).
        return here.astype(jnp.int32), here

    ws = jnp.arange(W - 1, -1, -1)
    _, rows = lax.scan(step, jnp.zeros((N,), jnp.int32), ws)
    return rows[::-1]  # [W, N] bool, row w = offset w


@jax.jit
def leader_support(parent, present, stakes, support_off, leader_idx):
    """Stake carried by certificates at `support_off` linking to the leader at
    the round below (bullshark.rs:66-76 / tusk.rs:66-74)."""
    links = jnp.take(parent, support_off, axis=0)[:, leader_idx]  # [N]
    voters = links.astype(bool) & jnp.take(present, support_off, axis=0).astype(bool)
    return jnp.sum(jnp.where(voters, stakes, 0))


class DagWindow:
    """Host-managed ring of the last W rounds as dense arrays, with the
    digest <-> (round, authority) maps the tensors can't hold. This is the
    'long context' of the system: rounds are the sequence axis, the committee
    the width (SURVEY §5.8)."""

    def __init__(self, committee: Committee, window: int = 64):
        self.committee = committee
        self.N = committee.size()
        self.W = window
        self.round_base: Round = 0
        self.present = np.zeros((self.W, self.N), np.uint8)
        self.parent = np.zeros((self.W, self.N, self.N), np.uint8)
        self.stakes = np.asarray(committee.stakes_array(), np.int32)
        self.certs: dict[tuple[Round, int], Certificate] = {}
        self.digest_pos: dict[Digest, tuple[Round, int]] = {}
        # Genesis certificates occupy round 0.
        for cert in Certificate.genesis(committee):
            self._place(cert)

    def _off(self, round: Round) -> int:
        return round - self.round_base

    def _place(self, cert: Certificate) -> None:
        idx = self.committee.index_of(cert.origin)
        off = self._off(cert.round)
        self.present[off, idx] = 1
        self.certs[(cert.round, idx)] = cert
        self.digest_pos[cert.digest] = (cert.round, idx)
        for pd in cert.header.parents:
            pos = self.digest_pos.get(pd)
            if pos is not None and pos[0] == cert.round - 1:
                self.parent[off, idx, pos[1]] = 1

    def insert(self, cert: Certificate, keep_floor: Round) -> bool:
        """Add a certificate; slides the window forward (dropping only rounds
        below keep_floor, the GC bound) or grows it when commits lag behind
        round production. Returns False only for certificates below the
        already-GC'd base."""
        if cert.round < self.round_base:
            return False
        while cert.round - self.round_base >= self.W:
            target = cert.round - self.W + 1
            if target <= keep_floor:
                self.slide_to(target)
            elif keep_floor > self.round_base:
                self.slide_to(keep_floor)
                self._grow()
            else:
                self._grow()
        self._place(cert)
        return True

    def _grow(self) -> None:
        """Double W (recompiles the jitted kernels for the new static shape —
        rare, only when the uncommitted span outgrows the window)."""
        new_w = self.W * 2
        present = np.zeros((new_w, self.N), np.uint8)
        parent = np.zeros((new_w, self.N, self.N), np.uint8)
        present[: self.W] = self.present
        parent[: self.W] = self.parent
        self.present, self.parent, self.W = present, parent, new_w

    def slide_to(self, new_base: Round) -> None:
        shift = new_base - self.round_base
        if shift <= 0:
            return
        if shift >= self.W:
            self.present[:] = 0
            self.parent[:] = 0
        else:
            self.present[:-shift] = self.present[shift:]
            self.present[-shift:] = 0
            self.parent[:-shift] = self.parent[shift:]
            self.parent[-shift:] = 0
        dropped = [(r, i) for (r, i) in self.certs if r < new_base]
        for key in dropped:
            cert = self.certs.pop(key)
            self.digest_pos.pop(cert.digest, None)
        self.round_base = new_base

    def cert_at(self, round: Round, idx: int) -> Certificate | None:
        return self.certs.get((round, idx))


class TpuBullshark:
    """Bullshark with the DAG walks on device. Drop-in for
    consensus.Bullshark (same process_certificate signature/semantics,
    equivalence-tested); the host retains only bookkeeping and the final
    index->certificate gather."""

    def __init__(
        self,
        committee: Committee,
        store: ConsensusStore | None,
        gc_depth: Round,
        leader_fn=None,
        window: int | None = None,
    ):
        self.committee = committee
        self.store = store
        self.gc_depth = gc_depth
        self._leader_fn = leader_fn
        self.win = DagWindow(committee, window or (gc_depth + 14))

    # -- leader election --------------------------------------------------
    def _leader_index(self, round: Round, dag) -> int | None:
        if self._leader_fn is not None:
            entry = self._leader_fn(self.committee, round, dag)
            if entry is None:
                return None
            return self.committee.index_of(entry[1].origin)
        name = self.committee.leader(round)
        idx = self.committee.index_of(name)
        off = self.win._off(round)
        if 0 <= off < self.win.W and self.win.present[off, idx]:
            return idx
        return None

    # -- tensor helpers ---------------------------------------------------
    def _uncommitted(self, state: ConsensusState) -> np.ndarray:
        lc = np.zeros((self.win.N,), np.int64)
        for pk, r in state.last_committed.items():
            lc[self.committee.index_of(pk)] = r
        rounds = self.win.round_base + np.arange(self.win.W)[:, None]
        return (self.win.present.astype(bool) & (rounds > lc[None, :])).astype(np.uint8)

    def _reach(self, state: ConsensusState, round: Round, idx: int) -> np.ndarray:
        onehot = np.zeros((self.win.N,), np.uint8)
        onehot[idx] = 1
        mask = reach_mask(
            jnp.asarray(self.win.parent),
            jnp.asarray(self._uncommitted(state)),
            jnp.int32(self.win._off(round)),
            jnp.asarray(onehot),
        )
        return np.asarray(mask)

    # -- protocol ---------------------------------------------------------
    def process_certificate(
        self,
        state: ConsensusState,
        consensus_index: SequenceNumber,
        certificate: Certificate,
    ) -> list[ConsensusOutput]:
        round = certificate.round
        state.add(certificate)  # host mirror for recovery parity
        keep_floor = max(0, state.last_committed_round - self.gc_depth)
        if not self.win.insert(certificate, keep_floor):
            raise RuntimeError(
                f"round {round} outside DAG window (base {self.win.round_base}, W {self.win.W})"
            )

        r = round - 1
        if r % 2 != 0 or r < 2:
            return []
        if r <= state.last_committed_round:
            return []
        leader_idx = self._leader_index(r, state.dag)
        if leader_idx is None:
            return []

        support = int(
            leader_support(
                jnp.asarray(self.win.parent),
                jnp.asarray(self.win.present),
                jnp.asarray(self.win.stakes),
                jnp.int32(self.win._off(round)),
                jnp.int32(leader_idx),
            )
        )
        if support < self.committee.validity_threshold():
            return []

        # Chain of linked leaders, newest to oldest (order_leaders).
        chain: list[tuple[Round, int]] = [(r, leader_idx)]
        cur_round, cur_idx = r, leader_idx
        cur_reach = self._reach(state, cur_round, cur_idx)
        for lr in range(r - 2, state.last_committed_round + 1, -2):
            prev_idx = self._leader_index(lr, state.dag)
            if prev_idx is None:
                continue
            off = self.win._off(lr)
            if 0 <= off < self.win.W and cur_reach[off, prev_idx]:
                chain.append((lr, prev_idx))
                cur_round, cur_idx = lr, prev_idx
                cur_reach = self._reach(state, cur_round, cur_idx)

        sequence: list[ConsensusOutput] = []
        for lr, lidx in reversed(chain):
            mask = self._reach(state, lr, lidx)
            # GC retain bound is evaluated at flatten time, before this
            # leader's own updates advance last_committed_round (the host
            # order_dag computes its filtered list up front).
            lcr_at_flatten = state.last_committed_round
            order = np.argwhere(mask)  # row-major: ascending (offset, authority)
            for off, aidx in order:
                cround = self.win.round_base + int(off)
                if cround + self.gc_depth < lcr_at_flatten:
                    continue
                cert = self.win.cert_at(cround, int(aidx))
                if cert is None:
                    continue
                state.update(cert, self.gc_depth)
                sequence.append(
                    ConsensusOutput(certificate=cert, consensus_index=consensus_index)
                )
                consensus_index += 1
                if self.store is not None:
                    self.store.write_consensus_state(
                        state.last_committed, consensus_index - 1, cert.digest
                    )
        return sequence

    def update_committee(self, new_committee: Committee) -> None:
        self.committee = new_committee
        self.win = DagWindow(new_committee, self.win.W)
