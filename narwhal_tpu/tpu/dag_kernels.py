"""Vectorized DAG kernels: the consensus commit walk as adjacency tensors.

Reference hot loop: /root/reference/consensus/src/utils.rs:11-101 — per-commit
pointer-chasing DFS (order_dag), frontier filtering (linked) and per-round
leader support counting — all O(window x committee) sequential work on CPU.

TPU-first redesign (SURVEY §5.8, §7.8b): the DAG window is dense tensors
  present[W, N]   uint8 — certificate exists at (round offset, authority)
  parent [W, N, N] uint8 — parent[w, a, p] = cert (w, a) links (w-1, p)
  stakes [N]      int32
with W = round-window size (>= gc_depth + slack) and N = committee size.
Reachability from any certificate is a backward scan of N x N bitwise matmuls
(MXU/VPU work, no pointer chasing); leader support is one masked dot product.
Commit traversal must not pass *through* already-committed certificates
(the DFS skip in utils.rs:86-89), so propagation masks them out via
last_committed[N].

All kernels are jit-compiled with static shapes; round offsets and indices
are traced scalars so one compilation serves every call. `TpuBullshark`
wraps them behind the exact ConsensusProtocol interface and is
equivalence-tested against the host engine on random lossy DAGs
(tests/test_dag_kernels.py).
"""

from __future__ import annotations

import functools
import logging
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import enable_compilation_cache
from . import kernel_registry

enable_compilation_cache()

from ..config import Committee
from ..stores import ConsensusStore
from ..types import Certificate, ConsensusOutput, Digest, Round, SequenceNumber
from ..consensus.state import ConsensusState


@kernel_registry.tracked_jit
def reach_mask(parent, uncommitted, start_off, start_onehot):
    """Reachability mask [W, N]: certificates reachable from the start
    certificate by walking parent links down the window, propagating only
    through uncommitted certificates (the vectorized order_dag/linked core).

    parent: uint8 [W, N, N]; uncommitted: uint8 [W, N] (present & not yet
    committed); start_off: int32 round offset; start_onehot: uint8 [N].
    """
    W, N, _ = parent.shape

    def step(frontier_above, w):
        # frontier_above = mask row already computed for offset w+1
        links = jnp.take(parent, jnp.minimum(w + 1, W - 1), axis=0)  # [N, N]
        from_above = (links.astype(jnp.int32).T @ frontier_above.astype(jnp.int32)) > 0
        here = jnp.where(
            w == start_off,
            start_onehot.astype(bool),
            jnp.where(w < start_off, from_above, False),
        )
        here = here & uncommitted[w].astype(bool)
        # Certificates below the start that are committed must not relay the
        # frontier; `here` is already masked by uncommitted, and the start
        # row is the leader itself (always explored, like the DFS root).
        return here.astype(jnp.int32), here

    ws = jnp.arange(W - 1, -1, -1)
    _, rows = lax.scan(step, jnp.zeros((N,), jnp.int32), ws)
    return rows[::-1]  # [W, N] bool, row w = offset w


@kernel_registry.tracked_jit(donate_argnums=(0, 1))
def roll_window(parent, present, shift):
    """Slide the device-resident window by `shift` rounds: drop the oldest
    `shift` rows and zero the vacated tail. One on-device shuffle instead of
    a full [W, N, N] host->device re-upload when GC advances the base.
    The window tensors are donated: the previous generation is dead the
    moment the roll dispatches, so XLA reuses its buffers instead of
    holding two [W, N, N] copies live."""
    W = present.shape[0]
    rows = jnp.arange(W, dtype=jnp.int32)
    keep = rows < (W - shift)
    present = jnp.roll(present, -shift, axis=0) * keep[:, None].astype(present.dtype)
    parent = jnp.roll(parent, -shift, axis=0) * keep[:, None, None].astype(parent.dtype)
    return parent, present


@kernel_registry.tracked_jit(donate_argnums=(0, 1))
def place_batch(parent, present, offs, idxs, rows, valid):
    """Scatter a batch of certificate placements into the device-resident
    window: for each valid slot t, present[offs[t], idxs[t]] = 1 and
    parent[offs[t], idxs[t], :] = rows[t]. Padded slots (valid=0) are
    no-ops, so power-of-two padded batches reuse one compilation per size.
    Donates the window tensors (see roll_window)."""

    def body(carry, inp):
        parent, present = carry
        off, idx, row, v = inp
        live = v.astype(bool)
        cur_row = parent[off, idx]
        cur_p = present[off, idx]
        parent = parent.at[off, idx].set(jnp.where(live, row, cur_row))
        present = present.at[off, idx].set(
            jnp.where(live, jnp.uint8(1), cur_p).astype(present.dtype)
        )
        return (parent, present), jnp.int32(0)

    (parent, present), _ = lax.scan(body, (parent, present), (offs, idxs, rows, valid))
    return parent, present


@kernel_registry.tracked_jit
def leader_support(parent, present, stakes, support_off, leader_idx):
    """Stake carried by certificates at `support_off` linking to the leader at
    the round below (bullshark.rs:66-76 / tusk.rs:66-74)."""
    links = jnp.take(parent, support_off, axis=0)[:, leader_idx]  # [N]
    voters = links.astype(bool) & jnp.take(present, support_off, axis=0).astype(bool)
    return jnp.sum(jnp.where(voters, stakes, 0))


@kernel_registry.tracked_jit
def chain_commit(parent, present, gc_depth, lc_rel, lcr_rel, offs, onehots):
    """One fused dispatch per commit event: the full chain flatten — a
    lax.scan over the chain's leaders (oldest first), each step computing
    that leader's reach mask through the certificates still uncommitted *at
    that point in the chain* and advancing the per-authority last-committed
    vector exactly as the host's state.update does between order_dag calls.

    parent [W,N,N] u8, present [W,N] u8; gc_depth i32;
    lc_rel [N] i32 = last committed round per authority, relative to the
    window base (may be negative); lcr_rel i32 = last committed round
    (max over authorities), relative; offs [K] i32 / onehots [K,N] u8 =
    chain leaders oldest-first, zero-padded (a zero onehot is a no-op slot).

    Returns masks [K,W,N] bool: post-GC-filter commit sets per leader; the
    host only gathers certificates and appends outputs from them.
    """
    W, N, _ = parent.shape
    rows = jnp.arange(W, dtype=jnp.int32)

    def per_leader(carry, inp):
        lc, lcr = carry
        off, onehot = inp
        uncommitted = (present.astype(bool) & (rows[:, None] > lc[None, :])).astype(
            jnp.uint8
        )
        mask = reach_mask(parent, uncommitted, off, onehot)  # [W, N] bool
        # order_dag's GC filter (utils.rs:93-97): drop certificates whose
        # round has fallen gc_depth behind the pre-flatten committed round.
        keep = mask & (rows[:, None] + gc_depth >= lcr)
        committed_rounds = jnp.max(
            jnp.where(keep, rows[:, None], jnp.int32(-(2**30))), axis=0
        )
        lc = jnp.maximum(lc, committed_rounds)
        lcr = jnp.maximum(lcr, jnp.max(committed_rounds))
        return (lc, lcr), keep

    _, masks = lax.scan(per_leader, (lc_rel, lcr_rel), (offs, onehots))
    return masks


# (W, N, auth-shards) chain_commit shapes already queued for background
# compilation in this process (prewarm dedupe across engine instances).
_PREWARMED_SHAPES: set[tuple[int, int, int]] = set()
# Live prewarm threads, joined at interpreter exit: a daemon thread frozen
# inside XLA C++ during Python finalization aborts the whole process
# ("FATAL: exception not rethrown"), so exit must wait for in-flight
# compiles. Long-lived nodes finish them long before shutdown; one-shot
# tools pass prewarm=False and never start them.
_PREWARM_THREADS: list = []
_PREWARM_ATEXIT = False


def _prune_prewarm_threads() -> None:
    """Drop finished threads so a long-lived node doesn't accumulate one
    Thread object per window doubling."""
    _PREWARM_THREADS[:] = [t for t in _PREWARM_THREADS if t.is_alive()]


def _join_prewarm_threads(grace: float = 60.0) -> None:
    # Bounded join: waiting forever would make a hung tunneled device (stuck
    # mid-compile in XLA C++) block process exit outright. 60 s is enough
    # for any cache-served compile; a thread still alive after that is
    # logged and abandoned — a daemon thread, so it cannot keep the
    # interpreter alive, and the abort-on-finalization hazard the join
    # exists to avoid is already vanishingly rare at that point.
    deadline = time.monotonic() + grace
    for t in list(_PREWARM_THREADS):
        t.join(timeout=max(0.0, deadline - time.monotonic()))
        if t.is_alive():
            logging.getLogger("narwhal.tpu.dag").warning(
                "prewarm compile thread %s did not finish within the exit "
                "join window; abandoning it",
                t.name,
            )
    _prune_prewarm_threads()


def join_prewarm_threads(grace: float = 60.0) -> None:
    """Bounded-join every in-flight background window compile. Called from
    `PrimaryNode.shutdown` (off-loop) so a node's prewarm threads cannot
    outlive it and contend with a successor's foreground traces for XLA's
    compiler locks — the PR-1 stabilization failure mode, previously
    handled only by the atexit hook (process exit), not node teardown."""
    _join_prewarm_threads(grace)


class DagWindow:
    """Host-managed ring of the last W rounds as dense arrays, with the
    digest <-> (round, authority) maps the tensors can't hold. This is the
    'long context' of the system: rounds are the sequence axis, the committee
    the width (SURVEY §5.8).

    `pad_authorities_to` widens the committee axis of the tensors with
    always-absent slots (present=0, stake=0) so the axis divides evenly
    across a device mesh's 'auth' dimension; padding is invisible to the
    protocol — padded slots never hold certificates, relay reachability or
    carry stake."""

    def __init__(
        self,
        committee: Committee,
        window: int = 64,
        pad_authorities_to: int | None = None,
        device_resident: bool = False,
    ):
        self.committee = committee
        n = committee.size()
        self.N = max(n, pad_authorities_to or 0)
        self.W = window
        self.round_base: Round = 0
        self.present = np.zeros((self.W, self.N), np.uint8)
        self.parent = np.zeros((self.W, self.N, self.N), np.uint8)
        stakes = np.zeros((self.N,), np.int32)
        stakes[:n] = np.asarray(committee.stakes_array(), np.int32)
        self.stakes = stakes
        self.certs: dict[tuple[Round, int], Certificate] = {}
        self.digest_pos: dict[Digest, tuple[Round, int]] = {}
        # Device-resident mirror (device_resident=True): the tensors live on
        # device between dispatches; inserts buffer as pending coordinates
        # and apply as ONE batched on-device scatter at the next
        # device_view(), window slides as one on-device roll. The hot read
        # path therefore never re-uploads the [W, N, N] adjacency.
        self._dev_resident = device_resident
        self._dev: tuple | None = None
        self._dev_base: Round = 0
        self._dev_stale = True
        self._dev_pending: list[tuple[Round, int]] = []
        # Genesis certificates occupy round 0.
        for cert in Certificate.genesis(committee):
            self._place(cert)

    def _off(self, round: Round) -> int:
        return round - self.round_base

    def _place(self, cert: Certificate) -> None:
        idx = self.committee.index_of(cert.origin)
        off = self._off(cert.round)
        self.present[off, idx] = 1
        self.certs[(cert.round, idx)] = cert
        self.digest_pos[cert.digest] = (cert.round, idx)
        for pd in cert.header.parents:
            pos = self.digest_pos.get(pd)
            if pos is not None and pos[0] == cert.round - 1:
                self.parent[off, idx, pos[1]] = 1
        if self._dev_resident:
            self._dev_pending.append((cert.round, idx))

    def insert(self, cert: Certificate, keep_floor: Round) -> bool:
        """Add a certificate; slides the window forward (dropping only rounds
        below keep_floor, the GC bound) or grows it when commits lag behind
        round production. Returns False only for certificates below the
        already-GC'd base."""
        if cert.round < self.round_base:
            return False
        while cert.round - self.round_base >= self.W:
            target = cert.round - self.W + 1
            if target <= keep_floor:
                self.slide_to(target)
            elif keep_floor > self.round_base:
                self.slide_to(keep_floor)
                self._grow()
            else:
                self._grow()
        self._place(cert)
        return True

    def _grow(self) -> None:
        """Double W (recompiles the jitted kernels for the new static shape —
        rare, only when the uncommitted span outgrows the window)."""
        new_w = self.W * 2
        present = np.zeros((new_w, self.N), np.uint8)
        parent = np.zeros((new_w, self.N, self.N), np.uint8)
        present[: self.W] = self.present
        parent[: self.W] = self.parent
        self.present, self.parent, self.W = present, parent, new_w
        self._dev_stale = True  # shape change: next device_view re-uploads

    def slide_to(self, new_base: Round) -> None:
        shift = new_base - self.round_base
        if shift <= 0:
            return
        if shift >= self.W:
            self.present[:] = 0
            self.parent[:] = 0
        else:
            self.present[:-shift] = self.present[shift:]
            self.present[-shift:] = 0
            self.parent[:-shift] = self.parent[shift:]
            self.parent[-shift:] = 0
        dropped = [(r, i) for (r, i) in self.certs if r < new_base]
        for key in dropped:
            cert = self.certs.pop(key)
            self.digest_pos.pop(cert.digest, None)
        self.round_base = new_base

    def cert_at(self, round: Round, idx: int) -> Certificate | None:
        return self.certs.get((round, idx))

    # -- device residency --------------------------------------------------

    def device_view(self):
        """The (parent, present) tensors resident on device, synced to the
        host mirror. Steady state is incremental: pending placements apply
        as one power-of-two-padded `place_batch` scatter and a slid base as
        one `roll_window` shuffle — zero [W, N, N] host->device traffic on
        the hot path. A full upload happens only on first use and after
        `_grow` (shape change)."""
        import jax.numpy as jnp

        if self._dev is None or self._dev_stale:
            self._dev = (jnp.asarray(self.parent), jnp.asarray(self.present))
            self._dev_base = self.round_base
            self._dev_stale = False
            self._dev_pending.clear()
            return self._dev
        parent, present = self._dev
        if self.round_base != self._dev_base:
            parent, present = roll_window(
                parent, present, np.int32(self.round_base - self._dev_base)
            )
            self._dev_base = self.round_base
        if self._dev_pending:
            # Rows come from the host mirror at sync time, so a placement's
            # final parent links are always what lands on device; entries
            # GC'd below the base since they were buffered are dropped.
            pend = [
                (r - self.round_base, i)
                for (r, i) in self._dev_pending
                if r >= self.round_base
            ]
            self._dev_pending.clear()
            if pend:
                k = len(pend)
                kpad = 1 if k <= 1 else 1 << (k - 1).bit_length()
                offs = np.zeros((kpad,), np.int32)
                idxs = np.zeros((kpad,), np.int32)
                rows = np.zeros((kpad, self.N), np.uint8)
                valid = np.zeros((kpad,), np.uint8)
                for t, (off, idx) in enumerate(pend):
                    offs[t] = off
                    idxs[t] = idx
                    rows[t] = self.parent[off, idx]
                    valid[t] = 1
                parent, present = place_batch(
                    parent, present, offs, idxs, rows, valid
                )
        self._dev = (parent, present)
        return self._dev


class TpuBullshark:
    """Bullshark with the DAG walks on device. Drop-in for
    consensus.Bullshark (same process_certificate signature/semantics,
    equivalence-tested); the host retains only bookkeeping and the final
    index->certificate gather.

    With `mesh` set (a jax.sharding.Mesh containing an 'auth' axis) the
    production chain_commit dispatch shards the committee axis of the DAG
    tensors across devices — parent [W,N,N] over its link axis, present
    [W,N] and last_committed [N] over N — exactly the layout
    __graft_entry__.dryrun_multichip validates; XLA inserts the ICI
    collectives for the per-round frontier psum (SURVEY §5.8: the window as
    a first-class sharding axis). The committee axis is padded to a
    multiple of the 'auth' size with always-absent slots."""

    def __init__(
        self,
        committee: Committee,
        store: ConsensusStore | None,
        gc_depth: Round,
        leader_fn=None,
        window: int | None = None,
        mesh=None,
        prewarm: bool | None = None,
    ):
        self.committee = committee
        self.store = store
        self.gc_depth = gc_depth
        self._leader_fn = leader_fn
        self.mesh = mesh
        # Unmeshed engines keep the window resident on device (the meshed
        # dispatch places operands itself via in_shardings, so it keeps the
        # host mirror as its operand source).
        self.win = DagWindow(
            committee, window or (gc_depth + 14),
            pad_authorities_to=self._pad_for(committee),
            device_resident=(mesh is None),
        )
        self._chain_commit = self._build_dispatch()
        self._dispatch_W = self.win.W
        if prewarm is None:
            # Default only — an explicit prewarm=True/False always wins.
            # Background compiles contend with foreground jit traces for
            # XLA's compiler locks; on a single-core host that serializes
            # every later trace behind a minutes-long compile (and has
            # wedged concurrent traces outright), so test suites on such
            # hosts export NARWHAL_TPU_PREWARM=0.
            prewarm = os.environ.get("NARWHAL_TPU_PREWARM", "1") != "0"
        self._prewarm_enabled = prewarm
        self._prewarm_threads: list = []
        if prewarm:
            # Compile the NEXT window size ahead of need: _grow() doubles W
            # mid-stream precisely when the node is already behind on
            # commits, and an uncached XLA compile there stalls the commit
            # path for seconds-to-minutes. The background compile writes
            # the persistent compilation cache, so the post-growth dispatch
            # is a (fast) cache deserialization instead of a compile.
            self._prewarm(self.win.W * 2)

    @property
    def _warmed(self):
        return _PREWARMED_SHAPES

    def _prewarm(self, W: int) -> None:
        # Deduped process-wide: 20 in-process engines must not spawn 20
        # concurrent compiles of the identical shape.
        key = (W, self.win.N, self.mesh.shape["auth"] if self.mesh else 0)
        if key in _PREWARMED_SHAPES:
            return
        _PREWARMED_SHAPES.add(key)
        import threading

        def compile_ahead():
            try:
                N = self.win.N
                for kpad in (1, 2, 4):  # steady state + catch-up chain buckets
                    self._chain_commit.lower(
                        np.zeros((W, N, N), np.uint8),
                        np.zeros((W, N), np.uint8),
                        np.int32(0),
                        np.zeros((N,), np.int32),
                        np.int32(-1),
                        np.zeros((kpad,), np.int32),
                        np.zeros((kpad, N), np.uint8),
                    ).compile()
            except Exception:  # pragma: no cover - warmup is best-effort
                import logging

                # Transient failures (tunnel hiccups) must not permanently
                # disable prewarming this shape for the process.
                _PREWARMED_SHAPES.discard(key)
                logging.getLogger("narwhal.tpu").warning(
                    "window prewarm failed for %s", key, exc_info=True
                )

        global _PREWARM_ATEXIT
        if not _PREWARM_ATEXIT:
            import atexit

            atexit.register(_join_prewarm_threads)
            _PREWARM_ATEXIT = True
        _prune_prewarm_threads()
        self._prewarm_threads = [t for t in self._prewarm_threads if t.is_alive()]
        t = threading.Thread(target=compile_ahead, daemon=True)
        t.start()
        self._prewarm_threads.append(t)
        _PREWARM_THREADS.append(t)

    def _pad_for(self, committee: Committee) -> int | None:
        """Committee-axis width the mesh requires: the next multiple of the
        'auth' axis size (None when unmeshed)."""
        if self.mesh is None:
            return None
        auth = self.mesh.shape["auth"]
        return -(-committee.size() // auth) * auth

    def _build_dispatch(self):
        """The chain_commit entry point: the module-level tracked kernel on
        a single device, or the REGISTRY's mesh-sharded wrapper when a mesh
        is configured — one jit per (chain_commit, mesh shape) process-wide,
        so N co-hosted engines (and every window regrowth) share one
        compiled program per W instead of re-jitting. Scalars and the small
        per-leader operands are replicated (empty PartitionSpec) so no
        operand ever falls back to the default backend's device placement."""
        if self.mesh is None:
            return chain_commit
        from jax.sharding import PartitionSpec as P

        return kernel_registry.sharded(
            chain_commit,
            self.mesh,
            in_specs=(
                P(None, None, "auth"),  # parent [W, N, N]: link axis
                P(None, "auth"),  # present [W, N]
                None,  # gc_depth scalar
                P("auth"),  # lc_rel [N]
                None,  # lcr_rel scalar
                None,  # offs [K]
                P(None, None),  # onehots [K, N]
            ),
            out_specs=P(None, None, "auth"),
        )

    def recover(self, state: ConsensusState) -> None:
        """Rebuild the device window from a recovered host state (the
        consensus runner's ConsensusState.new_from_store) so a restarted node
        resumes committing from the on-disk DAG. Insertion is round-ascending
        because parent links resolve against already-placed digests."""
        keep_floor = max(0, state.last_committed_round - self.gc_depth)
        for round in sorted(state.dag):
            for _, cert in state.dag[round].values():
                self.win.insert(cert, keep_floor)

    # -- leader election --------------------------------------------------
    def _leader_index(self, round: Round, dag) -> int | None:
        if self._leader_fn is not None:
            entry = self._leader_fn(self.committee, round, dag)
            if entry is None:
                return None
            return self.committee.index_of(entry[1].origin)
        name = self.committee.leader(round)
        idx = self.committee.index_of(name)
        off = self.win._off(round)
        # DagWindow is mutated only by the Dag task's ingest/flush, never
        # mid-yield; consensus reads tolerate a one-flush-stale window
        # (absent leader just means "not present yet" — retried next round).
        if 0 <= off < self.win.W and self.win.present[off, idx]:  # lint: allow(multi-task-mutation)
            return idx
        return None

    # -- host bookkeeping -------------------------------------------------
    def _linked_np(self, round: Round, idx: int, prev_round: Round, prev_idx: int) -> bool:
        """Host-side chain linkage between consecutive even-round leaders
        (utils.rs:40-53 `linked`): a 2-round frontier propagation over the
        numpy parent mirror — O(N^2) bookkeeping, not the hot walk."""
        frontier = np.zeros((self.win.N,), bool)
        frontier[idx] = True
        for rr in range(round, prev_round, -1):
            off = self.win._off(rr)
            if not (0 <= off < self.win.W):
                return False
            # Same discipline as above: Dag-task-only writes, stale-tolerant
            # reads (missing links fail toward "not linked", retried later).
            links = self.win.parent[off]  # lint: allow(multi-task-mutation)
            frontier = (links[frontier].any(axis=0)) & self.win.present[
                self.win._off(rr - 1)
            ].astype(bool)
            if not frontier.any():
                return False
        return bool(frontier[prev_idx])

    def _lc_rel(self, state: ConsensusState) -> np.ndarray:
        lc = np.zeros((self.win.N,), np.int32)
        for pk, r in state.last_committed.items():
            lc[self.committee.index_of(pk)] = r
        return lc - np.int32(self.win.round_base)

    # -- protocol ---------------------------------------------------------
    def process_certificate(
        self,
        state: ConsensusState,
        consensus_index: SequenceNumber,
        certificate: Certificate,
    ) -> list[ConsensusOutput]:
        dispatch = self._ingest_and_dispatch(state, certificate)
        if dispatch is None:
            return []
        masks_dev, K = dispatch
        # Device->host readback of the commit masks: ~flat round-trip latency
        # on a tunneled chip, microseconds on a local one. The async variant
        # overlaps this with the node's event loop.
        masks = np.asarray(masks_dev)  # [Kpad, W, N] bool, post-GC commit sets
        return self._materialize(state, consensus_index, masks, K)

    async def process_certificate_async(
        self,
        state: ConsensusState,
        consensus_index: SequenceNumber,
        certificate: Certificate,
    ) -> list[ConsensusOutput]:
        """process_certificate with the device readback awaited off-thread so
        the node's event loop (workers, proposer, RPC) keeps running during
        the device->host round trip. Used by the Consensus runner; events
        stay serialized because the runner awaits each certificate in order."""
        import asyncio

        dispatch = self._ingest_and_dispatch(state, certificate)
        if dispatch is None:
            return []
        masks_dev, K = dispatch
        loop = asyncio.get_running_loop()
        masks = await loop.run_in_executor(None, np.asarray, masks_dev)
        return self._materialize(state, consensus_index, masks, K)

    def _commit_coords(self, round: Round) -> tuple[Round, Round] | None:
        """Bullshark rule (bullshark.rs:47-82): on a round-r+1 certificate
        the candidate leader sits at even round r, supported by round r+1.
        Returns (leader_round, support_round) or None when `round` cannot
        trigger a commit."""
        r = round - 1
        if r % 2 != 0 or r < 2:
            return None
        return r, round

    def _ingest(self, state: ConsensusState, certificate: Certificate) -> None:
        """Record one certificate in the host mirror + window (no dispatch)."""
        state.add(certificate)  # host mirror for recovery parity
        keep_floor = max(0, state.last_committed_round - self.gc_depth)
        if not self.win.insert(certificate, keep_floor):
            raise RuntimeError(
                f"round {certificate.round} outside DAG window "
                f"(base {self.win.round_base}, W {self.win.W})"
            )

    def _refresh_dispatch(self) -> None:
        if self.win.W != self._dispatch_W:
            # The window grew (or slid through a regrow): re-derive the
            # dispatch from the kernel registry instead of trusting the
            # wrapper captured at construction. Same mesh -> the registry
            # returns the same process-wide sharded program, so a meshed
            # engine keeps its 'auth'-partitioned layouts across growth
            # rather than silently re-tracing an unsharded (replicated)
            # kernel; tests/test_dag_kernels.py pins the invariant.
            self._chain_commit = self._build_dispatch()
            self._dispatch_W = self.win.W
        if self._prewarm_enabled:
            # Keep one doubling ahead of the current window size.
            self._prewarm(self.win.W * 2)

    def _eval_commit(self, state: ConsensusState, round: Round):
        """Evaluate the commit rule for a round-`round` certificate against
        SETTLED state and dispatch the fused chain walk when it commits.
        Returns (device masks, chain length) or None."""
        coords = self._commit_coords(round)
        if coords is None:
            return None
        leader_round, support_round = coords
        if leader_round <= state.last_committed_round:
            return None
        leader_idx = self._leader_index(leader_round, state.dag)
        if leader_idx is None:
            return None
        return self._dispatch_commit(state, leader_round, support_round, leader_idx)

    def _ingest_and_dispatch(self, state: ConsensusState, certificate: Certificate):
        """Shared pre-readback half of process_certificate: record the
        certificate, evaluate the commit rule on the host mirror, and — when
        this certificate commits a leader — dispatch the fused chain walk.
        Returns (device masks, chain length) or None."""
        self._ingest(state, certificate)
        self._refresh_dispatch()
        return self._eval_commit(state, certificate.round)

    def process_batch(
        self,
        state: ConsensusState,
        consensus_index: SequenceNumber,
        certificates: list[Certificate],
    ) -> list[ConsensusOutput]:
        """Batched process_certificate: all inserts land as ONE device
        scatter (the window syncs once, at the first commit dispatch), the
        commit rule is then evaluated per trigger in arrival order, and
        each commit event's mask readback is deferred one event so it
        overlaps the next event's host bookkeeping.

        The output sequence is IDENTICAL to per-certificate calls on the
        same (causally ordered) stream: Bullshark/Tusk re-evaluate the
        commit rule on every support-round certificate, a leader's reach
        mask covers only rounds at or below it, and chain linkage walks
        the LEADER's ancestry (present before the leader under causal
        delivery) — so batching arrivals can move where a commit is
        yielded, never its content or order. Each event still materializes
        before the next event's rule evaluation: last_committed gates both
        the rule and the GC filter."""
        for cert in certificates:
            self._ingest(state, cert)
        self._refresh_dispatch()
        outputs: list[ConsensusOutput] = []
        pending = None
        for cert in certificates:
            if self._commit_coords(cert.round) is None:
                continue
            if pending is not None:
                masks_dev, K = pending
                outs = self._materialize(
                    state, consensus_index, np.asarray(masks_dev), K
                )
                consensus_index += len(outs)
                outputs.extend(outs)
            pending = self._eval_commit(state, cert.round)
        if pending is not None:
            masks_dev, K = pending
            outputs.extend(
                self._materialize(state, consensus_index, np.asarray(masks_dev), K)
            )
        return outputs

    async def process_batch_async(
        self,
        state: ConsensusState,
        consensus_index: SequenceNumber,
        certificates: list[Certificate],
    ) -> list[ConsensusOutput]:
        """process_batch with each deferred readback awaited off-thread —
        the Consensus runner's greedy-drain path, so a certificate burst
        costs one batched insert and the loop keeps serving RPC during
        every device->host round trip."""
        import asyncio

        loop = asyncio.get_running_loop()
        for cert in certificates:
            self._ingest(state, cert)
        self._refresh_dispatch()
        outputs: list[ConsensusOutput] = []
        pending = None
        for cert in certificates:
            if self._commit_coords(cert.round) is None:
                continue
            if pending is not None:
                masks_dev, K = pending
                masks = await loop.run_in_executor(None, np.asarray, masks_dev)
                outs = self._materialize(state, consensus_index, masks, K)
                consensus_index += len(outs)
                outputs.extend(outs)
            pending = self._eval_commit(state, cert.round)
        if pending is not None:
            masks_dev, K = pending
            masks = await loop.run_in_executor(None, np.asarray, masks_dev)
            outputs.extend(self._materialize(state, consensus_index, masks, K))
        return outputs

    def _dispatch_commit(self, state, r, support_round, leader_idx):
        """Quorum pre-check + chain detection on the host mirror (cheap
        bookkeeping), then ONE fused device dispatch for every flatten walk
        of the commit event. `r` is the leader's round; support is counted
        among `support_round` certificates linking it. Returns (device
        masks, chain length) or None."""
        # Support quorum pre-check (one column read): a device readback costs
        # a full round trip, so dispatch only when this certificate commits.
        off_r = self.win._off(support_round)
        voters = self.win.parent[off_r, :, leader_idx].astype(bool) & self.win.present[
            off_r
        ].astype(bool)
        support = int(self.win.stakes[voters].sum())
        if support < self.committee.validity_threshold():
            return None

        # Chain of linked leaders (order_leaders): consecutive-leader linkage
        # spans only two rounds, so it is cheap host bookkeeping; the O(W*N^2)
        # flatten walks run on device in ONE fused dispatch.
        chain: list[tuple[Round, int]] = [(r, leader_idx)]
        cur_round, cur_idx = r, leader_idx
        for lr in range(r - 2, state.last_committed_round + 1, -2):
            prev_idx = self._leader_index(lr, state.dag)
            if prev_idx is None:
                continue
            if self._linked_np(cur_round, cur_idx, lr, prev_idx):
                chain.append((lr, prev_idx))
                cur_round, cur_idx = lr, prev_idx

        # Pad the chain to power-of-two bucket lengths so one compilation
        # serves steady state (K=1) and catch-up bursts alike.
        chain = list(reversed(chain))  # oldest first, scan order
        K = len(chain)
        Kpad = 1
        while Kpad < K:
            Kpad *= 2
        offs = np.zeros((Kpad,), np.int32)
        onehots = np.zeros((Kpad, self.win.N), np.uint8)
        for i, (lr, lidx) in enumerate(chain):
            offs[i] = self.win._off(lr)
            onehots[i, lidx] = 1

        # Meshed: numpy operands, placed per in_shardings. Unmeshed: the
        # device-resident window, so the commit walk uploads nothing but
        # the per-event scalars and the [Kpad, N] leader onehots.
        if self.mesh is None:
            parent_op, present_op = self.win.device_view()
        else:
            parent_op, present_op = self.win.parent, self.win.present
        masks_dev = self._chain_commit(
            parent_op,
            present_op,
            np.int32(self.gc_depth),
            self._lc_rel(state),
            np.int32(state.last_committed_round - self.win.round_base),
            offs,
            onehots,
        )
        # Start the device->host copy as soon as the walk finishes so the
        # materialization readback finds the masks already local.
        try:
            masks_dev.copy_to_host_async()
        except AttributeError:
            pass
        return masks_dev, K

    def _materialize(
        self, state: ConsensusState, consensus_index: SequenceNumber, masks, K: int
    ) -> list[ConsensusOutput]:
        """Gather certificates from the per-leader commit masks, update the
        host recovery state and persist, in canonical (round, origin) order."""
        sequence: list[ConsensusOutput] = []
        for k in range(K):
            order = np.argwhere(masks[k])  # ascending (offset, authority)
            for off, aidx in order:
                cert = self.win.cert_at(self.win.round_base + int(off), int(aidx))
                if cert is None:
                    continue
                state.update(cert, self.gc_depth)
                sequence.append(
                    ConsensusOutput(certificate=cert, consensus_index=consensus_index)
                )
                consensus_index += 1
                if self.store is not None:
                    self.store.write_consensus_state(
                        state.last_committed, consensus_index - 1, cert.digest
                    )
        return sequence

    def update_committee(self, new_committee: Committee) -> None:
        self.committee = new_committee
        self.win = DagWindow(
            new_committee,
            self.win.W,
            pad_authorities_to=self._pad_for(new_committee),
            device_resident=(self.mesh is None),
        )


class TpuTusk(TpuBullshark):
    """Tusk with the DAG walks on device: identical machinery to
    TpuBullshark, the asynchronous commit rule (tusk.rs:47-82): a round-r
    certificate (r-1 even, r-1 >= 4) makes the leader at round r-3 a commit
    candidate, supported by its children at round r-2 carrying >= f+1
    stake. Drop-in for consensus.Tusk."""

    def _commit_coords(self, round: Round) -> tuple[Round, Round] | None:
        r = round - 1
        if r % 2 != 0 or r < 4:
            return None
        return r - 2, r - 1
