"""TpuVerifier: host wrapper turning (pk, msg, sig) batches into fixed-shape
device dispatches of the ed25519 kernel.

Plugs into the batch-verification seam (crypto.set_batch_verifier) that the
primary's certificate path and the worker's batch path call — the TPU-era
`TpuVerifier` service of SURVEY §7.8a. Responsibilities:

- host prechecks the kernel doesn't do: length, canonical S (< L), canonical
  R/A encodings (y < p);
- the SHA-512 challenge k = H(R || A || M) mod L (hashlib is C-speed; the
  device only sees 256-bit scalars as 4-bit window digits);
- shape bucketing: pad each call to the next power-of-two batch so XLA
  compiles a handful of programs, not one per batch size;
- CPU fallback when no device kernel is usable (import or platform failure).

An async coalescing front (`AsyncVerifierPool`) batches concurrent requests
with a size-or-deadline window, the BatchMaker pattern applied to crypto
(SURVEY §7 "hard parts": offload must be batched or it adds latency).
"""

from __future__ import annotations

import asyncio
import collections
import hashlib
import logging
import queue
import threading
import time
from typing import Sequence

import numpy as np

from ..config import ConfigError
from ..crypto import BatchItem

logger = logging.getLogger("narwhal.tpu.verifier")

_MIN_BUCKET = 16
_MAX_BUCKET = 8192


def _scalar_lib():
    """The native host scalar pipeline, or None (pure-Python fallback)."""
    from ..native import load_scalar

    return load_scalar()


def _next_pow2(n: int) -> int:
    b = _MIN_BUCKET
    while b < n:
        b *= 2
    return b


def _sharded_kernels(kernel, mesh, data_axis: str):
    """The mesh-sharded verify pipeline: STAGED kernels from the process-
    wide registry (kernel_registry.sharded — one compile per (kernel, mesh
    shape) no matter how many verifiers/modes share the mesh).

    The monolithic verify_batch_kernel/msm_accumulate_kernel traces compile
    as single multi-minute XLA modules (the MULTICHIP_r05 rc=124 bill);
    the sharded variant dispatches the split stages instead —
    ed25519.verify_decompress_kernel (ONE ladder compile serving the A set,
    the R set, AND both msm point sets), verify_straus_kernel,
    verify_verdict_kernel, msm_window_kernel — with intermediates resident
    on device between stages and donated forward. Per-lane arithmetic is
    identical to the monoliths, so verdicts are bit-equal.

    Returns (item_fn, msm_fn) with the monoliths' host-facing signatures.
    """
    from jax.sharding import PartitionSpec as P

    from . import kernel_registry

    b = P(data_axis)  # [B]
    bn = P(data_axis, None)  # [B, NLIMB] / [B, W] host-layout rows
    cnb = P(None, None, data_axis)  # [4, NLIMB, B] coord stacks

    decompress = kernel_registry.sharded(
        kernel.verify_decompress_kernel, mesh,
        in_specs=(bn, b), out_specs=(cnb, b),
    )
    straus = kernel_registry.sharded(
        kernel.verify_straus_kernel, mesh,
        in_specs=(cnb, bn, bn), out_specs=cnb,
        donate_argnums=(0,),
    )
    # No donation on verdict/msm_window: their outputs are far smaller
    # than the coordinate-stack inputs, so nothing could alias and jax
    # would warn 'donated buffers were not usable' on every compile.
    verdict = kernel_registry.sharded(
        kernel.verify_verdict_kernel, mesh,
        in_specs=(cnb, cnb, bn, b, b, b), out_specs=(b, b),
    )
    msm_window = kernel_registry.sharded(
        kernel.msm_window_kernel, mesh,
        # V [4, NLIMB, W] has no batch axis left: per-device partial
        # accumulates, one XLA-inserted cross-device reduce (replicated).
        in_specs=(cnb, bn), out_specs=None,
        static_argnames=("chunk",),
    )

    def item_fn(a_y, a_sign, r_y, r_sign, k_digits, s_digits):
        a_pt, a_valid = decompress(a_y, a_sign)
        r_pt, r_valid = decompress(r_y, r_sign)
        acc = straus(a_pt, k_digits, s_digits)
        return verdict(acc, r_pt, r_y, r_sign, a_valid, r_valid)

    def msm_fn(a_y, a_sign, r_y, r_sign, ak_digits, z_digits):
        a_pt, a_valid = decompress(a_y, a_sign)
        r_pt, r_valid = decompress(r_y, r_sign)
        v_a = msm_window(a_pt, ak_digits)
        v_r = msm_window(r_pt, z_digits)
        return v_a, v_r, a_valid & r_valid

    return item_fn, msm_fn


def msm_epilogue_check(
    va_limbs: np.ndarray, vr_limbs: np.ndarray, sum_s: int, kernel
) -> bool:
    """Host half of the batch check: Horner-collapse the device's
    per-window point sums and test
    [8]([Σ z_iS_i]B + Σ_w 16^(63-w) (V_a[w] + V_r[w-32])) == identity.

    va_limbs: int32[4, NLIMB, 64] and vr_limbs: int32[4, NLIMB, 32] loose
    X/Y/Z/T limbs from msm_accumulate_kernel (MSB-first window lanes; the
    R accumulator covers only the low 32 windows because z_i < 2^128).
    ~450 bigint point ops (~2 ms), amortized over the whole batch; the
    device equivalent would be sub-tile sequential work costing hundreds
    of ms.

    COFACTORED (the [8]·): torsion components of adversarial A/R cancel
    deterministically, so acceptance never depends on the random z_i — a
    cofactorless batch would accept a torsion-defect signature with
    probability 1/8 over z, making two honest verifiers of the SAME bytes
    disagree at random (a consensus-splitting vector). This matches
    ed25519-dalek's batch_verify semantics (RFC 8032 cofactored); the
    strict per-item rule differs on such crafted inputs, so in msm mode
    every per-item verdict (small buckets, fallback) also uses the
    kernel's device-computed cofactored output, keeping the whole tpu
    backend deterministic.
    Committees must not mix cofactored (tpu) and cofactorless (cpu host
    library) backends if adversarially-crafted torsion keys are a concern.
    """
    ref = kernel.ref
    Wa = va_limbs.shape[2]
    off = Wa - vr_limbs.shape[2]

    def window_point(v, w):
        return tuple(kernel.limbs_to_int(v[c, :, w]) % ref.P for c in range(4))

    acc = (0, 1, 1, 0)  # identity, extended coordinates
    for w in range(Wa):
        for _ in range(4):
            acc = ref.point_double(acc)
        acc = ref.point_add(acc, window_point(va_limbs, w))
        if w >= off:
            acc = ref.point_add(acc, window_point(vr_limbs, w - off))
    acc = ref.point_add(acc, ref.point_mul(sum_s % ref.L, ref.G))
    for _ in range(3):  # cofactor 8
        acc = ref.point_double(acc)
    # Identity ⇔ X ≡ 0 and Y ≡ Z (mod p).
    return acc[0] % ref.P == 0 and (acc[1] - acc[2]) % ref.P == 0


class TpuVerifier:
    """Synchronous batch verifier backed by the JAX kernels.

    mode="msm" (default): one random-linear-combination check per bucket —
    [Σ z_iS_i]B − Σ[z_ik_i]A_i − Σ[z_i]R_i == 0 with fresh 128-bit z_i —
    sharing a single doubling chain across the whole bucket (~2x the
    per-item kernel's throughput). A failed bucket (any bad or malformed
    signature) falls back to the per-item kernel to locate offenders, so
    adversarial input degrades one bucket to ~old cost, never correctness.
    All msm-mode verdicts — the batch check, small buckets and the
    per-item fallback — use the device-computed COFACTORED rule, so the
    accept set is deterministic and independent of flush composition.
    mode="item": always the per-item Straus kernel, strict verdict.
    """

    def __init__(
        self,
        max_bucket: int = _MAX_BUCKET,
        mode: str | None = None,
        msm_min_bucket: int = 512,
        fixed_bucket: bool = False,
        mesh=None,
        data_axis: str = "data",
    ):
        import os

        from . import ed25519 as kernel  # deferred: imports jax

        self.kernel = kernel
        self.max_bucket = max_bucket
        self.mode = mode or os.environ.get("NARWHAL_TPU_VERIFY_MODE", "msm")
        # Small buckets stay on the per-item kernel: they're the latency
        # path, the msm advantage is amortization, and each extra bucket
        # shape costs a multi-minute first compile.
        self.msm_min_bucket = msm_min_bucket
        # fixed_bucket pads EVERY dispatch to max_bucket: one shape means
        # one jit trace per process (~60 s of single-core Python for the
        # big kernels — the persistent cache only skips the XLA compile,
        # not tracing) and the device cost is link-RTT-dominated anyway
        # (a 16-item and a 4096-item dispatch both take ~100 ms through
        # the tunnel). The protocol-serving VerifyService runs this way.
        self.fixed_bucket = fixed_bucket
        # mesh: shard verify batches over the mesh's data axis (SURVEY
        # §7.8a's TpuVerifier service at §5.8 scale — the certificate
        # analog of `--dag-shards` for the commit walk). Items are
        # embarrassingly parallel; the per-item kernel shards its whole
        # batch, the msm kernel's shared accumulator V comes back via the
        # XLA-inserted cross-device reduction. Constraint: every bucket
        # size (powers of two up to max_bucket) must be divisible by the
        # data-axis size.
        self.mesh = mesh
        if mesh is not None:
            # Fail at CONSTRUCTION, not first dispatch: every bucket this
            # verifier can ever pad to is a power of two in
            # [_MIN_BUCKET, max_bucket] (or exactly max_bucket when
            # fixed_bucket), and the data axis must divide each — a
            # mis-sized mesh must stop a node at startup the way
            # verify_rule validation does, not stall it at the first
            # verify (advisor r4).
            if data_axis not in mesh.shape:
                raise ConfigError(
                    f"verifier mesh has no {data_axis!r} axis "
                    f"(axes: {tuple(mesh.shape)})"
                )
            data_size = mesh.shape[data_axis]
            smallest = self.max_bucket if self.fixed_bucket else _MIN_BUCKET
            if smallest % data_size != 0 or self.max_bucket % data_size != 0:
                raise ConfigError(
                    f"verify shard count {data_size} must divide every "
                    f"dispatch bucket (smallest {smallest}, largest "
                    f"{self.max_bucket}); use a power of two <= {smallest}"
                )

            # Shared per-mesh jit wrappers: every verifier over this mesh
            # (either mode — msm keeps the item kernel as its fallback)
            # reuses ONE compiled kernel pair instead of re-jitting.
            self._item_kernel, self._msm_kernel = _sharded_kernels(
                kernel, mesh, data_axis
            )
        else:
            self._item_kernel = kernel.verify_batch_kernel
            self._msm_kernel = kernel.msm_accumulate_kernel

    def precompile(self, sizes: Sequence[int] = ()) -> None:
        """Warm the jit trace+compile caches for the given bucket sizes —
        in msm mode also the per-item fallback kernel (via a deliberately
        corrupt signature), so the first adversarial input at runtime
        doesn't stall the pipeline behind a fresh trace."""
        from ..crypto import KeyPair

        kp = KeyPair.generate()
        sig = kp.sign(b"warmup")
        for size in sizes or (_MIN_BUCKET, self.max_bucket):
            items = [(kp.public, b"warmup", sig)] * size
            # Plain checks, not asserts: under python -O asserts vanish and
            # the warmup would silently dispatch nothing.
            if not all(self(items)):
                raise RuntimeError("verifier warmup rejected a valid batch")
            if self.mode == "msm" and size >= self.msm_min_bucket:
                bad = list(items)
                bad[-1] = (kp.public, b"not-warmup", sig)
                if self(bad)[-1]:
                    raise RuntimeError("verifier warmup accepted a forgery")

    def _precheck_native(self, items: Sequence[BatchItem], lib):
        """Batched canonicality checks + challenge scalars in C (GIL
        released for the call): returns (precheck[n] bool, a_raw, r_raw,
        s_raw, k_raw as uint8[n, 32])."""
        n = len(items)
        lenok = np.ones(n, bool)
        pk_parts: list[bytes] = []
        sig_parts: list[bytes] = []
        msg_parts: list[bytes] = []
        lens = np.empty(n, np.int64)
        zero32, zero64 = b"\0" * 32, b"\0" * 64
        for i, (pk, msg, sig) in enumerate(items):
            if len(pk) != 32 or len(sig) != 64:
                lenok[i] = False
                pk_parts.append(zero32)
                sig_parts.append(zero64)
                lens[i] = 0
                continue
            pk_parts.append(pk)
            sig_parts.append(sig)
            msg_parts.append(msg)
            lens[i] = len(msg)
        pk_buf = b"".join(pk_parts)
        sig_buf = b"".join(sig_parts)
        offs = np.zeros(n + 1, np.int64)
        np.cumsum(lens, out=offs[1:])
        k_raw = np.empty((n, 32), np.uint8)
        ok_raw = np.empty(n, np.uint8)
        rc = lib.ed25519_precheck_k(
            n,
            pk_buf,
            sig_buf,
            b"".join(msg_parts),
            offs.ctypes.data,
            k_raw.ctypes.data,
            ok_raw.ctypes.data,
        )
        if rc != 0:  # pragma: no cover - internal failure only
            raise RuntimeError(f"ed25519_precheck_k failed: rc={rc}")
        precheck = ok_raw.astype(bool) & lenok
        sig_rows = np.frombuffer(sig_buf, np.uint8).reshape(n, 64)
        a_raw = np.frombuffer(pk_buf, np.uint8).reshape(n, 32)
        return precheck, a_raw, sig_rows[:, :32], sig_rows[:, 32:], k_raw

    def _precheck_py(self, items: Sequence[BatchItem]):
        """Pure-Python twin of `_precheck_native` (no-toolchain fallback);
        bit-identical outputs — asserted by tests/test_tpu_ed25519.py."""
        n = len(items)
        precheck = np.zeros(n, bool)
        a_raw = np.zeros((n, 32), np.uint8)
        r_raw = np.zeros((n, 32), np.uint8)
        s_raw = np.zeros((n, 32), np.uint8)
        k_raw = np.zeros((n, 32), np.uint8)
        L = self.kernel.ref.L
        P = self.kernel.ref.P
        sha512 = hashlib.sha512
        top_mask = (1 << 255) - 1
        frombuf = np.frombuffer
        for i, (pk, msg, sig) in enumerate(items):
            if len(pk) != 32 or len(sig) != 64:
                continue
            rs, sb = sig[:32], sig[32:]
            if int.from_bytes(sb, "little") >= L:
                continue
            if (int.from_bytes(pk, "little") & top_mask) >= P:
                continue
            if (int.from_bytes(rs, "little") & top_mask) >= P:
                continue
            k_int = int.from_bytes(sha512(rs + pk + msg).digest(), "little") % L
            a_raw[i] = frombuf(pk, np.uint8)
            r_raw[i] = frombuf(rs, np.uint8)
            s_raw[i] = frombuf(sb, np.uint8)
            k_raw[i] = frombuf(k_int.to_bytes(32, "little"), np.uint8)
            precheck[i] = True
        return precheck, a_raw, r_raw, s_raw, k_raw

    def submit(self, items: Sequence[BatchItem]):
        """Pack + precheck on host and enqueue the device dispatch(es).
        Returns an opaque handle for `collect` — dispatch is asynchronous, so
        several submitted batches stay in flight and the device readback
        latency overlaps the next batch's host packing and compute.

        The per-item host work (SHA-512 challenge, canonicality checks,
        msm scalars) runs in native/scalar_ops.cpp when available — the
        Python loop it replaces was the pipelined path's ceiling (~250 ms
        per 32k batch vs ~3 ms native)."""
        n = len(items)
        if n == 0:
            return (np.zeros(0, bool), np.zeros(0, np.int64), [], None, items)
        ok = np.zeros(n, bool)
        lib = _scalar_lib()
        if lib is not None:
            precheck, a_all, r_all, s_all, k_all = self._precheck_native(items, lib)
        else:
            precheck, a_all, r_all, s_all, k_all = self._precheck_py(items)

        idx = np.flatnonzero(precheck)
        if idx.size == 0:
            return (ok, idx, [], None, items)

        # Compact to precheck-passing rows (contiguous for the C fold and
        # the device upload).
        a_raw = np.ascontiguousarray(a_all[idx])
        r_raw = np.ascontiguousarray(r_all[idx])
        s_raw = np.ascontiguousarray(s_all[idx])
        k_raw = np.ascontiguousarray(k_all[idx])
        # Narrow upload dtypes (limbs < 2^13, digits < 16): ~3x fewer bytes
        # over the device link; the kernel widens to int32 lanes on device.
        a_y = self.kernel.bytes_to_limbs(a_raw).astype(np.int16)
        r_y = self.kernel.bytes_to_limbs(r_raw).astype(np.int16)
        a_sign = (a_raw[:, 31] >> 7).astype(np.int8)
        r_sign = (r_raw[:, 31] >> 7).astype(np.int8)
        # k/s digit planes are only needed by the per-item kernel — in msm
        # mode that's the rare fallback path, so they're derived lazily in
        # _dispatch_items instead of packed (and uploaded) eagerly.
        packed = (a_y, a_sign, r_y, r_sign, k_raw, s_raw)

        outs = []  # (kind, lo, hi, pad, device out)
        for lo in range(0, idx.size, self.max_bucket):
            hi = min(lo + self.max_bucket, idx.size)
            if self.fixed_bucket:
                bucket = self.max_bucket
            else:
                bucket = _MIN_BUCKET
                while bucket < hi - lo:
                    bucket *= 2
            pad = bucket - (hi - lo)

            if self.mode == "msm" and bucket >= self.msm_min_bucket:
                out = self._dispatch_msm(packed, lo, hi, pad)
                kind = "msm"
                arrays = out[0]  # ((V_a, V_r, valid), sum_s)
            else:
                out = self._dispatch_items(packed, lo, hi, pad)
                kind = "item"
                arrays = out  # (strict, cofactored) device arrays
            # Kick off the device->host copy as soon as the kernel finishes
            # so collect() finds the bytes already local instead of paying
            # the transfer round trip synchronously.
            for arr in arrays:
                try:
                    arr.copy_to_host_async()
                except AttributeError:
                    pass
            outs.append((kind, lo, hi, pad, out))
        return (ok, idx, outs, packed, items)

    def _dispatch_items(self, packed, lo, hi, pad):
        """Per-item Straus kernel over one padded bucket (k/s scalar rows
        are expanded to 4-bit digit planes here, on demand)."""
        a_y, a_sign, r_y, r_sign, k_raw, s_raw = packed

        def pad_to(arr):
            if pad == 0:
                return arr[lo:hi]
            return np.concatenate(
                [arr[lo:hi], np.repeat(arr[lo : lo + 1], pad, axis=0)]
            )

        k_digits = self.kernel.bytes_to_digits(pad_to(k_raw)).astype(np.int8)
        s_digits = self.kernel.bytes_to_digits(pad_to(s_raw)).astype(np.int8)
        return self._item_kernel(
            pad_to(a_y), pad_to(a_sign), pad_to(r_y), pad_to(r_sign),
            k_digits, s_digits,
        )

    def _fold_native(self, lib, k_rows: np.ndarray, s_rows: np.ndarray, rnd: bytes):
        """ak_i = z_i*k_i mod L and sum(z_i*s_i) mod L in C."""
        m = k_rows.shape[0]
        ak_raw = np.empty((m, 32), np.uint8)
        sum_raw = np.empty(32, np.uint8)
        lib.scalar_fold(
            m,
            k_rows.ctypes.data,
            s_rows.ctypes.data,
            rnd,
            ak_raw.ctypes.data,
            sum_raw.ctypes.data,
        )
        return ak_raw, int.from_bytes(sum_raw.tobytes(), "little")

    def _fold_py(self, k_rows: np.ndarray, s_rows: np.ndarray, rnd: bytes):
        """Python twin of `_fold_native` (identical outputs)."""
        L = self.kernel.ref.L
        m = k_rows.shape[0]
        from_bytes = int.from_bytes
        kb, sb = k_rows.tobytes(), s_rows.tobytes()
        ak_parts: list[bytes] = []
        sum_s = 0
        for t in range(m):
            z = from_bytes(rnd[16 * t : 16 * (t + 1)], "little")
            k = from_bytes(kb[32 * t : 32 * (t + 1)], "little")
            s = from_bytes(sb[32 * t : 32 * (t + 1)], "little")
            ak_parts.append(((z * k) % L).to_bytes(32, "little"))
            sum_s += z * s
        ak_raw = np.frombuffer(b"".join(ak_parts), np.uint8).reshape(m, 32)
        return ak_raw, sum_s % L

    def _dispatch_msm(self, packed, lo, hi, pad):
        """Random-linear-combination check over one bucket. Fresh 128-bit
        z_i per item per call (os.urandom — the adversary must not predict
        them); zero rows are inert padding. Returns (device (V, valid),
        sum_s) — the Horner/identity epilogue runs on host at collect
        time."""
        import os as _os

        a_y, a_sign, r_y, r_sign, k_raw, s_raw = packed
        m = hi - lo
        # RLC folding weights must be unpredictable to an adversary who
        # crafts signatures (a seeded stream would let forged batches pass
        # the combined check); verdicts don't depend on the draw — a failed
        # fold bisects deterministically — so replays stay bit-identical
        # where it matters.
        rnd = _os.urandom(16 * m)  # lint: allow(raw-entropy)
        k_rows = np.ascontiguousarray(k_raw[lo:hi])
        s_rows = np.ascontiguousarray(s_raw[lo:hi])
        lib = _scalar_lib()
        if lib is not None:
            ak_raw, sum_s = self._fold_native(lib, k_rows, s_rows, rnd)
        else:
            ak_raw, sum_s = self._fold_py(k_rows, s_rows, rnd)
        if pad:
            ak_raw = np.concatenate([ak_raw, np.zeros((pad, 32), np.uint8)])
        z_raw = np.zeros((m + pad, 32), np.uint8)
        z_raw[:m, :16] = np.frombuffer(rnd, np.uint8).reshape(m, 16)

        ak_digits = self.kernel.bytes_to_digits(ak_raw).astype(np.int8)
        # z < 2^128: the MSB-first digit vector's low half carries it.
        z_digits = self.kernel.bytes_to_digits(z_raw)[:, 32:].astype(np.int8)

        def zpad(arr):
            if pad == 0:
                return arr[lo:hi]
            return np.concatenate(
                [arr[lo:hi], np.zeros((pad,) + arr.shape[1:], arr.dtype)]
            )

        out = self._msm_kernel(
            zpad(a_y), zpad(a_sign), zpad(r_y), zpad(r_sign),
            ak_digits, z_digits,
        )
        return (out, sum_s)

    def submit_groups(self, groups):
        """Dispatch half-aggregated certificate proofs (types.Certificate
        compact form). Each group is (items [(pk, msg, R)], zs, s_agg):
        the claim sum(z_i s_i) = s_agg over the verification equations
        [s_i]B = R_i + [k_i]A_i. One msm dispatch checks the OUTER random
        combination over all groups — fresh 128-bit w_g per group, so
        adversarially related groups cannot cancel each other:
          [sum_g w_g s_agg_g]B == sum_g w_g (sum_i z_i R_i + [z_i k_i]A_i)
        Each signer contributes two kernel rows (A_i with scalar w z k, and
        R_i — fed through the A slot — with scalar w z; the R slot's
        128-bit scalar lane is too narrow for the 256-bit products). Zero
        R-slot rows are inert. Returns a handle for `collect_groups`."""
        import os as _os

        n_groups = len(groups)
        ok = np.zeros(n_groups, bool)
        candidates = []  # (group index, items, zs, s_agg, w)
        for g, (items, zs, s_agg) in enumerate(groups):
            if items and 2 * len(items) <= self.max_bucket:
                # Adversarial RLC weight: same argument as _fold above.
                w = int.from_bytes(_os.urandom(16), "little")  # lint: allow(raw-entropy)
                candidates.append((g, items, zs, s_agg, w))
            # oversized/empty groups fall back at collect (host verify)
        outs = []
        lo = 0
        while lo < len(candidates):
            # Greedy-pack whole groups into one bucket (a group must not
            # straddle dispatches: the epilogue identity is per dispatch).
            hi, rows = lo, 0
            while hi < len(candidates) and rows + 2 * len(candidates[hi][1]) <= self.max_bucket:
                rows += 2 * len(candidates[hi][1])
                hi += 1
            chunk = candidates[lo:hi]
            lo = hi
            outs.append((chunk, self._dispatch_group_chunk(chunk, rows)))
        return (ok, candidates, outs, groups)

    def _dispatch_group_chunk(self, chunk, rows):
        """One msm dispatch over the doubled rows of `chunk`'s groups.
        Returns ((device out), sum_s) like _dispatch_msm."""
        L = self.kernel.ref.L
        lib = _scalar_lib()
        sum_s = 0
        # Per item: k_i = H(R||A||m) + canonicality (native precheck path;
        # the fake 64-byte signature is R || 0 so the s-range check passes).
        flat_items = []
        for _, items, zs, s_agg, w in chunk:
            flat_items.extend(items)
        m = len(flat_items)
        sig_rows = b"".join(r + b"\0" * 32 for _, _, r in flat_items)
        fake = [(pk, msg, sig_rows[64 * i : 64 * (i + 1)]) for i, (pk, msg, _) in enumerate(flat_items)]
        if lib is not None:
            precheck, a_all, r_all, _s, k_all = self._precheck_native(fake, lib)
        else:
            precheck, a_all, r_all, _s, k_all = self._precheck_py(fake)
        if not bool(precheck.all()):
            # Some item failed canonicality prechecks: the combined check
            # cannot pass attribution; collect falls back per group.
            return None

        # Effective scalars y_i = w_g * z_i and ak_i = y_i * k_i (mod L).
        w_rows = np.empty((m, 32), np.uint8)
        z_rows = np.empty((m, 32), np.uint8)
        t = 0
        for _, items, zs, s_agg, w in chunk:
            sum_s = (sum_s + w * s_agg) % L
            wb = np.frombuffer(w.to_bytes(32, "little"), np.uint8)
            for z in zs:
                w_rows[t] = wb
                z_rows[t] = np.frombuffer(z.to_bytes(32, "little"), np.uint8)
                t += 1
        if lib is not None:
            y_rows = np.empty((m, 32), np.uint8)
            ak_items = np.empty((m, 32), np.uint8)
            lib.scalar_mulmod(
                m, w_rows.ctypes.data, z_rows.ctypes.data, y_rows.ctypes.data
            )
            lib.scalar_mulmod(
                m,
                y_rows.ctypes.data,
                np.ascontiguousarray(k_all[:m]).ctypes.data,
                ak_items.ctypes.data,
            )
        else:
            y_rows = np.empty((m, 32), np.uint8)
            ak_items = np.empty((m, 32), np.uint8)
            for i in range(m):
                w_i = int.from_bytes(w_rows[i].tobytes(), "little")
                z_i = int.from_bytes(z_rows[i].tobytes(), "little")
                k_i = int.from_bytes(k_all[i].tobytes(), "little")
                y = (w_i * z_i) % L
                y_rows[i] = np.frombuffer(y.to_bytes(32, "little"), np.uint8)
                ak_items[i] = np.frombuffer(
                    ((y * k_i) % L).to_bytes(32, "little"), np.uint8
                )

        # Doubled rows: even = A_i with scalar ak_i, odd = R_i (through the
        # A slot) with scalar y_i.
        a_rows = np.zeros((rows, 32), np.uint8)
        ak_rows = np.zeros((rows, 32), np.uint8)
        a_rows[0::2] = a_all[:m]
        a_rows[1::2] = r_all[:m]
        ak_rows[0::2] = ak_items
        ak_rows[1::2] = y_rows
        bucket = self.max_bucket if self.fixed_bucket else _next_pow2(rows)
        pad = bucket - rows
        if pad:
            a_rows = np.concatenate([a_rows, np.zeros((pad, 32), np.uint8)])
            ak_rows = np.concatenate([ak_rows, np.zeros((pad, 32), np.uint8)])
        a_y = self.kernel.bytes_to_limbs(a_rows).astype(np.int16)
        a_sign = (a_rows[:, 31] >> 7).astype(np.int8)
        zero_y = np.zeros_like(a_y)
        zero_sign = np.zeros_like(a_sign)
        ak_digits = self.kernel.bytes_to_digits(ak_rows).astype(np.int8)
        z_digits = np.zeros((bucket, 32), np.int8)
        out = self._msm_kernel(
            a_y, a_sign, zero_y, zero_sign, ak_digits, z_digits
        )
        for arr in out:
            try:
                arr.copy_to_host_async()
            except AttributeError:
                pass
        return (out, sum_s)

    def _chunk_passes(self, dispatched) -> bool:
        """Force one `_dispatch_group_chunk` result: device validity lanes
        plus the host epilogue identity."""
        if dispatched is None:
            return False
        (va_dev, vr_dev, valid_dev), sum_s = dispatched
        valid = np.asarray(valid_dev)
        return bool(valid.all()) and msm_epilogue_check(
            np.asarray(va_dev), np.asarray(vr_dev), sum_s, self.kernel
        )

    def collect_groups(self, handle) -> list[bool]:
        """Resolve a `submit_groups` handle. A failed combined check
        RE-DISPATCHES each group as its own device msm chunk (all singles
        in flight before the first readback, so the bisect stays
        pipelined); only groups whose solo device check still fails reach
        the pure-Python host verifier. One adversarial compact certificate
        therefore costs the attacker's own group a host walk — it cannot
        drag every honest group in the chunk onto the 1-core host (the
        r4-advisor liveness-DoS amplification). Oversized groups (2 rows
        per signer > max_bucket — a committee larger than half the service
        bucket) still host-verify; splitting one group's epilogue identity
        across dispatches isn't supported."""
        from ..types import host_verify_aggregate

        ok, candidates, outs, groups = handle
        for chunk, dispatched in outs:
            if self._chunk_passes(dispatched):
                for g, *_ in chunk:
                    ok[g] = True
                continue
            if len(chunk) > 1:
                logger.warning(
                    "aggregate chunk of %d certificate groups failed the "
                    "combined check; re-dispatching each group solo",
                    len(chunk),
                )
                solos = [
                    (entry, self._dispatch_group_chunk([entry], 2 * len(entry[1])))
                    for entry in chunk
                ]
            else:
                solos = [(chunk[0], dispatched)]
            for (g, items, zs, s_agg, _), disp in solos:
                if len(chunk) > 1 and self._chunk_passes(disp):
                    ok[g] = True
                else:
                    # The group's own device check failed: almost surely
                    # invalid, but the host verdict is authoritative for
                    # the rare device-fault case.
                    ok[g] = host_verify_aggregate(items, zs, s_agg)
        # Oversized/empty groups never dispatched: host-verify them too.
        dispatched_gs = {g for g, *_ in candidates}
        for g, (items, zs, s_agg) in enumerate(groups):
            if g not in dispatched_gs:
                ok[g] = host_verify_aggregate(items, zs, s_agg) if items else False
        return ok.tolist()

    def collect(self, handle) -> list[bool]:
        """Materialize a `submit` handle's results (blocks on the device).
        A failed msm bucket re-dispatches the per-item kernel to locate the
        offending signatures (rare path: only adversarial/corrupt input);
        strict-kernel rejects are then re-checked against the cofactored
        rule so the msm mode's accept set stays deterministic."""
        ok, idx, outs, packed, items = handle
        if idx.size:
            results = np.zeros(idx.size, bool)
            # In msm mode EVERY verdict is the device-computed cofactored
            # one — small buckets, fallback buckets and the batch check all
            # share one accept set, so no signature's fate can depend on
            # flush size or bucket composition (consensus-split safety),
            # and there is no per-item host recheck an attacker could
            # amplify. mode="item" keeps the strict (host-library) rule.
            pick = 1 if self.mode == "msm" else 0

            for kind, lo, hi, pad, out in outs:
                if kind == "item":
                    results[lo:hi] = np.asarray(out[pick])[: hi - lo]
                    continue
                (va_dev, vr_dev, valid_dev), sum_s = out
                valid = np.asarray(valid_dev)
                if bool(valid.all()) and msm_epilogue_check(
                    np.asarray(va_dev), np.asarray(vr_dev), sum_s, self.kernel
                ):
                    results[lo:hi] = True
                else:
                    fallback = self._dispatch_items(packed, lo, hi, pad)
                    results[lo:hi] = np.asarray(fallback[1])[: hi - lo]
            ok[idx] = results
        return ok.tolist()

    def __call__(self, items: Sequence[BatchItem]) -> list[bool]:
        return self.collect(self.submit(items))


def data_mesh(shards: int, devices=None):
    """The verify-sharding mesh: `shards` devices on a 1-axis 'data' mesh
    (SURVEY §7.8a at §5.8 scale — the certificate analog of --dag-shards).
    This is THE construction path for sharded verifiers: the node surface
    (--verify-shards) and the driver dryrun both come through here, so the
    dryrun's CPU-mesh evidence covers exactly what the CLI wires.

    `devices` pins an explicit list (tests; the dryrun's hermetic device
    set). By default uses the default backend's devices, falling back to
    the virtual CPU mesh — loudly — when the backend is too small."""
    import jax
    import numpy as _np
    from jax.sharding import Mesh

    devs = list(devices) if devices is not None else jax.devices()
    if len(devs) < shards:
        if devices is not None:
            raise ConfigError(
                f"--verify-shards {shards} exceeds the {len(devs)} pinned "
                "devices"
            )
        cpus = jax.devices("cpu")
        if len(cpus) < shards:
            raise ConfigError(
                f"--verify-shards {shards} exceeds available devices "
                f"({len(devs)} {devs[0].platform}, {len(cpus)} cpu)"
            )
        logger.warning(
            "--verify-shards %d exceeds the %d-device %s backend; sharding "
            "over %d virtual CPU devices instead",
            shards, len(devs), devs[0].platform, shards,
        )
        devs = cpus
    return Mesh(_np.array(devs[:shards]), ("data",))


def make_batch_verifier(
    fallback_on_error: bool = True, mode: str | None = None, require: bool = False
):
    """Build a crypto.BatchVerifier backed by the TPU kernel, falling back to
    the host loop if the device path fails.

    `mode` pins the accept set ("item" = strict/cofactorless like the host
    library, "msm" = cofactored batch rule); None defers to the
    NARWHAL_TPU_VERIFY_MODE env default. Node startup always passes an
    explicit mode derived from the committee-wide Parameters.verify_rule.

    `require=True` raises instead of returning None when the device path
    cannot be built: under a cofactored committee a silent host fallback
    would permanently run the STRICT accept set — the consensus-split
    hazard the startup validation exists to prevent — so the node must
    refuse to start rather than limp along on the wrong rule."""
    from .. import crypto

    try:
        verifier = TpuVerifier(mode=mode)
    except Exception:  # jax/platform import failure
        if require:
            raise RuntimeError(
                "TPU verifier unavailable but the committee's verify rule "
                "requires it (host fallback implements a different accept "
                "set); refusing to start"
            )
        logger.exception("TPU verifier unavailable; using host verification")
        return None

    def backend(items: Sequence[BatchItem]) -> list[bool]:
        try:
            return verifier(items)
        except Exception:
            if not fallback_on_error:
                raise
            # The host library is strict/cofactorless; under mode="msm"
            # (cofactored committee) this error-path fallback is a
            # different accept set — tolerable for a transient device
            # hiccup, but say so loudly.
            logger.exception(
                "TPU verify dispatch failed; host fallback%s",
                " (STRICT accept set, differs from the committee's"
                " cofactored rule on crafted torsion signatures)"
                if verifier.mode == "msm"
                else "",
            )
            return crypto._host_batch_verify(items)

    return backend


class VerifyService:
    """Process-wide pipelined verification front for the TPU backend.

    The per-node AsyncVerifierPool coalesces one node's concurrent
    requests, but a host running many nodes (the in-process committee
    bench; any multi-node-per-host deployment) then issues many small
    device dispatches — and through a high-RTT link (the tunneled bench
    chip: ~200 ms) those serialize into a committee-wide stall
    (VERDICT r3: crypto=tpu executed ~0 tx at N=20). This service is the
    fix: ONE instance per process merges every node's items into large
    buckets and keeps several batches in flight, so all protocol hops of
    all nodes share flushes and the link RTT is paid once per large batch
    instead of once per hop.

    Thread model (asyncio-loop agnostic — nodes on different loops can
    share it):
      callers     append (item, loop, future) under a lock;
      submit thread seals a merged batch (size- or deadline-triggered)
                  and runs TpuVerifier.submit — host packing is the
                  GIL-releasing native pipeline;
      collect thread blocks on the device result and resolves futures via
                  loop.call_soon_threadsafe.
    A bounded in-flight queue applies backpressure when the device falls
    behind. Presents the AsyncVerifierPool interface (`await verify(...)`,
    `close()`)."""

    _shared: dict[str, "VerifyService"] = {}

    def __init__(
        self,
        verifier: TpuVerifier,
        max_batch: int = 4096,
        max_delay: float = 0.003,
        inflight: int = 3,
    ):
        self.verifier = verifier
        self.max_batch = max_batch
        self.max_delay = max_delay
        # Dispatch-failure fallback: only for mode="item", where the host
        # library computes the SAME (strict) accept set. Under "msm"
        # (cofactored committees) errors propagate — a strict fallback
        # would be a consensus-split hazard, so dropping the message is
        # the safe degradation (liveness cost, never safety).
        if verifier.mode != "msm":
            from .. import crypto as _crypto

            self._fallback = _crypto._host_batch_verify
        else:
            self._fallback = None
        self._pending: collections.deque = collections.deque()
        # Aggregate-certificate groups (compact certs) ride a second lane:
        # they dispatch through submit_groups (doubled rows, per-group
        # random outer weights) but share the same submit/collect threads
        # and inflight pipeline.
        self._pending_groups: collections.deque = collections.deque()
        self.max_group_rows = max_batch  # 2 rows per signer, same bucket
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._inflight: queue.Queue = queue.Queue(maxsize=inflight)
        self._closed = False
        self._submit_thread = threading.Thread(
            target=self._submit_loop, daemon=True, name="verify-submit"
        )
        self._collect_thread = threading.Thread(
            target=self._collect_loop, daemon=True, name="verify-collect"
        )
        self._submit_thread.start()
        self._collect_thread.start()
        # A daemon thread frozen inside XLA C++ during interpreter
        # finalization aborts the process ("FATAL: exception not
        # rethrown") — same hazard the DAG prewarm threads guard against.
        # Stop the loops and bounded-join before Python tears down.
        import atexit

        atexit.register(self.shutdown)

    @classmethod
    def shared(
        cls, mode: str, shards: int = 1, devices=None, **kw
    ) -> "VerifyService":
        """The process-wide instance for an accept-set mode ('item'/'msm')
        and shard count. Raises if the device verifier cannot be built —
        callers decide whether that is fatal (cofactored committees) or
        fallback-able. `shards > 1` (--verify-shards) shards every flush
        over a `data_mesh`; divisibility against the fixed bucket is
        validated at construction, so a mis-sized mesh stops the node at
        startup rather than at its first verify.

        The verifier runs fixed-bucket (pad every flush to one shape):
        dispatch cost through a device link is RTT-flat in batch size, and
        one shape means one ~minute jit trace per process instead of one
        per power-of-two flush size — the difference between a committee
        that boots inside its warmup window and one that stalls (r4)."""
        key = f"{mode}:{shards}"
        svc = cls._shared.get(key)
        if svc is None:
            svc = cls(
                TpuVerifier(
                    max_bucket=2048,
                    msm_min_bucket=16,
                    mode=mode,
                    fixed_bucket=True,
                    mesh=data_mesh(shards, devices) if shards > 1 else None,
                ),
                max_batch=2048,
                **kw,
            )
            cls._shared[key] = svc
        return svc

    async def verify(self, public_key: bytes, message: bytes, signature: bytes) -> bool:
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        with self._wake:
            if self._closed:
                # The submit thread is gone (or draining): an enqueued
                # future would never resolve.
                raise RuntimeError("verify service shut down")
            self._pending.append(
                ((public_key, message, signature), loop, fut, time.monotonic())
            )
            self._wake.notify()
        return await fut

    async def verify_aggregate(self, items, zs, s_agg: int) -> bool:
        """Half-aggregated certificate proof (compact certs): queued on the
        group lane and checked on device — many groups fuse into one msm
        dispatch under an outer random combination."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        with self._wake:
            if self._closed:
                raise RuntimeError("verify service shut down")
            self._pending_groups.append(
                ((items, zs, s_agg), loop, fut, time.monotonic())
            )
            self._wake.notify()
        return await fut

    def _seal(self) -> list | None:
        """Under the lock: a singles batch worth dispatching, or None."""
        if not self._pending:
            return None
        n = len(self._pending)
        if n >= self.max_batch or (
            time.monotonic() - self._pending[0][3] >= self.max_delay
        ):
            take = min(n, self.max_batch)
            return [self._pending.popleft() for _ in range(take)]
        return None

    def _seal_groups(self) -> list | None:
        """Under the lock: a groups batch (by total doubled-row budget)."""
        if not self._pending_groups:
            return None
        rows = sum(2 * len(g[0][0]) for g in self._pending_groups)
        if rows >= self.max_group_rows or (
            time.monotonic() - self._pending_groups[0][3] >= self.max_delay
        ):
            out, budget = [], self.max_group_rows
            while self._pending_groups:
                need = 2 * len(self._pending_groups[0][0][0])
                if out and need > budget:
                    break
                g = self._pending_groups.popleft()
                out.append(g)
                budget -= need
            return out
        return None

    def _oldest_age(self) -> float | None:
        ages = []
        if self._pending:
            ages.append(time.monotonic() - self._pending[0][3])
        if self._pending_groups:
            ages.append(time.monotonic() - self._pending_groups[0][3])
        return max(ages) if ages else None

    def _submit_loop(self) -> None:
        while True:
            with self._wake:
                batch = self._seal()
                gbatch = self._seal_groups()
                while batch is None and gbatch is None and not self._closed:
                    # Wake early enough to honor the oldest item's deadline.
                    age = self._oldest_age()
                    timeout = (
                        None if age is None else max(0.0, self.max_delay - age) + 1e-4
                    )
                    self._wake.wait(timeout=timeout)
                    batch = self._seal()
                    gbatch = self._seal_groups()
                if batch is None and gbatch is None and self._closed:
                    # Drain: anything still queued will never dispatch —
                    # fail its futures instead of leaving awaiters hanging.
                    leftovers = list(self._pending) + list(self._pending_groups)
                    self._pending.clear()
                    self._pending_groups.clear()
                    if leftovers:
                        self._resolve_error(
                            leftovers, RuntimeError("verify service shut down")
                        )
                    self._inflight.put(None)  # collector shutdown
                    return
            if batch is not None:
                items = [e[0] for e in batch]
                try:
                    handle = self.verifier.submit(items)
                except Exception as e:
                    logger.exception("verify submit failed for %d items", len(items))
                    self._finish_failed(batch, items, e)
                else:
                    self._inflight.put(("s", handle, batch))
            if gbatch is not None:
                groups = [e[0] for e in gbatch]
                try:
                    ghandle = self.verifier.submit_groups(groups)
                except Exception as e:
                    logger.exception(
                        "aggregate submit failed for %d groups", len(groups)
                    )
                    self._resolve_error(gbatch, e)
                else:
                    self._inflight.put(("g", ghandle, gbatch))

    def _collect_loop(self) -> None:
        while True:
            got = self._inflight.get()
            if got is None:
                return
            kind, handle, entries = got
            try:
                if kind == "g":
                    results = self.verifier.collect_groups(handle)
                else:
                    results = self.verifier.collect(handle)
            except Exception as e:
                logger.exception("verify collect failed for %d entries", len(entries))
                if kind == "g":
                    self._resolve_error(entries, e)
                else:
                    self._finish_failed(entries, [e[0] for e in entries], e)
                continue
            for (item, loop, fut, _), res in zip(entries, results):
                self._post(loop, fut, res, None)

    def _finish_failed(self, entries, items, exc) -> None:
        """Device dispatch failed: host-verify when the accept set allows
        it, otherwise propagate the error to every waiter."""
        if self._fallback is not None:
            try:
                results = self._fallback(items)
            except Exception as e:  # pragma: no cover - host library failure
                self._resolve_error(entries, e)
                return
            for (item, loop, fut, _), res in zip(entries, results):
                self._post(loop, fut, res, None)
            return
        self._resolve_error(entries, exc)

    def _resolve_error(self, entries, exc) -> None:
        for _, loop, fut, _ in entries:
            self._post(loop, fut, None, exc)

    @staticmethod
    def _post(loop, fut, result, exc) -> None:
        def setter() -> None:
            if fut.done():
                return
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(result)

        try:
            loop.call_soon_threadsafe(setter)
        except RuntimeError:
            # The caller's loop closed (its cluster/test tore down before
            # the device answered); nobody is waiting anymore.
            pass

    async def close(self) -> None:
        """Per-node shutdown is a no-op for the process-wide instance: other
        nodes (and the next in-process cluster) keep using it; threads are
        daemons and idle when no traffic flows."""
        return None

    def shutdown(self) -> None:
        """Really stop the threads (tests; process teardown)."""
        with self._wake:
            self._closed = True
            self._wake.notify_all()
        self._submit_thread.join(timeout=10.0)
        self._collect_thread.join(timeout=10.0)
        for key, svc in list(self._shared.items()):
            if svc is self:
                del self._shared[key]


class AsyncVerifierPool:
    """Size-or-deadline coalescing of concurrent verification requests.

    await pool.verify(pk, msg, sig) from any task; items are flushed to the
    backend in one batch when `max_batch` are waiting or `max_delay` elapsed
    since the first queued item (BatchMaker's seal rule, applied to crypto).
    The backend call runs in a thread so the event loop never blocks on the
    device.
    """

    def __init__(
        self,
        backend=None,
        max_batch: int = 512,
        max_delay: float = 0.002,
        group_backend=None,
        max_groups: int = 64,
    ):
        from .. import crypto
        from ..types import host_batch_verify_aggregates

        self.backend = backend or crypto.batch_verify
        self.max_batch = max_batch
        self.max_delay = max_delay
        self._pending: list[tuple[BatchItem, asyncio.Future]] = []
        self._flusher: asyncio.Task | None = None
        self._batches: set[asyncio.Task] = set()  # strong refs: loop holds weak
        # Aggregate-certificate group lane (compact certs): concurrent
        # verify_aggregate calls coalesce under the same seal rule and
        # dispatch as ONE host_batch_verify_aggregates call — one
        # bucket-method MSM amortized across every certificate in the
        # flush, the host analog of VerifyService's device group lane.
        self.group_backend = group_backend or host_batch_verify_aggregates
        self.max_groups = max_groups
        self._pending_groups: list[tuple[tuple, asyncio.Future]] = []
        self._group_flusher: asyncio.Task | None = None

    async def verify(self, public_key: bytes, message: bytes, signature: bytes) -> bool:
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._pending.append(((public_key, message, signature), fut))
        if len(self._pending) >= self.max_batch:
            self._flush_now()
        elif self._flusher is None or self._flusher.done():
            self._flusher = asyncio.ensure_future(self._deadline_flush())
        return await fut

    def _flush_now(self) -> None:
        pending, self._pending = self._pending, []
        if pending:
            task = asyncio.ensure_future(self._run_batch(pending))
            self._batches.add(task)
            task.add_done_callback(self._batches.discard)

    async def _deadline_flush(self) -> None:
        await asyncio.sleep(self.max_delay)
        self._flush_now()

    async def _run_batch(self, pending) -> None:
        items = [item for item, _ in pending]
        loop = asyncio.get_running_loop()
        try:
            results = await loop.run_in_executor(None, self.backend, items)
        except Exception as e:
            for _, fut in pending:
                if not fut.done():
                    fut.set_exception(e)
            return
        for (_, fut), res in zip(pending, results):
            if not fut.done():
                fut.set_result(res)

    async def verify_aggregate(self, items, zs, s_agg: int) -> bool:
        """Half-aggregated certificate proof check (compact certs), batched:
        groups queued by concurrent callers — the verifier stage's
        per-message tasks, the block synchronizer's catch-up fetches —
        seal into one `host_batch_verify_aggregates` dispatch (size- or
        deadline-triggered, like the item lane), so many certificates
        share one randomized-linear-combination MSM instead of paying a
        per-certificate scalar-mul walk."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._pending_groups.append(((items, zs, s_agg), fut))
        if len(self._pending_groups) >= self.max_groups:
            self._flush_groups_now()
        elif self._group_flusher is None or self._group_flusher.done():
            self._group_flusher = asyncio.ensure_future(self._deadline_flush_groups())
        return await fut

    def _flush_groups_now(self) -> None:
        pending, self._pending_groups = self._pending_groups, []
        if pending:
            task = asyncio.ensure_future(self._run_group_batch(pending))
            self._batches.add(task)
            task.add_done_callback(self._batches.discard)

    async def _deadline_flush_groups(self) -> None:
        await asyncio.sleep(self.max_delay)
        self._flush_groups_now()

    async def _run_group_batch(self, pending) -> None:
        groups = [group for group, _ in pending]
        loop = asyncio.get_running_loop()
        try:
            results = await loop.run_in_executor(None, self.group_backend, groups)
        except Exception as e:
            for _, fut in pending:
                if not fut.done():
                    fut.set_exception(e)
            return
        for (_, fut), res in zip(pending, results):
            if not fut.done():
                fut.set_result(res)

    async def close(self) -> None:
        if self._flusher is not None:
            self._flusher.cancel()
            self._flusher = None
        if self._group_flusher is not None:
            self._group_flusher.cancel()
            self._group_flusher = None
        self._flush_now()
        self._flush_groups_now()
        # In-flight batch dispatches resolve their callers' futures; give
        # them a bounded window to finish, then cancel stragglers so no
        # batch task survives its owner (a wedged executor thread must not
        # hang node shutdown or leak tasks into the next test).
        if self._batches:
            _, stuck = await asyncio.wait(set(self._batches), timeout=5.0)
            for t in stuck:
                t.cancel()
