"""TpuVerifier: host wrapper turning (pk, msg, sig) batches into fixed-shape
device dispatches of the ed25519 kernel.

Plugs into the batch-verification seam (crypto.set_batch_verifier) that the
primary's certificate path and the worker's batch path call — the TPU-era
`TpuVerifier` service of SURVEY §7.8a. Responsibilities:

- host prechecks the kernel doesn't do: length, canonical S (< L), canonical
  R/A encodings (y < p);
- the SHA-512 challenge k = H(R || A || M) mod L (hashlib is C-speed; the
  device only sees 256-bit scalars as 4-bit window digits);
- shape bucketing: pad each call to the next power-of-two batch so XLA
  compiles a handful of programs, not one per batch size;
- CPU fallback when no device kernel is usable (import or platform failure).

An async coalescing front (`AsyncVerifierPool`) batches concurrent requests
with a size-or-deadline window, the BatchMaker pattern applied to crypto
(SURVEY §7 "hard parts": offload must be batched or it adds latency).
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
from typing import Sequence

import numpy as np

from ..crypto import BatchItem

logger = logging.getLogger("narwhal.tpu.verifier")

_MIN_BUCKET = 16
_MAX_BUCKET = 8192


class TpuVerifier:
    """Synchronous batch verifier backed by the JAX kernel."""

    def __init__(self, max_bucket: int = _MAX_BUCKET):
        from . import ed25519 as kernel  # deferred: imports jax

        self.kernel = kernel
        self.max_bucket = max_bucket

    def precompile(self, sizes: Sequence[int] = ()) -> None:
        """Warm the jit cache for the given bucket sizes."""
        from ..crypto import KeyPair

        kp = KeyPair.generate()
        sig = kp.sign(b"warmup")
        for size in sizes or (_MIN_BUCKET, self.max_bucket):
            self([(kp.public, b"warmup", sig)] * size)

    def submit(self, items: Sequence[BatchItem]):
        """Pack + precheck on host and enqueue the device dispatch(es).
        Returns an opaque handle for `collect` — dispatch is asynchronous, so
        several submitted batches stay in flight and the device readback
        latency overlaps the next batch's host packing and compute."""
        n = len(items)
        if n == 0:
            return (np.zeros(0, bool), np.zeros(0, np.int64), [])
        ok = np.zeros(n, bool)
        a_raw = np.zeros((n, 32), np.uint8)
        r_raw = np.zeros((n, 32), np.uint8)
        s_raw = np.zeros((n, 32), np.uint8)
        k_raw = np.zeros((n, 32), np.uint8)
        precheck = np.zeros(n, bool)
        for i, (pk, msg, sig) in enumerate(items):
            if len(pk) != 32 or len(sig) != 64:
                continue
            rs, sb = sig[:32], sig[32:]
            s_int = int.from_bytes(sb, "little")
            if s_int >= self.kernel.ref.L:
                continue
            if (int.from_bytes(pk, "little") & ((1 << 255) - 1)) >= self.kernel.ref.P:
                continue
            if (int.from_bytes(rs, "little") & ((1 << 255) - 1)) >= self.kernel.ref.P:
                continue
            k_int = int.from_bytes(
                hashlib.sha512(rs + pk + msg).digest(), "little"
            ) % self.kernel.ref.L
            a_raw[i] = np.frombuffer(pk, np.uint8)
            r_raw[i] = np.frombuffer(rs, np.uint8)
            s_raw[i] = np.frombuffer(sb, np.uint8)
            k_raw[i] = np.frombuffer(k_int.to_bytes(32, "little"), np.uint8)
            precheck[i] = True

        idx = np.flatnonzero(precheck)
        if idx.size == 0:
            return (ok, idx, [])

        # Narrow upload dtypes (limbs < 2^13, digits < 16): ~3x fewer bytes
        # over the device link; the kernel widens to int32 lanes on device.
        a_y = self.kernel.bytes_to_limbs(a_raw[idx]).astype(np.int16)
        r_y = self.kernel.bytes_to_limbs(r_raw[idx]).astype(np.int16)
        a_sign = (a_raw[idx, 31] >> 7).astype(np.int8)
        r_sign = (r_raw[idx, 31] >> 7).astype(np.int8)
        k_digits = self.kernel.bytes_to_digits(k_raw[idx]).astype(np.int8)
        s_digits = self.kernel.bytes_to_digits(s_raw[idx]).astype(np.int8)

        outs = []  # (lo, hi, device array)
        for lo in range(0, idx.size, self.max_bucket):
            hi = min(lo + self.max_bucket, idx.size)
            bucket = _MIN_BUCKET
            while bucket < hi - lo:
                bucket *= 2
            pad = bucket - (hi - lo)

            def pad_to(arr):
                if pad == 0:
                    return arr[lo:hi]
                return np.concatenate(
                    [arr[lo:hi], np.repeat(arr[lo : lo + 1], pad, axis=0)]
                )

            out = self.kernel.verify_batch_kernel(
                pad_to(a_y),
                pad_to(a_sign),
                pad_to(r_y),
                pad_to(r_sign),
                pad_to(k_digits),
                pad_to(s_digits),
            )
            # Kick off the device->host copy as soon as the kernel finishes
            # so collect() finds the bytes already local instead of paying
            # the transfer round trip synchronously.
            try:
                out.copy_to_host_async()
            except AttributeError:
                pass
            outs.append((lo, hi, out))
        return (ok, idx, outs)

    @staticmethod
    def collect(handle) -> list[bool]:
        """Materialize a `submit` handle's results (blocks on the device)."""
        ok, idx, outs = handle
        if idx.size:
            results = np.zeros(idx.size, bool)
            for lo, hi, out in outs:
                results[lo:hi] = np.asarray(out)[: hi - lo]
            ok[idx] = results
        return ok.tolist()

    def __call__(self, items: Sequence[BatchItem]) -> list[bool]:
        return self.collect(self.submit(items))


def make_batch_verifier(fallback_on_error: bool = True):
    """Build a crypto.BatchVerifier backed by the TPU kernel, falling back to
    the host loop if the device path fails."""
    from .. import crypto

    try:
        verifier = TpuVerifier()
    except Exception:  # jax/platform import failure
        logger.exception("TPU verifier unavailable; using host verification")
        return None

    def backend(items: Sequence[BatchItem]) -> list[bool]:
        try:
            return verifier(items)
        except Exception:
            if not fallback_on_error:
                raise
            logger.exception("TPU verify dispatch failed; host fallback")
            return crypto._host_batch_verify(items)

    return backend


class AsyncVerifierPool:
    """Size-or-deadline coalescing of concurrent verification requests.

    await pool.verify(pk, msg, sig) from any task; items are flushed to the
    backend in one batch when `max_batch` are waiting or `max_delay` elapsed
    since the first queued item (BatchMaker's seal rule, applied to crypto).
    The backend call runs in a thread so the event loop never blocks on the
    device.
    """

    def __init__(
        self,
        backend=None,
        max_batch: int = 512,
        max_delay: float = 0.002,
    ):
        from .. import crypto

        self.backend = backend or crypto.batch_verify
        self.max_batch = max_batch
        self.max_delay = max_delay
        self._pending: list[tuple[BatchItem, asyncio.Future]] = []
        self._flusher: asyncio.Task | None = None
        self._batches: set[asyncio.Task] = set()  # strong refs: loop holds weak

    async def verify(self, public_key: bytes, message: bytes, signature: bytes) -> bool:
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._pending.append(((public_key, message, signature), fut))
        if len(self._pending) >= self.max_batch:
            self._flush_now()
        elif self._flusher is None or self._flusher.done():
            self._flusher = asyncio.ensure_future(self._deadline_flush())
        return await fut

    def _flush_now(self) -> None:
        pending, self._pending = self._pending, []
        if pending:
            task = asyncio.ensure_future(self._run_batch(pending))
            self._batches.add(task)
            task.add_done_callback(self._batches.discard)

    async def _deadline_flush(self) -> None:
        await asyncio.sleep(self.max_delay)
        self._flush_now()

    async def _run_batch(self, pending) -> None:
        items = [item for item, _ in pending]
        loop = asyncio.get_running_loop()
        try:
            results = await loop.run_in_executor(None, self.backend, items)
        except Exception as e:
            for _, fut in pending:
                if not fut.done():
                    fut.set_exception(e)
            return
        for (_, fut), res in zip(pending, results):
            if not fut.done():
                fut.set_result(res)

    async def close(self) -> None:
        if self._flusher is not None:
            self._flusher.cancel()
        self._flush_now()
