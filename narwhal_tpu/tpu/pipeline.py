"""Fused double-buffered device pipeline: verify -> DAG insert -> commit.

The per-certificate hot path on a device-backed node crosses the host
boundary three times (verify readback, window scatter, commit-walk
readback) with the host re-touching the certificate at each stage. This
module fuses the three stages into one pipelined flow over BATCHES of
accepted certificates:

  feed(batch k+1)  — host packs the signature items and dispatches the
                     verify kernels; the device computes batch k+1's
                     verify WHILE batch k's DAG walk/readback completes
                     (jax dispatch is asynchronous, and TpuVerifier.submit
                     front-loads the device->host copies);
  _resolve(batch k)— verdicts gathered; accepted certificates enter the
                     consensus engine through ONE `process_batch` call:
                     one `place_batch` scatter for the whole batch, the
                     commit rule evaluated per trigger, each commit
                     event's chain_commit readback deferred one event so
                     it overlaps the next event's host bookkeeping.

The host therefore touches each certificate once at pack time and once at
accept time — never per stage — and with `depth` batches in flight the
device never idles between verify and walk dispatches (double-buffered at
the default depth=2).

Output equivalence: the commit sequence is identical to feeding the same
certificates one at a time through `process_certificate` (Bullshark's
commit rule is re-evaluated on every support-round certificate, so
batching arrivals can only move WHERE a commit is yielded, never its
content or order — pinned by tests/test_multichip.py).
"""

from __future__ import annotations

import collections
import logging
from typing import Iterable, Sequence

from ..clock import now
from ..types import Certificate, ConsensusOutput

logger = logging.getLogger("narwhal.tpu.pipeline")


class FusedCertificatePipeline:
    """verify -> place_batch -> chain_commit over certificate batches.

    verifier: a TpuVerifier (mesh-sharded or not) — its submit/collect
    halves are the pipeline's stage boundary; engine: a TpuBullshark (or
    TpuTusk); state: the ConsensusState the engine mutates. `depth` is
    the number of verify batches kept in flight (2 = double-buffered)."""

    def __init__(
        self, verifier, engine, state, start_index: int = 0, depth: int = 2,
        tracer=None,
    ):
        self.verifier = verifier
        self.engine = engine
        self.state = state
        self.consensus_index = start_index
        self.depth = max(1, depth)
        self.tracer = tracer
        self._inflight: collections.deque = collections.deque()
        self.outputs: list[ConsensusOutput] = []
        self.rejected: list[Certificate] = []

    def _span_key(self, certs: Sequence[Certificate]):
        """Device sub-spans are per-batch, keyed by the batch's first
        certificate digest (the batch has no digest of its own); the n=
        attribute records how many certificates the span covers."""
        tracer = self.tracer
        if tracer is None or not tracer.enabled or not certs:
            return None
        key = certs[0].digest
        return key if tracer.sampled(key) else None

    def feed(self, certs: Sequence[Certificate], committee=None) -> None:
        """Pack + dispatch one verify batch; resolves the oldest in-flight
        batch first when the pipeline is full, so at most `depth` batches
        ride the device at once. Full-format certificates dispatch their
        per-vote signature items; compact certificates ride the verifier's
        aggregate group lane (submit_groups — the default dispatch shape
        now that compact is the committee-wide default), both halves of one
        batch in flight together."""
        while len(self._inflight) >= self.depth:
            self._resolve_one()
        committee = committee or self.engine.committee
        span_key = self._span_key(certs)
        t_pack = now()
        items: list = []
        groups: list = []
        # Input order preserved: ("item", cert, lo, hi) spans index into the
        # item verdicts, ("group", cert, g) into the group verdicts; g/lo of
        # None marks a signature-free certificate (genesis): valid.
        spans: list[tuple] = []
        # Staging split (traced batches only — the untraced path pays no
        # extra clock reads): items_s is the full-format per-vote item
        # staging, groups_s the compact-format aggregate decompress. The
        # epilogue attributor (tools/perf/epilogue.py) keys on these.
        items_s = groups_s = 0.0
        for cert in certs:
            t_cert = now() if span_key is not None else 0.0
            if cert.is_compact:
                group = cert.aggregate_group(committee)
                if group is None:
                    spans.append(("group", cert, None))
                else:
                    spans.append(("group", cert, len(groups)))
                    groups.append(group)
                if span_key is not None:
                    groups_s += now() - t_cert
            else:
                cert_items = cert.verify_items(committee)
                spans.append(("item", cert, len(items), len(items) + len(cert_items)))
                items.extend(cert_items)
                if span_key is not None:
                    items_s += now() - t_cert
        t_dispatch = now()
        handle = self.verifier.submit(items)
        ghandle = self.verifier.submit_groups(groups) if groups else None
        if span_key is not None:
            n = len(certs)
            self.tracer.span("device_pack", span_key, t_pack, t_dispatch, {"n": n})
            # Sub-spans laid out back to back inside device_pack: widths are
            # the measured per-branch staging time, which is what the
            # attributor consumes.
            self.tracer.span(
                "pack_items", span_key, t_pack, t_pack + items_s,
                {"n_items": len(items)},
            )
            self.tracer.span(
                "pack_groups", span_key, t_pack + items_s,
                t_pack + items_s + groups_s, {"n_groups": len(groups)},
            )
            self.tracer.span("device_dispatch", span_key, t_dispatch, now(), {"n": n})
        self._inflight.append((spans, handle, ghandle, span_key))

    def _resolve_one(self) -> None:
        spans, handle, ghandle, span_key = self._inflight.popleft()
        t_collect = now()
        ok = self.verifier.collect(handle)
        gok = self.verifier.collect_groups(ghandle) if ghandle is not None else []
        if span_key is not None:
            # collect() blocks on the device->host verdict copies: the
            # mask-readback sub-span of this batch's device-plane timeline.
            self.tracer.span(
                "device_mask_readback", span_key, t_collect, now(),
                {"n": len(spans)},
            )
        t_epilogue = now()
        accepted: list[Certificate] = []
        for span in spans:
            if span[0] == "group":
                _, cert, g = span
                passed = True if g is None else gok[g]
            else:
                _, cert, lo, hi = span
                # Genesis certificates carry no signatures (empty span):
                # valid.
                passed = all(ok[lo:hi])
            if passed:
                accepted.append(cert)
            else:
                self.rejected.append(cert)
        t_unpack = now()
        if accepted:
            outs = self.engine.process_batch(
                self.state, self.consensus_index, accepted
            )
            self.consensus_index += len(outs)
            self.outputs.extend(outs)
        if span_key is not None:
            # Host-side epilogue, split so its books balance: unpack
            # (verdict routing) + commit (process_batch: DAG insert, commit
            # walk, output bookkeeping) partition [t_epilogue, t_end]
            # exactly — a stage added outside the two sub-spans shows up as
            # unattributed drift in tools/perf/epilogue.py.
            t_end = now()
            self.tracer.span(
                "epilogue_unpack", span_key, t_epilogue, t_unpack,
                {"n": len(spans)},
            )
            self.tracer.span(
                "epilogue_commit", span_key, t_unpack, t_end,
                {"n_accepted": len(accepted)},
            )
            self.tracer.span(
                "host_epilogue", span_key, t_epilogue, t_end, {"n": len(spans)}
            )

    def drain(self) -> list[ConsensusOutput]:
        """Resolve every in-flight batch and return the full committed
        sequence so far."""
        while self._inflight:
            self._resolve_one()
        return self.outputs

    def run(self, batches: Iterable[Sequence[Certificate]]) -> list[ConsensusOutput]:
        for batch in batches:
            self.feed(batch)
        return self.drain()
