"""TPU offload kernels (crypto + DAG) and their host wrappers.

The kernel modules (ed25519, dag_kernels) call `enable_compilation_cache`
when THEY import — the package itself stays jax-free so that pure-host
paths (the `pool` crypto backend, config/CLI imports) never pay the
multi-second jax import. The cache (repo `.jax_cache/`, override with
JAX_COMPILATION_CACHE_DIR) matters because the big kernels — the per-item
ed25519 Straus walk, the batch MSM accumulate, the chain_commit scan —
take minutes to compile uncached on slow hosts/tunnels, and every process
(node, bench, pytest) should pay that once per machine, not once per run.

CPU targets are cache-DISABLED by default (r5: XLA:CPU AOT entries encode
compile-machine pseudo-features the loader has crashed on), EXCEPT when
the operator explicitly opts in with NARWHAL_JAX_CACHE_DIR — the
multichip sweep's knob: an 8-virtual-device CPU mesh pays minutes-long
sharded kernel compiles, and the opt-in cache makes every process after
the first deserialize them instead (measured safe round-trip on this
container; see README "Multi-chip device plane"). The opt-in stays
per-platform-subdirectoried so a cpu entry can never poison a real
chip's cache dir.
"""

from __future__ import annotations

import os

_cache_enabled = False


def enable_compilation_cache() -> None:
    """Idempotent; requires jax to be importable (callers import it)."""
    global _cache_enabled
    if _cache_enabled:
        return
    # NARWHAL_JAX_CACHE_DIR: explicit operator opt-in — enables the
    # persistent cache even for CPU-target processes (virtual-device
    # meshes), where the default below refuses. Empty value = unset.
    opt_in_dir = os.environ.get("NARWHAL_JAX_CACHE_DIR", "").strip()
    cache_dir = opt_in_dir or os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(__file__))), ".jax_cache"
        ),
    )
    try:
        import jax

        # Per-platform subdirectory: AOT executables are machine-feature
        # specific, and a cache mixing entries from different backends /
        # feature sets can SIGILL on load (observed with cpu entries under
        # the axon plugin's environment).
        try:
            platform = jax.default_backend()
        except Exception:
            platform = "unknown"
        # Never persist CPU-target executables BY DEFAULT: XLA:CPU AOT
        # entries encode compile-machine pseudo-features
        # (+prefer-no-scatter, ...) that the loader rejects or CRASHES on
        # — entries written by a process on THIS host SIGSEGV'd the next
        # suite run inside compilation_cache.get_executable_and_time. The
        # cache's purpose is the real chip's minutes-long tunnel compiles;
        # CPU-backend runs (tests, dry runs) rely on in-process caching
        # only — unless NARWHAL_JAX_CACHE_DIR explicitly opts in (the
        # multichip sweep, where the sharded compiles dominate and the
        # round-trip is re-verified by the sweep itself). A process counts
        # as CPU-target when the default backend is cpu, JAX_PLATFORMS
        # forces cpu, or jax_default_device is pinned to a cpu device (the
        # conftest/dryrun configurations — their default backend can still
        # be the accelerator plugin, which would otherwise mix poisonous
        # cpu entries into the chip's cache dir).
        forced = os.environ.get("JAX_PLATFORMS", "").strip().lower()
        pinned = getattr(jax.config, "jax_default_device", None)
        cpu_target = (
            platform == "cpu"
            or forced.startswith("cpu")
            or (pinned is not None and getattr(pinned, "platform", "") == "cpu")
        )
        if cpu_target and not opt_in_dir:
            _cache_enabled = True
            return
        if cpu_target:
            platform = "cpu"  # opt-in: keep cpu entries in their own subdir
        cache_dir = os.path.join(cache_dir, platform)
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        _cache_enabled = True
    except Exception:  # pragma: no cover - cache is an optimization only
        pass
