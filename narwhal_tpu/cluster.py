"""In-process multi-node cluster for integration tests and local benchmarks.

Reference: /root/reference/test_utils/src/cluster.rs:31-793 — a whole
committee in one process: every authority runs a real primary (with consensus
and executor) plus workers as asyncio tasks over real loopback TCP, with
per-node registries; progress is asserted by scraping metrics
(assert_progress, cluster.rs:210-269).
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import replace

from . import clock, tracing
from .config import (
    Authority,
    Committee,
    Parameters,
    WorkerCache,
    WorkerInfo,
    get_available_port,
)
from .fixtures import CommitteeFixture
from .metrics import Registry
from .node import PrimaryNode, SimpleExecutionState, WorkerNode
from .stores import NodeStorage
from .types import PublicKey

logger = logging.getLogger("narwhal.cluster")


class AuthorityDetails:
    """Handles for one authority's roles (cluster.rs AuthorityDetails)."""

    def __init__(self, cluster: "Cluster", index: int, name: PublicKey):
        self.cluster = cluster
        self.index = index
        self.name = name
        self.primary: PrimaryNode | None = None
        self.workers: dict[int, WorkerNode] = {}
        self.store_path: str | None = None

    @property
    def registry(self) -> Registry | None:
        return self.primary.registry if self.primary else None

    def metric(self, name: str) -> float:
        """Scrape one gauge/counter from the primary's registry
        (cluster.rs:315 PrimaryNodeDetails::metric)."""
        return self.primary.registry.value(name)

    def worker_transactions_address(self, worker_id: int = 0) -> str:
        return self.cluster.worker_cache.worker(self.name, worker_id).transactions

    def worker_transactions_addresses(self) -> list[str]:
        """All W client-facing lanes of this validator, in worker-id order —
        what a sharding client round-robins across."""
        return [
            self.cluster.worker_cache.worker(self.name, wid).transactions
            for wid in sorted(self.workers)
        ]

    async def stop_worker(self, worker_id: int) -> None:
        """Kill ONE worker lane (the worker-loss fault of ROADMAP item 3's
        scenario axis); the primary and the other W-1 pipelines keep
        running."""
        w = self.workers.pop(worker_id, None)
        if w is not None:
            await w.shutdown()

    async def stop(self) -> None:
        if self.primary is not None:
            await self.primary.shutdown()
            self.primary = None
        for w in self.workers.values():
            await w.shutdown()
        self.workers.clear()


class Cluster:
    def __init__(
        self,
        size: int = 4,
        workers: int = 1,
        parameters: Parameters | None = None,
        internal_consensus: bool = True,
        benchmark: bool = False,
        store_base: str | None = None,
        crypto_backend: str = "cpu",
        dag_backend: str = "cpu",
        dag_shards: int = 1,
        consensus_protocol: str = "bullshark",
        max_header_delay: float = 0.05,
        max_batch_delay: float = 0.05,
        auth: bool = True,
    ):
        # Each cluster is a fresh tracer incarnation: successive in-process
        # clusters reuse node labels and (seeded fixtures) certificate
        # digests, so without the bump `tracing.live_dumps()` would merge a
        # prior cluster's spans into this one's waterfalls.
        tracing.new_generation()
        self.fixture = CommitteeFixture(size=size, workers=workers)
        # The delay kwargs override the fixture defaults (fast rounds for
        # tests) but an explicitly passed Parameters wins outright — latency
        # tests/benches can exercise real configurations either way.
        self.parameters = parameters or replace(
            self.fixture.parameters,
            max_header_delay=max_header_delay,
            max_batch_delay=max_batch_delay,
        )
        if crypto_backend == "tpu" and parameters is None:
            # Default only: every node in this in-process cluster runs the
            # tpu backend, so the committee can uniformly use the
            # cofactored accept set (the msm batch kernel). An explicitly
            # passed Parameters keeps its verify_rule — callers may want
            # the strict per-item kernel on the tpu backend.
            self.parameters = replace(self.parameters, verify_rule="cofactored")
        self.internal_consensus = internal_consensus
        self.benchmark = benchmark
        self.store_base = store_base
        self.crypto_backend = crypto_backend
        self.dag_backend = dag_backend
        self.dag_shards = dag_shards
        self.consensus_protocol = consensus_protocol
        # auth=False skips the transport handshake/AEAD layer: servers run
        # open and clients connect plain. Only for harnesses where the
        # medium itself is trusted (simnet's in-memory fabric at large N,
        # where 2·N·(N-1) pure-Python X25519 handshakes dominate boot).
        self.auth = auth
        self._assign_addresses()
        self.committee: Committee = self.fixture.committee
        self.worker_cache: WorkerCache = self.fixture.worker_cache
        self.authorities: list[AuthorityDetails] = [
            AuthorityDetails(self, i, a.public)
            for i, a in enumerate(self.fixture.authorities)
        ]

    def _assign_addresses(self) -> None:
        """Pre-assign real loopback ports so no early broadcast targets a
        placeholder. The simnet cluster overrides this with fabric-owned
        synthetic addresses (zero sockets, zero fds)."""
        committee = self.fixture.committee
        for pk, auth in committee.authorities.items():
            committee.authorities[pk] = replace(
                auth, primary_address=f"127.0.0.1:{get_available_port()}"
            )
        for pk, ws in self.fixture.worker_cache.workers.items():
            for wid, info in ws.items():
                ws[wid] = WorkerInfo(
                    name=info.name,
                    transactions=f"127.0.0.1:{get_available_port()}",
                    worker_address=f"127.0.0.1:{get_available_port()}",
                )

    def _commit_tap(self, index: int):
        """Per-node commit observation hook handed to Consensus; the simnet
        cluster records (epoch, round, digest) sequences for the oracles."""
        return None

    def _store(self, index: int, role: str) -> NodeStorage:
        if self.store_base is None:
            return NodeStorage(None)
        return NodeStorage(f"{self.store_base}/node-{index}-{role}")

    async def start_node(self, index: int) -> AuthorityDetails:
        """(cluster.rs start_node): boot one authority's primary + workers."""
        details = self.authorities[index]
        fixture_auth = self.fixture.authorities[index]
        storage = self._store(index, "primary")
        details.primary = PrimaryNode(
            fixture_auth.keypair,
            self.committee,
            self.worker_cache,
            self.parameters,
            storage,
            internal_consensus=self.internal_consensus,
            consensus_protocol=self.consensus_protocol,
            crypto_backend=self.crypto_backend,
            dag_backend=self.dag_backend,
            dag_shards=self.dag_shards,
            network_keypair=fixture_auth.network_keypair if self.auth else None,
            commit_tap=self._commit_tap(index),
        )
        await details.primary.spawn()
        for wid in range(self.fixture.workers_per_authority):
            wn = WorkerNode(
                fixture_auth.public,
                wid,
                self.committee,
                self.worker_cache,
                self.parameters,
                self._store(index, f"worker-{wid}"),
                benchmark=self.benchmark,
                network_keypair=(
                    fixture_auth.worker_keypairs[wid] if self.auth else None
                ),
            )
            await wn.spawn()
            details.workers[wid] = wn
        return details

    async def start(self, nodes: int | None = None) -> None:
        n = nodes if nodes is not None else len(self.authorities)
        for i in range(n):
            await self.start_node(i)

    async def stop_node(self, index: int) -> None:
        await self.authorities[index].stop()

    async def restart_node(self, index: int) -> AuthorityDetails:
        await self.stop_node(index)
        return await self.start_node(index)

    async def shutdown(self) -> None:
        for a in self.authorities:
            await a.stop()

    async def assert_progress(
        self,
        expected_nodes: int | None = None,
        commit_threshold: int = 1,
        timeout: float = 30.0,
    ) -> dict[PublicKey, float]:
        """Wait until every running node's last committed round reaches
        commit_threshold (cluster.rs assert_progress via metric scraping)."""
        expected = expected_nodes or sum(
            1 for a in self.authorities if a.primary is not None
        )
        deadline = clock.now() + timeout
        while True:
            rounds = {
                a.name: a.metric("consensus_last_committed_round")
                for a in self.authorities
                if a.primary is not None
            }
            ok = [r for r in rounds.values() if r >= commit_threshold]
            if len(ok) >= expected:
                return rounds
            if clock.now() > deadline:
                raise AssertionError(
                    f"no progress: committed rounds {rounds} < {commit_threshold}"
                )
            await asyncio.sleep(0.1)
