"""Prometheus-style metrics: registry, counters/gauges/histograms, text
exposition, HTTP exporter.

The reference registers ~50+ metrics per role (primary/src/metrics.rs:51-485,
worker/src/metrics.rs, consensus/src/metrics.rs:13-49) and exposes them over
HTTP (node/src/main.rs:279-285); cluster tests assert progress by scraping the
registry (test_utils/src/cluster.rs:210-269,315). We implement the same shape
in-process: a Registry of named metrics with labels, rendered in the
Prometheus text format, served by a tiny asyncio HTTP endpoint.
"""

from __future__ import annotations

import asyncio
import time
from collections import defaultdict
from typing import Iterable


class _Metric:
    def __init__(self, name: str, help_: str, label_names: tuple[str, ...]):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._children: dict[tuple[str, ...], _Child] = {}

    def labels(self, *values: str) -> "_Child":
        key = tuple(str(v) for v in values)
        child = self._children.get(key)
        if child is None:
            child = self._make_child()
            self._children[key] = child
        return child

    def _default(self) -> "_Child":
        return self.labels()

    def _make_child(self) -> "_Child":
        raise NotImplementedError


class _Child:
    pass


class _CounterChild(_Child):
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def inc(self, by: float = 1.0) -> None:
        self.value += by


class Counter(_Metric):
    kind = "counter"

    def _make_child(self) -> _CounterChild:
        return _CounterChild()

    def inc(self, by: float = 1.0) -> None:
        self._default().inc(by)

    def get(self) -> float:
        return self._default().value


class _GaugeChild(_Child):
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = v

    def inc(self, by: float = 1.0) -> None:
        self.value += by

    def dec(self, by: float = 1.0) -> None:
        self.value -= by


class Gauge(_Metric):
    kind = "gauge"

    def _make_child(self) -> _GaugeChild:
        return _GaugeChild()

    def set(self, v: float) -> None:
        self._default().set(v)

    def inc(self, by: float = 1.0) -> None:
        self._default().inc(by)

    def dec(self, by: float = 1.0) -> None:
        self._default().dec(by)

    def get(self) -> float:
        return self._default().value


class _HistogramChild(_Child):
    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets: tuple[float, ...]):
        self.buckets = buckets
        self.counts = [0] * len(buckets)
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        self.sum += v
        self.count += 1
        for i, b in enumerate(self.buckets):
            if v <= b:
                self.counts[i] += 1


DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_, label_names, buckets=DEFAULT_BUCKETS):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(buckets)

    def _make_child(self) -> _HistogramChild:
        return _HistogramChild(self.buckets)

    def observe(self, v: float) -> None:
        self._default().observe(v)


class Registry:
    """One per role process, like the reference's per-role registries
    (node/src/metrics.rs)."""

    def __init__(self) -> None:
        self._metrics: dict[str, _Metric] = {}

    def counter(self, name: str, help_: str = "", labels: Iterable[str] = ()) -> Counter:
        return self._register(Counter(name, help_, tuple(labels)))

    def gauge(self, name: str, help_: str = "", labels: Iterable[str] = ()) -> Gauge:
        return self._register(Gauge(name, help_, tuple(labels)))

    def histogram(
        self, name: str, help_: str = "", labels: Iterable[str] = (), buckets=DEFAULT_BUCKETS
    ) -> Histogram:
        return self._register(Histogram(name, help_, tuple(labels), buckets))

    def _register(self, metric: _Metric) -> _Metric:
        existing = self._metrics.get(metric.name)
        if existing is not None:
            if type(existing) is not type(metric):
                raise ValueError(f"metric {metric.name} re-registered with new type")
            return existing
        self._metrics[metric.name] = metric
        return metric

    def get(self, name: str) -> _Metric | None:
        return self._metrics.get(name)

    def value(self, name: str, *label_values: str) -> float:
        """Test/assertion helper, the analog of PrimaryNodeDetails::metric
        (test_utils/src/cluster.rs:315)."""
        m = self._metrics.get(name)
        if m is None:
            return 0.0
        child = m._children.get(tuple(str(v) for v in label_values))
        if child is None:
            return 0.0
        if isinstance(child, _HistogramChild):
            return child.count
        return child.value

    def render(self) -> str:
        out: list[str] = []
        for m in self._metrics.values():
            out.append(f"# HELP {m.name} {m.help}")
            out.append(f"# TYPE {m.name} {m.kind}")
            for key, child in m._children.items():
                lbl = (
                    "{" + ",".join(f'{n}="{v}"' for n, v in zip(m.label_names, key)) + "}"
                    if key
                    else ""
                )
                if isinstance(child, _HistogramChild):
                    cum = 0
                    for b, c in zip(child.buckets, child.counts):
                        cum += c
                        sep = "," if key else ""
                        base = lbl[:-1] + sep if key else "{"
                        out.append(f'{m.name}_bucket{base}le="{b}"}} {cum}')
                    base = lbl[:-1] + ("," if key else "")
                    if not key:
                        base = "{"
                    out.append(f'{m.name}_bucket{base}le="+Inf"}} {child.count}')
                    out.append(f"{m.name}_sum{lbl} {child.sum}")
                    out.append(f"{m.name}_count{lbl} {child.count}")
                else:
                    out.append(f"{m.name}{lbl} {child.value}")
        return "\n".join(out) + "\n"


def parse_exposition(text: str) -> dict[str, dict]:
    """Parse Prometheus text exposition (the exact dialect render() emits)
    into {name: {"type", "help", "samples": {label_suffix: value}}}. The
    label_suffix key is the raw '{...}' chunk ('' for unlabelled samples),
    so round-tripping a scrape is lossless for assertions and benchmark
    snapshots; _bucket/_sum/_count series fold under their base name."""
    out: dict[str, dict] = {}

    def entry(name: str) -> dict:
        return out.setdefault(
            name, {"type": "untyped", "help": "", "samples": {}}
        )

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            name, _, help_ = line[len("# HELP "):].partition(" ")
            entry(name)["help"] = help_
            continue
        if line.startswith("# TYPE "):
            name, _, kind = line[len("# TYPE "):].partition(" ")
            entry(name)["type"] = kind.strip()
            continue
        if line.startswith("#"):
            continue
        series, _, raw_value = line.rpartition(" ")
        try:
            value = float(raw_value)
        except ValueError:
            continue
        name, labels = series, ""
        if "{" in series:
            name, _, rest = series.partition("{")
            labels = "{" + rest
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in out:
                base = name[: -len(suffix)]
                labels = name[len(base):] + labels
                break
        entry(base)["samples"][labels] = value
    return out


def scrape_snapshot(registry: Registry) -> dict[str, dict]:
    """Benchmark-sized scrape snapshot: the full exposition parsed back,
    minus histogram bucket series (they dominate the byte count and the
    percentile story belongs to the trace-waterfall artifacts). Counters,
    gauges, and histogram _sum/_count survive — enough for any A/B to
    recompute rates and means from the embedded record alone."""
    out = {}
    for name, entry in parse_exposition(registry.render()).items():
        samples = {
            k: v
            for k, v in entry["samples"].items()
            if not k.startswith("_bucket")
        }
        out[name] = {"type": entry["type"], "samples": samples}
    return out


async def serve_metrics(registry: Registry, host: str, port: int):
    """Minimal HTTP /metrics exporter (node/src/main.rs:279-285). Returns the
    asyncio server; the bound port is server.sockets[0].getsockname()[1]."""

    async def handle(reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            await reader.readuntil(b"\r\n\r\n")
        except (asyncio.IncompleteReadError, asyncio.LimitOverrunError):
            writer.close()
            return
        body = registry.render().encode()
        writer.write(
            b"HTTP/1.1 200 OK\r\nContent-Type: text/plain; version=0.0.4\r\n"
            + f"Content-Length: {len(body)}\r\n\r\n".encode()
            + body
        )
        try:
            await writer.drain()
        finally:
            writer.close()

    return await asyncio.start_server(handle, host, port)
