"""Benchmark transaction client: fixed-rate submission with sample markers.

Reference: /root/reference/node/src/benchmark_client.rs:19- — submits
`size`-byte transactions at `rate` tx/s in 1s ticks (burst per tick), marking
one transaction per burst as a *sample* (first byte 0, big-endian u64 counter
following) so the log parser can compute end-to-end latency; all other
transactions carry first byte 1 and a random-ish payload. Logs the
benchmark-parsed lines "Sending sample transaction {id}" and warns when a
burst cannot keep rate.
"""

from __future__ import annotations

import asyncio
import logging
import struct
import time

from .messages import SubmitTransactionStreamMsg
from .network import NetworkClient, RpcError

logger = logging.getLogger("narwhal.benchmark_client")

PRECISION = 20  # bursts per second (reference uses 50ms sub-ticks)


class BenchmarkClient:
    def __init__(
        self,
        target,  # worker transactions address(es): str or sequence of str
        size: int = 512,
        rate: int = 1_000,
        nodes: tuple[str, ...] = (),
    ):
        # Payload-plane sharding: a validator running W workers exposes W
        # transaction endpoints; a client given several targets round-robins
        # its bursts across them (deterministic by burst counter — the
        # hash-shard analog for anonymous benchmark traffic), so every
        # worker pipeline carries rate/W and the validator's ingest scales
        # with W instead of serializing on one lane.
        self.targets: tuple[str, ...] = (
            (target,) if isinstance(target, str) else tuple(target)
        )
        if not self.targets:
            raise ValueError("benchmark client needs at least one target")
        self.target = self.targets[0]  # compat: single-lane callers
        self.size = max(size, 9)
        self.rate = rate
        self.nodes = nodes
        self.network = NetworkClient()
        self.counter = 0
        self._task: asyncio.Task | None = None
        self._inflight: set[asyncio.Task] = set()
        # Per-client nonce so filler transactions differ across clients and
        # no two authorities seal byte-identical batches (the reference uses
        # random filler bytes, benchmark_client.rs).
        import secrets

        # Load-generator CLI, not protocol code: the nonce only needs to be
        # unique per client process and is never replayed under a seed.
        self._nonce = secrets.token_bytes(8)  # lint: allow(raw-entropy)

    async def wait_for_nodes(self, timeout: float = 30.0) -> None:
        """Wait until every node's tx port accepts connections
        (benchmark_client.rs wait)."""
        deadline = time.monotonic() + timeout
        for address in (*self.targets, *self.nodes):
            host, port = address.rsplit(":", 1)
            while True:
                try:
                    _, w = await asyncio.open_connection(host, int(port))
                    w.close()
                    break
                except OSError:
                    if time.monotonic() > deadline:
                        raise TimeoutError(f"node {address} never came up")
                    await asyncio.sleep(0.5)

    def spawn(self) -> asyncio.Task:
        self._task = asyncio.ensure_future(self.run())
        return self._task

    async def _submit(self, target: str, txs: tuple[bytes, ...]) -> None:
        try:
            await self.network.request(
                target, SubmitTransactionStreamMsg(txs), timeout=5.0
            )
        except (RpcError, OSError) as e:
            logger.warning("Failed to send transaction burst: %s", e)

    async def run(self) -> None:
        # Parameter lines the log parser reads (benchmark_client.rs logs).
        logger.info("Transactions size: %d B", self.size)
        logger.info("Transactions rate: %d tx/s", self.rate)
        logger.info("Start sending transactions")
        # At low rates fall back to 1-tx bursts at `rate` ticks/s so the
        # delivered rate matches the requested one instead of rounding up.
        precision = max(1, min(PRECISION, self.rate))
        burst = max(1, self.rate // precision)
        interval = 1.0 / precision
        next_tick = time.monotonic()
        while True:
            # One sample tx per burst, rest are filler (benchmark_client.rs).
            txs = []
            sample_id = self.counter
            for i in range(burst):
                if i == 0:
                    # Sample marker + id, then the nonce: low-rate clients
                    # (burst == 1) send only samples, which must still differ
                    # across clients or authorities seal identical batches.
                    tx = b"\0" + struct.pack(">Q", sample_id) + self._nonce
                else:
                    tx = b"\1" + struct.pack(">Q", self.counter * burst + i) + self._nonce
                txs.append(tx.ljust(self.size, b"\0"))
            logger.info("Sending sample transaction %d", sample_id)
            # Fire-and-forget: a slow ack must not stall the rate loop.
            target = self.targets[self.counter % len(self.targets)]
            task = asyncio.ensure_future(self._submit(target, tuple(txs)))
            self._inflight.add(task)
            task.add_done_callback(self._inflight.discard)
            self.counter += 1
            next_tick += interval
            sleep = next_tick - time.monotonic()
            if sleep > 0:
                await asyncio.sleep(sleep)
            elif sleep < -1.0:
                logger.warning("Transaction rate too high for this client")
                next_tick = time.monotonic()

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
        for task in list(self._inflight):
            task.cancel()
        self.network.close()
