"""Test fixtures: committees, signed headers/votes/certificates, DAG generators.

Reference: /root/reference/test_utils/src/lib.rs — CommitteeFixture :602-793,
synthetic DAG generators make_optimal_certificates / make_certificates(...,
failure_probability) / make_signed_certificates / mock_certificate :397-599.
Lives in the package (not tests/) because the benchmark harness and bench.py
also build committees from it, like the reference's test_utils crate being a
workspace member.
"""

from __future__ import annotations

import random
from dataclasses import dataclass

from .config import Authority, Committee, Parameters, WorkerCache, WorkerInfo
from .crypto import KeyPair, SignatureService
from .types import Certificate, Digest, Header, PublicKey, Round, Vote, WorkerId


@dataclass
class AuthorityFixture:
    keypair: KeyPair
    network_keypair: KeyPair
    worker_keypairs: dict[WorkerId, KeyPair]

    @property
    def public(self) -> PublicKey:
        return self.keypair.public

    def signature_service(self) -> SignatureService:
        return SignatureService(self.keypair)


class CommitteeFixture:
    """Deterministic committee of `size` authorities with `workers` workers
    each, equal stake, loopback addresses
    (/root/reference/test_utils/src/lib.rs:602-793)."""

    def __init__(
        self,
        size: int = 4,
        workers: int = 1,
        epoch: int = 0,
        seed: int = 0,
        base_port: int = 0,
        stakes: list[int] | None = None,
    ):
        self.size = size
        self.workers_per_authority = workers
        self.epoch = epoch
        self.authorities: list[AuthorityFixture] = []
        for i in range(size):
            kp = KeyPair.from_seed(f"authority-{seed}-{i}".encode().ljust(32, b"\0")[:32])
            nk = KeyPair.from_seed(f"network-{seed}-{i}".encode().ljust(32, b"\0")[:32])
            wks = {
                w: KeyPair.from_seed(
                    f"worker-{seed}-{i}-{w}".encode().ljust(32, b"\0")[:32]
                )
                for w in range(workers)
            }
            self.authorities.append(AuthorityFixture(kp, nk, wks))
        # Sort fixtures into committee canonical (pubkey-sorted) order so
        # authority index i here == committee dense index i.
        self.authorities.sort(key=lambda a: a.public)
        stakes = stakes or [1] * size
        port = [base_port]  # 0 => addresses are placeholders until bound

        def addr() -> str:
            if base_port == 0:
                return "127.0.0.1:0"
            port[0] += 1
            return f"127.0.0.1:{port[0]}"

        self.committee = Committee(
            {
                a.public: Authority(
                    stake=stakes[i], primary_address=addr(), network_key=a.network_keypair.public
                )
                for i, a in enumerate(self.authorities)
            },
            epoch=epoch,
        )
        self.worker_cache = WorkerCache(
            {
                a.public: {
                    w: WorkerInfo(
                        name=a.worker_keypairs[w].public,
                        transactions=addr(),
                        worker_address=addr(),
                    )
                    for w in range(workers)
                }
                for a in self.authorities
            },
            epoch=epoch,
        )
        self.parameters = Parameters()

    def authority(self, i: int) -> AuthorityFixture:
        return self.authorities[i]

    def keypair(self, name: PublicKey) -> KeyPair:
        for a in self.authorities:
            if a.public == name:
                return a.keypair
        raise KeyError(name.hex())

    # -- protocol object builders ----------------------------------------
    def header(
        self,
        author: int = 0,
        round: Round = 1,
        payload: dict[Digest, WorkerId] | None = None,
        parents: set[Digest] | None = None,
    ) -> Header:
        if parents is None:
            parents = {c.digest for c in Certificate.genesis(self.committee)}
        a = self.authorities[author]
        return Header.build(
            a.public, round, self.epoch, payload or {}, parents, a.keypair
        )

    def votes(self, header: Header, exclude_author: bool = True) -> list[Vote]:
        out = []
        for a in self.authorities:
            if exclude_author and a.public == header.author:
                continue
            out.append(Vote.for_header(header, a.public, a.keypair))
        return out

    def certificate(self, header: Header) -> Certificate:
        """Fully-signed certificate with a quorum of votes (header author's
        own implicit vote included, as the reference's VotesAggregator counts
        the author's stake)."""
        signers, sigs = [], []
        for a in self.authorities:
            v = Vote.for_header(header, a.public, a.keypair)
            signers.append(self.committee.index_of(a.public))
            sigs.append(v.signature)
        return Certificate(header, tuple(signers), tuple(sigs))


def mock_certificate(
    committee: Committee,
    origin: PublicKey,
    round: Round,
    parents: frozenset[Digest] | set[Digest],
    payload: dict[Digest, WorkerId] | None = None,
) -> Certificate:
    """Unsigned certificate for consensus/DAG tests
    (/root/reference/test_utils/src/lib.rs:575-599)."""
    return Certificate(
        Header(
            author=origin,
            round=round,
            epoch=committee.epoch,
            payload=payload or {},
            parents=frozenset(parents),
        )
    )


def make_optimal_certificates(
    committee: Committee,
    start_round: Round,
    end_round: Round,
    initial_parents: set[Digest],
    keys: list[PublicKey] | None = None,
) -> tuple[list[Certificate], set[Digest]]:
    """Fully-connected DAG rounds [start, end]
    (/root/reference/test_utils/src/lib.rs:397-420)."""
    return make_certificates(
        committee, start_round, end_round, initial_parents, keys, failure_probability=0.0
    )


def make_certificates(
    committee: Committee,
    start_round: Round,
    end_round: Round,
    initial_parents: set[Digest],
    keys: list[PublicKey] | None = None,
    failure_probability: float = 0.0,
    rng: random.Random | None = None,
) -> tuple[list[Certificate], set[Digest]]:
    """Possibly-lossy DAG: each certificate links to each previous-round parent
    with probability 1-failure_probability, but always keeps a quorum of links
    (/root/reference/test_utils/src/lib.rs:430-500)."""
    rng = rng or random.Random(0)
    keys = keys or committee.authority_keys()
    certificates: list[Certificate] = []
    parents = set(initial_parents)
    for r in range(start_round, end_round + 1):
        next_parents: set[Digest] = set()
        for pk in keys:
            parent_list = sorted(parents)
            if failure_probability > 0.0:
                quorum = (2 * len(parent_list)) // 3 + 1
                kept = [
                    p for p in parent_list if rng.random() >= failure_probability
                ]
                if len(kept) < quorum:
                    kept = rng.sample(parent_list, quorum)
                parent_list = kept
            cert = mock_certificate(committee, pk, r, set(parent_list))
            certificates.append(cert)
            next_parents.add(cert.digest)
        parents = next_parents
    return certificates, parents


def make_certificates_with_epoch(
    committee: Committee,
    start_round: Round,
    end_round: Round,
    epoch: int,
    initial_parents: set[Digest],
    keys: list[PublicKey] | None = None,
) -> tuple[list[Certificate], set[Digest]]:
    """(/root/reference/test_utils/src/lib.rs:502-540)."""
    keys = keys or committee.authority_keys()
    certificates: list[Certificate] = []
    parents = set(initial_parents)
    for r in range(start_round, end_round + 1):
        next_parents: set[Digest] = set()
        for pk in keys:
            cert = Certificate(
                Header(
                    author=pk,
                    round=r,
                    epoch=epoch,
                    payload={},
                    parents=frozenset(parents),
                )
            )
            certificates.append(cert)
            next_parents.add(cert.digest)
        parents = next_parents
    return certificates, parents


def make_signed_certificates(
    fixture: CommitteeFixture,
    start_round: Round,
    end_round: Round,
    initial_parents: set[Digest],
) -> tuple[list[Certificate], set[Digest]]:
    """Fully-signed DAG (/root/reference/test_utils/src/lib.rs:542-573)."""
    certificates: list[Certificate] = []
    parents = set(initial_parents)
    for r in range(start_round, end_round + 1):
        next_parents: set[Digest] = set()
        for i, a in enumerate(fixture.authorities):
            header = Header.build(
                a.public, r, fixture.epoch, {}, parents, a.keypair
            )
            cert = fixture.certificate(header)
            certificates.append(cert)
            next_parents.add(cert.digest)
        parents = next_parents
    return certificates, parents
