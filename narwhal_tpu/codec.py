"""Canonical deterministic binary serialization.

The reference serializes all wire types with bincode (little-endian, length
prefixes; see /root/reference/types/build.rs:42-121 where anemo services use a
bincode codec, and /root/reference/node/src/generate_format.rs which snapshots
the serde formats for stability). We define our own equally-simple canonical
encoding rather than chasing bincode compatibility: little-endian fixed-width
integers, u32 length prefixes for byte strings and sequences, and maps encoded
as key-sorted sequences so that encoding is a pure function of the value.

A format-snapshot test (tests/test_formats.py, mirroring
/root/reference/node/tests/formats.rs:5) guards accidental format drift.
"""

from __future__ import annotations

import struct

_U8 = struct.Struct("<B")
_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")


class Writer:
    """Append-only canonical encoder."""

    __slots__ = ("_parts",)

    def __init__(self) -> None:
        self._parts: list[bytes] = []

    def u8(self, v: int) -> "Writer":
        self._parts.append(_U8.pack(v))
        return self

    def u16(self, v: int) -> "Writer":
        self._parts.append(_U16.pack(v))
        return self

    def u32(self, v: int) -> "Writer":
        self._parts.append(_U32.pack(v))
        return self

    def u64(self, v: int) -> "Writer":
        self._parts.append(_U64.pack(v))
        return self

    def raw(self, b: bytes) -> "Writer":
        """Fixed-size field: no length prefix (caller knows the size)."""
        self._parts.append(b)
        return self

    def bytes(self, b: bytes) -> "Writer":
        self._parts.append(_U32.pack(len(b)))
        self._parts.append(b)
        return self

    def seq(self, items, enc) -> "Writer":
        items = list(items)
        self._parts.append(_U32.pack(len(items)))
        for it in items:
            enc(self, it)
        return self

    def bytes_seq(self, items) -> "Writer":
        """Sequence of byte strings, same wire form as seq(..., bytes) but
        without per-item closure dispatch — the transaction hot path."""
        pack = _U32.pack
        append = self._parts.append
        append(pack(len(items)))
        for b in items:
            append(pack(len(b)))
            append(b)
        return self

    def sorted_map(self, mapping, enc_key, enc_val) -> "Writer":
        """Maps are encoded sorted by raw key so encoding is canonical."""
        items = sorted(mapping.items())
        self._parts.append(_U32.pack(len(items)))
        for k, v in items:
            enc_key(self, k)
            enc_val(self, v)
        return self

    def finish(self) -> bytes:
        if len(self._parts) == 1:
            return self._parts[0]  # zero-copy for raw single-part bodies
        return b"".join(self._parts)


class Reader:
    """Matching decoder. Raises CodecError on truncation."""

    __slots__ = ("_buf", "_pos")

    def __init__(self, buf: bytes) -> None:
        self._buf = buf
        self._pos = 0

    def _take(self, n: int) -> bytes:
        end = self._pos + n
        if end > len(self._buf):
            raise CodecError(
                f"truncated input: need {n} bytes at offset {self._pos}, "
                f"have {len(self._buf) - self._pos}"
            )
        out = self._buf[self._pos : end]
        self._pos = end
        return out

    def u8(self) -> int:
        return _U8.unpack(self._take(1))[0]

    def u16(self) -> int:
        return _U16.unpack(self._take(2))[0]

    def u32(self) -> int:
        return _U32.unpack(self._take(4))[0]

    def u64(self) -> int:
        return _U64.unpack(self._take(8))[0]

    def raw(self, n: int) -> bytes:
        return self._take(n)

    def bytes(self) -> bytes:
        return self._take(self.u32())

    def seq(self, dec) -> list:
        n = self.u32()
        if n > len(self._buf) - self._pos:
            # Every element costs >=1 byte; cheap sanity bound against
            # maliciously huge length prefixes.
            raise CodecError(f"sequence length {n} exceeds remaining input")
        return [dec(self) for _ in range(n)]

    def bytes_seq(self) -> list:
        """Counterpart of Writer.bytes_seq: decode without per-item closures."""
        n = self.u32()
        buf, pos, end = self._buf, self._pos, len(self._buf)
        if n > end - pos:
            raise CodecError(f"sequence length {n} exceeds remaining input")
        unpack = _U32.unpack_from
        out = []
        for _ in range(n):
            if pos + 4 > end:
                raise CodecError("truncated byte-sequence length")
            (size,) = unpack(buf, pos)
            pos += 4
            if pos + size > end:
                raise CodecError("truncated byte-sequence element")
            out.append(buf[pos : pos + size])
            pos += size
        self._pos = pos
        return out

    def rest(self) -> bytes:
        """Take everything remaining (raw-passthrough payloads)."""
        out = self._buf[self._pos :]
        self._pos = len(self._buf)
        return out

    def map(self, dec_key, dec_val) -> dict:
        n = self.u32()
        if n > len(self._buf) - self._pos:
            raise CodecError(f"map length {n} exceeds remaining input")
        out = {}
        for _ in range(n):
            k = dec_key(self)
            out[k] = dec_val(self)
        return out

    def done(self) -> None:
        if self._pos != len(self._buf):
            raise CodecError(
                f"{len(self._buf) - self._pos} trailing bytes after decode"
            )


class CodecError(ValueError):
    pass
