"""The Consensus actor: feeds certificates to the ordering engine.

Reference: /root/reference/consensus/src/consensus.rs:175-361 — recover state
from the consensus/certificate stores, then loop: pull certificates from the
primary, run the protocol, forward ordered outputs to the executor
(tx_output) and committed certificates back to the primary (tx_primary, which
drives StateHandler GC), logging the benchmark-parsed "Committed ..." lines.
Epoch changes observed on the reconfigure watch reset the state.
"""

from __future__ import annotations

import asyncio
import logging

from ..channels import Channel, Subscriber, Watch
from ..config import Committee
from ..stores import CertificateStore, ConsensusStore
from ..types import Certificate, ConsensusOutput, ReconfigureNotification, Round
from .state import ConsensusState

logger = logging.getLogger("narwhal.consensus")


class Consensus:
    def __init__(
        self,
        committee: Committee,
        protocol,
        consensus_store: ConsensusStore,
        cert_store: CertificateStore,
        rx_new_certificates: Channel,
        tx_primary: Channel,
        tx_output: Channel,
        rx_reconfigure: Watch,
        gc_depth: Round,
        metrics=None,
        tx_accepted: Channel | None = None,  # non-blocking tap -> Prefetcher
        commit_tap=None,  # callable(ConsensusOutput): observation hook
    ):
        self.committee = committee
        self.protocol = protocol
        self.consensus_store = consensus_store
        self.cert_store = cert_store
        self.rx_new_certificates = rx_new_certificates
        self.tx_primary = tx_primary
        self.tx_output = tx_output
        self.rx_reconfigure = Subscriber(rx_reconfigure)
        self.gc_depth = gc_depth
        self.metrics = metrics
        self.tx_accepted = tx_accepted
        # Synchronous, non-blocking observation hook per committed output:
        # the simnet safety/liveness oracles read the exact commit sequence
        # here without adding a channel (and without racing the executor).
        self.commit_tap = commit_tap
        self.consensus_index = consensus_store.last_consensus_index()
        self.state = ConsensusState.new_from_store(
            Certificate.genesis(committee),
            consensus_store.read_last_committed(),
            cert_store,
            gc_depth,
            metrics,
        )
        # Device-backed protocols mirror the recovered host DAG into their
        # window tensors (TpuBullshark.recover); host engines need nothing.
        if hasattr(protocol, "recover"):
            protocol.recover(self.state)
        self._task: asyncio.Task | None = None

    def spawn(self) -> asyncio.Task:
        self._task = asyncio.ensure_future(self.run())
        return self._task

    async def run(self) -> None:
        recon_task = asyncio.ensure_future(self.rx_reconfigure.changed())
        cert_task = asyncio.ensure_future(self.rx_new_certificates.recv())
        try:
            while True:
                done, _ = await asyncio.wait(
                    {recon_task, cert_task}, return_when=asyncio.FIRST_COMPLETED
                )
                if recon_task in done:
                    note: ReconfigureNotification = recon_task.result()
                    if note.kind == "shutdown":
                        return
                    if note.committee is not None:
                        self.committee = note.committee
                        self.protocol.update_committee(note.committee)
                        self.state = ConsensusState(
                            Certificate.genesis(note.committee), self.metrics
                        )
                        self.consensus_index = 0
                        logger.info("Committee updated to epoch %s", note.committee.epoch)
                    recon_task = asyncio.ensure_future(self.rx_reconfigure.changed())
                if cert_task in done:
                    certs: list[Certificate] = [cert_task.result()]
                    # Greedy bounded drain: a burst of certificates from
                    # the primary is ordered in one pass instead of one
                    # select round-trip per certificate.
                    while len(certs) < 64:
                        extra = self.rx_new_certificates.try_recv()
                        if extra is None:
                            break
                        certs.append(extra)
                    cert_task = asyncio.ensure_future(self.rx_new_certificates.recv())
                    batch: list[Certificate] = []
                    for certificate in certs:
                        if certificate.epoch != self.committee.epoch:
                            continue  # stale epoch, drop
                        if self.metrics is not None:
                            # Stage tracing: acceptance -> sequenced in a
                            # committed causal history (_emit stops it).
                            self.metrics.commit_timer.start(certificate.digest)
                        if self.tx_accepted is not None:
                            # Speculative prefetch tap: batch digests are
                            # known NOW, rounds before this certificate can
                            # commit. Strictly non-blocking — speculation
                            # must never backpressure ordering, so a full
                            # channel just drops the hint (the commit-time
                            # fetch covers it).
                            if (
                                not self.tx_accepted.try_send(certificate)
                                and self.metrics is not None
                            ):
                                self.metrics.accepted_tap_dropped.inc()
                        batch.append(certificate)
                    if len(batch) > 1 and hasattr(
                        self.protocol, "process_batch_async"
                    ):
                        # Device-backed burst path: one batched window
                        # scatter + per-event dispatches with readbacks
                        # deferred one event (the fused pipeline), instead
                        # of one full dispatch round trip per certificate.
                        sequence = await self.protocol.process_batch_async(
                            self.state, self.consensus_index, batch
                        )
                        await self._emit(sequence)
                    else:
                        for certificate in batch:
                            await self._process(certificate)
        finally:
            recon_task.cancel()
            cert_task.cancel()

    async def _process(self, certificate: Certificate) -> None:
        if hasattr(self.protocol, "process_certificate_async"):
            # Device-backed protocols overlap their device->host readback
            # with the rest of the node's event loop.
            sequence = await self.protocol.process_certificate_async(
                self.state, self.consensus_index, certificate
            )
        else:
            sequence = self.protocol.process_certificate(
                self.state, self.consensus_index, certificate
            )
        await self._emit(sequence)

    async def _emit(self, sequence: list[ConsensusOutput]) -> None:
        if sequence:
            self.consensus_index = sequence[-1].consensus_index + 1
        for output in sequence:
            cert = output.certificate
            if cert.round % 10 == 0:
                logger.debug("Committed %s round %s", cert.digest.hex()[:16], cert.round)
            # The benchmark-parsed commit lines (consensus.rs:305-316): one
            # per payload batch, mirroring the Created lines.
            logger.info("Committed B%s(%s)", cert.round, cert.digest.hex())
            for batch_digest in cert.header.payload:
                logger.info(
                    "Committed B%s(%s) -> %s",
                    cert.round,
                    cert.digest.hex(),
                    batch_digest.hex(),
                )
            if self.metrics is not None:
                self.metrics.last_committed_round.set(self.state.last_committed_round)
                self.metrics.committed_certificates.inc()
                self.metrics.commit_timer.stop(cert.digest)
            if self.commit_tap is not None:
                self.commit_tap(output)
            await self.tx_primary.send(cert)
            await self.tx_output.send(output)
        if self.metrics is not None:
            self.metrics.consensus_dag_size.set(self.state.dag_size())
