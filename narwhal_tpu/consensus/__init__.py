from .state import ConsensusState
from .bullshark import Bullshark
from .tusk import Tusk
from .runner import Consensus

__all__ = ["ConsensusState", "Bullshark", "Tusk", "Consensus"]
