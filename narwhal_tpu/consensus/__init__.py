from .state import ConsensusState
from .bullshark import Bullshark
from .tusk import Tusk
from .runner import Consensus
from .dag import Dag, ValidatorDagError

__all__ = [
    "ConsensusState",
    "Bullshark",
    "Tusk",
    "Consensus",
    "Dag",
    "ValidatorDagError",
]
