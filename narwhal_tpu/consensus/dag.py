"""External-consensus Dag service over the generic compressed DAG.

Reference: /root/reference/consensus/src/dag.rs:37-516 — an actor holding
`NodeDag<Certificate>` plus a `(PublicKey, Round) -> Digest` index, serving
Insert/Contains/HasEverContained/Rounds/ReadCausal/NodeReadCausal/Remove/
NotifyRead; GC is mark (remove -> make_compressible) and sweep (triggered by
`rounds`). Genesis certificates are inserted at construction and, being
payload-empty, are compressible — DAG walks never report them
(types/src/primary.rs:633-644).

Here the actor mailbox is replaced by a single asyncio lock: our runtime is
one event loop, so serialized async methods give the identical external
behavior without the command-enum plumbing.
"""

from __future__ import annotations

import asyncio
import logging
from collections import defaultdict

from ..channels import Channel
from ..config import Committee
from ..dag import DroppedDigest, NodeDag, UnknownDigests
from ..types import Certificate, Digest, PublicKey, Round

logger = logging.getLogger("narwhal.consensus.dag")


class ValidatorDagError(Exception):
    pass


class OutOfCertificates(ValidatorDagError):
    def __init__(self, origin: PublicKey):
        super().__init__(f"no certificates for origin {origin.hex()[:16]}")


class NoCertificateForCoordinates(ValidatorDagError):
    def __init__(self, origin: PublicKey, round: Round):
        super().__init__(f"no certificate at ({origin.hex()[:16]}, {round})")


class _CertVertex:
    """Adapter giving Certificate the Affiliated shape (digest attr +
    parents()/compressible() methods)."""

    __slots__ = ("cert",)

    def __init__(self, cert: Certificate):
        self.cert = cert

    @property
    def digest(self) -> Digest:
        return self.cert.digest

    def parents(self) -> list[Digest]:
        return sorted(self.cert.header.parents)

    def compressible(self) -> bool:
        # Genesis and empty blocks never show up in causal reads.
        return not self.cert.header.payload


class Dag:
    """The external consensus: certificates in, queryable DAG out.

    `spawn()` attaches the feed from the primary's tx_new_certificates
    channel (node/src/lib.rs:198-213); all query methods are usable with or
    without the feed running.
    """

    def __init__(self, committee: Committee, rx_primary: Channel | None = None):
        self.rx_primary = rx_primary
        self._dag: NodeDag = NodeDag()
        self._vertices: dict[tuple[PublicKey, Round], Digest] = {}
        self._lock = asyncio.Lock()
        self._obligations: dict[Digest, list[asyncio.Future]] = defaultdict(list)
        self._task: asyncio.Task | None = None
        for cert in Certificate.genesis(committee):
            self._insert(cert)

    # -- feed -------------------------------------------------------------

    def spawn(self) -> asyncio.Task:
        self._task = asyncio.ensure_future(self._run())
        return self._task

    async def _run(self) -> None:
        assert self.rx_primary is not None, "spawn() needs the primary feed"
        while True:
            certificate: Certificate = await self.rx_primary.recv()
            async with self._lock:
                # Core guarantees causal completion before handing certs over.
                try:
                    self._insert(certificate)
                except UnknownDigests as e:
                    logger.warning("dag feed: missing parents %s", e.digests)

    # -- internals (lock held by callers of the async wrappers) -----------

    def _insert(self, certificate: Certificate) -> None:
        self._dag.try_insert(_CertVertex(certificate))
        self._vertices[(certificate.origin, certificate.round)] = certificate.digest
        for fut in self._obligations.pop(certificate.digest, []):
            if not fut.done():
                fut.set_result(certificate)

    # -- commands (consensus/src/dag.rs:370-516) ---------------------------

    async def insert(self, certificate: Certificate) -> None:
        async with self._lock:
            self._insert(certificate)

    async def contains(self, digest: Digest) -> bool:
        async with self._lock:
            return self._dag.contains_live(digest)

    async def has_ever_contained(self, digest: Digest) -> bool:
        async with self._lock:
            return self._dag.contains(digest)

    async def rounds(self, origin: PublicKey) -> tuple[Round, Round]:
        """(earliest, latest) live rounds for a validator; triggers the GC
        sweep first so answers match subsequent read_causal results."""
        async with self._lock:
            if self._dag.sweep():
                # Prune the coordinate index of tombstoned vertices, or it
                # grows with total history (the reference cleans it here too).
                self._vertices = {
                    k: d
                    for k, d in self._vertices.items()
                    if self._dag.contains_live(d)
                }
            alive = sorted(
                r
                for (pk, r), digest in self._vertices.items()
                if pk == origin and self._dag.contains_live(digest)
            )
            if not alive:
                raise OutOfCertificates(origin)
            return alive[0], alive[-1]

    async def read_causal(self, start: Digest) -> list[Digest]:
        """BFS of the causal history of `start` over live vertices; bypassed
        (compressible) vertices are never reported."""
        async with self._lock:
            try:
                return [v.cert.digest for v in self._dag.bft(start)]
            except (UnknownDigests, DroppedDigest) as e:
                raise ValidatorDagError(str(e)) from e

    async def node_read_causal(self, origin: PublicKey, round: Round) -> list[Digest]:
        async with self._lock:
            digest = self._vertices.get((origin, round))
            if digest is None:
                raise NoCertificateForCoordinates(origin, round)
            try:
                return [v.cert.digest for v in self._dag.bft(digest)]
            except (UnknownDigests, DroppedDigest) as e:
                raise ValidatorDagError(str(e)) from e

    async def remove(self, digests: list[Digest]) -> None:
        """Mark certificates for compression and drop them from the
        coordinate index; unknown digests error, already-dropped are fine."""
        async with self._lock:
            unknown: list[Digest] = []
            removed: list[Digest] = []
            todrop = set(digests)
            for digest in todrop:
                try:
                    self._dag.make_compressible(digest)
                    removed.append(digest)
                except UnknownDigests:
                    unknown.append(digest)
                except DroppedDigest:
                    removed.append(digest)
            self._vertices = {
                k: v for k, v in self._vertices.items() if v not in todrop
            }
            # A digest actually removed will never be inserted again: fail its
            # waiters now rather than leaving futures pending forever. Unknown
            # digests are NOT failed — they were not removed and may still be
            # inserted later by the feed.
            for digest in removed:
                for fut in self._obligations.pop(digest, []):
                    if not fut.done():
                        fut.set_exception(
                            ValidatorDagError(f"{digest!r} was removed")
                        )
            if unknown:
                raise ValidatorDagError(f"unknown digests {unknown!r}")

    async def notify_read(self, digest: Digest) -> Certificate:
        async with self._lock:
            try:
                return self._dag.get(digest).cert
            except DroppedDigest:
                raise ValidatorDagError(f"{digest!r} was dropped")
            except UnknownDigests:
                fut = asyncio.get_running_loop().create_future()
                self._obligations[digest].append(fut)
                # Prune cancelled waiters so the map cannot grow unboundedly
                # with digests that never arrive.
                fut.add_done_callback(lambda f, d=digest: self._prune_obligation(d, f))
        return await fut

    def _prune_obligation(self, digest: Digest, fut: asyncio.Future) -> None:
        waiters = self._obligations.get(digest)
        if waiters is None:
            return
        if fut in waiters:
            waiters.remove(fut)
        if not waiters:
            self._obligations.pop(digest, None)

    def size(self) -> int:
        return self._dag.size()

    async def shutdown(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
