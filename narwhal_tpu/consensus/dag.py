"""External-consensus Dag service over the generic compressed DAG.

Reference: /root/reference/consensus/src/dag.rs:37-516 — an actor holding
`NodeDag<Certificate>` plus a `(PublicKey, Round) -> Digest` index, serving
Insert/Contains/HasEverContained/Rounds/ReadCausal/NodeReadCausal/Remove/
NotifyRead; GC is mark (remove -> make_compressible) and sweep (triggered by
`rounds`). Genesis certificates are inserted at construction and, being
payload-empty, are compressible — DAG walks never report them
(types/src/primary.rs:633-644).

Here the actor mailbox is replaced by a single asyncio lock: our runtime is
one event loop, so serialized async methods give the identical external
behavior without the command-enum plumbing.
"""

from __future__ import annotations

import asyncio
import logging
from collections import defaultdict

from ..channels import Channel
from ..config import Committee
from ..dag import DroppedDigest, NodeDag, UnknownDigests
from ..types import Certificate, Digest, PublicKey, Round

logger = logging.getLogger("narwhal.consensus.dag")


class ValidatorDagError(Exception):
    pass


class OutOfCertificates(ValidatorDagError):
    def __init__(self, origin: PublicKey):
        super().__init__(f"no certificates for origin {origin.hex()[:16]}")


class NoCertificateForCoordinates(ValidatorDagError):
    def __init__(self, origin: PublicKey, round: Round):
        super().__init__(f"no certificate at ({origin.hex()[:16]}, {round})")


class _CertVertex:
    """Adapter giving Certificate the Affiliated shape (digest attr +
    parents()/compressible() methods)."""

    __slots__ = ("cert",)

    def __init__(self, cert: Certificate):
        self.cert = cert

    @property
    def digest(self) -> Digest:
        return self.cert.digest

    def parents(self) -> list[Digest]:
        return sorted(self.cert.header.parents)

    def compressible(self) -> bool:
        # Genesis and empty blocks never show up in causal reads.
        return not self.cert.header.payload


class Dag:
    """The external consensus: certificates in, queryable DAG out.

    `spawn()` attaches the feed from the primary's tx_new_certificates
    channel (node/src/lib.rs:198-213); all query methods are usable with or
    without the feed running.
    """

    def __init__(
        self,
        committee: Committee,
        rx_primary: Channel | None = None,
        backend: str = "cpu",  # cpu | tpu: device-resident causal reads
        window: int = 64,
    ):
        self.rx_primary = rx_primary
        self._dag: NodeDag = NodeDag()
        self._vertices: dict[tuple[PublicKey, Round], Digest] = {}
        # Live-vertex count per round, maintained incrementally so the
        # device backend's window-floor decisions are O(1) per operation
        # instead of rescanning every live vertex (the paths are sold as
        # flat in committee size).
        self._round_live: dict[Round, int] = defaultdict(int)
        self._min_live: Round = 0
        self._lock = asyncio.Lock()
        self._obligations: dict[Digest, list[asyncio.Future]] = defaultdict(list)
        self._task: asyncio.Task | None = None
        # Device window (backend="tpu"): the dense [W, N, N] adjacency of
        # the live rounds, so ReadCausal/NodeReadCausal run as ONE
        # reach_mask dispatch — flat in committee size — instead of a host
        # BFS (the rayon-parallel walk of /root/reference/dag/src/
        # lib.rs:231-276, re-expressed as a device scan; a 1-core host has
        # no thread parallelism to offer, the device does).
        self._win = None
        self._reach = None
        if backend == "tpu":
            from ..tpu.dag_kernels import DagWindow, reach_mask
            import jax

            self._win = DagWindow(committee, window)
            self._reach = jax.jit(reach_mask)
        for cert in Certificate.genesis(committee):
            self._insert(cert)

    # -- feed -------------------------------------------------------------

    def spawn(self) -> asyncio.Task:
        self._task = asyncio.ensure_future(self._run())
        return self._task

    async def _run(self) -> None:
        assert self.rx_primary is not None, "spawn() needs the primary feed"
        while True:
            certificate: Certificate = await self.rx_primary.recv()
            async with self._lock:
                # Core guarantees causal completion before handing certs over.
                try:
                    self._insert(certificate)
                except UnknownDigests as e:
                    logger.warning("dag feed: missing parents %s", e.digests)

    # -- internals (lock held by callers of the async wrappers) -----------

    def _vertices_changed(self, added: Round | None = None) -> None:
        """Maintain the per-round live counts after a single insert
        (`added`) or a bulk rebuild of `_vertices` (added=None)."""
        if added is not None:
            if self._round_live[added] == 0 and added < self._min_live:
                self._min_live = added
            self._round_live[added] += 1
            return
        self._round_live = defaultdict(int)
        for (_, r) in self._vertices:
            self._round_live[r] += 1
        self._min_live = min(self._round_live, default=0)

    def _floor(self) -> Round:
        """Lowest round with a live vertex, O(1) amortized."""
        while self._round_live and self._round_live.get(self._min_live, 0) == 0:
            self._round_live.pop(self._min_live, None)
            self._min_live += 1
        return self._min_live if self._round_live else 0

    def _insert(self, certificate: Certificate) -> None:
        self._dag.try_insert(_CertVertex(certificate))
        key = (certificate.origin, certificate.round)
        if key not in self._vertices:
            self._vertices_changed(added=certificate.round)
        self._vertices[key] = certificate.digest
        if self._win is not None:
            # keep_floor = lowest live round: the window may slide past
            # anything below it (those vertices are gone from _vertices),
            # preserving the invariant that every live round is in-window.
            self._win.insert(certificate, self._floor())
        for fut in self._obligations.pop(certificate.digest, []):
            if not fut.done():
                fut.set_result(certificate)

    def _device_causal(self, start: Digest) -> list[Digest] | None:
        """ReadCausal as one reach_mask dispatch over the device window;
        None -> caller falls back to the host BFS (start outside the
        window, or live history extends below the window base)."""
        import numpy as np

        win = self._win
        pos = win.digest_pos.get(start)
        if pos is None:
            return None
        if self._floor() < win.round_base:
            return None  # incomplete coverage; host walk is authoritative
        round_, idx = pos
        onehot = np.zeros((win.N,), np.uint8)
        onehot[idx] = 1
        mask = np.asarray(
            self._reach(
                win.parent,
                win.present,
                np.int32(round_ - win.round_base),
                onehot,
            )
        )
        out: list[Digest] = []
        ws, ns = np.nonzero(mask)
        # Start-first, ancestors after (descending round), the shape of the
        # host BFS; within a round the order is ascending authority index.
        for w, n in sorted(zip(ws.tolist(), ns.tolist()), key=lambda t: (-t[0], t[1])):
            cert = win.cert_at(win.round_base + int(w), int(n))
            if cert is None:
                continue
            node = self._dag._nodes.get(cert.digest)
            if node is None or not node.live:
                continue
            # The BFS reports the start plus its INCOMPRESSIBLE ancestors;
            # the raw-edge mask also hits compressed interior vertices —
            # filter them (reachability through them is identical).
            if cert.digest != start and node.compressible:
                continue
            out.append(cert.digest)
        return out

    # -- commands (consensus/src/dag.rs:370-516) ---------------------------

    async def insert(self, certificate: Certificate) -> None:
        async with self._lock:
            self._insert(certificate)

    async def contains(self, digest: Digest) -> bool:
        async with self._lock:
            return self._dag.contains_live(digest)

    async def has_ever_contained(self, digest: Digest) -> bool:
        async with self._lock:
            return self._dag.contains(digest)

    async def rounds(self, origin: PublicKey) -> tuple[Round, Round]:
        """(earliest, latest) live rounds for a validator; triggers the GC
        sweep first so answers match subsequent read_causal results."""
        async with self._lock:
            if self._dag.sweep():
                # Prune the coordinate index of tombstoned vertices, or it
                # grows with total history (the reference cleans it here too).
                self._vertices = {
                    k: d
                    for k, d in self._vertices.items()
                    if self._dag.contains_live(d)
                }
                self._vertices_changed()
            alive = sorted(
                r
                for (pk, r), digest in self._vertices.items()
                if pk == origin and self._dag.contains_live(digest)
            )
            if not alive:
                raise OutOfCertificates(origin)
            return alive[0], alive[-1]

    async def read_causal(self, start: Digest) -> list[Digest]:
        """Causal history of `start` over live vertices; bypassed
        (compressible) vertices are never reported. With the tpu backend
        the traversal is one device reach_mask dispatch when the window
        covers the live history (host BFS fallback otherwise)."""
        async with self._lock:
            return self._read_causal_locked(start)

    def _read_causal_locked(self, start: Digest) -> list[Digest]:
        if self._win is not None:
            try:
                self._dag.get(start)  # same unknown/dropped semantics as bft
            except (UnknownDigests, DroppedDigest) as e:
                raise ValidatorDagError(str(e)) from e
            dev = self._device_causal(start)
            if dev is not None:
                return dev
        try:
            return [v.cert.digest for v in self._dag.bft(start)]
        except (UnknownDigests, DroppedDigest) as e:
            raise ValidatorDagError(str(e)) from e

    async def node_read_causal(self, origin: PublicKey, round: Round) -> list[Digest]:
        async with self._lock:
            digest = self._vertices.get((origin, round))
            if digest is None:
                raise NoCertificateForCoordinates(origin, round)
            return self._read_causal_locked(digest)

    async def remove(self, digests: list[Digest]) -> None:
        """Mark certificates for compression and drop them from the
        coordinate index; unknown digests error, already-dropped are fine."""
        async with self._lock:
            unknown: list[Digest] = []
            removed: list[Digest] = []
            todrop = set(digests)
            for digest in todrop:
                try:
                    self._dag.make_compressible(digest)
                    removed.append(digest)
                except UnknownDigests:
                    unknown.append(digest)
                except DroppedDigest:
                    removed.append(digest)
            self._vertices = {
                k: v for k, v in self._vertices.items() if v not in todrop
            }
            self._vertices_changed()
            # A digest actually removed will never be inserted again: fail its
            # waiters now rather than leaving futures pending forever. Unknown
            # digests are NOT failed — they were not removed and may still be
            # inserted later by the feed.
            for digest in removed:
                for fut in self._obligations.pop(digest, []):
                    if not fut.done():
                        fut.set_exception(
                            ValidatorDagError(f"{digest!r} was removed")
                        )
            if unknown:
                raise ValidatorDagError(f"unknown digests {unknown!r}")

    async def notify_read(self, digest: Digest) -> Certificate:
        async with self._lock:
            try:
                return self._dag.get(digest).cert
            except DroppedDigest:
                raise ValidatorDagError(f"{digest!r} was dropped")
            except UnknownDigests:
                fut = asyncio.get_running_loop().create_future()
                self._obligations[digest].append(fut)
                # Prune cancelled waiters so the map cannot grow unboundedly
                # with digests that never arrive.
                fut.add_done_callback(lambda f, d=digest: self._prune_obligation(d, f))
        return await fut

    def _prune_obligation(self, digest: Digest, fut: asyncio.Future) -> None:
        waiters = self._obligations.get(digest)
        if waiters is None:
            return
        if fut in waiters:
            waiters.remove(fut)
        if not waiters:
            self._obligations.pop(digest, None)

    def size(self) -> int:
        return self._dag.size()

    async def shutdown(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
