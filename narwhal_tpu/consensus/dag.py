"""External-consensus Dag service over the generic compressed DAG.

Reference: /root/reference/consensus/src/dag.rs:37-516 — an actor holding
`NodeDag<Certificate>` plus a `(PublicKey, Round) -> Digest` index, serving
Insert/Contains/HasEverContained/Rounds/ReadCausal/NodeReadCausal/Remove/
NotifyRead; GC is mark (remove -> make_compressible) and sweep (triggered by
`rounds`). Genesis certificates are inserted at construction and, being
payload-empty, are compressible — DAG walks never report them
(types/src/primary.rs:633-644).

Here the actor mailbox is replaced by a single asyncio lock: our runtime is
one event loop, so serialized async methods give the identical external
behavior without the command-enum plumbing.

ORDERING: ReadCausal/NodeReadCausal return the causal set in CANONICAL
order — round-descending, authority-index-ascending, digest as tiebreak —
on every backend. The reference's order is whatever its BFS visits
(dag/src/bft.rs:57-127); serving one deterministic order regardless of
backend (host BFS vs device reach_mask) keeps the external API bit-stable
when a node switches serving paths mid-stream (advisor r4).

ROUTING (backend="tpu"): the device path pays a flat dispatch (RTT-bound
through a tunneled chip) while the host BFS is O(live vertices); neither
dominates everywhere, so the service MEASURES both and routes each request
through a COST MODEL (VERDICT r5 item 6, refining the r4 measured-crossover
EWMA): predicted host cost = EWMA(seconds per reported vertex) x live
vertex count (the walk's footprint tracks the window round-span x committee
frontier), predicted device cost = EWMA(seconds per fused dispatch) /
(pending coalesce-queue depth + 1) — the flat dispatch amortizes over every
reader already waiting for the next flush. The predicted loser is still
probed periodically so the decision tracks drift. Concurrent
ReadCausal/NodeReadCausal requests coalesce into ONE vmapped reach_mask
dispatch over the DEVICE-RESIDENT window (DagWindow.device_view: inserts
sync as a batched on-device scatter, slides as an on-device roll), so the
hot path uploads nothing but the [K, N] start onehots.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import defaultdict

from ..channels import Channel
from ..config import Committee
from ..dag import DroppedDigest, NodeDag, UnknownDigests
from ..types import Certificate, Digest, PublicKey, Round

logger = logging.getLogger("narwhal.consensus.dag")

def _pow2_at_least(n: int) -> int:
    """Next power of two >= n (the coalesced dispatch's padded batch size;
    shared by the dispatch padding and the per-size compile-warm set so
    the two can never drift apart)."""
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


# EWMA smoothing for the per-path service-time estimates.
_ALPHA = 0.2
# Probe the currently-losing path every this many routed requests so the
# routing tracks load/geometry drift instead of freezing on stale numbers.
_PROBE_EVERY = 32


class ValidatorDagError(Exception):
    pass


class OutOfCertificates(ValidatorDagError):
    def __init__(self, origin: PublicKey):
        super().__init__(f"no certificates for origin {origin.hex()[:16]}")


class NoCertificateForCoordinates(ValidatorDagError):
    def __init__(self, origin: PublicKey, round: Round):
        super().__init__(f"no certificate at ({origin.hex()[:16]}, {round})")


class _CertVertex:
    """Adapter giving Certificate the Affiliated shape (digest attr +
    parents()/compressible() methods)."""

    __slots__ = ("cert",)

    def __init__(self, cert: Certificate):
        self.cert = cert

    @property
    def digest(self) -> Digest:
        return self.cert.digest

    def parents(self) -> list[Digest]:
        return sorted(self.cert.header.parents)

    def compressible(self) -> bool:
        # Genesis and empty blocks never show up in causal reads.
        return not self.cert.header.payload


class Dag:
    """The external consensus: certificates in, queryable DAG out.

    `spawn()` attaches the feed from the primary's tx_new_certificates
    channel (node/src/lib.rs:198-213); all query methods are usable with or
    without the feed running.

    `policy` (backend="tpu" only):
      adaptive — route each ReadCausal to host BFS or device reach_mask by
                 measured EWMA service time (default);
      device   — always the device path when the window covers the history
                 (tests; kernel benchmarking);
      host     — never dispatch (the window still tracks inserts).
    """

    def __init__(
        self,
        committee: Committee,
        rx_primary: Channel | None = None,
        backend: str = "cpu",  # cpu | tpu: device-resident causal reads
        window: int = 64,
        policy: str = "adaptive",
        metrics=None,  # ConsensusMetrics: per-route latency + batch gauges
    ):
        self.rx_primary = rx_primary
        self._committee = committee
        self._dag: NodeDag = NodeDag()
        self._vertices: dict[tuple[PublicKey, Round], Digest] = {}
        # Live-vertex count per round, maintained incrementally so the
        # device backend's window-floor decisions are O(1) per operation
        # instead of rescanning every live vertex (the paths are sold as
        # flat in committee size).
        self._round_live: dict[Round, int] = defaultdict(int)
        self._min_live: Round = 0
        self._lock = asyncio.Lock()
        self._obligations: dict[Digest, list[asyncio.Future]] = defaultdict(list)
        self._task: asyncio.Task | None = None
        # Device window (backend="tpu"): the dense [W, N, N] adjacency of
        # the live rounds, so ReadCausal/NodeReadCausal run as ONE
        # reach_mask dispatch — flat in committee size — instead of a host
        # BFS (the rayon-parallel walk of /root/reference/dag/src/
        # lib.rs:231-276, re-expressed as a device scan; a 1-core host has
        # no thread parallelism to offer, the device does).
        self._win = None
        self._reach_many: dict[int, object] = {}
        if policy not in ("adaptive", "device", "host"):
            raise ValueError(f"unknown dag routing policy {policy!r}")
        self._policy = policy
        self._metrics = metrics
        # Cost-model routing state (policy="adaptive"): per-path amortized
        # per-request EWMAs (stats + cold-start fallbacks) plus the two
        # model coefficients — host seconds-per-reported-vertex and device
        # seconds-per-fused-dispatch.
        self._ewma = {"host": None, "dev": None}
        self._host_pv: float | None = None
        self._dev_dispatch: float | None = None
        self._last_batch = 0
        self._routed = {"host": 0, "dev": 0}
        self._route_n = 0
        # Batch sizes whose vmapped kernel has already been traced: the
        # first dispatch AT EACH padded size carries a fresh jit compile,
        # and recording that into the EWMA would bias routing against the
        # device for thousands of requests.
        self._dev_warmed: set[int] = set()
        # Coalescing queue: (start digest, future) pairs awaiting the next
        # fused device dispatch.
        self._dev_queue: list[tuple[Digest, asyncio.Future]] = []
        self._flush_task: asyncio.Task | None = None
        if backend == "tpu":
            from ..tpu.dag_kernels import DagWindow

            self._win = DagWindow(committee, window, device_resident=True)
        for cert in Certificate.genesis(committee):
            self._insert(cert)

    # -- feed -------------------------------------------------------------

    def spawn(self) -> asyncio.Task:
        self._task = asyncio.ensure_future(self._run())
        return self._task

    async def _run(self) -> None:
        assert self.rx_primary is not None, "spawn() needs the primary feed"
        while True:
            certificate: Certificate = await self.rx_primary.recv()
            async with self._lock:
                # Core guarantees causal completion before handing certs over.
                try:
                    self._insert(certificate)
                except UnknownDigests as e:
                    logger.warning("dag feed: missing parents %s", e.digests)

    # -- internals (lock held by callers of the async wrappers) -----------

    def _vertices_changed(self, added: Round | None = None) -> None:
        """Maintain the per-round live counts after a single insert
        (`added`) or a bulk rebuild of `_vertices` (added=None)."""
        if added is not None:
            if self._round_live[added] == 0 and added < self._min_live:
                self._min_live = added
            self._round_live[added] += 1
            return
        self._round_live = defaultdict(int)
        for (_, r) in self._vertices:
            self._round_live[r] += 1
        self._min_live = min(self._round_live, default=0)

    def _floor(self) -> Round:
        """Lowest round with a live vertex, O(1) amortized."""
        while self._round_live and self._round_live.get(self._min_live, 0) == 0:
            self._round_live.pop(self._min_live, None)
            self._min_live += 1
        return self._min_live if self._round_live else 0

    def _insert(self, certificate: Certificate) -> None:
        self._dag.try_insert(_CertVertex(certificate))
        key = (certificate.origin, certificate.round)
        if key not in self._vertices:
            self._vertices_changed(added=certificate.round)
        self._vertices[key] = certificate.digest
        if self._win is not None:
            # keep_floor = lowest live round: the window may slide past
            # anything below it (those vertices are gone from _vertices),
            # preserving the invariant that every live round is in-window.
            self._win.insert(certificate, self._floor())
        for fut in self._obligations.pop(certificate.digest, []):
            if not fut.done():
                fut.set_result(certificate)

    # -- ordering ----------------------------------------------------------

    def _canonical(self, certs: list[Certificate]) -> list[Digest]:
        """The service's one deterministic output order: round-descending,
        authority-index-ascending, digest tiebreak. The start vertex is the
        strict round-maximum of its own causal history, so it always sorts
        first (the `d[0] == start` shape callers rely on)."""
        index_of = self._committee.index_of
        return [
            c.digest
            for c in sorted(
                certs, key=lambda c: (-c.round, index_of(c.origin), c.digest)
            )
        ]

    # -- device path -------------------------------------------------------

    def _dev_eligible(self, start: Digest):
        """(round, idx) when the window can serve `start`, else None."""
        if self._win is None:
            return None
        # DagWindow is a Dag-private composite: only Dag._run/Consensus.run
        # mutate it, always between awaits (no yield mid-update), and these
        # reads tolerate a one-round-stale window (the host walk stays
        # authoritative when coverage is incomplete).
        pos = self._win.digest_pos.get(start)  # lint: allow(multi-task-mutation)
        if pos is None:
            return None
        if self._floor() < self._win.round_base:  # lint: allow(multi-task-mutation)
            return None  # incomplete coverage; host walk is authoritative
        return pos

    def _reach_k(self, k: int):
        """The K-batched reach kernel (vmapped over starts), cached per
        padded batch size so coalesced dispatch reuses a handful of
        compiled programs."""
        fn = self._reach_many.get(k)
        if fn is None:
            import jax

            from ..tpu.dag_kernels import reach_mask

            fn = jax.jit(jax.vmap(reach_mask, in_axes=(None, None, 0, 0)))
            self._reach_many[k] = fn
        return fn

    def _device_causal_many(
        self, starts: list[tuple[Digest, tuple[Round, int]]]
    ) -> list[list[Digest]]:
        """All of `starts` in ONE fused reach_mask dispatch over the
        device-resident window (the coalesced path: K concurrent readers pay
        one device round trip, and the [W, N, N] adjacency never leaves the
        device — only the [K, N] onehots upload)."""
        import numpy as np

        win = self._win
        parent_dev, present_dev = win.device_view()
        kpad = _pow2_at_least(len(starts))
        offs = np.zeros((kpad,), np.int32)
        onehots = np.zeros((kpad, win.N), np.uint8)
        for t, (_, (round_, idx)) in enumerate(starts):
            offs[t] = round_ - win.round_base
            onehots[t, idx] = 1
        masks = np.asarray(self._reach_k(kpad)(parent_dev, present_dev, offs, onehots))
        out: list[list[Digest]] = []
        for t, (start, _) in enumerate(starts):
            certs: list[Certificate] = []
            ws, ns = np.nonzero(masks[t])
            for w, n in zip(ws.tolist(), ns.tolist()):
                cert = win.cert_at(win.round_base + int(w), int(n))
                if cert is None:
                    continue
                # NodeDag is Dag-owned; Dag._run is its only mutator and
                # never yields mid-update, so this read is atomic-consistent.
                node = self._dag._nodes.get(cert.digest)  # lint: allow(multi-task-mutation)
                if node is None or not node.live:
                    continue
                # The walk reports the start plus its INCOMPRESSIBLE
                # ancestors; the raw-edge mask also hits compressed interior
                # vertices — filter them (reachability through them is
                # identical).
                if cert.digest != start and node.compressible:
                    continue
                certs.append(cert)
            out.append(self._canonical(certs))
        return out

    # -- routing -----------------------------------------------------------

    def _record(self, path: str, dt: float) -> None:
        prev = self._ewma[path]
        self._ewma[path] = dt if prev is None else (1 - _ALPHA) * prev + _ALPHA * dt
        self._routed[path] += 1
        if self._metrics is not None:
            route = "host" if path == "host" else "device"
            self._metrics.dag_read_latency.labels(route).observe(dt)
            self._metrics.dag_read_route_ewma_ms.labels(route).set(
                self._ewma[path] * 1000
            )

    def _predict(self, path: str) -> float:
        """Predicted per-request service time (seconds) for routing one more
        request down `path` right now — the cost model of the module
        docstring. Falls back to the plain per-request EWMA until the model
        coefficient for a path has been measured."""
        if path == "host":
            if self._host_pv is not None:
                return self._host_pv * max(1, len(self._vertices))
            return self._ewma["host"]
        if self._dev_dispatch is not None:
            # One more rider on the next fused dispatch: the flat dispatch
            # cost splits across everyone already queued plus this request.
            return self._dev_dispatch / (len(self._dev_queue) + 1)
        return self._ewma["dev"]

    def _pick_path(self) -> str:
        """host | dev (policy='adaptive'): route to the cost model's
        predicted winner. Unmeasured paths get tried once; the predicted
        loser is re-probed every _PROBE_EVERY requests so the decision
        tracks load and geometry drift."""
        if self._policy == "device":
            return "dev"
        if self._policy == "host":
            return "host"
        if self._ewma["host"] is None:
            return "host"
        if self._ewma["dev"] is None:
            return "dev"
        self._route_n += 1
        fast, slow = (
            ("host", "dev")
            if self._predict("host") <= self._predict("dev")
            else ("dev", "host")
        )
        if self._route_n % _PROBE_EVERY == 0:
            return slow
        return fast

    def routing_stats(self) -> dict:
        """The live routing policy, for benchmarks/metrics: per-path call
        counts, EWMA service times (ms) and the cost-model coefficients."""
        return {
            "policy": self._policy,
            "host_calls": self._routed["host"],
            "dev_calls": self._routed["dev"],
            "ewma_host_ms": None
            if self._ewma["host"] is None
            else round(self._ewma["host"] * 1000, 3),
            "ewma_dev_ms": None
            if self._ewma["dev"] is None
            else round(self._ewma["dev"] * 1000, 3),
            "host_us_per_vertex": None
            if self._host_pv is None
            else round(self._host_pv * 1e6, 3),
            "dev_dispatch_ms": None
            if self._dev_dispatch is None
            else round(self._dev_dispatch * 1000, 3),
            "last_coalesced_batch": self._last_batch,
            "live_vertices": len(self._vertices),
        }

    # -- commands (consensus/src/dag.rs:370-516) ---------------------------

    async def insert(self, certificate: Certificate) -> None:
        async with self._lock:
            self._insert(certificate)

    async def contains(self, digest: Digest) -> bool:
        async with self._lock:
            return self._dag.contains_live(digest)

    async def has_ever_contained(self, digest: Digest) -> bool:
        async with self._lock:
            return self._dag.contains(digest)

    async def rounds(self, origin: PublicKey) -> tuple[Round, Round]:
        """(earliest, latest) live rounds for a validator; triggers the GC
        sweep first so answers match subsequent read_causal results."""
        async with self._lock:
            if self._dag.sweep():
                # Prune the coordinate index of tombstoned vertices, or it
                # grows with total history (the reference cleans it here too).
                self._vertices = {
                    k: d
                    for k, d in self._vertices.items()
                    if self._dag.contains_live(d)
                }
                self._vertices_changed()
            alive = sorted(
                r
                for (pk, r), digest in self._vertices.items()
                if pk == origin and self._dag.contains_live(digest)
            )
            if not alive:
                raise OutOfCertificates(origin)
            return alive[0], alive[-1]

    async def read_causal(self, start: Digest) -> list[Digest]:
        """Causal history of `start` over live vertices, in canonical
        order; bypassed (compressible) vertices are never reported. With
        the tpu backend, requests routed to the device coalesce into one
        fused reach_mask dispatch per event-loop tick."""
        async with self._lock:
            out = self._route_locked(start)
        return await out if isinstance(out, asyncio.Future) else out

    def _route_locked(self, start: Digest):
        """Lock held: validate `start`, then either serve the host walk
        now (returns the list) or enqueue a device-coalesced request
        (returns the future to await AFTER releasing the lock). One lock
        scope covers lookup + routing so a concurrent remove() cannot
        interleave."""
        try:
            self._dag.get(start)  # unknown/dropped semantics as bft
        except (UnknownDigests, DroppedDigest) as e:
            raise ValidatorDagError(str(e)) from e
        if self._dev_eligible(start) is not None and self._pick_path() == "dev":
            fut = asyncio.get_running_loop().create_future()
            self._dev_queue.append((start, fut))
            if self._flush_task is None or self._flush_task.done():
                self._flush_task = asyncio.ensure_future(self._flush_dev())
            return fut
        return self._host_causal(start)

    def _host_causal(self, start: Digest) -> list[Digest]:
        """The host BFS, timed into the routing EWMA and the cost model's
        per-vertex coefficient (lock held)."""
        # CPU cost for the host/device routing model, not protocol time:
        # wall time is the semantically correct clock even under simnet.
        t0 = time.perf_counter()  # lint: allow(no-wall-clock-in-actors)
        try:
            certs = [v.cert for v in self._dag.bft(start)]
        except (UnknownDigests, DroppedDigest) as e:
            raise ValidatorDagError(str(e)) from e
        out = self._canonical(certs)
        dt = time.perf_counter() - t0  # lint: allow(no-wall-clock-in-actors)
        self._record("host", dt)
        pv = dt / max(1, len(certs))
        self._host_pv = (
            pv if self._host_pv is None else (1 - _ALPHA) * self._host_pv + _ALPHA * pv
        )
        return out

    async def _flush_dev(self) -> None:
        """Serve every queued device request in one fused dispatch. Runs a
        tick after the first enqueue so concurrent readers coalesce."""
        await asyncio.sleep(0)
        async with self._lock:
            batch, self._dev_queue = self._dev_queue, []
            if not batch:
                return
            eligible: list[tuple[Digest, tuple[Round, int]]] = []
            futs: list[asyncio.Future] = []
            for start, fut in batch:
                if fut.done():  # caller gone (cancelled/timeout)
                    continue
                # Re-validate between enqueue and flush: a remove() in the
                # gap may have tombstoned the start, and the device mask
                # would silently skip the non-live vertex (violating the
                # d[0] == start contract) where the host path raises.
                try:
                    self._dag.get(start)
                except (UnknownDigests, DroppedDigest) as e:
                    fut.set_exception(ValidatorDagError(str(e)))
                    continue
                pos = self._dev_eligible(start)
                if pos is None:
                    # Window slid (or coverage broke) between enqueue and
                    # flush: the host walk is authoritative.
                    try:
                        fut.set_result(self._host_causal(start))
                    except ValidatorDagError as e:
                        fut.set_exception(e)
                    continue
                eligible.append((start, pos))
                futs.append(fut)
            if not eligible:
                return
            kpad = _pow2_at_least(len(eligible))
            # Device-dispatch CPU cost for the routing model (see above).
            t0 = time.perf_counter()  # lint: allow(no-wall-clock-in-actors)
            try:
                results = self._device_causal_many(eligible)
            except Exception:  # device dispatch failure: host fallback
                logger.exception("fused device read_causal failed; host fallback")
                for (start, _), fut in zip(eligible, futs):
                    if not fut.done():
                        try:
                            fut.set_result(self._host_causal(start))
                        except ValidatorDagError as err:
                            fut.set_exception(err)
                return
            dt = time.perf_counter() - t0  # lint: allow(no-wall-clock-in-actors)
            self._last_batch = len(eligible)
            if self._metrics is not None:
                self._metrics.dag_read_coalesced_batch.set(len(eligible))
            if kpad in self._dev_warmed:
                # Per-request amortized cost is what competes with one host
                # BFS in the routing decision; the full dispatch wall time
                # feeds the cost model's amortization term.
                self._dev_dispatch = (
                    dt
                    if self._dev_dispatch is None
                    else (1 - _ALPHA) * self._dev_dispatch + _ALPHA * dt
                )
                for _ in eligible:
                    self._record("dev", dt / len(eligible))
            else:
                # First dispatch AT THIS padded batch size carries the jit
                # trace+compile; recording it would bias routing against
                # the device for the whole run. It still served requests,
                # so it counts in the routing stats.
                self._dev_warmed.add(kpad)
                self._routed["dev"] += len(eligible)
            for res, fut in zip(results, futs):
                if not fut.done():
                    fut.set_result(res)

    async def node_read_causal(self, origin: PublicKey, round: Round) -> list[Digest]:
        async with self._lock:
            digest = self._vertices.get((origin, round))
            if digest is None:
                raise NoCertificateForCoordinates(origin, round)
            # Same lock scope as the lookup: a concurrent remove() between
            # lookup and walk would otherwise turn just-resolved
            # coordinates into a spurious DroppedDigest error.
            out = self._route_locked(digest)
        return await out if isinstance(out, asyncio.Future) else out

    async def remove(self, digests: list[Digest]) -> None:
        """Mark certificates for compression and drop them from the
        coordinate index; unknown digests error, already-dropped are fine."""
        async with self._lock:
            unknown: list[Digest] = []
            removed: list[Digest] = []
            todrop = set(digests)
            for digest in todrop:
                try:
                    self._dag.make_compressible(digest)
                    removed.append(digest)
                except UnknownDigests:
                    unknown.append(digest)
                except DroppedDigest:
                    removed.append(digest)
            self._vertices = {
                k: v for k, v in self._vertices.items() if v not in todrop
            }
            self._vertices_changed()
            # A digest actually removed will never be inserted again: fail its
            # waiters now rather than leaving futures pending forever. Unknown
            # digests are NOT failed — they were not removed and may still be
            # inserted later by the feed.
            for digest in removed:
                for fut in self._obligations.pop(digest, []):
                    if not fut.done():
                        fut.set_exception(
                            ValidatorDagError(f"{digest!r} was removed")
                        )
            if unknown:
                raise ValidatorDagError(f"unknown digests {unknown!r}")

    async def notify_read(self, digest: Digest) -> Certificate:
        async with self._lock:
            try:
                return self._dag.get(digest).cert
            except DroppedDigest:
                raise ValidatorDagError(f"{digest!r} was dropped")
            except UnknownDigests:
                fut = asyncio.get_running_loop().create_future()
                self._obligations[digest].append(fut)
                # Prune cancelled waiters so the map cannot grow unboundedly
                # with digests that never arrive.
                fut.add_done_callback(lambda f, d=digest: self._prune_obligation(d, f))
        return await fut

    def _prune_obligation(self, digest: Digest, fut: asyncio.Future) -> None:
        waiters = self._obligations.get(digest)
        if waiters is None:
            return
        if fut in waiters:
            waiters.remove(fut)
        if not waiters:
            self._obligations.pop(digest, None)

    def size(self) -> int:
        return self._dag.size()

    async def shutdown(self) -> None:
        for task in (self._task, self._flush_task):
            if task is not None:
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:  # lint: allow(no-silent-except)
                    pass  # the cancellation we just requested arriving back
        # Cancelling the flush task can strand queued device requests:
        # fail their futures so in-flight read_causal callers error out
        # instead of awaiting forever.
        pending, self._dev_queue = self._dev_queue, []
        for _, fut in pending:
            if not fut.done():
                fut.set_exception(ValidatorDagError("dag service shut down"))
