"""Consensus metrics (/root/reference/consensus/src/metrics.rs:13-49)."""

from __future__ import annotations

from ..metrics import Registry
from ..pacing import StageTimer


class ConsensusMetrics:
    def __init__(self, registry: Registry, tracer=None):
        self.tracer = tracer
        # -- stage tracing --------------------------------------------------
        self.stage_latency = registry.histogram(
            "consensus_stage_latency_seconds",
            "Per-stage pipeline latency in consensus (stage=commit: "
            "certificate accepted by the ordering engine -> sequenced in a "
            "committed leader's causal history)",
            labels=("stage",),
        )
        # Bounded: certificates that never commit (GC'd past the window)
        # age out of the pending map instead of leaking.
        self.commit_timer = StageTimer(self.stage_latency, "commit", tracer=tracer)
        self.last_committed_round = registry.gauge(
            "consensus_last_committed_round", "The last committed leader round"
        )
        self.committed_certificates = registry.counter(
            "consensus_committed_certificates", "Certificates sequenced by consensus"
        )
        self.consensus_dag_size = registry.gauge(
            "consensus_dag_size", "Certificates resident in the consensus DAG"
        )
        self.recovered_consensus_state = registry.counter(
            "consensus_recovered_consensus_state",
            "Times the consensus state was rebuilt from the store at startup",
        )
        # External Dag service read path (consensus/dag.py): per-route
        # service latency, the router's live per-request EWMA, and the size
        # of the most recent fused device dispatch (how many concurrent
        # readers shared one reach_mask round trip).
        self.dag_read_latency = registry.histogram(
            "consensus_dag_read_causal_latency_seconds",
            "read_causal service time by route (host BFS vs device reach_mask)",
            labels=("route",),
        )
        self.dag_read_route_ewma_ms = registry.gauge(
            "consensus_dag_read_route_ewma_ms",
            "EWMA per-request read_causal service time by route, milliseconds",
            labels=("route",),
        )
        self.dag_read_coalesced_batch = registry.gauge(
            "consensus_dag_read_coalesced_batch_size",
            "Requests served by the most recent fused device read_causal dispatch",
        )
        # Accepted-certificate tap feeding the executor's speculative
        # payload prefetcher (runner.py): the tap is strictly non-blocking,
        # so drops here mean the prefetcher is falling behind acceptance —
        # commits then pay their payload RTT at stage time again.
        self.accepted_tap_dropped = registry.counter(
            "consensus_accepted_tap_dropped",
            "Accepted certificates dropped from the full prefetch tap "
            "channel (speculation hint lost, never blocks ordering)",
        )
