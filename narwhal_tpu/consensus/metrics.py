"""Consensus metrics (/root/reference/consensus/src/metrics.rs:13-49)."""

from __future__ import annotations

from ..metrics import Registry


class ConsensusMetrics:
    def __init__(self, registry: Registry):
        self.last_committed_round = registry.gauge(
            "consensus_last_committed_round", "The last committed leader round"
        )
        self.committed_certificates = registry.counter(
            "consensus_committed_certificates", "Certificates sequenced by consensus"
        )
        self.consensus_dag_size = registry.gauge(
            "consensus_dag_size", "Certificates resident in the consensus DAG"
        )
        self.recovered_consensus_state = registry.counter(
            "consensus_recovered_consensus_state",
            "Times the consensus state was rebuilt from the store at startup",
        )
