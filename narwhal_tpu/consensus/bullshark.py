"""Bullshark ordering engine — the default, lower-latency commit rule.

Reference: /root/reference/consensus/src/bullshark.rs:18-166. On a round-r
certificate: the candidate leader sits at even round r-1; it commits once the
round-r certificates referencing it carry >= f+1 stake (validity threshold);
committing a leader first commits every earlier linked leader, each flattening
its causal sub-DAG.
"""

from __future__ import annotations

from ..config import Committee
from ..stores import ConsensusStore
from ..types import Certificate, ConsensusOutput, Round, SequenceNumber
from . import ordering
from .state import ConsensusState


class Bullshark:
    def __init__(
        self,
        committee: Committee,
        store: ConsensusStore,
        gc_depth: Round,
        leader_fn=None,
    ):
        self.committee = committee
        self.store = store
        self.gc_depth = gc_depth
        # Tests may pin the leader (the reference's cfg(test) fixed-leader
        # shim, bullshark.rs:150-156); default is stake-weighted by round.
        self._leader_fn = leader_fn or ordering.dag_leader

    def process_certificate(
        self,
        state: ConsensusState,
        consensus_index: SequenceNumber,
        certificate: Certificate,
    ) -> list[ConsensusOutput]:
        round = certificate.round
        state.add(certificate)

        r = round - 1
        if r % 2 != 0 or r < 2:
            return []
        leader_round = r
        if leader_round <= state.last_committed_round:
            return []
        entry = self._leader_fn(self.committee, leader_round, state.dag)
        if entry is None:
            return []
        leader_digest, leader = entry

        support = sum(
            self.committee.stake(cert.origin)
            for _, cert in state.dag.get(round, {}).values()
            if leader_digest in cert.header.parents
        )
        if support < self.committee.validity_threshold():
            return []

        sequence: list[ConsensusOutput] = []
        for chain_leader in reversed(
            ordering.order_leaders(self.committee, leader, state, self._leader_fn)
        ):
            for cert in ordering.order_dag(self.gc_depth, chain_leader, state):
                state.update(cert, self.gc_depth)
                sequence.append(
                    ConsensusOutput(certificate=cert, consensus_index=consensus_index)
                )
                consensus_index += 1
                self.store.write_consensus_state(
                    state.last_committed, consensus_index - 1, cert.digest
                )
        return sequence

    def update_committee(self, new_committee: Committee) -> None:
        self.committee = new_committee
