"""In-memory consensus DAG state with crash recovery and GC.

Reference: /root/reference/consensus/src/consensus.rs:24-157 (ConsensusState,
new_from_store, construct_dag_from_cert_store, update). The DAG is
round -> {authority -> (digest, certificate)}; `last_committed` per authority
both deduplicates commits and drives GC.
"""

from __future__ import annotations

from ..stores import CertificateStore
from ..types import Certificate, Digest, PublicKey, Round

DagMap = dict[Round, dict[PublicKey, tuple[Digest, Certificate]]]


class ConsensusState:
    def __init__(self, genesis: list[Certificate], metrics=None):
        gen = {c.origin: (c.digest, c) for c in genesis}
        self.last_committed_round: Round = 0
        self.last_committed: dict[PublicKey, Round] = {
            pk: cert.round for pk, (_, cert) in gen.items()
        }
        self.dag: DagMap = {0: gen}
        self.metrics = metrics

    @staticmethod
    def new_from_store(
        genesis: list[Certificate],
        recover_last_committed: dict[PublicKey, Round],
        cert_store: CertificateStore,
        gc_depth: Round,
        metrics=None,
    ) -> "ConsensusState":
        """Rebuild the DAG window from the certificate store after a crash
        (consensus.rs:63-129)."""
        state = ConsensusState(genesis, metrics)
        if not recover_last_committed:
            return state
        last_committed_round = max(recover_last_committed.values())
        if last_committed_round == 0:
            return state
        state.last_committed_round = last_committed_round
        state.last_committed = dict(recover_last_committed)
        min_round = max(0, last_committed_round - gc_depth)
        dag: DagMap = {}
        for cert in cert_store.after_round(min_round + 1):
            # Mirror the shape update() leaves behind in a live state: each
            # authority keeps its certificate at exactly its last committed
            # round, nothing older (consensus.rs:145-156). Without this, a
            # recovered window would re-expose already-committed certificates
            # to the ordering walk.
            if cert.round < recover_last_committed.get(cert.origin, 0):
                continue
            dag.setdefault(cert.round, {})[cert.origin] = (cert.digest, cert)
        state.dag = dag
        if metrics is not None:
            metrics.recovered_consensus_state.inc()
        return state

    def add(self, certificate: Certificate) -> None:
        self.dag.setdefault(certificate.round, {})[certificate.origin] = (
            certificate.digest,
            certificate,
        )

    def update(self, certificate: Certificate, gc_depth: Round) -> None:
        """Advance last_committed and GC the window (consensus.rs:131-157)."""
        origin = certificate.origin
        self.last_committed[origin] = max(
            self.last_committed.get(origin, 0), certificate.round
        )
        self.last_committed_round = max(self.last_committed.values())

        # Purge rounds beyond the GC window.
        for r in [r for r in self.dag if r + gc_depth < self.last_committed_round]:
            del self.dag[r]
        # Purge each authority's certificates before its own last commit.
        for name, committed_round in self.last_committed.items():
            for r in list(self.dag):
                if r < committed_round:
                    self.dag[r].pop(name, None)
                    if not self.dag[r]:
                        del self.dag[r]

    def dag_size(self) -> int:
        return sum(len(v) for v in self.dag.values())
