"""Commit ordering: leader chains and causal-history flattening.

Reference: /root/reference/consensus/src/utils.rs:11-101 (order_leaders,
linked, order_dag) — the per-commit DAG-walk hot path named by the north star.
This module is the host (exact-semantics) implementation; the vectorized
adjacency-tensor version lives in narwhal_tpu/tpu/dag_kernels.py and is
equivalence-tested against this one on random lossy DAGs.

Determinism note: the reference iterates Rust HashSets during the DFS, so its
within-round tie order is platform-defined. We iterate parents in sorted
digest order, making the full sequence a pure function of the DAG — which is
what lets the TPU kernel reproduce it bit-for-bit.
"""

from __future__ import annotations

from typing import Callable

from ..config import Committee
from ..types import Certificate, Digest, Round
from .state import ConsensusState, DagMap

LeaderFn = Callable[[Committee, Round, DagMap], tuple[Digest, Certificate] | None]


def order_leaders(
    committee: Committee,
    leader: Certificate,
    state: ConsensusState,
    get_leader: LeaderFn,
) -> list[Certificate]:
    """Walk even rounds back to the last committed round, keeping each prior
    leader that is linked to the one after it (utils.rs:11-38). Returned
    newest-first, like the reference (callers commit in reverse)."""
    to_commit = [leader]
    current = leader
    for r in range(leader.round - 2, state.last_committed_round + 1, -2):
        entry = get_leader(committee, r, state.dag)
        if entry is None:
            continue
        _, prev_leader = entry
        if linked(current, prev_leader, state.dag):
            to_commit.append(prev_leader)
            current = prev_leader
    return to_commit


def linked(leader: Certificate, prev_leader: Certificate, dag: DagMap) -> bool:
    """Is there a DAG path from leader down to prev_leader (utils.rs:40-53)?
    Round-by-round frontier propagation — on the TPU this is the bitwise
    matmul chain over parent adjacency matrices."""
    frontier = [leader]
    for r in range(leader.round - 1, prev_leader.round - 1, -1):
        certs = dag.get(r, {})
        parent_digests = set()
        for cert in frontier:
            parent_digests |= cert.header.parents
        frontier = [
            cert for digest, cert in certs.values() if digest in parent_digests
        ]
    return any(c.digest == prev_leader.digest for c in frontier)


def order_dag(
    gc_depth: Round, leader: Certificate, state: ConsensusState
) -> list[Certificate]:
    """Flatten the leader's uncommitted causal history, oldest round first
    (utils.rs:55-101): DFS collecting certificates not yet committed for
    their authority, drop anything past the GC bound, stable-sort by round."""
    ordered: list[Certificate] = []
    seen: set[Digest] = set()
    buffer = [leader]
    while buffer:
        cert = buffer.pop()
        ordered.append(cert)
        round_certs = state.dag.get(cert.round - 1, {})
        by_digest = {digest: c for digest, c in round_certs.values()}
        for parent_digest in sorted(cert.header.parents):
            parent = by_digest.get(parent_digest)
            if parent is None:
                continue  # already ordered or garbage collected
            if parent_digest in seen:
                continue
            # The reference checks equality here (utils.rs:86-89), relying on
            # update() having purged anything older from the DAG; we use >= so
            # the guard also holds on a freshly-recovered DAG window, where
            # already-committed certificates may still be present.
            if state.last_committed.get(parent.origin, 0) >= parent.round:
                continue
            seen.add(parent_digest)
            buffer.append(parent)

    ordered = [
        c for c in ordered if c.round + gc_depth >= state.last_committed_round
    ]
    # Canonical commit order: (round, origin). The reference only sorts by
    # round and leaves within-round order to Rust HashSet iteration (i.e.
    # nondeterministic); fixing the tie-break on the origin key makes the
    # sequence a pure function of the DAG and lets the TPU adjacency-matrix
    # kernel (tpu/dag_kernels.py) reproduce it exactly — origin order equals
    # committee dense-index order because committees sort by public key.
    ordered.sort(key=lambda c: (c.round, c.origin))
    return ordered


def dag_leader(
    committee: Committee, round: Round, dag: DagMap
) -> tuple[Digest, Certificate] | None:
    """The elected leader's certificate at `round`, if present
    (bullshark.rs:141-166). Stake-weighted choice seeded by the round."""
    name = committee.leader(round)
    entry = dag.get(round, {}).get(name)
    return entry
