"""ctypes loader for the native (C++) runtime components.

Builds native/storage_engine.cpp into a shared library on first use (cached
by source mtime) and exposes a thin wrapper. Loading is best-effort: when the
toolchain or library is unavailable the callers fall back to the pure-Python
implementations, so the framework never hard-depends on a compiler at
runtime. Disable explicitly with NARWHAL_NATIVE=0.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess

logger = logging.getLogger("narwhal.native")

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_ROOT, "native", "storage_engine.cpp")
_LIB = os.path.join(_ROOT, "native", "libnarwhal_storage.so")
_SCALAR_SRC = os.path.join(_ROOT, "native", "scalar_ops.cpp")
_SCALAR_LIB = os.path.join(_ROOT, "native", "libnarwhal_scalar.so")

_lib: ctypes.CDLL | None = None
_tried = False
_scalar: ctypes.CDLL | None = None
_scalar_tried = False


def _build_lib(src: str, lib: str, extra: list[str]) -> bool:
    try:
        if os.path.exists(lib) and os.path.getmtime(lib) >= os.path.getmtime(src):
            return True
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", lib, src, *extra],
            check=True,
            capture_output=True,
        )
        return True
    except (OSError, subprocess.CalledProcessError) as e:
        logger.warning("native build of %s failed: %s", os.path.basename(src), e)
        return False


def _build() -> bool:
    return _build_lib(_SRC, _LIB, ["-lz"])


def load() -> ctypes.CDLL | None:
    """The shared library, built on demand; None if unavailable."""
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("NARWHAL_NATIVE", "1") == "0":
        return None
    if not os.path.exists(_SRC) or not _build():
        return None
    try:
        lib = ctypes.CDLL(_LIB)
    except OSError as e:
        logger.warning("native storage engine load failed: %s", e)
        return None
    lib.nse_open.restype = ctypes.c_void_p
    lib.nse_open.argtypes = [ctypes.c_char_p]
    lib.nse_write_batch.restype = ctypes.c_int
    lib.nse_write_batch.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
    lib.nse_get.restype = ctypes.c_int
    lib.nse_get.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_uint32,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
        ctypes.POINTER(ctypes.c_uint32),
    ]
    lib.nse_contains.restype = ctypes.c_int
    lib.nse_contains.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_uint32,
    ]
    lib.nse_len.restype = ctypes.c_uint64
    lib.nse_len.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.nse_dump.restype = None
    lib.nse_dump.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.nse_compact.restype = None
    lib.nse_compact.argtypes = [ctypes.c_void_p]
    lib.nse_close_log.restype = None
    lib.nse_close_log.argtypes = [ctypes.c_void_p]
    lib.nse_close.restype = None
    lib.nse_close.argtypes = [ctypes.c_void_p]
    _lib = lib
    return _lib


def load_scalar() -> ctypes.CDLL | None:
    """The ed25519 host scalar pipeline (native/scalar_ops.cpp), built on
    demand; None when the toolchain is unavailable or NARWHAL_NATIVE=0.
    ctypes releases the GIL for the call duration, so batched hashing and
    mod-L arithmetic genuinely overlap device compute in the verify
    pipeline."""
    global _scalar, _scalar_tried
    if _scalar_tried:
        return _scalar
    _scalar_tried = True
    if os.environ.get("NARWHAL_NATIVE", "1") == "0":
        return None
    if not os.path.exists(_SCALAR_SRC) or not _build_lib(_SCALAR_SRC, _SCALAR_LIB, []):
        return None
    try:
        lib = ctypes.CDLL(_SCALAR_LIB)
    except OSError as e:
        logger.warning("native scalar pipeline load failed: %s", e)
        return None
    lib.ed25519_precheck_k.restype = ctypes.c_int
    lib.ed25519_precheck_k.argtypes = [
        ctypes.c_int64,
        ctypes.c_char_p,  # pk rows
        ctypes.c_char_p,  # sig rows
        ctypes.c_char_p,  # msg buffer
        ctypes.c_void_p,  # int64 offsets
        ctypes.c_void_p,  # out k rows
        ctypes.c_void_p,  # out ok bytes
    ]
    lib.scalar_fold.restype = None
    lib.scalar_fold.argtypes = [
        ctypes.c_int64,
        ctypes.c_void_p,  # k rows
        ctypes.c_void_p,  # s rows
        ctypes.c_char_p,  # z rows
        ctypes.c_void_p,  # out ak rows
        ctypes.c_void_p,  # out sum
    ]
    lib.scalar_mulmod.restype = None
    lib.scalar_mulmod.argtypes = [
        ctypes.c_int64,
        ctypes.c_void_p,  # a rows (32B)
        ctypes.c_void_p,  # b rows (32B)
        ctypes.c_void_p,  # out rows (32B)
    ]
    _scalar = lib
    return _scalar


class NativeEngine:
    """Handle on one C++ engine instance (tables + WAL)."""

    def __init__(self, path: str | None):
        lib = load()
        if lib is None:
            raise RuntimeError("native storage engine unavailable")
        self._lib = lib
        self._h = lib.nse_open((path or "").encode())
        if not self._h:
            raise RuntimeError(f"nse_open failed for {path!r}")

    def write_batch(self, body: bytes) -> None:
        if self._lib.nse_write_batch(self._h, body, len(body)) != 0:
            raise RuntimeError("malformed write batch")

    def get(self, cf: bytes, key: bytes) -> bytes | None:
        val = ctypes.POINTER(ctypes.c_ubyte)()
        vlen = ctypes.c_uint32()
        hit = self._lib.nse_get(
            self._h, cf, key, len(key), ctypes.byref(val), ctypes.byref(vlen)
        )
        if not hit:
            return None
        return ctypes.string_at(val, vlen.value)

    def contains(self, cf: bytes, key: bytes) -> bool:
        return bool(self._lib.nse_contains(self._h, cf, key, len(key)))

    def len(self, cf: bytes) -> int:
        return int(self._lib.nse_len(self._h, cf))

    def items(self, cf: bytes) -> list[tuple[bytes, bytes]]:
        buf = ctypes.POINTER(ctypes.c_ubyte)()
        blen = ctypes.c_uint64()
        self._lib.nse_dump(self._h, cf, ctypes.byref(buf), ctypes.byref(blen))
        raw = ctypes.string_at(buf, blen.value) if blen.value else b""
        out = []
        pos = 0
        while pos < len(raw):
            klen = int.from_bytes(raw[pos : pos + 4], "little")
            pos += 4
            key = raw[pos : pos + klen]
            pos += klen
            vlen = int.from_bytes(raw[pos : pos + 4], "little")
            pos += 4
            out.append((key, raw[pos : pos + vlen]))
            pos += vlen
        return out

    def compact(self) -> None:
        self._lib.nse_compact(self._h)

    def close(self) -> None:
        """Stop appends; tables stay readable (Python-engine close parity —
        late reads during shutdown must not hit a freed handle)."""
        if self._h:
            self._lib.nse_close_log(self._h)

    def __del__(self) -> None:
        h, self._h = getattr(self, "_h", None), None
        if h:
            try:
                self._lib.nse_close(h)
            except Exception:
                pass
