"""Parameters, Committee and WorkerCache.

Reference: /root/reference/config/src/lib.rs — Parameters :107-138 (defaults
:259-275), Committee + stake math :488-685, WorkerCache :360-473, JSON
Import/Export traits :65-97, SharedCommittee/SharedWorkerCache hot-swap :358,485.

Addresses here are plain "host:port" strings (the reference uses multiaddrs
over QUIC; our transport is an asyncio TCP mesh, see network/). Durations are
float seconds in memory, serialized as milliseconds in JSON.
"""

from __future__ import annotations

import json
import logging
import os
import socket
from collections import deque
from dataclasses import asdict, dataclass, field, replace
from typing import Mapping

from .bounded_cache import BoundedCache
from .crypto import digest256
from .types import Epoch, PublicKey, Round, WorkerId

logger = logging.getLogger("narwhal.config")

Stake = int


class ConfigError(ValueError):
    """Operator-facing misconfiguration (mis-sized shard count, bad flag
    combination): always fatal at boot, never fallback-able. Distinct from
    plain ValueError so callers with a documented degradation path (e.g.
    strict-rule nodes falling back to host crypto when the device verifier
    fails for NON-config reasons) can re-raise config mistakes while still
    degrading on environmental ones."""


@dataclass
class Parameters:
    """Tuning knobs (/root/reference/config/src/lib.rs:107-275 defaults)."""

    header_size: int = 1_000  # bytes of payload digests before sealing a header
    max_header_delay: float = 0.1  # s; reference default 100ms
    gc_depth: int = 50  # rounds
    sync_retry_delay: float = 5.0  # s
    sync_retry_nodes: int = 3  # lucky-broadcast fan-out
    batch_size: int = 500_000  # bytes
    max_batch_delay: float = 0.1  # s
    max_concurrent_requests: int = 500_000
    block_synchronizer_range_timeout: float = 30.0
    block_synchronizer_certs_timeout: float = 2.0
    block_synchronizer_payload_timeout: float = 2.0
    block_synchronizer_payload_retries: int = 5
    consensus_api_grpc_address: str = "127.0.0.1:0"
    prometheus_address: str = "127.0.0.1:0"
    # Committee-wide ed25519 accept set for PER-ITEM signatures (headers,
    # votes, full-format certificate vote vectors) — every node MUST use
    # the same rule or adversarially crafted torsion-component signatures
    # make honest nodes disagree (a consensus-split vector; see
    # narwhal_tpu/tpu/verifier.py msm_epilogue_check). Validated at node
    # assembly (ConfigError on anything else):
    #   strict     — the host library's cofactorless rule (ed25519-dalek
    #                `verify` semantics); supported by every crypto backend.
    #   cofactored — RFC 8032 batch rule (ed25519-dalek `batch_verify`
    #                semantics); only the tpu backend's msm kernel applies
    #                it per-item, so cpu/pool nodes refuse to start under
    #                this rule. Note compact-certificate PROOFS are
    #                cofactored on every backend by construction (the
    #                half-aggregated equation admits no other rule) —
    #                verify_rule only governs per-item checks.
    verify_rule: str = "strict"
    # Certificate wire form — committee-wide (mixed committees would
    # disagree about certificate bytes):
    #   compact — the DEFAULT: half-aggregated, 32-byte R per signer + one
    #             32-byte aggregate scalar (~2x smaller proofs, and the
    #             broadcast sheds the header body via CertificateRefMsg —
    #             3.2x smaller announcements measured at N=50; see types.py
    #             Certificate). Every backend verifies proofs batched: the
    #             tpu backend fuses groups into one device msm dispatch,
    #             cpu/pool run the same randomized-linear-combination rule
    #             over one host bucket-method MSM per flush
    #             (types.host_batch_verify_aggregates), amortizing the
    #             group math across every certificate in a dispatch.
    #   full    — the opt-out: one 64-byte ed25519 signature per signer
    #             (reference-like). Every node always ACCEPTS both forms on
    #             the wire; this picks what the committee assembles.
    cert_format: str = "compact"
    # Byte budget for the executor's speculative payload prefetcher
    # (executor/prefetcher.py): unclaimed pre-commit payload held in the
    # temp batch store never exceeds this; 0 disables prefetching entirely.
    # Env override: NARWHAL_PREFETCH_BUDGET (bytes, read at node assembly).
    prefetch_budget: int = 64 << 20
    # -- adaptive pacing (pacing.PacingController) -------------------------
    # max_batch_delay / max_header_delay become CEILINGS: the effective
    # delay shrinks toward these floors when the channel-depth EWMA says
    # queues are shallow (latency mode) and grows back toward the ceiling
    # under load (throughput mode). NARWHAL_PACING=0 disables adaptation
    # (fixed ceilings, the seed behavior); NARWHAL_BATCH_DELAY_FLOOR /
    # NARWHAL_HEADER_DELAY_FLOOR override the floors (seconds).
    batch_delay_floor: float = 0.005
    header_delay_floor: float = 0.02
    pacing_low_occupancy: float = 0.05  # EWMA at/below -> floor delay
    pacing_high_occupancy: float = 0.5  # EWMA at/above -> ceiling delay
    pacing_ewma_alpha: float = 0.2
    # -- end-to-end admission control (pacing.IngestGate) ------------------
    # Policy at the worker's client-facing ingest once the admission level
    # (max of local ingest occupancy and the primary-pushed downstream
    # backlog) crosses the high watermark: 'shed' answers RESOURCE_EXHAUSTED
    # immediately, 'block' holds the submission until the level falls below
    # the low watermark (bounded, then sheds), 'off' restores the seed's
    # unbounded queueing. Env override: NARWHAL_INGEST_POLICY.
    ingest_policy: str = "shed"
    backpressure_high_watermark: float = 0.75  # occupancy fraction
    backpressure_low_watermark: float = 0.5  # hysteresis release
    backpressure_poll_interval: float = 0.25  # primary->worker push period, s
    backpressure_stale_after: float = 2.0  # worker fails OPEN past this, s
    # Overload is mostly SERVICE-TIME saturation, not queue depth (items on
    # the hot channels are whole batches/certificates, so channels stay
    # shallow while rounds take seconds): the admission level also tracks
    # the commit-stage latency EWMA against this target — EWMA == target
    # lands on the high watermark, and a commit STALL longer than the
    # target pins the level at 1.0. 0 disables the latency signals.
    # Env override: NARWHAL_COMMIT_LATENCY_TARGET (seconds).
    commit_latency_target: float = 4.0
    # -- payload-plane wire diet (primary/fanout.py, primary/delta.py) -----
    # Fanout-tree dissemination of header/certificate broadcasts: the
    # origin sends to at most `relay_fanout` children of a deterministic
    # stake-weighted per-round tree and every receiver forwards to its own
    # children in the same tree; peers the origin has not heard an ack from
    # within relay_fallback_timeout get the original message by direct
    # reliable send, so reliable-broadcast semantics survive crashed
    # relays. Relaying engages only when the committee is large enough for
    # the tree to have depth >= 2 (more others than relay_fanout); 0
    # disables it outright. Env overrides: NARWHAL_RELAY_FANOUT, and
    # NARWHAL_RELAY=0 as a kill-switch.
    relay_fanout: int = 3
    relay_fallback_timeout: float = 0.5
    # Header/certificate announcement wire form — committee-interoperable
    # (every node always ACCEPTS both forms; this picks what we SEND):
    #   full  — self-describing HeaderMsg/CertificateMsg (seed behavior).
    #   delta — DeltaHeaderMsg (the payload pairs added since the sender's
    #           last header + 2-byte parent refs into the receiver's
    #           recent-certificate index) and CertificateDeltaMsg
    #           (signatures by header reference). Receivers that cannot
    #           reconstruct fall back to the full-map resync path
    #           (HeaderResyncRequest keyed off their last-seen round).
    # Env override: NARWHAL_HEADER_WIRE.
    header_wire: str = "delta"
    # -- connection pool (network/pool.py) ---------------------------------
    # One multiplexed authenticated connection per peer NODE pair: every
    # lane (primary plane + each worker plane) of the pair shares one
    # socket with a lane id in the frame header, taking an N-node W-worker
    # mesh from O(N^2 * (1+W)) sockets to one per unordered pair (the anemo
    # one-QUIC-connection-per-peer model). False restores per-role-pair
    # dedicated connections. Env kill-switch: NARWHAL_POOL=0.
    connection_pool: bool = True
    # Crossed-dial damping: the pool end whose network key sorts HIGHER
    # than the peer's waits this long for the peer's inbound connection to
    # be adopted before dialing itself (the canonical connection is the one
    # dialed by the lower key; a crossed dial is resolved by closing the
    # higher side's, so this wait turns a boot-time close/redial churn into
    # a no-op for all but the slowest pairs).
    pool_passive_dial_delay: float = 0.2
    # Grace period before the losing connection of a crossed dial is torn
    # down, letting responses already in flight on it drain.
    pool_linger: float = 1.0
    # Byte budget of the per-server relay dedup cache (digest-keyed decoded
    # messages; duplicate RelayMsg/Relay2Msg copies skip the codec).
    relay_dedup_cache_bytes: int = 32 << 20

    def to_json(self) -> str:
        return json.dumps(asdict(self), indent=2, sort_keys=True)

    @staticmethod
    def from_json(text: str) -> "Parameters":
        data = json.loads(text)
        known = {f for f in Parameters.__dataclass_fields__}
        return Parameters(**{k: v for k, v in data.items() if k in known})

    def export(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())

    @staticmethod
    def import_(path: str) -> "Parameters":
        with open(path) as f:
            return Parameters.from_json(f.read())


def connection_pool_effective(parameters: "Parameters") -> bool:
    """Whether the node runs the per-peer-pair connection pool after the
    NARWHAL_POOL env kill-switch (0/false/off forces dedicated per-role
    connections, the pre-pool behavior)."""
    if os.environ.get("NARWHAL_POOL", "1").lower() in ("0", "false", "off"):
        return False
    return bool(parameters.connection_pool)


def pacing_enabled() -> bool:
    """NARWHAL_PACING=0/false/off pins the seal/header delays at their
    configured ceilings (the pre-pacing behavior); anything else adapts."""
    return os.environ.get("NARWHAL_PACING", "1").lower() not in ("0", "false", "off")


def env_float(name: str, default: float) -> float:
    """Environment override for a float knob; non-numeric values are
    ignored loudly rather than crashing the boot."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        logger.warning("ignoring non-numeric %s=%r (using %s)", name, raw, default)
        return default


def env_int(name: str, default: int) -> int:
    """Environment override for an int knob; non-numeric values are
    ignored loudly rather than crashing the boot."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return int(raw)
    except ValueError:
        logger.warning("ignoring non-numeric %s=%r (using %s)", name, raw, default)
        return default


def relay_fanout_effective(parameters: "Parameters") -> int:
    """The relay fanout after env overrides: NARWHAL_RELAY=0/false/off is
    the kill-switch (forces direct all-to-all broadcast), NARWHAL_RELAY_FANOUT
    overrides the branching factor."""
    if os.environ.get("NARWHAL_RELAY", "1").lower() in ("0", "false", "off"):
        return 0
    return max(0, env_int("NARWHAL_RELAY_FANOUT", parameters.relay_fanout))


def header_wire_effective(parameters: "Parameters") -> str:
    """The header/certificate announcement wire form after the
    NARWHAL_HEADER_WIRE env override (full | delta)."""
    return os.environ.get("NARWHAL_HEADER_WIRE", parameters.header_wire)


@dataclass(frozen=True)
class Authority:
    """Stake + addresses of one validator
    (/root/reference/config/src/lib.rs:475-486)."""

    stake: Stake
    primary_address: str
    network_key: PublicKey


class Committee:
    """The validator set with stake math
    (/root/reference/config/src/lib.rs:488-685)."""

    def __init__(self, authorities: Mapping[PublicKey, Authority], epoch: Epoch = 0):
        # Canonical order: sorted by public key. Index in this order is the
        # authority's dense id used by certificates' signer lists and by every
        # TPU DAG tensor ([rounds x authorities] layout).
        self.authorities: dict[PublicKey, Authority] = dict(
            sorted(authorities.items())
        )
        self.epoch = epoch
        self._keys: list[PublicKey] = list(self.authorities)
        self._index: dict[PublicKey, int] = {pk: i for i, pk in enumerate(self._keys)}
        self._total_stake: Stake = sum(a.stake for a in self.authorities.values())
        self._transcript_digest: bytes | None = None
        # Structural signer-set memo (see signer_group): one computation
        # per distinct certificate signer tuple under this committee.
        self._signer_groups = BoundedCache(max_entries=1 << 16)

    # -- size / stake -----------------------------------------------------
    def size(self) -> int:
        return len(self.authorities)

    def stake(self, name: PublicKey) -> Stake:
        a = self.authorities.get(name)
        return a.stake if a else 0

    def total_stake(self) -> Stake:
        return self._total_stake

    def quorum_threshold(self) -> Stake:
        """2f+1 equivalent: ceil((2N+1)/3) of total stake
        (/root/reference/config/src/lib.rs:537-544)."""
        return (2 * self._total_stake) // 3 + 1

    def validity_threshold(self) -> Stake:
        """f+1 equivalent (/root/reference/config/src/lib.rs:546-550)."""
        return (self._total_stake + 2) // 3

    # -- identity ---------------------------------------------------------
    def authority_keys(self) -> list[PublicKey]:
        return self._keys

    def transcript_digest(self) -> bytes:
        """Content identity of this validator set (memoized): epoch plus
        the canonical (public key, stake) sequence. Keys the process-wide
        aggregate-verdict front cache, where verdicts reached under
        different committees with overlapping signer indices must never
        collide. Committees are immutable after construction (reconfigure
        builds a new one), so memoizing is safe."""
        d = self._transcript_digest
        if d is None:
            parts = [int(self.epoch).to_bytes(8, "little")]
            for pk, a in self.authorities.items():
                parts.append(pk)
                parts.append(int(a.stake).to_bytes(8, "little"))
            d = self._transcript_digest = digest256(b"".join(parts))
        return d

    def index_of(self, name: PublicKey) -> int:
        return self._index[name]

    def key_of(self, index: int) -> PublicKey:
        return self._keys[index]

    def stakes_array(self) -> list[Stake]:
        return [self.authorities[pk].stake for pk in self._keys]

    def signer_group(
        self, signers: tuple[int, ...]
    ) -> tuple[tuple[PublicKey, ...], Stake]:
        """Memoized structural resolution of a certificate signer set:
        `(signer public keys in order, their total stake)`, validated for
        duplicates and index range — computed ONCE per (committee, signer
        tuple) instead of per certificate COPY. In the relay fan-out every
        member re-verifies the same certificate, so at N=200 the per-copy
        O(N) index/stake walk was a top-3 term of the liveness wall; the
        same few thousand distinct signer sets recur across copies and
        sanitize/verify stages. Committees are immutable after construction
        (reconfigure builds a new one), so memoizing on the instance is
        safe. Raises ValueError on malformed sets (config cannot import the
        DAG error types; callers wrap)."""
        group = self._signer_groups.get(signers)
        if group is None:
            if len(set(signers)) != len(signers):
                raise ValueError("duplicate signers")
            keys = self._keys
            pks = []
            stake = 0
            for idx in signers:
                if idx >= len(keys):
                    raise ValueError(f"signer index {idx} out of range")
                pk = keys[idx]
                stake += self.authorities[pk].stake
                pks.append(pk)
            group = (tuple(pks), stake)
            # First write wins (deterministic values), so a concurrent
            # resolution of the same tuple settles on one canonical group.
            self._signer_groups.put(signers, group)
        return group

    # -- leader election --------------------------------------------------
    def leader(self, seed: int) -> PublicKey:
        """Stake-weighted deterministic leader
        (/root/reference/config/src/lib.rs:553-567): a seeded PRNG pick
        weighted by stake. We derive the pick from digest256(seed) so every
        implementation (host Python, JAX kernel) agrees bit-for-bit."""
        h = digest256(seed.to_bytes(8, "little") + self.epoch.to_bytes(8, "little"))
        ticket = int.from_bytes(h[:8], "little") % self._total_stake
        acc = 0
        for pk in self._keys:
            acc += self.authorities[pk].stake
            if ticket < acc:
                return pk
        return self._keys[-1]

    def leader_index(self, seed: int) -> int:
        return self._index[self.leader(seed)]

    # -- addressing -------------------------------------------------------
    def primary_address(self, name: PublicKey) -> str:
        return self.authorities[name].primary_address

    def network_key(self, name: PublicKey) -> PublicKey:
        return self.authorities[name].network_key

    def others_primaries(self, me: PublicKey) -> list[tuple[PublicKey, str, PublicKey]]:
        """(name, address, network_key) of every other primary
        (/root/reference/config/src/lib.rs:585-600)."""
        return [
            (pk, a.primary_address, a.network_key)
            for pk, a in self.authorities.items()
            if pk != me
        ]

    def update_primary_network_info(
        self, updates: Mapping[PublicKey, tuple[Stake, str]]
    ) -> None:
        """Mid-epoch address updates
        (/root/reference/config/src/lib.rs:621-685): every authority must be
        covered and stakes must match."""
        if set(updates) != set(self.authorities):
            raise ValueError("updates must cover exactly the current committee")
        for pk, (stake, addr) in updates.items():
            if self.authorities[pk].stake != stake:
                raise ValueError(f"stake mismatch for {pk.hex()[:16]}")
        for pk, (stake, addr) in updates.items():
            self.authorities[pk] = replace(self.authorities[pk], primary_address=addr)

    # -- serialization ----------------------------------------------------
    def to_json(self) -> str:
        return json.dumps(
            {
                "epoch": self.epoch,
                "authorities": {
                    pk.hex(): {
                        "stake": a.stake,
                        "primary_address": a.primary_address,
                        "network_key": a.network_key.hex(),
                    }
                    for pk, a in self.authorities.items()
                },
            },
            indent=2,
            sort_keys=True,
        )

    @staticmethod
    def from_json(text: str) -> "Committee":
        data = json.loads(text)
        return Committee(
            {
                bytes.fromhex(pk): Authority(
                    stake=a["stake"],
                    primary_address=a["primary_address"],
                    network_key=bytes.fromhex(a["network_key"]),
                )
                for pk, a in data["authorities"].items()
            },
            epoch=data["epoch"],
        )

    def export(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())

    @staticmethod
    def import_(path: str) -> "Committee":
        with open(path) as f:
            return Committee.from_json(f.read())

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Committee)
            and self.epoch == other.epoch
            and self.authorities == other.authorities
        )


@dataclass(frozen=True)
class WorkerInfo:
    """(/root/reference/config/src/lib.rs:348-358): name = worker network key,
    transactions = client-facing tx ingest address, worker_address = the
    worker<->worker mesh address."""

    name: PublicKey
    transactions: str
    worker_address: str


class WorkerCache:
    """Worker topology of the whole committee
    (/root/reference/config/src/lib.rs:360-473)."""

    def __init__(
        self, workers: Mapping[PublicKey, Mapping[WorkerId, WorkerInfo]], epoch: Epoch = 0
    ):
        self.workers: dict[PublicKey, dict[WorkerId, WorkerInfo]] = {
            pk: dict(ws) for pk, ws in workers.items()
        }
        self.epoch = epoch

    def worker(self, authority: PublicKey, worker_id: WorkerId) -> WorkerInfo:
        return self.workers[authority][worker_id]

    def has_worker(self, authority: PublicKey, worker_id: WorkerId) -> bool:
        return worker_id in self.workers.get(authority, {})

    def our_workers(self, authority: PublicKey) -> dict[WorkerId, WorkerInfo]:
        return self.workers[authority]

    def others_workers(
        self, me: PublicKey, worker_id: WorkerId
    ) -> list[tuple[PublicKey, WorkerInfo]]:
        """Same-id workers at every other authority
        (/root/reference/config/src/lib.rs:432-450)."""
        return [
            (pk, ws[worker_id])
            for pk, ws in self.workers.items()
            if pk != me and worker_id in ws
        ]

    def all_workers(self) -> list[WorkerInfo]:
        return [w for ws in self.workers.values() for w in ws.values()]

    def to_json(self) -> str:
        return json.dumps(
            {
                "epoch": self.epoch,
                "workers": {
                    pk.hex(): {
                        str(wid): {
                            "name": w.name.hex(),
                            "transactions": w.transactions,
                            "worker_address": w.worker_address,
                        }
                        for wid, w in ws.items()
                    }
                    for pk, ws in self.workers.items()
                },
            },
            indent=2,
            sort_keys=True,
        )

    @staticmethod
    def from_json(text: str) -> "WorkerCache":
        data = json.loads(text)
        return WorkerCache(
            {
                bytes.fromhex(pk): {
                    int(wid): WorkerInfo(
                        name=bytes.fromhex(w["name"]),
                        transactions=w["transactions"],
                        worker_address=w["worker_address"],
                    )
                    for wid, w in ws.items()
                }
                for pk, ws in data["workers"].items()
            },
            epoch=data["epoch"],
        )

    def export(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())

    @staticmethod
    def import_(path: str) -> "WorkerCache":
        with open(path) as f:
            return WorkerCache.from_json(f.read())


class Shared:
    """Hot-swappable holder, the SharedCommittee/SharedWorkerCache analog
    (Arc<ArcSwap<_>>, /root/reference/config/src/lib.rs:358,485). In asyncio
    a plain attribute swap is atomic."""

    __slots__ = ("value",)

    def __init__(self, value):
        self.value = value

    def load(self):
        return self.value

    def swap(self, new):
        self.value = new


_HANDED_OUT: set[int] = set()
_HANDED_ORDER: deque[int] = deque()
# Placeholder sockets keep every handed-out port BOUND (with SO_REUSEPORT)
# until the real server co-binds: between assignment and bind the kernel
# would otherwise happily hand the same port to an ephemeral *outbound*
# connection — with a 20-node committee (~100 pre-assigned ports, thousands
# of mesh dials) that collision is routine, and the server's bind then fails
# with EADDRINUSE. Outbound sockets don't set SO_REUSEPORT so they can never
# share a placeheld port; servers do (RpcServer reuse_port, gRPC's default),
# so they bind straight through the placeholder.
_PLACEHOLDERS: dict[int, socket.socket] = {}
# Only the recent tail matters: servers bind within moments of assignment,
# and an unbounded set would eventually exhaust the 64 bind attempts in a
# long-lived process that keeps building clusters.
_HANDED_WINDOW = 1024


def get_available_port(host: str = "127.0.0.1") -> int:
    """(/root/reference/config/src/utils.rs:9-33). Ports are pre-assigned
    before servers bind them: hand out a port at most once per window and
    keep it placeheld (see _PLACEHOLDERS) until its server binds.

    The probe binds WITHOUT SO_REUSEPORT — the kernel then never selects a
    port owned by a live reuse-port listener (which a REUSEPORT probe would
    happily co-bind, silently splitting that listener's traffic). The
    placeholder then re-binds the probed port with SO_REUSEPORT so the real
    server can bind through it; losing the tiny re-bind race just retries.
    """
    for _ in range(64):
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            s.bind((host, 0))
            port = s.getsockname()[1]
        except OSError:
            s.close()
            continue
        s.close()
        if port in _HANDED_OUT:
            continue
        ph = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ph.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        try:
            ph.bind((host, port))
        except OSError:
            ph.close()  # an ephemeral connection won the re-bind race
            continue
        _HANDED_OUT.add(port)
        _HANDED_ORDER.append(port)
        _PLACEHOLDERS[port] = ph
        while len(_HANDED_ORDER) > _HANDED_WINDOW:
            old = _HANDED_ORDER[0]
            if old in _PLACEHOLDERS:
                # Still placeheld: its server has not bound yet. Closing the
                # placeholder here would re-open the exact collision it
                # exists to prevent (an ephemeral connection or a fresh
                # hand-out grabbing the port before the server binds), so
                # keep it and let the window grow. Loud, because a window
                # full of unbound ports usually means someone is leaking
                # placeholders (forgot release_port/release_all_ports).
                logger.warning(
                    "port window (%d) full of still-placeheld ports; "
                    "oldest=%d not evicted — check for placeholder leaks",
                    _HANDED_WINDOW,
                    old,
                )
                break
            _HANDED_ORDER.popleft()
            _HANDED_OUT.discard(old)
        return port
    raise OSError("no available port after 64 attempts")


def placeheld_ports() -> list[int]:
    """The ports this process currently reserves with live placeholders.
    Harness parents advertise exactly this list (NARWHAL_PLACEHELD_PORTS)
    to their node children, so the children co-bind only genuinely
    placeheld ports and every other duplicate bind still fails fast."""
    return sorted(_PLACEHOLDERS)


# Ports with a live server bound by THIS process. The parent's
# NARWHAL_PLACEHELD_PORTS advertisement is spawn-time static, so without
# this set a second server in the same child (same node started twice, a
# committee file assigning one port to two roles) would still co-bind
# "through" an advertisement whose placeholder its sibling already consumed.
_BOUND_IN_PROCESS: set[int] = set()


def mark_port_bound(port: int) -> None:
    """Record that a server in this process holds `port` (RpcServer.start)."""
    _BOUND_IN_PROCESS.add(port)


def mark_port_unbound(port: int) -> None:
    """The server on `port` has stopped; a later bind (node restart) may
    again co-bind through a parent's still-live placeholder."""
    _BOUND_IN_PROCESS.discard(port)


def port_is_placeheld(port: int) -> bool:
    """True when `port` is reserved by a live SO_REUSEPORT placeholder —
    this process's (_PLACEHOLDERS) or a harness parent's, advertised via
    NARWHAL_PLACEHELD_PORTS ("all", or a comma-separated port list). Servers
    use this to decide whether co-binding with reuse_port is intended
    (binding through a placeholder) or a misconfiguration that should fail
    fast with EADDRINUSE (two servers on one address). A port already bound
    by a live server in this process is never placeheld — the placeholder
    behind any advertisement has done its job."""
    if port in _BOUND_IN_PROCESS:
        return False
    if port in _PLACEHOLDERS:
        return True
    env = os.environ.get("NARWHAL_PLACEHELD_PORTS", "")
    if env == "all":
        return True
    return any(tok.strip() == str(port) for tok in env.split(",") if tok.strip())


def release_port(port: int) -> None:
    """Drop the placeholder for `port` once its real server has bound (or
    will never bind). Safe to call for ports this process never placeheld —
    a subprocess binding a parent-assigned port simply co-binds via
    SO_REUSEPORT and the parent releases via release_all_ports."""
    s = _PLACEHOLDERS.pop(port, None)
    if s is not None:
        s.close()


def release_all_ports() -> None:
    """Drop every live placeholder. For multi-process harness parents: the
    children bind the assigned ports themselves, so the parent must free
    its placeholder fds once the fleet is up (a sweep would otherwise
    accumulate them toward the fd ulimit)."""
    while _PLACEHOLDERS:
        _, s = _PLACEHOLDERS.popitem()
        s.close()
