"""Pure dependency-check helper used by the core.

Reference: /root/reference/primary/src/synchronizer.rs:22-178 —
`missing_payload` checks the payload store and queues a SyncBatches command for
anything absent; `get_parents` reads parent certificates from the store and
queues SyncParents when incomplete; `deliver_certificate` checks a
certificate's ancestry is complete.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..channels import Channel
from ..stores import CertificateStore, PayloadStore
from ..types import Certificate, Digest, Header, PublicKey, WorkerId


@dataclass
class SyncBatches:
    """Ask own workers to fetch `missing` batches, then replay `header`
    (WaiterMessage::SyncBatches)."""

    missing: dict[Digest, WorkerId]
    header: Header


@dataclass
class SyncParents:
    """Fetch `missing` parent certificates from `header.author`'s primary, then
    replay `header` (WaiterMessage::SyncParents)."""

    missing: list[Digest]
    header: Header


class Synchronizer:
    def __init__(
        self,
        name: PublicKey,
        certificate_store: CertificateStore,
        payload_store: PayloadStore,
        tx_header_waiter: Channel,
        genesis: dict[Digest, Certificate],
    ):
        self.name = name
        self.certificate_store = certificate_store
        self.payload_store = payload_store
        self.tx_header_waiter = tx_header_waiter
        self.genesis = dict(genesis)
        self.genesis_digests = frozenset(genesis)

    def update_genesis(self, committee) -> None:
        """Genesis digests embed the epoch; recompute them on reconfiguration
        or round-1 headers of the new epoch would suspend forever."""
        self.genesis = {c.digest: c for c in Certificate.genesis(committee)}
        self.genesis_digests = frozenset(self.genesis)

    async def missing_payload(self, header: Header) -> bool:
        """True if some batch of the header isn't locally available yet; queues
        the repair (synchronizer.rs:60-113). Our own headers never miss: we
        created them from digests our workers reported."""
        if header.author == self.name:
            return False
        missing = {
            digest: worker_id
            for digest, worker_id in header.payload.items()
            if not self.payload_store.contains(digest, worker_id)
        }
        if missing:
            await self.tx_header_waiter.send(SyncBatches(missing, header))
            return True
        return False

    async def get_parents(self, header: Header) -> list[Certificate] | None:
        """The parent certificates, or None (repair queued) if any is missing
        (synchronizer.rs:115-144). Genesis certificates are returned like any
        other parent (synchronizer.rs:119-125) so the caller's round-match and
        stake-quorum checks always run — an empty or sub-quorum genesis parent
        set must be rejected, not silently voted for."""
        parents: list[Certificate] = []
        missing: list[Digest] = []
        for digest in header.parents:
            genesis_cert = self.genesis.get(digest)
            if genesis_cert is not None:
                parents.append(genesis_cert)
                continue
            cert = self.certificate_store.read(digest)
            if cert is None:
                missing.append(digest)
            else:
                parents.append(cert)
        if missing:
            await self.tx_header_waiter.send(SyncParents(missing, header))
            return None
        return parents

    def deliver_certificate(self, certificate: Certificate) -> bool:
        """True iff the certificate's direct ancestry is locally complete
        (synchronizer.rs:146-178)."""
        return all(
            digest in self.genesis_digests or self.certificate_store.contains(digest)
            for digest in certificate.header.parents
        )
