"""BlockWaiter: fetch a certificate's payload ("block"/"collection") from our
own workers.

Reference: /root/reference/primary/src/block_waiter.rs:45-845 — GetBlock /
GetBlocks commands resolve a certificate digest to its batches; concurrent
requests for the same block are deduplicated; batch requests time out after
10s. Used by the Validator gRPC API. Data-plane batching delta from the
reference: a block's batch fetches group by target worker and each group
rides ONE coalesced RequestBatchesMsg (one RPC, one coalesced store read on
the worker) instead of one RequestBatch round trip per batch; partial
responses map onto the same BlockError kinds (a deadline anywhere is
BatchTimeout, an authoritative miss or transport failure is BatchError).
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass

from ..clock import now
from ..config import WorkerCache
from ..messages import RequestBatchesMsg, RequestedBatchesMsg
from ..network import NetworkClient, RpcError
from ..stores import CertificateStore
from ..types import Batch, Certificate, Digest, PublicKey, serialized_batch_digest

logger = logging.getLogger("narwhal.primary")

# Per-batch worker deadline (block_waiter.rs BATCH_RETRIEVE_TIMEOUT = 10s)
# and a short bounded retry for transient transport failures — a worker
# restarting mid-fetch should not fail the whole block.
BATCH_RETRIEVE_TIMEOUT = 10.0
BATCH_RETRY_ATTEMPTS = 3
BATCH_RETRY_DELAY = 0.25


class _BatchTimeout(Exception):
    """Internal: a worker held the connection but exceeded the per-batch
    deadline (distinct from transport errors, which map to BatchError)."""


class BlockError(Exception):
    def __init__(self, digest: Digest, kind: str):
        super().__init__(f"block {digest.hex()[:16]}: {kind}")
        self.digest = digest
        self.kind = kind  # "BlockNotFound" | "BatchTimeout" | "BatchError"


@dataclass
class BlockResponse:
    digest: Digest
    batches: list[tuple[Digest, Batch]]


class BlockWaiter:
    def __init__(
        self,
        name: PublicKey,
        worker_cache: WorkerCache,
        certificate_store: CertificateStore,
        network: NetworkClient,
        block_synchronizer=None,  # optional: fetch unknown certs from peers
        batch_timeout: float = BATCH_RETRIEVE_TIMEOUT,
        retry_attempts: int = BATCH_RETRY_ATTEMPTS,
        retry_delay: float = BATCH_RETRY_DELAY,
    ):
        self.name = name
        self.worker_cache = worker_cache
        self.certificate_store = certificate_store
        self.network = network
        self.block_synchronizer = block_synchronizer
        self.batch_timeout = batch_timeout
        self.retry_attempts = retry_attempts
        self.retry_delay = retry_delay
        # Dedup map: one in-flight fetch per block digest
        # (block_waiter.rs pending_get_block).
        self._pending: dict[Digest, asyncio.Future] = {}

    async def get_block(self, digest: Digest) -> BlockResponse:
        fut = self._pending.get(digest)
        if fut is None:
            fut = asyncio.ensure_future(self._fetch_block(digest))
            self._pending[digest] = fut
            fut.add_done_callback(lambda _: self._pending.pop(digest, None))
        return await asyncio.shield(fut)

    async def get_blocks(self, digests: list[Digest]) -> list[BlockResponse | BlockError]:
        results = await asyncio.gather(
            *(self.get_block(d) for d in digests), return_exceptions=True
        )
        out: list[BlockResponse | BlockError] = []
        for digest, res in zip(digests, results):
            if isinstance(res, BlockResponse):
                out.append(res)
            elif isinstance(res, BlockError):
                out.append(res)
            else:
                out.append(BlockError(digest, "BatchError"))
        return out

    async def _certificate(self, digest: Digest) -> Certificate | None:
        cert = self.certificate_store.read(digest)
        if cert is None and self.block_synchronizer is not None:
            certs = await self.block_synchronizer.synchronize_block_headers([digest])
            for c in certs:
                if c.digest == digest:
                    return c
        return cert

    async def _fetch_block(self, digest: Digest) -> BlockResponse:
        certificate = await self._certificate(digest)
        if certificate is None:
            raise BlockError(digest, "BlockNotFound")
        payload = list(certificate.header.payload.items())
        groups: dict[int, list[Digest]] = {}
        for d, w in payload:
            groups.setdefault(w, []).append(d)
        # One coalesced fetch per target worker; return_exceptions keeps
        # sibling worker fetches from running on unobserved after the first
        # failure. A deadline anywhere outranks transport errors in the
        # reported kind (block_waiter.rs maps the per-batch deadline to
        # BatchTimeout).
        results = await asyncio.gather(
            *(self._fetch_batches(w, ds) for w, ds in groups.items()),
            return_exceptions=True,
        )
        if any(isinstance(r, _BatchTimeout) for r in results):
            raise BlockError(digest, "BatchTimeout")
        fetched: dict[Digest, Batch] = {}
        for r in results:
            if isinstance(r, BaseException):
                logger.debug("block %s batch error: %s", digest.hex()[:16], r)
                raise BlockError(digest, "BatchError")
            fetched.update(r)
        return BlockResponse(digest, [(d, fetched[d]) for d, _ in payload])

    async def _fetch_batches(
        self, worker_id: int, digests: list[Digest]
    ) -> dict[Digest, Batch]:
        """Every batch one worker holds for this block, under the per-batch
        deadline, as one RequestBatchesMsg round trip; transient transport
        failures retry a bounded number of times so a restarting worker
        doesn't fail the block. Partial responses are authoritative: a
        found=False entry means the worker lacks the batch and retrying
        won't help (BatchError), exactly the single-fetch semantics."""
        info = self.worker_cache.worker(self.name, worker_id)
        last: Exception | None = None
        # One deadline covers ALL attempts: retries are for fast transport
        # failures (connection refused while a worker restarts) and must not
        # stretch the reference's hard per-batch bound.
        deadline = now() + self.batch_timeout
        for attempt in range(self.retry_attempts):
            remaining = deadline - now()
            if remaining <= 0:
                break
            try:
                resp: RequestedBatchesMsg = await asyncio.wait_for(
                    self.network.request(
                        info.worker_address, RequestBatchesMsg(tuple(digests)),
                        timeout=None,
                    ),
                    remaining,
                )
            except asyncio.TimeoutError:
                raise _BatchTimeout(
                    f"worker {worker_id} batches "
                    f"{[d.hex()[:16] for d in digests[:3]]} "
                    f"deadline ({self.batch_timeout}s)"
                ) from None
            except (RpcError, OSError) as e:
                last = e
                if attempt + 1 < self.retry_attempts:
                    await asyncio.sleep(
                        min(self.retry_delay * (attempt + 1),
                            max(0.0, deadline - now()))
                    )
                continue
            entries = {d: (found, raw) for d, found, raw in resp.batches}
            out: dict[Digest, Batch] = {}
            for d in digests:
                found, raw = entries.get(d, (False, b""))
                if not found or serialized_batch_digest(raw) != d:
                    # The worker answered authoritatively: retrying won't
                    # help (the reference's BatchError reply path).
                    raise RpcError(
                        f"worker {worker_id} lacks batch {d.hex()[:16]}"
                    )
                out[d] = Batch.from_bytes(raw)
            return out
        if last is not None:
            raise last
        raise _BatchTimeout(
            f"worker {worker_id} batches {[d.hex()[:16] for d in digests[:3]]} "
            f"deadline ({self.batch_timeout}s)"
        )
