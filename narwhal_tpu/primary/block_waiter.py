"""BlockWaiter: fetch a certificate's payload ("block"/"collection") from our
own workers.

Reference: /root/reference/primary/src/block_waiter.rs:45-845 — GetBlock /
GetBlocks commands resolve a certificate digest to its batches by sending
`RequestBatch` to the worker that holds each batch; concurrent requests for
the same block are deduplicated; batch requests time out after 10s. Used by
the executor's subscriber and the Validator gRPC API.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass

from ..config import WorkerCache
from ..messages import RequestBatchMsg, RequestedBatchMsg
from ..network import NetworkClient, RpcError
from ..stores import CertificateStore
from ..types import Batch, Certificate, Digest, PublicKey, serialized_batch_digest

logger = logging.getLogger("narwhal.primary")

# Per-batch worker deadline (block_waiter.rs BATCH_RETRIEVE_TIMEOUT = 10s)
# and a short bounded retry for transient transport failures — a worker
# restarting mid-fetch should not fail the whole block.
BATCH_RETRIEVE_TIMEOUT = 10.0
BATCH_RETRY_ATTEMPTS = 3
BATCH_RETRY_DELAY = 0.25


class _BatchTimeout(Exception):
    """Internal: a worker held the connection but exceeded the per-batch
    deadline (distinct from transport errors, which map to BatchError)."""


class BlockError(Exception):
    def __init__(self, digest: Digest, kind: str):
        super().__init__(f"block {digest.hex()[:16]}: {kind}")
        self.digest = digest
        self.kind = kind  # "BlockNotFound" | "BatchTimeout" | "BatchError"


@dataclass
class BlockResponse:
    digest: Digest
    batches: list[tuple[Digest, Batch]]


class BlockWaiter:
    def __init__(
        self,
        name: PublicKey,
        worker_cache: WorkerCache,
        certificate_store: CertificateStore,
        network: NetworkClient,
        block_synchronizer=None,  # optional: fetch unknown certs from peers
        batch_timeout: float = BATCH_RETRIEVE_TIMEOUT,
        retry_attempts: int = BATCH_RETRY_ATTEMPTS,
        retry_delay: float = BATCH_RETRY_DELAY,
    ):
        self.name = name
        self.worker_cache = worker_cache
        self.certificate_store = certificate_store
        self.network = network
        self.block_synchronizer = block_synchronizer
        self.batch_timeout = batch_timeout
        self.retry_attempts = retry_attempts
        self.retry_delay = retry_delay
        # Dedup map: one in-flight fetch per block digest
        # (block_waiter.rs pending_get_block).
        self._pending: dict[Digest, asyncio.Future] = {}

    async def get_block(self, digest: Digest) -> BlockResponse:
        fut = self._pending.get(digest)
        if fut is None:
            fut = asyncio.ensure_future(self._fetch_block(digest))
            self._pending[digest] = fut
            fut.add_done_callback(lambda _: self._pending.pop(digest, None))
        return await asyncio.shield(fut)

    async def get_blocks(self, digests: list[Digest]) -> list[BlockResponse | BlockError]:
        results = await asyncio.gather(
            *(self.get_block(d) for d in digests), return_exceptions=True
        )
        out: list[BlockResponse | BlockError] = []
        for digest, res in zip(digests, results):
            if isinstance(res, BlockResponse):
                out.append(res)
            elif isinstance(res, BlockError):
                out.append(res)
            else:
                out.append(BlockError(digest, "BatchError"))
        return out

    async def _certificate(self, digest: Digest) -> Certificate | None:
        cert = self.certificate_store.read(digest)
        if cert is None and self.block_synchronizer is not None:
            certs = await self.block_synchronizer.synchronize_block_headers([digest])
            for c in certs:
                if c.digest == digest:
                    return c
        return cert

    async def _fetch_block(self, digest: Digest) -> BlockResponse:
        certificate = await self._certificate(digest)
        if certificate is None:
            raise BlockError(digest, "BlockNotFound")
        payload = list(certificate.header.payload.items())
        # return_exceptions keeps sibling batch fetches from running on
        # unobserved after the first failure; a timeout anywhere outranks
        # transport errors in the reported kind (block_waiter.rs maps the
        # per-batch deadline to BatchTimeout).
        results = await asyncio.gather(
            *(self._fetch_batch(d, w) for d, w in payload), return_exceptions=True
        )
        if any(isinstance(r, _BatchTimeout) for r in results):
            raise BlockError(digest, "BatchTimeout")
        for r in results:
            if isinstance(r, BaseException):
                logger.debug("block %s batch error: %s", digest.hex()[:16], r)
                raise BlockError(digest, "BatchError")
        return BlockResponse(digest, list(zip((d for d, _ in payload), results)))

    async def _fetch_batch(self, batch_digest: Digest, worker_id: int) -> Batch:
        """One batch from the worker that holds it, under the per-batch
        deadline; transient transport failures retry a bounded number of
        times so a restarting worker doesn't fail the block."""
        info = self.worker_cache.worker(self.name, worker_id)
        last: Exception | None = None
        # One deadline covers ALL attempts: retries are for fast transport
        # failures (connection refused while a worker restarts) and must not
        # stretch the reference's hard per-batch bound.
        loop = asyncio.get_running_loop()
        deadline = loop.time() + self.batch_timeout
        for attempt in range(self.retry_attempts):
            remaining = deadline - loop.time()
            if remaining <= 0:
                break
            try:
                resp: RequestedBatchMsg = await asyncio.wait_for(
                    self.network.request(
                        info.worker_address, RequestBatchMsg(batch_digest),
                        timeout=None,
                    ),
                    remaining,
                )
            except asyncio.TimeoutError:
                raise _BatchTimeout(
                    f"worker {worker_id} batch {batch_digest.hex()[:16]} "
                    f"deadline ({self.batch_timeout}s)"
                ) from None
            except (RpcError, OSError) as e:
                last = e
                if attempt + 1 < self.retry_attempts:
                    await asyncio.sleep(
                        min(self.retry_delay * (attempt + 1),
                            max(0.0, deadline - loop.time()))
                    )
                continue
            if (
                not resp.found
                or serialized_batch_digest(resp.serialized_batch) != batch_digest
            ):
                # The worker answered authoritatively: retrying won't help.
                raise RpcError(
                    f"worker {worker_id} lacks batch {batch_digest.hex()[:16]}"
                )
            return Batch.from_bytes(resp.serialized_batch)
        if last is not None:
            raise last
        raise _BatchTimeout(
            f"worker {worker_id} batch {batch_digest.hex()[:16]} "
            f"deadline ({self.batch_timeout}s)"
        )
