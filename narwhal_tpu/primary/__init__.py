"""The primary: DAG construction protocol (headers, votes, certificates).

Reference crate: /root/reference/primary/ (see SURVEY §2.8).
"""

from .aggregators import CertificatesAggregator, VotesAggregator
from .certificate_waiter import CertificateWaiter
from .core import Core
from .header_waiter import HeaderWaiter
from .helper import Helper
from .metrics import PrimaryMetrics
from .payload_receiver import PayloadReceiver
from .primary import Primary
from .proposer import NetworkModel, Proposer
from .state_handler import StateHandler
from .synchronizer import SyncBatches, SyncParents, Synchronizer

__all__ = [
    "CertificateWaiter",
    "CertificatesAggregator",
    "Core",
    "HeaderWaiter",
    "Helper",
    "NetworkModel",
    "PayloadReceiver",
    "Primary",
    "PrimaryMetrics",
    "Proposer",
    "StateHandler",
    "SyncBatches",
    "SyncParents",
    "Synchronizer",
    "VotesAggregator",
]
