"""The StateHandler: consensus feedback, GC triggering, reconfigure protocol.

Reference: /root/reference/primary/src/state_handler.rs:15-177 — receives
committed certificates from consensus, tracks the last committed round,
signals it on the consensus-round watch (the GC trigger for core and both
waiters), sends Cleanup to our own workers, and executes the
reconfigure/shutdown protocol by swapping the committee and fanning the
notification out to every actor's select loop plus our workers.
"""

from __future__ import annotations

import asyncio
import logging

from ..channels import Channel, Watch
from ..config import Committee, WorkerCache
from ..messages import CleanupMsg, ReconfigureMsg
from ..network import NetworkClient
from ..types import Certificate, PublicKey, ReconfigureNotification, Round

logger = logging.getLogger("narwhal.primary")


class StateHandler:
    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        worker_cache: WorkerCache,
        network: NetworkClient,
        rx_committed_certificates: Channel,  # from consensus (tx_primary)
        rx_state_handler: Channel,  # ReconfigureNotification from workers
        tx_consensus_round_updates: Watch,  # Round
        tx_reconfigure: Watch,  # ReconfigureNotification fan-out
        metrics=None,
    ):
        self.name = name
        self.committee = committee
        self.worker_cache = worker_cache
        self.network = network
        self.rx_committed_certificates = rx_committed_certificates
        self.rx_state_handler = rx_state_handler
        self.tx_consensus_round_updates = tx_consensus_round_updates
        self.tx_reconfigure = tx_reconfigure
        self.metrics = metrics

        self.last_committed_round: Round = 0
        self._task: asyncio.Task | None = None

    def spawn(self) -> asyncio.Task:
        self._task = asyncio.ensure_future(self.run())
        return self._task

    def _our_worker_addresses(self) -> list[str]:
        try:
            return [
                w.worker_address
                for w in self.worker_cache.our_workers(self.name).values()
            ]
        except KeyError:
            return []

    async def _handle_commit(self, certificate: Certificate) -> None:
        """(state_handler.rs:57-98): advance the committed round, trigger GC
        downstream and batch cleanup at our workers."""
        round = certificate.round
        if round <= self.last_committed_round:
            return
        self.last_committed_round = round
        self.tx_consensus_round_updates.send(round)
        await self.network.unreliable_broadcast(
            self._our_worker_addresses(), CleanupMsg(round)
        )

    async def _handle_reconfigure(self, note: ReconfigureNotification) -> None:
        """(state_handler.rs:100-172): swap the committee, notify every local
        actor via the watch, and forward to our workers."""
        if note.committee is not None:
            self.committee = note.committee
        self.tx_reconfigure.send(note)
        committee_json = note.committee.to_json() if note.committee is not None else ""
        msg = ReconfigureMsg(note.kind, committee_json)
        await self.network.unreliable_broadcast(self._our_worker_addresses(), msg)
        if note.kind == "shutdown":
            logger.info("State handler executing shutdown")

    async def run(self) -> None:
        commit_task = asyncio.ensure_future(self.rx_committed_certificates.recv())
        state_task = asyncio.ensure_future(self.rx_state_handler.recv())
        try:
            while True:
                done, _ = await asyncio.wait(
                    {commit_task, state_task}, return_when=asyncio.FIRST_COMPLETED
                )
                if commit_task in done:
                    certificate = commit_task.result()
                    commit_task = asyncio.ensure_future(
                        self.rx_committed_certificates.recv()
                    )
                    await self._handle_commit(certificate)
                if state_task in done:
                    note = state_task.result()
                    state_task = asyncio.ensure_future(self.rx_state_handler.recv())
                    await self._handle_reconfigure(note)
                    if note.kind == "shutdown":
                        return
        finally:
            commit_task.cancel()
            state_task.cancel()
