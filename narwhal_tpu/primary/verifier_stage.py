"""Async pre-verification stage: pipeline signature checks off the Core.

The reference verifies every header/vote/certificate inline in the Core's
single-threaded loop (core.rs sanitize_*, the crypto hot path named by the
north star). Here, when a crypto pool is configured, the RPC handlers hand
messages to this stage instead: structural checks run immediately, signature
items go to the AsyncVerifierPool (which coalesces across ALL concurrently
arriving messages into fixed-shape device batches), and only successfully
verified messages are forwarded to the Core wrapped in `PreVerified` so its
sanitize step skips redundant signature work. The Core state machine stays
single-threaded; only crypto becomes pipelined + batched.
"""

from __future__ import annotations

import asyncio
import logging

from ..channels import Channel
from ..config import Committee, WorkerCache
from ..types import Certificate, DagError, Header, InvalidEpoch, Vote

logger = logging.getLogger("narwhal.primary")


class PreVerified:
    """Marker carrying a message whose signatures have been checked."""

    __slots__ = ("inner",)

    def __init__(self, inner):
        self.inner = inner


class VerifierStage:
    def __init__(
        self,
        committee: Committee,
        worker_cache: WorkerCache,
        pool,  # AsyncVerifierPool-compatible: await pool.verify(pk, msg, sig)
        tx_out: Channel,
        rx_reconfigure=None,  # Watch[ReconfigureNotification]: epoch swaps
        max_pending: int = 1_024,
    ):
        self._committee = committee
        self.worker_cache = worker_cache
        self.pool = pool
        self.tx_out = tx_out
        self.rx_reconfigure = rx_reconfigure
        self._sem = asyncio.Semaphore(max_pending)
        self._tasks: set[asyncio.Task] = set()

    @property
    def committee(self) -> Committee:
        """Latest committee: epoch changes land on the reconfigure watch, and
        a stage pinned to the boot committee would silently drop every
        new-epoch message."""
        if self.rx_reconfigure is not None:
            note = self.rx_reconfigure.value
            if note is not None and getattr(note, "committee", None) is not None:
                self._committee = note.committee
        return self._committee

    async def submit(self, msg) -> None:
        await self._sem.acquire()
        task = asyncio.ensure_future(self._verify(msg))
        self._tasks.add(task)

        def _done(t: asyncio.Task) -> None:
            self._tasks.discard(t)
            self._sem.release()

        task.add_done_callback(_done)

    async def _verify(self, msg) -> None:
        agg_group = None
        agg_committee = None
        try:
            if isinstance(msg, Header):
                msg.verify(self.committee, self.worker_cache, check_signature=False)
                items = [msg.signature_item()]
            elif isinstance(msg, Vote):
                msg.verify(self.committee, check_signature=False)
                items = [msg.signature_item()]
            elif isinstance(msg, Certificate) and msg.is_compact:
                # Half-aggregated proof: one aggregate check for the vote
                # quorum + the embedded header's own signature. The
                # content-keyed front cache short-circuits the transcript
                # rebuild (Fiat-Shamir weights + per-signer vote digests)
                # whenever any co-hosted node — or an earlier relay copy
                # arriving at this one — already decided this exact proof
                # under this committee. Structural checks always run, so
                # the InvalidEpoch/DagError semantics below are unchanged.
                agg_committee = self.committee
                verdict = msg.cached_aggregate_verdict(agg_committee)
                items = []
                if verdict is not None:
                    msg.structural_verify(agg_committee)
                    if not verdict:
                        logger.debug(
                            "verifier stage dropped compact certificate with "
                            "known-bad aggregate proof"
                        )
                        return
                    if not msg.is_genesis():
                        msg.header.verify(
                            agg_committee, self.worker_cache, check_signature=False
                        )
                        items.append(msg.header.signature_item())
                else:
                    agg_group = msg.aggregate_group(agg_committee)
                    if agg_group is not None:
                        msg.header.verify(
                            agg_committee, self.worker_cache, check_signature=False
                        )
                        items.append(msg.header.signature_item())
            elif isinstance(msg, Certificate):
                items = msg.verify_items(self.committee)
                if items:
                    msg.header.verify(
                        self.committee, self.worker_cache, check_signature=False
                    )
                    items.append(msg.header.signature_item())
            else:
                await self.tx_out.send(msg)
                return
        except InvalidEpoch:
            # NOT this stage's call: the Core buffers exactly-one-epoch-ahead
            # messages for replay after its reconfigure notification lands
            # (the epoch-change deadlock fix) and logs the stale drops.
            # Forward RAW (un-preverified): the Core re-runs the full
            # sanitize path — including signatures, against whatever
            # committee it holds when the message is finally handled.
            await self.tx_out.send(msg)
            return
        except DagError as e:
            logger.debug("verifier stage dropped malformed message: %s", e)
            return
        if items or agg_group is not None:
            try:
                awaitables = [self.pool.verify(pk, m, sig) for pk, m, sig in items]
                if agg_group is not None:
                    awaitables.append(self.pool.verify_aggregate(*agg_group))
                results = await asyncio.gather(*awaitables)
            except Exception:
                # Backend dispatch failure with the host fallback disabled
                # (cofactored committees: a strict-rule fallback would be a
                # consensus-split hazard). Drop the message — conservative
                # rejection affects liveness, never safety — and say so.
                logger.exception(
                    "verify backend failed; dropping %s (no host fallback "
                    "under this committee's accept rule)",
                    type(msg).__name__,
                )
                return
            if agg_group is not None:
                # Publish the paid-for MSM verdict under the front key so
                # every later copy of this certificate — same node's relay
                # duplicates or a co-hosted peer's — skips the transcript.
                msg.record_aggregate_verdict(agg_committee, bool(results[-1]))
            if not all(results):
                logger.warning(
                    "verifier stage rejected %s with bad signature",
                    type(msg).__name__,
                )
                return
        await self.tx_out.send(PreVerified(msg))

    def shutdown(self) -> None:
        for t in list(self._tasks):
            t.cancel()
