"""Primary metrics (/root/reference/primary/src/metrics.rs:51-485)."""

from __future__ import annotations

from ..metrics import Registry
from ..pacing import StageTimer


class PrimaryMetrics:
    def __init__(self, registry: Registry, tracer=None):
        # The node's tracing.Tracer rides with the metrics object: every
        # actor already holds the metrics, so the span/link sites need no
        # extra plumbing.
        self.tracer = tracer
        # -- pacing / stage tracing ----------------------------------------
        self.stage_latency = registry.histogram(
            "primary_stage_latency_seconds",
            "Per-stage pipeline latency on the primary (stage=propose: "
            "batch digest arrival -> included in a proposed header; "
            "stage=certify: own header proposed -> certificate assembled)",
            labels=("stage",),
        )
        # Shared timers: the proposer starts them, the proposer (propose)
        # or the core (certify) stops them. Bounded maps — headers that
        # never certify and digests dropped on epoch reset age out.
        self.propose_timer = StageTimer(self.stage_latency, "propose", tracer=tracer)
        self.certify_timer = StageTimer(self.stage_latency, "certify", tracer=tracer)
        self.effective_header_delay = registry.gauge(
            "primary_effective_header_delay_seconds",
            "The adaptive header delay currently in force (floor when "
            "queues are shallow, max_header_delay under load)",
        )
        self.pacing_occupancy = registry.gauge(
            "primary_pacing_occupancy",
            "EWMA queue occupancy the proposer pacing controller reads",
        )
        self.headers_processed = registry.counter(
            "primary_headers_processed", "Headers accepted by the core"
        )
        self.headers_suspended = registry.counter(
            "primary_headers_suspended", "Headers parked awaiting parents/payload"
        )
        self.votes_processed = registry.counter(
            "primary_votes_processed", "Votes aggregated by the core"
        )
        self.certificates_processed = registry.counter(
            "primary_certificates_processed", "Certificates accepted by the core"
        )
        self.certificates_created = registry.counter(
            "primary_certificates_created", "Certificates assembled from our own headers"
        )
        self.certificates_suspended = registry.counter(
            "primary_certificates_suspended", "Certificates parked awaiting ancestors"
        )
        self.current_round = registry.gauge(
            "primary_current_round", "The proposer's current round"
        )
        self.proposed_headers = registry.counter(
            "primary_proposed_headers", "Headers proposed by this authority"
        )
        self.gc_round = registry.gauge(
            "primary_gc_round", "Last garbage-collected consensus round"
        )
        self.pending_header_waits = registry.gauge(
            "primary_pending_header_waits", "Headers pending in the header waiter"
        )
        self.pending_certificate_waits = registry.gauge(
            "primary_pending_certificate_waits",
            "Certificates pending in the certificate waiter",
        )
        self.sync_batch_requests = registry.counter(
            "primary_sync_batch_requests", "Synchronize commands sent to own workers"
        )
        self.sync_parent_requests = registry.counter(
            "primary_sync_parent_requests", "Parent-certificate fetches sent to peers"
        )
        self.votes_sent = registry.counter(
            "primary_votes_sent", "Votes sent to header authors"
        )
        self.core_burst = registry.histogram(
            "primary_core_burst_size",
            "messages the core drained per select iteration (greedy "
            "bounded burst; >1 means one grouped commit served several)",
            buckets=(1, 2, 4, 8, 16, 32, 64),
        )
        # -- payload-plane wire diet (fanout tree + delta headers) ---------
        self.round_egress_bytes = registry.gauge(
            "primary_round_egress_bytes",
            "Bytes this primary wrote to the wire between its two most "
            "recent own headers (MB/round from metrics, not log scraping)",
        )
        self.relay_broadcasts = registry.counter(
            "primary_relay_broadcasts",
            "Own announcements disseminated through the fanout tree "
            "instead of all-to-all",
        )
        self.relays_forwarded = registry.counter(
            "primary_relays_forwarded",
            "Relay envelopes forwarded to our children in a peer's tree",
        )
        self.relay_acks_received = registry.counter(
            "primary_relay_acks_received",
            "Receipt confirmations for our own fanout broadcasts",
        )
        self.relay_fallback_sends = registry.counter(
            "primary_relay_fallback_sends",
            "Direct reliable sends to peers un-acked past "
            "relay_fallback_timeout (the crashed-relay recovery path)",
        )
        self.delta_headers_rebuilt = registry.counter(
            "primary_delta_headers_rebuilt",
            "Delta header announcements reconstructed from the local "
            "recent-certificate index (no resync round trip)",
        )
        self.delta_resyncs = registry.counter(
            "primary_delta_resyncs",
            "Full-map resync requests sent because a delta header would "
            "not reconstruct (missing parent certificate or digest "
            "mismatch)",
        )
