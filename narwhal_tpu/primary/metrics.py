"""Primary metrics (/root/reference/primary/src/metrics.rs:51-485)."""

from __future__ import annotations

from ..metrics import Registry


class PrimaryMetrics:
    def __init__(self, registry: Registry):
        self.headers_processed = registry.counter(
            "primary_headers_processed", "Headers accepted by the core"
        )
        self.headers_suspended = registry.counter(
            "primary_headers_suspended", "Headers parked awaiting parents/payload"
        )
        self.votes_processed = registry.counter(
            "primary_votes_processed", "Votes aggregated by the core"
        )
        self.certificates_processed = registry.counter(
            "primary_certificates_processed", "Certificates accepted by the core"
        )
        self.certificates_created = registry.counter(
            "primary_certificates_created", "Certificates assembled from our own headers"
        )
        self.certificates_suspended = registry.counter(
            "primary_certificates_suspended", "Certificates parked awaiting ancestors"
        )
        self.current_round = registry.gauge(
            "primary_current_round", "The proposer's current round"
        )
        self.proposed_headers = registry.counter(
            "primary_proposed_headers", "Headers proposed by this authority"
        )
        self.gc_round = registry.gauge(
            "primary_gc_round", "Last garbage-collected consensus round"
        )
        self.pending_header_waits = registry.gauge(
            "primary_pending_header_waits", "Headers pending in the header waiter"
        )
        self.pending_certificate_waits = registry.gauge(
            "primary_pending_certificate_waits",
            "Certificates pending in the certificate waiter",
        )
        self.sync_batch_requests = registry.counter(
            "primary_sync_batch_requests", "Synchronize commands sent to own workers"
        )
        self.sync_parent_requests = registry.counter(
            "primary_sync_parent_requests", "Parent-certificate fetches sent to peers"
        )
        self.votes_sent = registry.counter(
            "primary_votes_sent", "Votes sent to header authors"
        )
        self.core_burst = registry.histogram(
            "primary_core_burst_size",
            "messages the core drained per select iteration (greedy "
            "bounded burst; >1 means one grouped commit served several)",
            buckets=(1, 2, 4, 8, 16, 32, 64),
        )
