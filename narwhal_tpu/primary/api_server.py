"""Public consensus API: the Validator / Proposer / Configuration services.

Reference: /root/reference/primary/src/grpc_server/{mod,validator,proposer,
configuration}.rs serving types/proto/narwhal.proto:127-152 over tonic on
`consensus_api_grpc_address`. Here the same surface is served over the
framework's typed RPC on its own listener:

- Validator.GetCollections  -> BlockWaiter (payload fetch via own workers)
- Validator.RemoveCollections -> BlockRemover (stores + workers + Dag)
- Validator.ReadCausal      -> Dag.read_causal
- Proposer.Rounds           -> Dag.rounds
- Proposer.NodeReadCausal   -> Dag.node_read_causal
- Configuration.NewEpoch    -> unimplemented (parity: configuration.rs:52-81)
- Configuration.NewNetworkInfo -> Committee.update_primary_network_info
- Configuration.GetPrimaryAddress
- Telemetry.Scrape          -> Registry.render (Prometheus text exposition)
- Telemetry.DumpFlightRecorder -> tracing.Tracer.dump (JSON)

The telemetry pair rides this typed listener so it is fabric-reachable
under simnet (grpc.aio binds real sockets and is skipped there).
"""

from __future__ import annotations

import json
import logging

from ..config import Committee
from ..messages import (
    FlightDumpMsg,
    FlightDumpResponse,
    GetCollectionsRequest,
    GetCollectionsResponse,
    GetPrimaryAddressRequest,
    GetPrimaryAddressResponse,
    NewEpochRequest,
    NewNetworkInfoRequest,
    NodeReadCausalRequest,
    ReadCausalRequest,
    ReadCausalResponse,
    RemoveCollectionsRequest,
    RoundsRequest,
    RoundsResponse,
    TelemetryScrapeMsg,
    TelemetryScrapeResponse,
)
from ..network import RpcServer
from ..types import PublicKey

logger = logging.getLogger("narwhal.primary.api")


class ConsensusApi:
    """Mounts the public API on its own RPC listener."""

    def __init__(
        self,
        name: PublicKey,
        committee,  # SharedCommittee-style holder with .load()/.swap() or Committee
        block_waiter,
        block_remover,
        dag=None,
        primary_address: str = "",
        max_concurrency: int = 100,
        registry=None,  # metrics.Registry: Telemetry.Scrape source
        tracer=None,  # tracing.Tracer: Telemetry.DumpFlightRecorder source
    ):
        self.name = name
        self._committee = committee
        self.block_waiter = block_waiter
        self.block_remover = block_remover
        self.dag = dag
        self.primary_address = primary_address
        self.registry = registry
        self.tracer = tracer
        self.server = RpcServer(max_concurrency)
        self.address: str = ""

    def _load_committee(self) -> Committee:
        load = getattr(self._committee, "load", None)
        return load() if load is not None else self._committee

    def set_primary_address(self, address: str) -> None:
        """Single write seam for the advertised primary address: the
        bound (possibly ephemeral) port only exists after Primary.spawn,
        so Node installs it here rather than poking the attribute."""
        self.primary_address = address

    async def spawn(self, address: str) -> str:
        host, port = address.rsplit(":", 1)
        bound = await self.server.start(host, int(port))
        self.address = f"{host}:{bound}"
        self.server.route(GetCollectionsRequest, self._on_get_collections)
        self.server.route(RemoveCollectionsRequest, self._on_remove_collections)
        self.server.route(ReadCausalRequest, self._on_read_causal)
        self.server.route(RoundsRequest, self._on_rounds)
        self.server.route(NodeReadCausalRequest, self._on_node_read_causal)
        self.server.route(NewEpochRequest, self._on_new_epoch)
        self.server.route(NewNetworkInfoRequest, self._on_new_network_info)
        self.server.route(GetPrimaryAddressRequest, self._on_get_primary_address)
        self.server.route(TelemetryScrapeMsg, self._on_scrape)
        self.server.route(FlightDumpMsg, self._on_flight_dump)
        logger.info("Consensus API listening on %s", self.address)
        return self.address

    async def shutdown(self) -> None:
        await self.server.stop()

    # -- Validator ---------------------------------------------------------

    async def _on_get_collections(self, msg: GetCollectionsRequest, peer: str):
        """(validator.rs GetCollections): batches or a per-digest error."""
        from .block_waiter import BlockError, BlockResponse

        results = []
        if not msg.digests:
            raise ValueError("Attempted fetch of no collections!")
        blocks = await self.block_waiter.get_blocks(list(msg.digests))
        for block in blocks:
            if isinstance(block, BlockResponse):
                results.append(
                    (
                        block.digest,
                        tuple(
                            (d, tuple(b.transactions)) for d, b in block.batches
                        ),
                        "",
                    )
                )
            else:
                results.append((block.digest, (), block.kind))
        return GetCollectionsResponse(tuple(results))

    async def _on_remove_collections(self, msg: RemoveCollectionsRequest, peer: str):
        if not msg.digests:
            raise ValueError("Attempted removal of no collections!")
        await self.block_remover.remove_blocks(list(msg.digests))
        return None  # Ack = Empty

    async def _on_read_causal(self, msg: ReadCausalRequest, peer: str):
        if self.dag is None:
            raise RuntimeError("ReadCausal needs the external consensus Dag")
        digests = await self.dag.read_causal(msg.digest)
        return ReadCausalResponse(tuple(digests))

    # -- Proposer ----------------------------------------------------------

    async def _on_rounds(self, msg: RoundsRequest, peer: str):
        if self.dag is None:
            raise RuntimeError("Rounds needs the external consensus Dag")
        committee = self._load_committee()
        if msg.public_key not in committee.authorities:
            raise ValueError("Invalid public key: unknown authority")
        oldest, newest = await self.dag.rounds(msg.public_key)
        return RoundsResponse(oldest, newest)

    async def _on_node_read_causal(self, msg: NodeReadCausalRequest, peer: str):
        if self.dag is None:
            raise RuntimeError("NodeReadCausal needs the external consensus Dag")
        digests = await self.dag.node_read_causal(msg.public_key, msg.round)
        return ReadCausalResponse(tuple(digests))

    # -- Configuration -----------------------------------------------------

    async def _on_new_epoch(self, msg: NewEpochRequest, peer: str):
        # Parity with the reference: parsed but not implemented
        # (configuration.rs:52-81).
        raise NotImplementedError(f"Not Implemented! epoch_number: {msg.epoch}")

    async def _on_new_network_info(self, msg: NewNetworkInfoRequest, peer: str):
        committee = self._load_committee()
        if msg.epoch != committee.epoch:
            raise ValueError(
                f"Passed in epoch {msg.epoch} does not match current epoch "
                f"{committee.epoch}"
            )
        info = {}
        for public_key, stake, address in msg.validators:
            if public_key not in committee.authorities:
                raise ValueError("Invalid public key: unknown authority")
            info[public_key] = (stake, address)
        committee.update_primary_network_info(info)
        return None

    async def _on_get_primary_address(self, msg: GetPrimaryAddressRequest, peer: str):
        return GetPrimaryAddressResponse(self.primary_address)

    # -- Telemetry ---------------------------------------------------------

    async def _on_scrape(self, msg: TelemetryScrapeMsg, peer: str):
        if self.registry is None:
            raise RuntimeError("Telemetry.Scrape: node mounted no registry")
        return TelemetryScrapeResponse(self.registry.render())

    async def _on_flight_dump(self, msg: FlightDumpMsg, peer: str):
        if self.tracer is None:
            raise RuntimeError(
                "Telemetry.DumpFlightRecorder: node mounted no tracer"
            )
        dump = self.tracer.dump(msg.max_events or None)
        return FlightDumpResponse(
            json.dumps(dump, sort_keys=True, separators=(",", ":")).encode()
        )
