"""BlockRemover: delete certificates and everything that hangs off them.

Reference: /root/reference/primary/src/block_remover.rs:39-648 — for a set of
certificate digests, instruct our workers to `DeleteBatches` for the grouped
payload, await their confirmations (with timeout), then clear the primary's
header/certificate/payload stores and the external Dag. Partial worker
failure aborts the store cleanup so a retry stays possible.
"""

from __future__ import annotations

import asyncio
import logging
from collections import defaultdict

from ..config import WorkerCache
from ..messages import DeleteBatchesMsg, DeletedBatchesMsg
from ..network import NetworkClient, RpcError
from ..stores import CertificateStore, HeaderStore, PayloadStore
from ..types import Certificate, Digest, PublicKey, WorkerId

logger = logging.getLogger("narwhal.primary")

REMOVE_TIMEOUT = 10.0


class BlockRemoverError(Exception):
    def __init__(self, digests: list[Digest], kind: str):
        super().__init__(f"remove failed ({kind}) for {len(digests)} blocks")
        self.digests = digests
        self.kind = kind  # "Timeout" | "Failed"


class BlockRemover:
    def __init__(
        self,
        name: PublicKey,
        worker_cache: WorkerCache,
        certificate_store: CertificateStore,
        header_store: HeaderStore,
        payload_store: PayloadStore,
        network: NetworkClient,
        dag=None,  # external consensus Dag, when running without internal
    ):
        self.name = name
        self.worker_cache = worker_cache
        self.certificate_store = certificate_store
        self.header_store = header_store
        self.payload_store = payload_store
        self.network = network
        self.dag = dag

    async def remove_blocks(self, digests: list[Digest]) -> None:
        certificates = [
            c for c in (self.certificate_store.read(d) for d in digests) if c is not None
        ]
        # Group payload per worker (block_remover.rs batches_by_worker).
        by_worker: dict[WorkerId, list[Digest]] = defaultdict(list)
        for cert in certificates:
            for batch_digest, worker_id in cert.header.payload.items():
                by_worker[worker_id].append(batch_digest)

        async def delete_at(worker_id: WorkerId, batch_digests: list[Digest]):
            info = self.worker_cache.worker(self.name, worker_id)
            resp: DeletedBatchesMsg = await self.network.request(
                info.worker_address, DeleteBatchesMsg(tuple(batch_digests))
            )
            return resp

        try:
            await asyncio.wait_for(
                asyncio.gather(*(delete_at(w, ds) for w, ds in by_worker.items())),
                REMOVE_TIMEOUT,
            )
        except asyncio.TimeoutError:
            raise BlockRemoverError(digests, "Timeout") from None
        except (RpcError, OSError, KeyError) as e:
            logger.warning("worker batch deletion failed: %s", e)
            raise BlockRemoverError(digests, "Failed") from None

        # Workers confirmed: now clean the primary stores + external Dag.
        if self.dag is not None:
            from ..consensus.dag import ValidatorDagError

            try:
                await self.dag.remove([c.digest for c in certificates])
            except ValidatorDagError as e:
                logger.debug("dag removal: %s", e)
        self.payload_store.delete_all(
            (bd, wid)
            for cert in certificates
            for bd, wid in cert.header.payload.items()
        )
        self.header_store.delete_all(c.header.digest for c in certificates)
        self.certificate_store.delete_all(c.digest for c in certificates)
