"""The Proposer: advances rounds and builds signed headers.

Reference: /root/reference/primary/src/proposer.rs:26-338. A new header needs a
quorum of round r-1 parent certificates (delivered as complete sets by the
core) plus either `header_size` bytes of batch digests or the
`max_header_delay` timer. Under partial synchrony, even rounds wait for the
leader's certificate and odd rounds for evidence that a quorum voted on the
leader (update_leader / enough_votes / ready, proposer.rs:131-217) so the
whole committee advances in lock-step with the leader when the network is
timely.
"""

from __future__ import annotations

import asyncio
import enum
import logging

from ..channels import Channel, Subscriber, Watch
from ..clock import now
from ..config import Committee
from ..crypto import SignatureService
from ..types import Certificate, Digest, Header, PublicKey, Round, WorkerId

logger = logging.getLogger("narwhal.primary")


class NetworkModel(enum.Enum):
    """(/root/reference/node/src/lib.rs:198-222): external consensus runs the
    DAG asynchronously; Bullshark assumes partial synchrony."""

    ASYNCHRONOUS = "asynchronous"
    PARTIALLY_SYNCHRONOUS = "partially_synchronous"


class Proposer:
    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        signature_service: SignatureService,
        header_size: int,
        max_header_delay: float,
        network_model: NetworkModel,
        rx_core: Channel,  # (parent certificates, round, epoch) from the core
        rx_workers: Channel,  # (batch digest, worker id) from our workers
        tx_core: Channel,  # our new headers to the core
        rx_reconfigure: Watch,
        metrics=None,
        pacing=None,  # pacing.PacingController: adaptive header delay
    ):
        self.name = name
        self.committee = committee
        self.signature_service = signature_service
        self.header_size = header_size
        self.max_header_delay = max_header_delay
        self.network_model = network_model
        self.rx_core = rx_core
        self.rx_workers = rx_workers
        self.tx_core = tx_core
        self.rx_reconfigure = Subscriber(rx_reconfigure)
        self.metrics = metrics
        self.pacing = pacing

        self.round: Round = 0
        self.last_parents: list[Certificate] = Certificate.genesis(committee)
        self.last_leader: Certificate | None = None
        self.digests: list[tuple[Digest, WorkerId]] = []
        self.payload_size = 0
        # When payload was last sighted — our own digests, or (via the
        # core's note_payload hook) ANY peer's payload-bearing header. Two
        # reasons this must outlive the payload itself: a committed
        # transaction needs the NEXT ~2 rounds too (Bullshark commits the
        # round-r leader once round r+2 exists), and round advance is gated
        # by a QUORUM of proposers — a node whose own worker saw no
        # transactions must still hurry while its peers carry payload, or
        # its idle-ceiling cadence paces the whole committee's commits.
        self._payload_seen_t = float("-inf")
        self.payload_grace = max(0.5, 3.0 * max_header_delay)
        self._task: asyncio.Task | None = None

    def spawn(self) -> asyncio.Task:
        self._task = asyncio.ensure_future(self.run())
        return self._task

    # -- leader gating (proposer.rs:131-217) ------------------------------
    def _update_leader(self) -> bool:
        """Even round: did we receive the current leader's certificate among
        the parents?"""
        leader = self.committee.leader(self.round)
        self.last_leader = next(
            (c for c in self.last_parents if c.origin == leader), None
        )
        return self.last_leader is not None

    def _enough_votes(self) -> bool:
        """Odd round: does the parent set prove the leader will (or cannot)
        get f+1 support at the even round below?"""
        if self.last_leader is None:
            return True
        leader_digest = self.last_leader.digest
        votes_for_leader = 0
        no_votes = 0
        for certificate in self.last_parents:
            stake = self.committee.stake(certificate.origin)
            if leader_digest in certificate.header.parents:
                votes_for_leader += stake
            else:
                no_votes += stake
        return (
            votes_for_leader >= self.committee.validity_threshold()
            or no_votes >= self.committee.quorum_threshold()
        )

    def _ready(self) -> bool:
        if self.network_model is NetworkModel.ASYNCHRONOUS:
            return True
        if self.round % 2 == 0:
            return self._update_leader()
        return self._enough_votes()

    # -- header construction ----------------------------------------------
    async def _make_header(self) -> None:
        if self.digests:
            self._payload_seen_t = now()
        header = Header.build(
            self.name,
            self.round,
            self.committee.epoch,
            dict(self.digests),
            {c.digest for c in self.last_parents},
            self.signature_service,
        )
        if self.metrics is not None:
            # Stage tracing: digest arrival -> included in a header, and the
            # certify clock this header's certificate will stop in the core.
            # The causal key hops here — batch digests fold into the header
            # digest — so record the link edges the waterfall joins on.
            tracer = self.metrics.tracer
            trace = tracer is not None and tracer.enabled
            for digest, _ in self.digests:
                self.metrics.propose_timer.stop(digest)
                if trace and tracer.sampled(digest):
                    tracer.link("propose", digest, header.digest)
            self.metrics.certify_timer.start(header.digest)
        self.digests.clear()
        self.payload_size = 0
        self.last_parents = []
        # Benchmark-parsed creation lines (proposer.rs:110-121): one line per
        # payload batch so the harness can tie batches to proposals.
        logger.info("Created B%s(%s)", header.round, header.digest.hex())
        for batch_digest in header.payload:
            logger.info(
                "Created B%s(%s) -> %s",
                header.round,
                header.digest.hex(),
                batch_digest.hex(),
            )
        if self.metrics is not None:
            self.metrics.proposed_headers.inc()
        await self.tx_core.send(header)

    def note_payload(self) -> None:
        """Committee-wide payload sighting (wired by Primary to the core's
        header path): a peer's payload-bearing header keeps THIS node's
        proposer on the floor cadence so the quorum advances rounds fast
        enough to commit it."""
        self._payload_seen_t = now()

    def _header_delay(self) -> float:
        """The effective header delay for this loop iteration. With a
        pacing controller the delay adapts between its floor and
        max_header_delay on queue occupancy — while payload is pending OR
        within payload_grace of the last sighting (the rounds that complete
        the last payload's commit). A genuinely idle proposer keeps the
        configured ceiling, so an unloaded committee does not spin empty
        rounds at the floor cadence forever (every round costs a header
        broadcast plus a quorum of votes)."""
        payload_active = (
            bool(self.digests)
            or not self.rx_workers.empty()
            or now() - self._payload_seen_t < self.payload_grace
        )
        if self.pacing is not None and payload_active:
            delay = self.pacing.delay()
        else:
            if self.pacing is not None:
                self.pacing.observe()  # keep the EWMA live across idle gaps
            delay = self.max_header_delay
        if self.metrics is not None:
            self.metrics.effective_header_delay.set(delay)
        return delay

    async def run(self) -> None:
        last_header_t = now()
        parents_task = asyncio.ensure_future(self.rx_core.recv())
        digest_task = asyncio.ensure_future(self.rx_workers.recv())
        recon_task = asyncio.ensure_future(self.rx_reconfigure.changed())
        try:
            while True:
                # Fixed deadline measured from the last proposed header,
                # recomputed each iteration so pacing changes (queues
                # draining or filling) take effect mid-round.
                timer_deadline = last_header_t + self._header_delay()
                enough_parents = bool(self.last_parents)
                enough_digests = self.payload_size >= self.header_size
                timer_expired = now() >= timer_deadline
                # The timer overrides the leader gating so the DAG cannot
                # stall when the leader is slow or faulty (proposer.rs:219-252).
                if (timer_expired or (enough_digests and self._ready())) and enough_parents:
                    if timer_expired and self.network_model is NetworkModel.PARTIALLY_SYNCHRONOUS:
                        logger.debug("Timer expired for round %s", self.round)
                    self.round += 1
                    if self.metrics is not None:
                        self.metrics.current_round.set(self.round)
                    logger.debug("Dag moved to round %s", self.round)
                    await self._make_header()
                    last_header_t = now()
                    timer_deadline = last_header_t + self._header_delay()

                # Past the deadline nothing changes until a message lands:
                # wait un-timed instead of polling with timeout=0 (with
                # floor-level delays that poll would busy-yield the loop
                # for the whole parent-quorum wait).
                remaining = timer_deadline - now()
                timeout = None if remaining <= 0 else remaining
                done, _ = await asyncio.wait(
                    {parents_task, digest_task, recon_task},
                    timeout=timeout,
                    return_when=asyncio.FIRST_COMPLETED,
                )
                if recon_task in done:
                    note = recon_task.result()
                    if note.kind == "shutdown":
                        return
                    if note.committee is not None:
                        self.committee = note.committee
                        self.round = 0
                        self.last_parents = Certificate.genesis(self.committee)
                        self.digests.clear()
                        self.payload_size = 0
                        logger.info("Proposer reset for epoch %s", self.committee.epoch)
                    recon_task = asyncio.ensure_future(self.rx_reconfigure.changed())
                if parents_task in done:
                    parents, round_, epoch = parents_task.result()
                    parents_task = asyncio.ensure_future(self.rx_core.recv())
                    if epoch == self.committee.epoch:
                        if round_ > self.round:
                            # Jump to the parents' round: propose on top of
                            # them (proposer.rs:254-282).
                            self.round = round_
                            self.last_parents = parents
                        elif round_ == self.round:
                            # Post-quorum stragglers for the current round
                            # (e.g. the leader's certificate) extend the
                            # parent set rather than replace it.
                            self.last_parents.extend(parents)
                if digest_task in done:
                    digest, worker_id = digest_task.result()
                    digest_task = asyncio.ensure_future(self.rx_workers.recv())
                    self.digests.append((digest, worker_id))
                    self.payload_size += len(digest)
                    if self.metrics is not None:
                        self.metrics.propose_timer.start(digest)
        finally:
            parents_task.cancel()
            digest_task.cancel()
            recon_task.cancel()
