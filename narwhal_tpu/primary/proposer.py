"""The Proposer: advances rounds and builds signed headers.

Reference: /root/reference/primary/src/proposer.rs:26-338. A new header needs a
quorum of round r-1 parent certificates (delivered as complete sets by the
core) plus either `header_size` bytes of batch digests or the
`max_header_delay` timer. Under partial synchrony, even rounds wait for the
leader's certificate and odd rounds for evidence that a quorum voted on the
leader (update_leader / enough_votes / ready, proposer.rs:131-217) so the
whole committee advances in lock-step with the leader when the network is
timely.
"""

from __future__ import annotations

import asyncio
import enum
import logging
import time

from ..channels import Channel, Subscriber, Watch
from ..config import Committee
from ..crypto import SignatureService
from ..types import Certificate, Digest, Header, PublicKey, Round, WorkerId

logger = logging.getLogger("narwhal.primary")


class NetworkModel(enum.Enum):
    """(/root/reference/node/src/lib.rs:198-222): external consensus runs the
    DAG asynchronously; Bullshark assumes partial synchrony."""

    ASYNCHRONOUS = "asynchronous"
    PARTIALLY_SYNCHRONOUS = "partially_synchronous"


class Proposer:
    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        signature_service: SignatureService,
        header_size: int,
        max_header_delay: float,
        network_model: NetworkModel,
        rx_core: Channel,  # (parent certificates, round, epoch) from the core
        rx_workers: Channel,  # (batch digest, worker id) from our workers
        tx_core: Channel,  # our new headers to the core
        rx_reconfigure: Watch,
        metrics=None,
    ):
        self.name = name
        self.committee = committee
        self.signature_service = signature_service
        self.header_size = header_size
        self.max_header_delay = max_header_delay
        self.network_model = network_model
        self.rx_core = rx_core
        self.rx_workers = rx_workers
        self.tx_core = tx_core
        self.rx_reconfigure = Subscriber(rx_reconfigure)
        self.metrics = metrics

        self.round: Round = 0
        self.last_parents: list[Certificate] = Certificate.genesis(committee)
        self.last_leader: Certificate | None = None
        self.digests: list[tuple[Digest, WorkerId]] = []
        self.payload_size = 0
        self._task: asyncio.Task | None = None

    def spawn(self) -> asyncio.Task:
        self._task = asyncio.ensure_future(self.run())
        return self._task

    # -- leader gating (proposer.rs:131-217) ------------------------------
    def _update_leader(self) -> bool:
        """Even round: did we receive the current leader's certificate among
        the parents?"""
        leader = self.committee.leader(self.round)
        self.last_leader = next(
            (c for c in self.last_parents if c.origin == leader), None
        )
        return self.last_leader is not None

    def _enough_votes(self) -> bool:
        """Odd round: does the parent set prove the leader will (or cannot)
        get f+1 support at the even round below?"""
        if self.last_leader is None:
            return True
        leader_digest = self.last_leader.digest
        votes_for_leader = 0
        no_votes = 0
        for certificate in self.last_parents:
            stake = self.committee.stake(certificate.origin)
            if leader_digest in certificate.header.parents:
                votes_for_leader += stake
            else:
                no_votes += stake
        return (
            votes_for_leader >= self.committee.validity_threshold()
            or no_votes >= self.committee.quorum_threshold()
        )

    def _ready(self) -> bool:
        if self.network_model is NetworkModel.ASYNCHRONOUS:
            return True
        if self.round % 2 == 0:
            return self._update_leader()
        return self._enough_votes()

    # -- header construction ----------------------------------------------
    async def _make_header(self) -> None:
        header = Header.build(
            self.name,
            self.round,
            self.committee.epoch,
            dict(self.digests),
            {c.digest for c in self.last_parents},
            self.signature_service,
        )
        self.digests.clear()
        self.payload_size = 0
        self.last_parents = []
        # Benchmark-parsed creation lines (proposer.rs:110-121): one line per
        # payload batch so the harness can tie batches to proposals.
        logger.info("Created B%s(%s)", header.round, header.digest.hex())
        for batch_digest in header.payload:
            logger.info(
                "Created B%s(%s) -> %s",
                header.round,
                header.digest.hex(),
                batch_digest.hex(),
            )
        if self.metrics is not None:
            self.metrics.proposed_headers.inc()
        await self.tx_core.send(header)

    async def run(self) -> None:
        timer_deadline = time.monotonic() + self.max_header_delay
        parents_task = asyncio.ensure_future(self.rx_core.recv())
        digest_task = asyncio.ensure_future(self.rx_workers.recv())
        recon_task = asyncio.ensure_future(self.rx_reconfigure.changed())
        try:
            while True:
                enough_parents = bool(self.last_parents)
                enough_digests = self.payload_size >= self.header_size
                timer_expired = time.monotonic() >= timer_deadline
                # The timer overrides the leader gating so the DAG cannot
                # stall when the leader is slow or faulty (proposer.rs:219-252).
                if (timer_expired or (enough_digests and self._ready())) and enough_parents:
                    if timer_expired and self.network_model is NetworkModel.PARTIALLY_SYNCHRONOUS:
                        logger.debug("Timer expired for round %s", self.round)
                    self.round += 1
                    if self.metrics is not None:
                        self.metrics.current_round.set(self.round)
                    logger.debug("Dag moved to round %s", self.round)
                    await self._make_header()
                    timer_deadline = time.monotonic() + self.max_header_delay

                timeout = max(0.0, timer_deadline - time.monotonic())
                done, _ = await asyncio.wait(
                    {parents_task, digest_task, recon_task},
                    timeout=timeout,
                    return_when=asyncio.FIRST_COMPLETED,
                )
                if recon_task in done:
                    note = recon_task.result()
                    if note.kind == "shutdown":
                        return
                    if note.committee is not None:
                        self.committee = note.committee
                        self.round = 0
                        self.last_parents = Certificate.genesis(self.committee)
                        self.digests.clear()
                        self.payload_size = 0
                        logger.info("Proposer reset for epoch %s", self.committee.epoch)
                    recon_task = asyncio.ensure_future(self.rx_reconfigure.changed())
                if parents_task in done:
                    parents, round_, epoch = parents_task.result()
                    parents_task = asyncio.ensure_future(self.rx_core.recv())
                    if epoch == self.committee.epoch:
                        if round_ > self.round:
                            # Jump to the parents' round: propose on top of
                            # them (proposer.rs:254-282).
                            self.round = round_
                            self.last_parents = parents
                        elif round_ == self.round:
                            # Post-quorum stragglers for the current round
                            # (e.g. the leader's certificate) extend the
                            # parent set rather than replace it.
                            self.last_parents.extend(parents)
                if digest_task in done:
                    digest, worker_id = digest_task.result()
                    digest_task = asyncio.ensure_future(self.rx_workers.recv())
                    self.digests.append((digest, worker_id))
                    self.payload_size += len(digest)
        finally:
            parents_task.cancel()
            digest_task.cancel()
            recon_task.cancel()
