"""Fanout-tree dissemination: O(fanout) origin egress instead of all-to-all.

Reference Narwhal broadcasts headers and certificates primary-to-primary
all-to-all (core.rs:149-179), which concentrates O(N) egress per round on
every origin — 13.7 MB/round at N=10@1k and O(N^2) toward the N=100 target.
This module spreads that egress over a deterministic, stake-weighted relay
tree, recomputed per (epoch, round, origin) so relay positions rotate and no
authority is a permanent interior node:

- Ordering: every node derives the same priority for each peer —
  `ticket = int(digest256(seed || pk)[:16]) // stake` sorted ascending — a
  pure-integer, platform-deterministic stake-weighted shuffle (higher stake
  => statistically earlier => closer to the root, carrying more relay duty,
  matching its resources). The seed binds epoch, round and origin.
- Topology: a complete `fanout`-ary heap over [origin] + ordering; children
  of position j are positions fanout*j+1 .. fanout*j+fanout. Depth >= 2
  whenever the committee has more others than the fanout (below that the
  broadcaster degrades to plain direct broadcast — a flat tree would only
  add envelope overhead).
- Transport: the origin reliable-sends a `RelayMsg` envelope (raw inner
  wire bytes, never re-encoded) to its direct children; every receiver
  delivers the inner message locally, forwards the unchanged envelope to
  its own children, and confirms receipt to the origin with a tiny
  `RelayAckMsg` (direct children are confirmed by the relay RPC ack
  itself).
- Reliability: reliable-broadcast semantics are preserved by a fallback —
  after `relay_fallback_timeout` the origin direct-sends the ORIGINAL
  message (reliable, retry-forever like the reference's broadcast) to every
  peer it has not heard from, so a crashed or byzantine-silent relay only
  delays its subtree by one timeout, never partitions it. All handles are
  round-keyed and cancelled at garbage collection, exactly like the core's
  cancel_handlers.
"""

from __future__ import annotations

import asyncio
import logging

from ..bounded_cache import BoundedCache
from ..clock import now
from ..channels import CancelOnDrop
from ..codec import Reader, Writer
from ..config import Committee
from ..crypto import DIGEST_LEN, digest256
from ..messages import (
    CertificateRefMsg,
    DeltaHeaderMsg,
    Relay2Msg,
    RelayAck2Msg,
    RelayAckMsg,
    RelayMsg,
    decode_message,
    encode_message,
)
from ..network import NetworkClient
from ..types import Digest, PublicKey, Round

logger = logging.getLogger("narwhal.primary")

# Relay2Msg body kinds (messages.Relay2Msg docstring).
R2_GENERIC = 0
R2_DELTA_HEADER = 1
R2_CERT_REF = 2


def _bitmap(indices, size: int) -> bytes:
    out = bytearray(-(-size // 8))
    for i in indices:
        out[i >> 3] |= 1 << (i & 7)
    return bytes(out)


def _bitmap_indices(bitmap: bytes) -> list[int]:
    return [
        (byte_i << 3) + bit
        for byte_i, b in enumerate(bitmap)
        for bit in range(8)
        if b & (1 << bit)
    ]


def encode_relay2(committee: Committee, name: PublicKey, round: Round, msg) -> Relay2Msg | None:
    """The slim relay envelope for our own announcement, or None when the
    slim ranges don't fit (huge round/epoch, foreign origin) — the caller
    then falls back to the legacy RelayMsg. Announcement fields duplicated
    by the envelope (origin, round, epoch) are DROPPED from the body; the
    receiver's decode_relay2 reconstitutes the exact fat message, so the
    resolution paths downstream never know the diet happened."""
    epoch = committee.epoch
    if round >= 1 << 32 or epoch >= 1 << 16:
        return None
    try:
        origin_index = committee.index_of(name)
    except KeyError:
        return None
    n = committee.size()
    w = Writer()
    if (
        isinstance(msg, CertificateRefMsg)
        and msg.origin == name
        and msg.round == round
        and msg.epoch == epoch
        and len(msg.agg_s) == 32
        and len(msg.rs) == len(msg.signers)
        and all(len(r) == 32 for r in msg.rs)
        and all(0 <= i < n for i in msg.signers)
        and list(msg.signers) == sorted(set(msg.signers))
    ):
        w.raw(msg.header_digest)
        w.raw(msg.agg_s)
        w.bytes(_bitmap(msg.signers, n))
        for r in msg.rs:  # signer-index order == ascending bitmap order
            w.raw(r)
        return Relay2Msg(origin_index, round, epoch, R2_CERT_REF, w.finish())
    if (
        isinstance(msg, DeltaHeaderMsg)
        and msg.author == name
        and msg.round == round
        and msg.epoch == epoch
        and len(msg.signature) == 64
        and all(0 <= i < n for i in msg.parent_indices)
        and list(msg.parent_indices) == sorted(set(msg.parent_indices))
        and all(0 <= wid < 1 << 16 for _, wid in msg.payload)
    ):
        w.raw(msg.header_digest)
        w.bytes(_bitmap(msg.parent_indices, n))
        w.raw(msg.signature)

        def enc_pair(w_: Writer, item) -> None:
            w_.raw(item[0])
            w_.u16(item[1])

        w.seq(msg.payload, enc_pair)
        return Relay2Msg(origin_index, round, epoch, R2_DELTA_HEADER, w.finish())
    tag, body = encode_message(msg)
    w.u16(tag)
    w.raw(body)
    return Relay2Msg(origin_index, round, epoch, R2_GENERIC, w.finish())


def decode_relay2(committee: Committee, msg: Relay2Msg):
    """Reconstitute the fat announcement a Relay2Msg carries. Raises
    ValueError/CodecError on anything malformed — byzantine envelopes can
    only be dropped (and the origin's own tree position is derived from the
    envelope, so a forged origin only mis-roots a tree the inner message's
    signature checks still gate)."""
    keys = committee.authority_keys()
    if msg.origin_index >= len(keys):
        raise ValueError(f"origin index {msg.origin_index} out of range")
    origin = keys[msg.origin_index]
    r = Reader(msg.body)
    if msg.kind == R2_GENERIC:
        tag = r.u16()
        return decode_message(tag, r.rest())
    if msg.kind == R2_CERT_REF:
        header_digest = r.raw(DIGEST_LEN)
        agg_s = r.raw(32)
        signers = tuple(_bitmap_indices(r.bytes()))
        if any(i >= len(keys) for i in signers):
            raise ValueError("signer bitmap exceeds committee")
        rs = tuple(r.raw(32) for _ in signers)
        r.done()
        return CertificateRefMsg(
            header_digest, msg.round, msg.epoch, origin, signers, rs, agg_s
        )
    if msg.kind == R2_DELTA_HEADER:
        header_digest = r.raw(DIGEST_LEN)
        parents = tuple(_bitmap_indices(r.bytes()))
        if any(i >= len(keys) for i in parents):
            raise ValueError("parent bitmap exceeds committee")
        signature = r.raw(64)
        payload = tuple(r.seq(lambda r_: (r_.raw(DIGEST_LEN), r_.u16())))
        r.done()
        return DeltaHeaderMsg(
            origin, msg.round, msg.epoch, header_digest, payload, parents, signature
        )
    raise ValueError(f"unknown relay2 kind {msg.kind}")


def relay_order(committee: Committee, epoch: int, round: Round, origin: PublicKey) -> list[PublicKey]:
    """Deterministic stake-weighted ordering of the origin's peers for the
    (epoch, round, origin) tree. Pure integer math so every implementation
    agrees bit-for-bit (the committee.leader discipline)."""
    seed = digest256(
        b"relay-tree"
        + int(epoch).to_bytes(8, "little")
        + int(round).to_bytes(8, "little")
        + origin
    )
    def ticket(pk: PublicKey) -> tuple[int, PublicKey]:
        stake = max(1, committee.stake(pk))
        return (int.from_bytes(digest256(seed + pk)[:16], "little") // stake, pk)

    return sorted(
        (pk for pk in committee.authority_keys() if pk != origin), key=ticket
    )


def relay_children(
    committee: Committee,
    epoch: int,
    round: Round,
    origin: PublicKey,
    me: PublicKey,
    fanout: int,
) -> list[PublicKey]:
    """My children in the (epoch, round, origin)-rooted tree (empty when I
    am a leaf or not a committee member for this epoch)."""
    order = relay_order(committee, epoch, round, origin)
    seq = [origin] + order
    try:
        j = seq.index(me)
    except ValueError:
        return []
    return seq[fanout * j + 1 : fanout * j + 1 + fanout]


class _TreeCache:
    """Bounded memo of relay orderings: every node derives each
    (epoch, round, origin) tree at least twice per round (the origin's
    header AND certificate broadcasts), and at N=50 each derivation is ~N
    digest256 tickets — measurable on a starved host. FIFO-bounded so a
    byzantine round/origin spray cannot grow it."""

    def __init__(self, capacity: int = 512):
        self._cache: dict[tuple, list[PublicKey]] = {}
        self._capacity = capacity

    def order(
        self, committee: Committee, epoch: int, round: Round, origin: PublicKey
    ) -> list[PublicKey]:
        key = (epoch, round, origin)
        cached = self._cache.get(key)
        if cached is None:
            cached = relay_order(committee, epoch, round, origin)
            while len(self._cache) >= self._capacity:
                del self._cache[next(iter(self._cache))]
            self._cache[key] = cached
        return cached

    def children(
        self,
        committee: Committee,
        epoch: int,
        round: Round,
        origin: PublicKey,
        me: PublicKey,
        fanout: int,
    ) -> list[PublicKey]:
        seq = [origin] + self.order(committee, epoch, round, origin)
        try:
            j = seq.index(me)
        except ValueError:
            return []
        return seq[fanout * j + 1 : fanout * j + 1 + fanout]

    def clear(self) -> None:
        self._cache.clear()


class FanoutBroadcaster:
    """Owns the relay plane of one primary: origin-side broadcasts with ack
    tracking + fallback, relay-side forwarding, and round-keyed handle GC."""

    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        network: NetworkClient,
        fanout: int,
        fallback_timeout: float,
        metrics=None,
    ):
        self.name = name
        self.network = network
        self.fanout = fanout
        self.fallback_timeout = fallback_timeout
        self.metrics = metrics
        # Reliable-send + fallback-task handles by round, cancelled at GC
        # (the cancel_handlers discipline of core.rs).
        self._round_handles: dict[Round, list] = {}
        # ack_id -> authorities confirmed (via RelayAckMsg or a completed
        # child send), for our own in-flight broadcasts only.
        self._acks: dict[Digest, set[PublicKey]] = {}
        self._ack_round: dict[Digest, Round] = {}
        self._ack_t0: dict[Digest, float] = {}
        # Observed broadcast->ack latency EWMA. The configured
        # fallback_timeout is a FLOOR, not the deadline: a CPU-starved
        # committee (N=50+ on a small host) legitimately takes seconds to
        # relay + ack, and falling back on a wall-clock guess re-sends the
        # whole broadcast direct — measured at N=50 this DOUBLED wire
        # bytes/round and halved rounds/s. Waiting ~4 observed latencies
        # keeps the fallback a crash-recovery path, not a steady-state one.
        self._ack_latency_ewma: float | None = None
        # round -> ack_id of OUR header broadcast at that round: votes are
        # implicit receipt confirmations (a vote travels to the broadcast's
        # origin — us — and proves the voter processed the header), so
        # receivers skip the explicit RelayAck2Msg for slim header relays
        # entirely. Peers that receive but cannot vote (suspended on
        # missing parents/payload) simply get one fallback direct send —
        # dedup'd on arrival, and the vote-fed latency EWMA keeps that
        # fallback deadline honest under load.
        self._header_ack_ids: dict[Round, Digest] = {}
        # Short-lived best-effort tasks (ack sends), kept strongly.
        self._tasks: set[asyncio.Task] = set()
        # ack_ids whose envelope we already forwarded to our children:
        # duplicate copies of the same broadcast (several relayers share us
        # as a child) still ACK per copy — the origin's fallback timer needs
        # every receipt — but re-forwarding each copy would re-amplify the
        # whole subtree O(copies) times. Bounded FIFO; capacity comfortably
        # covers the in-flight rounds of the largest committees.
        self._forwarded = BoundedCache(max_entries=8192)
        self._trees = _TreeCache()
        self.change_epoch(committee)

    # -- configuration -----------------------------------------------------
    def relaying(self) -> bool:
        """Relay only when the tree has depth >= 2; a flat tree is just a
        direct broadcast wearing an envelope."""
        return 0 < self.fanout < self.committee.size() - 1

    # -- origin side -------------------------------------------------------
    def broadcast(self, round: Round, msg) -> list:
        """Disseminate our own header/certificate announcement. Returns the
        handles the caller should treat like network.broadcast handles
        (this object ALSO tracks them for its own GC, so callers may simply
        drop the return value)."""
        others = self.committee.others_primaries(self.name)
        if not self.relaying():
            handles = self.network.broadcast([a for _, a, _ in others], msg)
            self._round_handles.setdefault(round, []).extend(handles)
            return handles
        relay = encode_relay2(self.committee, self.name, round, msg)
        if relay is not None:
            ack_id = relay.ack_id
        else:  # slim ranges don't fit: legacy fat envelope
            tag, body = encode_message(msg)
            ack_id = digest256(tag.to_bytes(2, "little") + body)
            relay = RelayMsg(self.name, round, self.committee.epoch, tag, body)
        children = self._trees.children(
            self.committee, self.committee.epoch, round, self.name, self.name,
            self.fanout,
        )
        acked: set[PublicKey] = set()
        self._acks[ack_id] = acked
        self._ack_round[ack_id] = round
        self._ack_t0[ack_id] = now()
        if isinstance(relay, Relay2Msg) and relay.kind == R2_DELTA_HEADER:
            self._header_ack_ids[round] = ack_id
        handles = []
        # Per-attempt deadline scaled to observed relay reality (like the
        # fallback deadline): a fixed 10 s deadline on a committee whose
        # broadcasts take seconds re-sends kilobyte envelopes to SLOW peers
        # — pure wire waste the receiver dedups.
        send_timeout = max(10.0, self._fallback_delay())
        for child in children:
            handle = self.network.send(
                self.committee.primary_address(child), relay,
                timeout=send_timeout,
            )
            handle.task.add_done_callback(
                lambda t, pk=child, a=ack_id: (
                    self._mark_acked(a, pk)
                    if not t.cancelled() and t.exception() is None
                    else None
                )
            )
            handles.append(handle)
        fallback = asyncio.ensure_future(
            self._fallback(ack_id, round, msg, [pk for pk, _, _ in others])
        )
        handles.append(CancelOnDrop(fallback))
        self._round_handles.setdefault(round, []).extend(handles)
        if self.metrics is not None:
            self.metrics.relay_broadcasts.inc()
        return handles

    def _mark_acked(self, ack_id: Digest, pk: PublicKey) -> None:
        acked = self._acks.get(ack_id)
        if acked is None or pk in acked:
            return
        acked.add(pk)
        t0 = self._ack_t0.get(ack_id)
        if t0 is not None:
            latency = now() - t0
            prev = self._ack_latency_ewma
            self._ack_latency_ewma = (
                latency if prev is None else 0.2 * latency + 0.8 * prev
            )

    def _fallback_delay(self) -> float:
        """The configured timeout floored against observed relay reality: a
        committee whose broadcasts take seconds end-to-end must not pay a
        full direct re-broadcast every round for being slow."""
        ewma = self._ack_latency_ewma
        if ewma is None:
            return self.fallback_timeout
        return min(60.0, max(self.fallback_timeout, 4.0 * ewma))

    async def _fallback(
        self, ack_id: Digest, round: Round, msg, targets: list[PublicKey]
    ) -> None:
        await asyncio.sleep(self._fallback_delay())
        acked = self._acks.get(ack_id, set())
        missing = [pk for pk in targets if pk not in acked]
        if not missing:
            return
        logger.debug(
            "relay fallback round %s: direct-sending to %d un-acked peers",
            round,
            len(missing),
        )
        if self.metrics is not None:
            self.metrics.relay_fallback_sends.inc(len(missing))
        send_timeout = max(10.0, self._fallback_delay())
        handles = [
            self.network.send(
                self.committee.primary_address(pk), msg, timeout=send_timeout
            )
            for pk in missing
        ]
        self._round_handles.setdefault(round, []).extend(handles)

    # -- relay side --------------------------------------------------------
    def on_relay(self, msg: RelayMsg) -> None:
        """Forward the unchanged LEGACY envelope to our children in the
        origin's tree and confirm receipt to the origin. Local delivery of
        the inner message is the caller's job (Primary routes it through
        the normal ingest paths). Non-blocking: forwards are reliable-send
        background handles, the ack a tracked best-effort task."""
        if msg.epoch != self.committee.epoch or msg.origin == self.name:
            # Cross-epoch relays can't place us in a tree we agree on; the
            # inner message still buffers/drops through the core's epoch
            # logic, and the origin's fallback covers our would-be subtree.
            return
        ack_id = msg.ack_id
        if self._forwarded.get(ack_id) is None:
            self._forwarded.put(ack_id, True)
            children = self._trees.children(
                self.committee, msg.epoch, msg.round, msg.origin, self.name,
                self.fanout,
            )
            forwards = [
                self.network.send(self.committee.primary_address(child), msg)
                for child in children
                if child != msg.origin
            ]
            self._round_handles.setdefault(msg.round, []).extend(forwards)
            if self.metrics is not None and forwards:
                self.metrics.relays_forwarded.inc(len(forwards))
        try:
            origin_address = self.committee.primary_address(msg.origin)
        except KeyError:
            return
        task = asyncio.ensure_future(
            self.network.unreliable_send(
                origin_address, RelayAckMsg(ack_id, self.name), timeout=5.0
            )
        )
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    def on_relay2(self, msg: Relay2Msg, origin: PublicKey) -> None:
        """Relay2 receive side: forward the unchanged slim envelope to our
        children and ack the origin — both as fire-and-forget KIND_ONEWAY
        frames. The per-hop RPC Ack and retry machinery are deliberately
        skipped: delivery of the WHOLE broadcast is guaranteed by the
        origin's ack tracking + direct fallback, so a frame lost on a torn
        connection costs one fallback send, while the removed response
        frames and deadline resends were ~10% of all control-plane bytes
        at N=50."""
        if msg.epoch != self.committee.epoch or origin == self.name:
            return
        ack_id = msg.ack_id
        sends = []
        if self._forwarded.get(ack_id) is None:
            self._forwarded.put(ack_id, True)
            children = self._trees.children(
                self.committee, msg.epoch, msg.round, origin, self.name,
                self.fanout,
            )
            sends = [
                self.network.oneway_send(self.committee.primary_address(child), msg)
                for child in children
                if child != origin
            ]
            if self.metrics is not None and sends:
                self.metrics.relays_forwarded.inc(len(sends))
        try:
            my_index = self.committee.index_of(self.name)
            origin_address = self.committee.primary_address(origin)
        except KeyError:
            my_index = None
        # Slim header relays are acked IMPLICITLY by the vote we send the
        # author (note_vote at the origin); only non-header relays need an
        # explicit receipt.
        if my_index is not None and msg.kind != R2_DELTA_HEADER:
            sends.append(
                self.network.oneway_send(
                    origin_address, RelayAck2Msg(ack_id, my_index)
                )
            )
        for coro in sends:
            task = asyncio.ensure_future(coro)
            self._tasks.add(task)
            task.add_done_callback(self._tasks.discard)

    def note_vote(self, round: Round, voter: PublicKey) -> None:
        """A vote for OUR round-`round` header arrived: the voter provably
        received (and processed) the header broadcast — the implicit
        receipt that replaces explicit RelayAck2Msg frames on the slim
        header lane."""
        ack_id = self._header_ack_ids.get(round)
        if ack_id is not None:
            self._mark_acked(ack_id, voter)

    def on_ack2(self, msg: RelayAck2Msg, peer_key: PublicKey | None) -> None:
        """Slim receipt confirmation: handshake-verified identity wins, the
        carried committee index is only trusted on open meshes (the
        RelayAckMsg discipline)."""
        if peer_key is not None:
            acker = self._authority_of_network_key.get(peer_key)
        else:
            keys = self.committee.authority_keys()
            acker = (
                keys[msg.acker_index] if msg.acker_index < len(keys) else None
            )
        if acker is None or msg.ack_id not in self._acks:
            return
        self._mark_acked(msg.ack_id, acker)
        if self.metrics is not None:
            self.metrics.relay_acks_received.inc()

    def on_ack(self, msg: RelayAckMsg, peer_key: PublicKey | None) -> None:
        """Record a receipt confirmation. The acker identity comes from the
        handshake-verified peer network key when the mesh is authenticated;
        the carried name is only trusted on open (bare-test) meshes — a
        byzantine peer must not be able to suppress another peer's
        fallback delivery by acking on its behalf."""
        acker = (
            self._authority_of_network_key.get(peer_key)
            if peer_key is not None
            else msg.acker
        )
        if acker is None or msg.ack_id not in self._acks:
            return
        self._mark_acked(msg.ack_id, acker)
        if self.metrics is not None:
            self.metrics.relay_acks_received.inc()

    # -- lifecycle ---------------------------------------------------------
    def gc(self, gc_round: Round) -> None:
        for r in [r for r in self._round_handles if r <= gc_round]:
            for handle in self._round_handles.pop(r):
                handle.cancel()
        for ack_id in [
            a for a, r in self._ack_round.items() if r <= gc_round
        ]:
            del self._ack_round[ack_id]
            self._acks.pop(ack_id, None)
            self._ack_t0.pop(ack_id, None)
        for r in [r for r in self._header_ack_ids if r <= gc_round]:
            del self._header_ack_ids[r]

    def change_epoch(self, committee: Committee) -> None:
        self.committee = committee
        self._authority_of_network_key: dict[PublicKey, PublicKey] = {
            a.network_key: pk for pk, a in committee.authorities.items()
        }
        for handles in self._round_handles.values():
            for handle in handles:
                handle.cancel()
        self._round_handles.clear()
        self._acks.clear()
        self._ack_round.clear()
        self._ack_t0.clear()
        self._header_ack_ids.clear()
        # Ack latencies of the old epoch say nothing about the new
        # committee — and an inflated stale EWMA would slow the fallback
        # exactly when cross-epoch slim relays depend on it for delivery.
        self._ack_latency_ewma = None
        self._trees.clear()

    def shutdown(self) -> None:
        for handles in self._round_handles.values():
            for handle in handles:
                handle.cancel()
        self._round_handles.clear()
        for task in list(self._tasks):
            task.cancel()
