"""The PayloadReceiver: records availability of other authorities' batches.

Reference: /root/reference/primary/src/payload_receiver.rs:17-41 — our workers
report (digest, worker_id) for every peer batch they store; the token in the
payload store is what `Synchronizer.missing_payload` checks when voting on
headers.
"""

from __future__ import annotations

import asyncio

from ..channels import Channel
from ..stores import PayloadStore


class PayloadReceiver:
    def __init__(self, payload_store: PayloadStore, rx_workers: Channel):
        self.payload_store = payload_store
        self.rx_workers = rx_workers
        self._task: asyncio.Task | None = None

    def spawn(self) -> asyncio.Task:
        self._task = asyncio.ensure_future(self.run())
        return self._task

    async def run(self) -> None:
        while True:
            digest, worker_id = await self.rx_workers.recv()
            self.payload_store.write(digest, worker_id)
