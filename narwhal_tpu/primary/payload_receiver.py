"""The PayloadReceiver: records availability of other authorities' batches.

Reference: /root/reference/primary/src/payload_receiver.rs:17-41 — our workers
report (digest, worker_id) for every peer batch they store; the token in the
payload store is what `Synchronizer.missing_payload` checks when voting on
headers.
"""

from __future__ import annotations

import asyncio

from ..channels import Channel
from ..stores import PayloadStore


class PayloadReceiver:
    MAX_BURST = 256

    def __init__(self, payload_store: PayloadStore, rx_workers: Channel):
        self.payload_store = payload_store
        self.rx_workers = rx_workers
        self._task: asyncio.Task | None = None

    def spawn(self) -> asyncio.Task:
        self._task = asyncio.ensure_future(self.run())
        return self._task

    async def run(self) -> None:
        while True:
            pairs = [await self.rx_workers.recv()]
            # Greedy bounded drain: a burst of worker reports becomes one
            # grouped availability commit (availability tokens are visible
            # via the memtable immediately; one fused flush covers all).
            while len(pairs) < self.MAX_BURST:
                extra = self.rx_workers.try_recv()
                if extra is None:
                    break
                pairs.append(extra)
            await self.payload_store.write_all_async(pairs)
